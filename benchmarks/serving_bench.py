"""Serving fast-path benchmark: live HTTP server under concurrent clients.

Measures the two layers ISSUE 2 added to `serving/` end to end, over the
wire, against the same server code `polyaxon serve` runs:

  * per_request mode (`ServingConfig(batching=False)`) — the legacy path:
    one exact-shape jitted program per request signature, one device
    dispatch per request. A randomized traffic mix recompiles constantly.
  * batched mode — shape-bucketed compile cache (prompts LEFT-pad up a
    geometric ladder; `prompt_lengths`/seeds are runtime [B] args) plus a
    decode worker coalescing compatible requests up to `max_batch` /
    `max_wait_ms`.

Each mode drives its own server with N concurrent clients posting
randomized (prompt_len, max_new, seed) requests, then reads GET /statsz.
Prints one JSON line per mode plus a speedup line, in the same schema
family as the other benches (tests/test_bench_script.py pins it):

  {"metric": "serving_requests_per_sec", "value": ..., "unit": "req/s",
   "mode": "batched", "clients": 16, "requests": 96, "p50_ms": ...,
   "p95_ms": ..., "ttft_p50_ms": ..., "ttft_p95_ms": ...,
   "compile_count": 4, "batches": ..., "mean_batch_occupancy": ...,
   "kv_pages_total": ..., "kv_pages_used_hwm": ..., "prefix_hit_rate": ...,
   "platform": ..., "device_kind": ...}
  {"metric": "serving_batched_speedup", "value": 3.1, "unit": "x", ...}

`--shared-prefix` runs the ISSUE 6 demonstration instead: a paged server
(KV page pool + prefix cache + streaming), one cold request that pays the
full prefill, then a warm burst sharing the same page-aligned prompt
prefix. Warm requests skip the shared prefill entirely — the record pins
hit rate and the client-measured (streamed) TTFT drop:

  {"metric": "serving_prefix_reuse_ttft_speedup", "value": ..., "unit": "x",
   "ttft_cold_ms": ..., "ttft_warm_p50_ms": ..., "ttft_warm_p95_ms": ...,
   "prefix_hit_rate": ..., "kv_pages_total": ..., "kv_pages_used_hwm": ...}

`--speculate` runs the ISSUE 8 fast-decode demonstration: a paged
baseline server vs the same server with speculative decoding
(`ServingConfig(speculate=True)`) on a copy-friendly cyclic workload
(crafted weights that greedily replay the prompt's cycle — see
decode_bench.cyclic_copy_params), outputs asserted identical, plus an
int8 quantized server (`quantize=True`) on ordinary random weights
against its fp twin for the quality/footprint record:

  {"metric": "serving_speculative_speedup", "value": ..., "unit": "x",
   "tokens_per_sec": ..., "baseline_tokens_per_sec": ...,
   "accept_rate": ..., "tokens_per_step": ..., "draft_tokens": K,
   "compile_count": ..., "identical_outputs": true}
  {"metric": "serving_quant_bytes_saved", "value": B, "unit": "bytes",
   "hbm_reduction": ..., "top1_agreement_vs_fp": ...,
   "tokens_per_sec": ...}

`--trace-overhead` runs the ISSUE 9 record: the same batched server with
per-request tracing on vs off (ServingConfig(trace=...)), min-of-repeats
after a warmup pass, pinning that span timelines cost ≈nothing on the
serving fast path (the smoke configuration fails above 5%):

  {"metric": "serving_trace_overhead", "value": ..., "unit": "%",
   "req_per_sec_on": ..., "req_per_sec_off": ..., "p99_on_ms": ...,
   "p99_off_ms": ...}

`--history-overhead` runs the ISSUE 18 record: the same batched server
with the metrics-history sampler on (a 4 Hz HistorySampler snapshotting
the registry into CRC-framed segments) vs off, interleaved passes,
min-of-repeats, pinning that continuous history capture costs ≤5% of
serving p95 in the smoke configuration and that the on-server actually
recorded samples (`history_samples > 0`):

  {"metric": "serving_history_overhead", "value": ..., "unit": "%",
   "p95_on_ms": ..., "p95_off_ms": ..., "req_per_sec_on": ...,
   "req_per_sec_off": ..., "history_samples": ..., "history_bytes": ...}

`--federation-overhead` runs the ISSUE 13 record: the same two-replica
rig behind two routers — one with request tracing + cross-process trace
stitching + /metricsz federation on, one with all three off —
interleaved passes, min-of-repeats, pinning that the cluster
observability plane costs ≤5% of routed p95 in the smoke configuration:

  {"metric": "serving_federation_overhead", "value": ..., "unit": "%",
   "p95_on_ms": ..., "p95_off_ms": ..., "req_per_sec_on": ...,
   "req_per_sec_off": ..., "federated_series": true,
   "cluster_aggregates": true}

`--router --replicas N` runs the ISSUE 10 horizontal-serving record: N
byte-identical replica processes (`--serve-replica` self-mode — same
model, same PRNGKey(0) init) behind the fleet router
(`serving/router.py`, JSQ + power-of-two-choices). Three claims, three
records:

  {"metric": "router_aggregate_speedup", "value": ..., "unit": "x",
   "replicas": N, "req_per_sec_router": ..., "req_per_sec_single_direct":
   ..., "host_cores": C, "gate_enforced": bool}
  {"metric": "router_latency_overhead", "value": ..., "unit": "%",
   "p50_direct_ms": ..., "p50_router_ms": ..., "p95_direct_ms": ...,
   "p95_router_ms": ..., "byte_identical": true}

`--affinity` runs the ISSUE 17 cluster-warm-KV record: two paged-pool
replicas with a host-RAM spill tier behind the affinity router. One
prompt prefilled cold, replayed warm (affinity routes it back to the
holder — TTFT skips the prefill), the holder's pool flooded until the
entry spills, replayed again (affinity still finds it; the replica
RESTORES pages instead of re-prefilling), and the same warm prompt
fired at the cold sibling to price the re-route affinity avoids. Every
router record also carries `cluster_prefix_hit_rate` (the federated
fleet-wide warm-KV picture):

  {"metric": "serving_affinity_warm_ttft_speedup", "value": ..., "unit":
   "x", "ttft_warm_ms": ..., "ttft_restore_ms": ...,
   "ttft_reroute_cold_ms": ..., "restore_speedup": ..., "spills": ...,
   "spill_restores": ..., "cluster_prefix_hit_rate": ...,
   "byte_identical": true, "host_cores": C, "gate_enforced": bool}

`--tenants` runs the ISSUE 19 multi-tenant records: the victim tenant's
p95 under a noisy-neighbor flood vs alone (per-tenant admission sheds
the flood as `tenant_quota`, the victim's tail must hold), and the
adapter-multiplexing tax — a server hot-swapping three seeded LoRA
adapters vs a plain LoRA twin, interleaved min-of-repeats, plus a churn
phase pricing a real evict→spill→restore swap:

  {"metric": "serving_tenant_isolation_p95_ratio", "value": ..., "unit":
   "x", "victim_p95_alone_ms": ..., "victim_p95_contended_ms": ...,
   "noisy_shed": ..., "victim_shed": 0, "host_cores": C,
   "gate_enforced": bool}
  {"metric": "serving_adapter_swap_overhead", "value": ..., "unit": "%",
   "p95_multi_ms": ..., "p95_solo_ms": ..., "swap_p50_ms": ...,
   "resident_p50_ms": ..., "swap_evictions": ..., "swap_restores": ...}

`--interference` runs the ISSUE 14 chunked-prefill record: one long-
prompt/long-decode request per round with a burst of short streamed
requests fired while it is in flight, against an unchunked paged server
(one blocking execute per group — shorts wait out the whole long
request) and the chunked step scheduler (`chunkedPrefill: true` — the
long prefill is sliced and the shorts' chunks/decode rows share device
steps). Pins short-request TTFT both ways; the ≥2× smoke gate follows
the router-scaling precedent (`gate_enforced` only with ≥2 cores):

  {"metric": "serving_interference_ttft_speedup", "value": ..., "unit":
   "x", "ttft_short_p95_unchunked_ms": ..., "ttft_short_p95_chunked_ms":
   ..., "long_total_p50_chunked_ms": ..., "prefill_chunks": ...,
   "host_cores": C, "gate_enforced": bool}

Aggregate scaling needs real parallel compute: replicas are separate
processes, so the ≥1.7× smoke gate at 2 replicas is enforced only when
the host has ≥2 usable cores (`gate_enforced`); on a 1-core host the
record still reports but two compute-bound processes cannot beat one.
The latency-overhead gate (router hop ≤10% of p95, interleaved
direct-vs-routed samples, min-of-repeats) and the byte-identity check
(greedy + seeded-sampled, streamed + not, same X-Request-Id both paths)
are core-independent and always enforced in --smoke.

  python benchmarks/serving_bench.py                 # full: 16 clients
  python benchmarks/serving_bench.py --smoke         # CI smoke: 4 clients
  python benchmarks/serving_bench.py --mode batched  # one side only
  python benchmarks/serving_bench.py --shared-prefix # prefix-reuse demo
  python benchmarks/serving_bench.py --speculate     # fast-decode demo
  python benchmarks/serving_bench.py --trace-overhead # tracing cost
  python benchmarks/serving_bench.py --history-overhead # history cost
  python benchmarks/serving_bench.py --federation-overhead # plane cost
  python benchmarks/serving_bench.py --interference  # chunked prefill
  python benchmarks/serving_bench.py --affinity      # cluster warm KV
  python benchmarks/serving_bench.py --smoke --router --replicas 2
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from polyaxon_tpu.telemetry import quantile  # noqa: E402 (needs sys.path)

MODEL_CFG = {
    "preset": "tiny", "seq_len": 128, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 256,
}


def _post(url: str, body: dict, timeout: float = 300.0) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def make_traffic(n_requests: int, seed: int) -> list[dict]:
    """Deterministic randomized request mix, drawn from the scenario
    engine's seeded `bench_mix` trace generator (ISSUE 16): a modest
    pool of distinct prompt lengths — enough variety that the
    exact-shape baseline keeps recompiling, small enough that the full
    run finishes on CPU — so the bench workload is a replayable trace
    (`trace_seed` in the records) instead of ad-hoc rng calls."""
    from polyaxon_tpu.scenarios.traces import bench_mix, body_for

    return [
        body_for(rec, MODEL_CFG["vocab_size"])
        for rec in bench_mix(seed, n=n_requests)
    ]


def build_server(batching: bool, max_batch: int, max_wait_ms: float,
                 kv_pool_pages: int | None = None,
                 kv_page_tokens: int = 16,
                 stream_chunk_tokens: int = 4,
                 trace: bool = True,
                 chunked_prefill: bool = False,
                 prefill_chunk_tokens: int = 64,
                 max_step_tokens: int = 256,
                 spill_ram_bytes: int | None = None,
                 history: dict | None = None,
                 lora_rank: int = 0,
                 adapters: dict | None = None,
                 tenants: list | None = None,
                 adapter_slots: int = 0,
                 role: str = "both"):
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer
    from polyaxon_tpu.serving.tenancy import (
        normalize_adapters, normalize_tenants,
    )

    cfg = dict(MODEL_CFG, lora_rank=lora_rank) if lora_rank else MODEL_CFG
    bundle = build_model("transformer_lm", cfg)
    params = bundle.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return ModelServer(
        bundle.module,
        params,
        model_name="serving-bench",
        config=ServingConfig(
            batching=batching, max_batch=max_batch, max_wait_ms=max_wait_ms,
            kv_pool_pages=kv_pool_pages, kv_page_tokens=kv_page_tokens,
            stream_chunk_tokens=stream_chunk_tokens, trace=trace,
            chunked_prefill=chunked_prefill,
            prefill_chunk_tokens=prefill_chunk_tokens,
            max_step_tokens=max_step_tokens,
            spill_ram_bytes=spill_ram_bytes,
            adapters=normalize_adapters(adapters or {}),
            tenants=normalize_tenants(tenants or []),
            adapter_slots=adapter_slots,
            role=role,
        ),
        history=history,
    )


def _stream_ttft(host: str, port: int, body: dict,
                 timeout: float = 300.0) -> tuple[float, list[int]]:
    """POST /generate?stream=1 and return (client-measured TTFT seconds,
    generated tokens of row 0) — TTFT is wall time to the first `tokens`
    SSE frame, the number a user actually experiences."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    t0 = time.perf_counter()
    conn.request("POST", "/generate?stream=1", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    if resp.status != 200:
        raise RuntimeError(f"stream status {resp.status}: {resp.read()!r}")
    ttft = None
    tokens: list[int] = []
    buf = b""
    while True:
        chunk = resp.read(64)
        if not chunk:
            break
        buf += chunk
        while b"\n\n" in buf:
            frame, buf = buf.split(b"\n\n", 1)
            ev = json.loads(frame[len(b"data: "):])
            if "tokens" in ev and ev.get("row") == 0:
                if ttft is None:
                    ttft = time.perf_counter() - t0
                tokens.extend(ev["tokens"])
    conn.close()
    if ttft is None:
        raise RuntimeError("stream produced no token frames")
    return ttft, tokens


def drive(mode: str, traffic: list[dict], clients: int, max_batch: int,
          max_wait_ms: float, kv_pool_pages: int | None = None) -> dict:
    """Run one server in `mode`, fire the traffic from `clients` threads,
    return the stats record. Mode `paged` is `batched` plus the block-
    paged KV pool (admission by page reservation + prefix cache)."""
    server = build_server(
        mode in ("batched", "paged"), max_batch, max_wait_ms,
        kv_pool_pages=kv_pool_pages if mode == "paged" else None,
    )
    port = server.start(port=0)
    url = f"http://127.0.0.1:{port}/generate"
    # round-robin the SAME traffic across client threads so both modes see
    # an identical request multiset regardless of thread scheduling
    shards = [traffic[i::clients] for i in range(clients)]
    latencies: list[float] = []
    errors: list[str] = []
    lock = threading.Lock()

    def client(shard: list[dict]):
        for body in shard:
            t0 = time.perf_counter()
            try:
                out = _post(url, body)
                dt = time.perf_counter() - t0
                row = out["tokens"][0]
                want = len(body["tokens"][0]) + body["maxNewTokens"]
                if len(row) != want:
                    raise AssertionError(
                        f"row length {len(row)} != prompt+new {want}"
                    )
                with lock:
                    latencies.append(dt)
            except Exception as e:  # noqa: BLE001 — count, keep driving
                with lock:
                    errors.append(f"{type(e).__name__}: {e}"[:200])

    threads = [
        threading.Thread(target=client, args=(s,), daemon=True)
        for s in shards if s
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    stats = json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statsz", timeout=30
        ).read()
    )
    server.stop()

    import jax

    device = jax.devices()[0]
    lat_ms = sorted(l * 1e3 for l in latencies)
    kv = stats.get("kv") or {}
    prefix = kv.get("prefix") or {}
    lookups = prefix.get("hits", 0) + prefix.get("misses", 0)
    # non-streamed requests deliver their first token with the response,
    # so client-side TTFT == request latency; the paged server also
    # reports true (first-sample) TTFT through its own histogram
    ttft = kv.get("ttft_ms") or {}
    rec = {
        "metric": "serving_requests_per_sec",
        "value": round(len(latencies) / wall, 2) if wall > 0 else 0.0,
        "unit": "req/s",
        "mode": mode,
        "clients": clients,
        "requests": len(latencies),
        "wall_s": round(wall, 2),
        "p50_ms": round(quantile(lat_ms, 0.5), 1) if lat_ms else None,
        "p95_ms": round(quantile(lat_ms, 0.95), 1) if lat_ms else None,
        "ttft_p50_ms": (
            ttft.get("p50")
            if kv.get("enabled")
            else (round(quantile(lat_ms, 0.5), 1) if lat_ms else None)
        ),
        "ttft_p95_ms": (
            ttft.get("p95")
            if kv.get("enabled")
            else (round(quantile(lat_ms, 0.95), 1) if lat_ms else None)
        ),
        "kv_pages_total": kv.get("pages_total", 0),
        "kv_pages_used_hwm": kv.get("pages_hwm", 0),
        "prefix_hit_rate": (
            round(prefix.get("hits", 0) / lookups, 3) if lookups else None
        ),
        "compile_count": stats["compile_count"],
        "batches": stats["batches"],
        "mean_batch_occupancy": stats["mean_batch_occupancy"],
        "platform": device.platform,
        "device_kind": device.device_kind,
    }
    if errors:
        rec["errors"] = len(errors)
        rec["first_error"] = errors[0]
    return rec


def drive_trace_overhead(traffic: list[dict], clients: int, max_batch: int,
                         max_wait_ms: float, repeats: int) -> dict:
    """ISSUE 9 record: the cost of per-request tracing on the serving
    fast path. Two identical batched servers — ServingConfig(trace=True)
    vs trace=False — each warmed with one full pass (compiles out of the
    way), then `repeats` timed passes; the BEST pass per config is
    compared (min-of-repeats cancels scheduler noise on shared CI
    hosts). Tracing is a handful of dict appends per request, so the
    overhead must stay within a few percent."""

    def one_pass(url: str) -> tuple[float, list[float]]:
        shards = [traffic[i::clients] for i in range(clients)]
        latencies: list[float] = []
        lock = threading.Lock()

        def client(shard):
            for body in shard:
                t0 = time.perf_counter()
                _post(url, body)
                dt = time.perf_counter() - t0
                with lock:
                    latencies.append(dt)

        threads = [
            threading.Thread(target=client, args=(s,), daemon=True)
            for s in shards if s
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, latencies

    # both servers live at once, passes interleaved on/off/on/off —
    # host-load drift hits both configs equally instead of whichever
    # ran second
    servers = {
        flag: build_server(True, max_batch, max_wait_ms, trace=flag)
        for flag in (True, False)
    }
    urls = {
        flag: f"http://127.0.0.1:{srv.start(port=0)}/generate"
        for flag, srv in servers.items()
    }
    best: dict = {}
    for flag in (True, False):
        one_pass(urls[flag])  # warmup: compiles + trace ring allocation
    for _ in range(repeats):
        for flag in (True, False):
            wall, lats = one_pass(urls[flag])
            if flag not in best or wall < best[flag][0]:
                best[flag] = (wall, lats)
    for srv in servers.values():
        srv.stop()

    def summarize(flag: bool) -> dict:
        wall, lats = best[flag]
        lat_ms = sorted(l * 1e3 for l in lats)
        return {
            "req_per_sec": round(len(lats) / wall, 2),
            "p99_ms": round(quantile(lat_ms, 0.99), 2),
        }

    on = summarize(True)
    off = summarize(False)
    overhead = (
        (off["req_per_sec"] - on["req_per_sec"]) / off["req_per_sec"] * 100
        if off["req_per_sec"] > 0
        else 0.0
    )
    import jax

    device = jax.devices()[0]
    return {
        "metric": "serving_trace_overhead",
        "value": round(overhead, 2),
        "unit": "%",
        "req_per_sec_on": on["req_per_sec"],
        "req_per_sec_off": off["req_per_sec"],
        "p99_on_ms": on["p99_ms"],
        "p99_off_ms": off["p99_ms"],
        "clients": clients,
        "requests": len(traffic),
        "repeats": repeats,
        "platform": device.platform,
        "device_kind": device.device_kind,
    }


def drive_history_overhead(traffic: list[dict], clients: int,
                           max_batch: int, max_wait_ms: float,
                           repeats: int) -> dict:
    """ISSUE 18 record: the cost of continuous metrics-history capture
    on the serving fast path. Two identical batched servers — one with a
    4 Hz HistorySampler snapshotting the full registry into CRC-framed
    segments, one without — both alive at once, passes interleaved
    on/off (drive_trace_overhead's methodology: host-load drift hits
    both configs equally), BEST pass per config compared after a warmup.
    The sampler runs off the request thread entirely (a daemon loop
    holding the registry lock for one snapshot per tick), so the p95
    cost must stay within a few percent."""
    import tempfile

    def one_pass(url: str) -> tuple[float, list[float]]:
        shards = [traffic[i::clients] for i in range(clients)]
        latencies: list[float] = []
        lock = threading.Lock()

        def client(shard):
            for body in shard:
                t0 = time.perf_counter()
                _post(url, body)
                dt = time.perf_counter() - t0
                with lock:
                    latencies.append(dt)

        threads = [
            threading.Thread(target=client, args=(s,), daemon=True)
            for s in shards if s
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, latencies

    hist_dir = tempfile.mkdtemp(prefix="bench-history-")
    servers = {
        True: build_server(
            True, max_batch, max_wait_ms,
            history={"dir": hist_dir, "interval_s": 0.25},
        ),
        False: build_server(True, max_batch, max_wait_ms),
    }
    urls = {
        flag: f"http://127.0.0.1:{srv.start(port=0)}/generate"
        for flag, srv in servers.items()
    }
    best: dict = {}
    for flag in (True, False):
        one_pass(urls[flag])  # warmup: compiles + first segment open
    for _ in range(repeats):
        for flag in (True, False):
            wall, lats = one_pass(urls[flag])
            if flag not in best or wall < best[flag][0]:
                best[flag] = (wall, lats)
    samples = int(servers[True].telemetry.snapshot().get(
        "history.samples", 0))
    hist_bytes = servers[True].history.total_bytes()
    for srv in servers.values():
        srv.stop()

    def summarize(flag: bool) -> dict:
        wall, lats = best[flag]
        lat_ms = sorted(l * 1e3 for l in lats)
        return {
            "req_per_sec": round(len(lats) / wall, 2),
            "p95_ms": round(quantile(lat_ms, 0.95), 2),
        }

    on = summarize(True)
    off = summarize(False)
    overhead = (
        (on["p95_ms"] - off["p95_ms"]) / off["p95_ms"] * 100
        if off["p95_ms"] > 0
        else 0.0
    )
    import jax

    device = jax.devices()[0]
    return {
        "metric": "serving_history_overhead",
        "value": round(overhead, 2),
        "unit": "%",
        "p95_on_ms": on["p95_ms"],
        "p95_off_ms": off["p95_ms"],
        "req_per_sec_on": on["req_per_sec"],
        "req_per_sec_off": off["req_per_sec"],
        "history_samples": samples,
        "history_bytes": hist_bytes,
        "clients": clients,
        "requests": len(traffic),
        "repeats": repeats,
        "platform": device.platform,
        "device_kind": device.device_kind,
    }


def drive_federation_overhead(traffic: list[dict], clients: int,
                              max_batch: int, max_wait_ms: float,
                              repeats: int, seed: int) -> dict:
    """ISSUE 13 record: the cost of the cluster observability plane on
    the routed serving path. Two routers over the SAME two in-process
    replicas — one with tracing + trace stitching + metrics federation
    on, one with all three off — interleaved passes, min-of-repeats
    (drive_trace_overhead's methodology). The on-router fetches each
    attempted replica's /tracez per request (the stitch hop) and
    federates every /metricsz scrape; both must stay within a few
    percent of p95."""
    from polyaxon_tpu.serving.router import P2CBalancer, Router

    servers = [
        build_server(True, max_batch, max_wait_ms) for _ in range(2)
    ]
    urls = [f"http://127.0.0.1:{srv.start(port=0)}" for srv in servers]
    routers = {
        flag: Router(
            urls,
            balancer=P2CBalancer(seed=seed),
            poll_interval_s=0.5,
            trace=flag,
            stitch=flag,
            federate=flag,
        )
        for flag in (True, False)
    }
    router_urls = {
        flag: f"http://127.0.0.1:{r.start(port=0)}/generate"
        for flag, r in routers.items()
    }

    def one_pass(url: str) -> tuple[float, list[float]]:
        shards = [traffic[i::clients] for i in range(clients)]
        latencies: list[float] = []
        lock = threading.Lock()

        def client(shard):
            for body in shard:
                t0 = time.perf_counter()
                _post(url, body)
                dt = time.perf_counter() - t0
                with lock:
                    latencies.append(dt)

        threads = [
            threading.Thread(target=client, args=(s,), daemon=True)
            for s in shards if s
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, latencies

    try:
        for flag in (True, False):
            one_pass(router_urls[flag])  # warmup: compiles, trace rings
        best: dict = {}
        for _ in range(repeats):
            for flag in (True, False):
                wall, lats = one_pass(router_urls[flag])
                lat_ms = sorted(l * 1e3 for l in lats)
                p95 = quantile(lat_ms, 0.95)
                if flag not in best or p95 < best[flag][0]:
                    best[flag] = (p95, wall, len(lats))
        federated_text = routers[True].render_metrics()
    finally:
        for r in routers.values():
            r.stop()
        for srv in servers:
            srv.stop()

    p95_on, wall_on, n_on = best[True]
    p95_off, wall_off, n_off = best[False]
    overhead = (
        (p95_on - p95_off) / p95_off * 100 if p95_off > 0 else 0.0
    )
    import jax

    device = jax.devices()[0]
    return {
        "metric": "serving_federation_overhead",
        "value": round(overhead, 2),
        "unit": "%",
        "p95_on_ms": round(p95_on, 2),
        "p95_off_ms": round(p95_off, 2),
        "req_per_sec_on": round(n_on / wall_on, 2) if wall_on > 0 else 0.0,
        "req_per_sec_off": (
            round(n_off / wall_off, 2) if wall_off > 0 else 0.0
        ),
        # sanity: the on-router really federated — replica-labeled series
        # and cluster aggregates present in its /metricsz text
        "federated_series": 'replica="r0"' in federated_text,
        "cluster_aggregates": "cluster:serving_" in federated_text,
        "replicas": 2,
        "clients": clients,
        "requests": len(traffic),
        "repeats": repeats,
        "platform": device.platform,
        "device_kind": device.device_kind,
    }


def drive_shared_prefix(warm_requests: int, max_batch: int,
                        max_wait_ms: float, kv_pool_pages: int,
                        seed: int) -> dict:
    """ISSUE 6 demonstration: paged server, one cold request paying the
    full prefill, then a warm burst sharing the same page-aligned prompt
    prefix. Warm rows alias the cached prefix pages (copy-on-write) and
    prefill only their short suffixes — hit rate must be > 0 and the
    streamed (client-measured) TTFT must drop."""
    page_tokens = 16
    server = build_server(
        True, max_batch, max_wait_ms,
        kv_pool_pages=kv_pool_pages, kv_page_tokens=page_tokens,
    )
    port = server.start(port=0)
    rng = random.Random(seed)
    # a long system-prompt-shaped prefix: 3 full pages, page-aligned so
    # the harvest of the cold request indexes exactly this content
    shared = [rng.randrange(MODEL_CFG["vocab_size"])
              for _ in range(3 * page_tokens)]

    def body(suffix_len: int, req_seed: int) -> dict:
        return {
            "tokens": [shared + [rng.randrange(MODEL_CFG["vocab_size"])
                                 for _ in range(suffix_len)]],
            "maxNewTokens": 8, "temperature": 0.8, "topK": 40,
            "seed": req_seed,
        }

    ttft_cold, _ = _stream_ttft("127.0.0.1", port, body(6, 0))
    warm = []
    for i in range(warm_requests):
        dt, toks = _stream_ttft("127.0.0.1", port, body(4 + i % 5, i + 1))
        if not toks:
            raise RuntimeError("warm request produced no tokens")
        warm.append(dt * 1e3)
    stats = json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statsz", timeout=30
        ).read()
    )
    server.stop()
    kv = stats["kv"]
    prefix = kv["prefix"]
    lookups = prefix["hits"] + prefix["misses"]
    warm_sorted = sorted(warm)
    warm_p50 = quantile(warm_sorted, 0.5)
    import jax

    device = jax.devices()[0]
    return {
        "metric": "serving_prefix_reuse_ttft_speedup",
        "value": round(ttft_cold * 1e3 / warm_p50, 2) if warm_p50 else None,
        "unit": "x",
        "ttft_cold_ms": round(ttft_cold * 1e3, 1),
        "ttft_warm_p50_ms": round(warm_p50, 1),
        "ttft_warm_p95_ms": round(quantile(warm_sorted, 0.95), 1),
        "warm_requests": warm_requests,
        "shared_prefix_tokens": len(shared),
        "page_tokens": page_tokens,
        "prefix_hit_rate": round(prefix["hits"] / lookups, 3),
        "prefix_hits": prefix["hits"],
        "kv_pages_total": kv["pages_total"],
        "kv_pages_used_hwm": kv["pages_hwm"],
        "platform": device.platform,
        "device_kind": device.device_kind,
    }


def drive_fast_decode(requests: int, draft_tokens: int,
                      kv_pool_pages: int) -> list[dict]:
    """ISSUE 8 demonstration. Speculation: two paged servers over the
    SAME crafted cyclic model (greedy decode replays the prompt's
    cycle), one plain and one with ServingConfig(speculate=True); the
    n-gram drafter accepts near-fully, tokens/sec is wall-clock over
    the wire, and outputs must be byte-identical. Quantization: a
    random-weight fp server vs its int8 twin (quantize-on-load) — the
    record pins the decode-weight footprint drop and the greedy token
    agreement, the serving-level "quality delta vs fp"."""
    import jax
    import jax.numpy as jnp

    from decode_bench import CYCLE, cyclic_copy_params
    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.models.quant import decode_weight_bytes
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    cfg = dict(MODEL_CFG, dim=128)  # dim 64 decode is dispatch-bound on
    # CPU — the verify window needs real per-token work to amortize
    bundle = build_model("transformer_lm", cfg)
    params = bundle.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32), train=False,
    )["params"]
    cyc_params = cyclic_copy_params(params, cfg)

    def server(p, **kw):
        return ModelServer(
            bundle.module, p, model_name="fast-decode",
            config=ServingConfig(
                max_batch=4, max_wait_ms=2.0, kv_pool_pages=kv_pool_pages,
                kv_page_tokens=16, stream_chunk_tokens=4, **kw,
            ),
        )

    max_new = 64
    cyc_prompt = list(CYCLE) * 4  # 32 tokens, bucket-aligned
    rng = random.Random(7)
    rand_prompts = [
        [rng.randrange(cfg["vocab_size"]) for _ in range(32)]
        for _ in range(requests)
    ]

    def fire(srv, prompts, new=None):
        port = srv.start(port=0)
        url = f"http://127.0.0.1:{port}/generate"
        outs = []
        t0 = time.perf_counter()
        for i, p in enumerate(prompts):
            outs.append(_post(url, {
                "tokens": [p], "maxNewTokens": new or max_new,
                "temperature": 0.0, "seed": i,
            })["tokens"][0])
        wall = time.perf_counter() - t0
        stats = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statsz", timeout=30
            ).read()
        )
        srv.stop()
        return outs, wall, stats

    device = jax.devices()[0]
    cyc_traffic = [cyc_prompt] * requests
    base_out, base_wall, _ = fire(server(cyc_params), cyc_traffic)
    spec_out, spec_wall, spec_stats = fire(
        server(cyc_params, speculate=True, draft_tokens=draft_tokens),
        cyc_traffic,
    )
    total = requests * max_new
    base_tps = total / base_wall
    spec_tps = total / spec_wall
    sp = spec_stats["speculation"]
    windows = sp["proposed"] / max(draft_tokens, 1)
    recs = [{
        "metric": "serving_speculative_speedup",
        "value": round(spec_tps / base_tps, 2),
        "unit": "x",
        "tokens_per_sec": round(spec_tps, 1),
        "baseline_tokens_per_sec": round(base_tps, 1),
        "accept_rate": sp["accept_rate"],
        "tokens_per_step": round(1 + sp["accepted"] / max(windows, 1), 2),
        "draft_tokens": draft_tokens,
        "proposed": sp["proposed"],
        "accepted": sp["accepted"],
        "rollbacks": sp["rollbacks"],
        "compile_count": spec_stats["compile_count"],
        "requests": requests,
        "max_new": max_new,
        "identical_outputs": spec_out == base_out,
        "platform": device.platform,
        "device_kind": device.device_kind,
    }]

    # a 16-token greedy horizon for the quality check: a random-weight
    # tiny model has near-tied logits, so one int8 flip cascades into an
    # unrelated (not worse) continuation — short-horizon agreement is
    # the signal, long-horizon agreement just measures chaos
    qnew = 16
    qtotal = requests * qnew
    fp_out, fp_wall, _ = fire(server(params), rand_prompts, new=qnew)
    q_out, q_wall, q_stats = fire(
        server(params, quantize=True), rand_prompts, new=qnew,
    )
    agree = sum(
        1
        for a, b in zip(fp_out, q_out)
        for x, y in zip(a[32:], b[32:])
        if x == y
    ) / qtotal
    target_fp, _ = decode_weight_bytes(params)
    saved = q_stats["quant"]["bytes_saved"]
    recs.append({
        "metric": "serving_quant_bytes_saved",
        "value": saved,
        "unit": "bytes",
        "hbm_reduction": round(saved / max(target_fp, 1), 3),
        "top1_agreement_vs_fp": round(agree, 4),
        "agreement_horizon": qnew,
        "tokens_per_sec": round(qtotal / q_wall, 1),
        "fp_tokens_per_sec": round(qtotal / fp_wall, 1),
        "requests": requests,
        "platform": device.platform,
        "device_kind": device.device_kind,
    })
    return recs


def drive_interference(rounds: int, shorts_per_round: int, max_batch: int,
                       max_wait_ms: float, kv_pool_pages: int, seed: int,
                       prefill_chunk_tokens: int = 16,
                       max_step_tokens: int = 64) -> dict:
    """ISSUE 14 record: head-of-line blocking under a mixed-length mix.

    Each round posts one long-prompt/long-decode request and then, while
    it is still in flight, a burst of short streamed requests. On the
    unchunked paged server the worker runs the long request as one
    blocking execute, so every short request's first token waits for the
    long request to finish. On the chunked server the step scheduler
    slices the long prefill and packs the shorts' chunks and decode rows
    into the same device steps — short TTFT stops scaling with the long
    request's length. The record pins short-request ttft_p95 both ways:

      {"metric": "serving_interference_ttft_speedup", "value": ...,
       "unit": "x", "ttft_short_p95_unchunked_ms": ...,
       "ttft_short_p95_chunked_ms": ..., "host_cores": C,
       "gate_enforced": bool}

    Like router scaling (PR 10), the gate needs real parallelism: the
    client threads that time TTFT and the server's step loop contend for
    CPU on a 1-core host, burying the scheduling win under scheduler
    noise — the ≥2x smoke gate is enforced only when `gate_enforced`.
    """
    import os

    import jax

    rng = random.Random(seed)
    long_len, short_len = 96, 8
    vocab = MODEL_CFG["vocab_size"]
    long_prompt = [rng.randrange(vocab) for _ in range(long_len)]
    short_prompts = [
        [rng.randrange(vocab) for _ in range(short_len)]
        for _ in range(rounds * shorts_per_round)
    ]

    def body(tokens: list[int], new: int, s: int) -> dict:
        return {"tokens": [tokens], "maxNewTokens": new,
                "temperature": 0.8, "topK": 40, "seed": s}

    sides = {}
    stats = {}
    for label, chunked in (("unchunked", False), ("chunked", True)):
        srv = build_server(
            True, max_batch, max_wait_ms, kv_pool_pages=kv_pool_pages,
            chunked_prefill=chunked,
            prefill_chunk_tokens=prefill_chunk_tokens,
            max_step_tokens=max_step_tokens,
        )
        port = srv.start(port=0)
        url = f"http://127.0.0.1:{port}/generate"
        try:
            # warm both shapes so compiles never land in a timed round
            _post(url, body(long_prompt, 32, 0))
            _stream_ttft("127.0.0.1", port, body(short_prompts[0], 4, 0))

            ttfts: list[float] = []
            longs: list[float] = []
            for r in range(rounds):
                t0 = time.perf_counter()
                done = threading.Event()

                def fire_long():
                    _post(url, body(long_prompt, 32, 100 + r))
                    longs.append(time.perf_counter() - t0)
                    done.set()

                t = threading.Thread(target=fire_long, daemon=True)
                t.start()
                time.sleep(0.01)  # let the long request enter the worker
                for i in range(shorts_per_round):
                    ttft, _ = _stream_ttft(
                        "127.0.0.1", port,
                        body(short_prompts[r * shorts_per_round + i], 4,
                             200 + r * shorts_per_round + i),
                    )
                    ttfts.append(ttft * 1000.0)
                done.wait(timeout=300.0)
            sides[label] = ttfts
            stats[label] = {
                "long_total_p50_ms": round(quantile(longs, 0.5) * 1000, 1),
                **json.loads(
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/statsz", timeout=30
                    ).read()
                ).get("chunked", {}),
            }
        finally:
            srv.stop()

    p95_un = quantile(sides["unchunked"], 0.95)
    p95_ch = quantile(sides["chunked"], 0.95)
    cores = len(os.sched_getaffinity(0))
    device = jax.devices()[0]
    return {
        "metric": "serving_interference_ttft_speedup",
        "value": round(p95_un / p95_ch, 2) if p95_ch else None,
        "unit": "x",
        "ttft_short_p50_unchunked_ms": round(
            quantile(sides["unchunked"], 0.5), 1),
        "ttft_short_p50_chunked_ms": round(
            quantile(sides["chunked"], 0.5), 1),
        "ttft_short_p95_unchunked_ms": round(p95_un, 1),
        "ttft_short_p95_chunked_ms": round(p95_ch, 1),
        "long_total_p50_unchunked_ms":
            stats["unchunked"]["long_total_p50_ms"],
        "long_total_p50_chunked_ms": stats["chunked"]["long_total_p50_ms"],
        "long_prompt_tokens": long_len,
        "short_prompt_tokens": short_len,
        "short_requests": len(sides["chunked"]),
        "prefill_chunk_tokens": prefill_chunk_tokens,
        "max_step_tokens": max_step_tokens,
        "steps": stats["chunked"].get("steps", 0),
        "prefill_chunks": stats["chunked"].get("prefill_chunks", 0),
        "host_cores": cores,
        # 1-core hosts bury the scheduling win under CPU contention
        # between the timing clients and the step loop (see router
        # scaling) — report honestly, gate only where it can express
        "gate_enforced": cores >= 2,
        "platform": device.platform,
        "device_kind": device.device_kind,
    }


def drive_disaggregated(rounds: int, shorts_per_round: int, max_batch: int,
                        max_wait_ms: float, seed: int, smoke: bool) -> dict:
    """ISSUE 20 record: the PR 14 interference cohort across a
    disaggregated prefill/decode split, plus the cost of the split
    itself — live KV handoff latency.

    The same mixed-length traffic runs twice behind a router: a
    2-replica monolithic chunked fleet, then a 1 prefill + 1 decode
    pooled pair. On the pooled pair every request's finished prefill
    pages ship over POST /kv_import (CRC-framed spill-segment bytes,
    single-owner leases) and decode continues on the other replica — the
    long prompt's slices never share a step budget with the shorts'
    decode rows. The headline value is the handoff latency p95 as the
    prefill replicas observed it (`serving_kv_handoff_ms`): the transfer
    is the tax the split pays, and it must stay small against the
    prefill time it hides.

      {"metric": "serving_disaggregated_handoff_p95_ms", "value": ...,
       "unit": "ms", "ttft_short_p95_pooled_ms": ...,
       "ttft_short_p95_monolithic_ms": ..., "handoff_exports": ...,
       "handoff_fallbacks": ..., "byte_identical": bool,
       "gate_enforced": bool}

    Mechanism gates hold everywhere: real handoffs happened (exports and
    imports counted, zero fallbacks — a pooled pair that quietly decodes
    monolithically is not evidence), every lease completed, and a pinned
    greedy request answers byte-identically on both fleets. The latency
    gate needs cores (the timing clients and four servers contend on a
    1-core host, same physics as --interference), so it is enforced only
    when `gate_enforced`.
    """
    import os

    import jax

    from polyaxon_tpu.serving.router import P2CBalancer, Router

    rng = random.Random(seed)
    long_len, short_len = 96, 12  # 12 / 1 full 8-token pages to hand off
    vocab = MODEL_CFG["vocab_size"]
    long_prompt = [rng.randrange(vocab) for _ in range(long_len)]
    short_prompts = [
        [rng.randrange(vocab) for _ in range(short_len)]
        for _ in range(rounds * shorts_per_round)
    ]

    def body(tokens: list[int], new: int, s: int) -> dict:
        return {"tokens": [tokens], "maxNewTokens": new,
                "temperature": 0.8, "topK": 40, "seed": s}

    kw = dict(kv_pool_pages=96, kv_page_tokens=8, chunked_prefill=True,
              prefill_chunk_tokens=16, max_step_tokens=64)
    sides = {}
    ledgers = {}
    raw = {}
    for label, roles in (("monolithic", ("both", "both")),
                         ("pooled", ("prefill", "decode"))):
        servers = [
            build_server(True, max_batch, max_wait_ms, role=r, **kw)
            for r in roles
        ]
        ports = [s.start(port=0) for s in servers]
        router = Router(
            [f"http://127.0.0.1:{p}" for p in ports],
            balancer=P2CBalancer(seed=seed + 7), poll_interval_s=0.1,
        )
        rport = router.start(port=0)
        try:
            # the pooled dispatch needs the scraped roles before the
            # first request, or the long prompt lands on the decode pool
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                router.poll_once()
                reps = router.stats()["replicas"]
                if len(reps) == 2 and all(r["healthy"] for r in reps):
                    break
                time.sleep(0.1)
            url = f"http://127.0.0.1:{rport}/generate"
            # warm through the router: compiles (and on the pooled side
            # the export/adopt paths) stay out of the timed rounds
            _post(url, body(long_prompt, 32, 0))
            _stream_ttft("127.0.0.1", rport, body(short_prompts[0], 4, 0))

            ttfts: list[float] = []
            for r in range(rounds):
                done = threading.Event()

                def fire_long():
                    _post(url, body(long_prompt, 32, 100 + r))
                    done.set()

                t = threading.Thread(target=fire_long, daemon=True)
                t.start()
                time.sleep(0.01)  # let the long request enter the worker
                for i in range(shorts_per_round):
                    ttft, _ = _stream_ttft(
                        "127.0.0.1", rport,
                        body(short_prompts[r * shorts_per_round + i], 4,
                             200 + r * shorts_per_round + i),
                    )
                    ttfts.append(ttft * 1000.0)
                done.wait(timeout=300.0)
            # identity probe: same pinned rid on both fleets must answer
            # the same bytes — the split may not change a single token
            raw[label] = _raw_post(
                f"http://127.0.0.1:{rport}",
                body(long_prompt[:24], 8, 0) | {"temperature": 0.0},
                rid="disagg-identity",
            )
            sides[label] = ttfts
            if label == "pooled":
                pre, dec = servers
                h = pre._m_handoff_ms
                ledgers["handoff_p95_ms"] = h.percentile(0.95)
                ledgers["handoff_p50_ms"] = h.percentile(0.5)
                ledgers["handoff_transfers"] = h.count
                ledgers["exports"] = pre.stats()["handoff"]["exports"]
                ledgers["fallbacks"] = pre.stats()["handoff"]["fallbacks"]
                ledgers["imports"] = dec.stats()["handoff"]["imports"]
                lease = dec.stats()["handoff"]["leases"]
                ledgers["lease_granted"] = lease["granted"]
                ledgers["lease_completed"] = lease["completed"]
        finally:
            router.stop()
            for s in servers:
                s.stop()

    p95_pooled = quantile(sides["pooled"], 0.95)
    p95_mono = quantile(sides["monolithic"], 0.95)
    cores = len(os.sched_getaffinity(0))
    device = jax.devices()[0]
    p95 = ledgers.get("handoff_p95_ms")
    return {
        "metric": "serving_disaggregated_handoff_p95_ms",
        "value": round(p95, 2) if p95 is not None else None,
        "unit": "ms",
        "handoff_p50_ms": (
            round(ledgers["handoff_p50_ms"], 2)
            if ledgers.get("handoff_p50_ms") is not None else None
        ),
        "handoff_transfers": ledgers.get("handoff_transfers", 0),
        "handoff_exports": ledgers.get("exports", 0),
        "handoff_imports": ledgers.get("imports", 0),
        "handoff_fallbacks": ledgers.get("fallbacks", 0),
        "lease_granted": ledgers.get("lease_granted", 0),
        "lease_completed": ledgers.get("lease_completed", 0),
        "ttft_short_p50_pooled_ms": round(
            quantile(sides["pooled"], 0.5), 1),
        "ttft_short_p50_monolithic_ms": round(
            quantile(sides["monolithic"], 0.5), 1),
        "ttft_short_p95_pooled_ms": round(p95_pooled, 1),
        "ttft_short_p95_monolithic_ms": round(p95_mono, 1),
        "byte_identical": raw["pooled"] == raw["monolithic"],
        "long_prompt_tokens": long_len,
        "short_prompt_tokens": short_len,
        "short_requests": len(sides["pooled"]),
        "rounds": rounds,
        "host_cores": cores,
        # 1-core hosts bury the handoff timing (and any phase-isolation
        # win) under CPU contention between the timing clients and four
        # servers — report honestly, gate only where it can express
        "gate_enforced": cores >= 2,
        "platform": device.platform,
        "device_kind": device.device_kind,
    }


def drive_affinity(max_batch: int, max_wait_ms: float, seed: int,
                   smoke: bool) -> dict:
    """ISSUE 17 record: cluster-wide warm KV — affinity routing and the
    eviction→spill→restore cycle, TTFT both ways.

    Two in-process replicas with a small paged pool + host-RAM spill
    tier sit behind the affinity router. One prompt is prefilled cold,
    then replayed warm: the router's prefix directory (fed by /kvz
    advertisements) routes the replay to the replica that already holds
    the prefix, so warm TTFT skips the prefill. The holder's pool is
    then flooded until the entry EVICTS to the spill tier, and the
    prompt replayed once more: affinity still finds the holder (spilled
    heads advertise too) and the replica RESTORES the pages instead of
    re-prefilling. The cost of losing affinity is measured directly —
    the same warm prompt fired at the cold sibling pays a full prefill:

      {"metric": "serving_affinity_warm_ttft_speedup", "value": ...,
       "unit": "x", "ttft_warm_ms": ..., "ttft_reroute_cold_ms": ...,
       "ttft_restore_ms": ..., "restore_speedup": ...,
       "cluster_prefix_hit_rate": ..., "gate_enforced": bool}

    Like --interference, the TTFT gates need real parallelism (the
    timing client and two servers contend for CPU on a 1-core host), so
    they are enforced only when `gate_enforced`; the mechanism gates —
    affinity hits, a real spill restore, byte-identical outputs — hold
    everywhere.
    """
    import os

    import jax

    from polyaxon_tpu.serving.router import Router

    page_tokens, pool_pages = 8, 24
    servers = [
        build_server(
            True, max_batch, max_wait_ms, kv_pool_pages=pool_pages,
            kv_page_tokens=page_tokens, spill_ram_bytes=32 << 20,
        )
        for _ in range(2)
    ]
    ports = [s.start(port=0) for s in servers]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    router = Router(urls, poll_interval_s=0.25)
    rport = router.start(port=0)
    try:
        rng = random.Random(seed)
        vocab = MODEL_CFG["vocab_size"]
        plen, new = 49, 6  # 6 full pages cached, tail + decode computed

        def prompt() -> list[int]:
            return [rng.randrange(vocab) for _ in range(plen)]

        def body(toks: list[int]) -> dict:
            return {"tokens": [toks], "maxNewTokens": new,
                    "temperature": 0.0, "seed": 7}

        target = prompt()
        # pay every compile outside the timed samples: same shapes,
        # disjoint token content (no accidental prefix sharing)
        for u, p in zip(urls, ports):
            _post(u + "/generate", body(prompt()))
            _stream_ttft("127.0.0.1", p, body(prompt()))

        ttft_cold, toks_cold = _stream_ttft(
            "127.0.0.1", rport, body(target)
        )
        router.poll_once()  # pick up the holder's /kvz advertisement
        ttft_warm, toks_warm = _stream_ttft(
            "127.0.0.1", rport, body(target)
        )
        rstats = router.stats()
        holder = max(rstats["replicas"], key=lambda r: r["requests"])
        hi = int(holder["slug"][1:])
        affinity_hits = rstats["affinity"]["hits"]

        # flood the holder until the target entry evicts into the spill
        # tier (pool holds ~4 six-page entries; 6 distinct prompts
        # guarantee LRU pushes the target out)
        for _ in range(6):
            _post(urls[hi] + "/generate", body(prompt()))
        router.poll_once()  # spilled head must re-advertise before replay
        ttft_restore, toks_restore = _stream_ttft(
            "127.0.0.1", rport, body(target)
        )
        hstats = json.loads(urllib.request.urlopen(
            urls[hi] + "/statsz", timeout=30).read())
        spill = hstats["kv"]["spill"]
        affinity_hits_after = router.stats()["affinity"]["hits"]

        # forced re-route: the SAME warm prompt on the cold sibling pays
        # a full prefill — the TTFT affinity routing avoids
        ttft_reroute, toks_reroute = _stream_ttft(
            "127.0.0.1", ports[1 - hi], body(target)
        )

        cluster = router.cluster_stats()
        cores = len(os.sched_getaffinity(0))
        device = jax.devices()[0]
        identical = (
            toks_cold == toks_warm == toks_restore == toks_reroute
        )
        return {
            "metric": "serving_affinity_warm_ttft_speedup",
            "value": round(ttft_reroute / ttft_warm, 2) if ttft_warm else None,
            "unit": "x",
            "ttft_cold_ms": round(ttft_cold * 1000, 1),
            "ttft_warm_ms": round(ttft_warm * 1000, 1),
            "ttft_restore_ms": round(ttft_restore * 1000, 1),
            "ttft_reroute_cold_ms": round(ttft_reroute * 1000, 1),
            "restore_speedup": (
                round(ttft_reroute / ttft_restore, 2) if ttft_restore else None
            ),
            "affinity_hits": affinity_hits_after,
            "spills": spill["spills"],
            "spill_restores": spill["restores"],
            "spilled_bytes": spill["spilled_bytes"],
            "cluster_prefix_hit_rate": cluster["prefix_hit_rate"],
            "byte_identical": identical,
            "prompt_tokens": plen,
            "page_tokens": page_tokens,
            "pool_pages": pool_pages,
            "host_cores": cores,
            # 1-core hosts bury the prefill-skip win under CPU contention
            # between the timing client and two servers (same physics as
            # --interference) — report honestly, gate where it can express
            "gate_enforced": cores >= 2,
            "platform": device.platform,
            "device_kind": device.device_kind,
        }
    finally:
        router.stop()
        for s in servers:
            s.stop()


def drive_tenants(clients: int, requests: int, max_batch: int,
                  max_wait_ms: float, repeats: int, seed: int,
                  smoke: bool) -> list[dict]:
    """ISSUE 19 records: noisy-neighbor isolation + adapter hot-swap cost.

    Isolation: one server with per-tenant admission — `noisy` capped at 2
    outstanding, `victim` uncapped. The victim's steady sequential trickle
    is timed twice per round: alone, then under a closed-loop noisy flood
    (the flood mostly sheds `tenant_quota`; the admitted residue rides the
    victim's batches). `value` is the best round's contended/alone p95
    ratio. Mechanism gates hold everywhere — the flood really shed, every
    noisy shed says `tenant_quota`, the victim never shed; the ratio gate
    needs cores (flood threads and the decode worker fight for one core).

    Swap cost: two LoRA servers, both alive, passes interleaved
    on/off/on/off, min-of-repeats (drive_trace_overhead's methodology) —
    one multiplexing three seeded adapters across resident slots (every
    request pins its tenant's slot and the decode gathers per-row), one
    plain (no slot axis, no registry). The p95 delta is the multiplexing
    tax and must stay within 10% in smoke. A sequential churn phase then
    rotates three adapters through TWO hot slots so every rotation pays a
    real evict→spill→restore cycle, pricing the swap itself
    (`swap_p50_ms` vs `resident_p50_ms`)."""
    import os

    import jax

    rng = random.Random(seed)
    vocab = MODEL_CFG["vocab_size"]

    def body(req_seed: int, tenant: str = "", new: int = 8) -> dict:
        b = {"tokens": [[rng.randrange(vocab) for _ in range(16)]],
             "maxNewTokens": new, "temperature": 0.0, "seed": req_seed}
        if tenant:
            b["tenant"] = tenant
        return b

    def warm_post(url: str, b: dict):
        try:
            _post(url, b)
        except urllib.error.HTTPError as e:
            e.read()  # capped tenants legitimately shed warmup bursts

    def warm(url: str, tenant: str = ""):
        # pay every batch-bucket compile outside the timed windows: the
        # contended/multiplexed passes coalesce up to max_batch rows
        burst = 1
        while burst <= max_batch:
            bodies = [body(s, tenant=tenant) for s in range(burst)]
            ws = [
                threading.Thread(target=warm_post, args=(url, b), daemon=True)
                for b in bodies
            ]
            for t in ws:
                t.start()
            for t in ws:
                t.join()
            burst *= 2

    def timed_post(url: str, b: dict) -> float:
        t0 = time.perf_counter()
        _post(url, b)
        return (time.perf_counter() - t0) * 1e3

    # ---- record 1: tenant isolation under a noisy-neighbor flood ------
    iso = build_server(
        True, max_batch, max_wait_ms,
        tenants=[{"name": "noisy", "max_outstanding": 2},
                 {"name": "victim"}],
    )
    port = iso.start(port=0)
    url = f"http://127.0.0.1:{port}/generate"
    n_victim = max(8, requests // 2)
    victim_bodies = [body(1000 + i, tenant="victim") for i in range(n_victim)]
    noisy_shed = 0
    noisy_ok = 0
    noisy_reasons: dict[str, int] = {}
    victim_shed = 0
    victim_errors = 0
    try:
        warm(url, tenant="victim")
        warm(url, tenant="noisy")

        def victim_pass() -> list[float]:
            # a shed or error against the UNCAPPED victim is an isolation
            # break — count it (the mechanism gate requires zero) and keep
            # driving so the record still reports the full picture
            nonlocal victim_shed, victim_errors
            lats = []
            for b in victim_bodies:
                t0 = time.perf_counter()
                try:
                    _post(url, b)
                    lats.append((time.perf_counter() - t0) * 1e3)
                except urllib.error.HTTPError as e:
                    e.read()
                    victim_shed += 1
                except Exception:  # noqa: BLE001 — counted, not fatal
                    victim_errors += 1
            return lats

        best = None
        for _ in range(repeats):
            alone = sorted(victim_pass())
            # closed-loop flood: each thread hammers `noisy` until the
            # victim pass drains; over-cap posts shed instantly (503)
            stop = threading.Event()
            lock = threading.Lock()

            def flood(k: int):
                nonlocal noisy_shed, noisy_ok
                i = 0
                while not stop.is_set():
                    i += 1
                    try:
                        _post(url, body(5000 + k * 10000 + i,
                                        tenant="noisy"))
                        with lock:
                            noisy_ok += 1
                    except urllib.error.HTTPError as e:
                        try:
                            reason = json.loads(e.read()).get("reason")
                        except Exception:  # noqa: BLE001
                            reason = None
                        with lock:
                            noisy_shed += 1
                            key = reason or f"http_{e.code}"
                            noisy_reasons[key] = (
                                noisy_reasons.get(key, 0) + 1
                            )
                    except Exception:  # noqa: BLE001 — flood is best-effort
                        pass

            floods = [
                threading.Thread(target=flood, args=(k,), daemon=True)
                for k in range(max(2, clients - 1))
            ]
            for t in floods:
                t.start()
            try:
                contended = sorted(victim_pass())
            finally:
                stop.set()
                for t in floods:
                    t.join()
            if not contended:
                contended = alone
            p95_a = quantile(alone, 0.95)
            p95_c = quantile(contended, 0.95)
            ratio = p95_c / p95_a if p95_a > 0 else None
            if ratio is not None and (best is None or ratio < best[0]):
                best = (ratio, alone, contended)
    finally:
        iso.stop()
    ratio, alone, contended = best
    cores = len(os.sched_getaffinity(0))
    device = jax.devices()[0]
    iso_rec = {
        "metric": "serving_tenant_isolation_p95_ratio",
        "value": round(ratio, 2),
        "unit": "x",
        "victim_p50_alone_ms": round(quantile(alone, 0.5), 1),
        "victim_p95_alone_ms": round(quantile(alone, 0.95), 1),
        "victim_p50_contended_ms": round(quantile(contended, 0.5), 1),
        "victim_p95_contended_ms": round(quantile(contended, 0.95), 1),
        "victim_requests": n_victim,
        "victim_shed": victim_shed,
        "victim_errors": victim_errors,
        "noisy_ok": noisy_ok,
        "noisy_shed": noisy_shed,
        "noisy_shed_reasons": noisy_reasons,
        "noisy_max_outstanding": 2,
        "flood_clients": max(2, clients - 1),
        "repeats": repeats,
        "host_cores": cores,
        # flood threads, the victim's timing loop and the decode worker
        # all fight for CPU on a 1-core host (see --interference) —
        # report honestly, gate the ratio only where it can express
        "gate_enforced": cores >= 2,
        "platform": device.platform,
        "device_kind": device.device_kind,
    }

    # ---- record 2: adapter multiplexing tax + the price of one swap ---
    adapters = {"acme": "seed:1", "beta": "seed:2", "gamma": "seed:3"}
    multi = build_server(
        True, max_batch, max_wait_ms, lora_rank=4,
        adapters=adapters, adapter_slots=2,
        tenants=[{"name": n, "adapter": n} for n in adapters],
    )
    solo = build_server(True, max_batch, max_wait_ms, lora_rank=4)
    murl = f"http://127.0.0.1:{multi.start(port=0)}/generate"
    surl = f"http://127.0.0.1:{solo.start(port=0)}/generate"
    # the timed passes rotate the TWO resident tenants only, so they
    # price the steady-state multiplexing tax (per-row slot gather +
    # registry pin/unpin), not cold loads; the churn phase below brings
    # in the third adapter and prices the swaps explicitly
    hot = ("acme", "beta")
    traffic = [(2000 + i, hot[i % len(hot)]) for i in range(requests)]

    def one_pass(url: str, tenanted: bool) -> list[float]:
        shards = [traffic[i::clients] for i in range(clients)]
        lats: list[float] = []
        lock = threading.Lock()

        def client(shard):
            for s, tenant in shard:
                dt = timed_post(url, body(s, tenant=tenant if tenanted else ""))
                with lock:
                    lats.append(dt)

        threads = [
            threading.Thread(target=client, args=(sh,), daemon=True)
            for sh in shards if sh
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return lats

    try:
        for tenant in hot:
            warm(murl, tenant=tenant)
        warm(surl)
        best_p95: dict = {}
        for _ in range(repeats):
            for label, url, tenanted in (
                ("multi", murl, True), ("solo", surl, False),
            ):
                lat = sorted(one_pass(url, tenanted))
                p95 = quantile(lat, 0.95)
                if label not in best_p95 or p95 < best_p95[label][0]:
                    best_p95[label] = (p95, lat)

        # churn: sequential rotation through all three adapters with only
        # two hot slots — every third-tenant request evicts the LRU idle
        # adapter (demoting its bytes to the spill tier) and, after the
        # first cycle, restores the incoming one from spill
        rotations = 2 if smoke else 4
        swap_lat: list[float] = []
        for r in range(rotations):
            for tenant in ("gamma", "acme", "beta"):
                swap_lat.append(
                    timed_post(murl, body(7000 + r, tenant=tenant))
                )
        resident_lat = sorted(
            timed_post(murl, body(8000 + i, tenant="beta"))
            for i in range(len(swap_lat))
        )
        stats = json.loads(urllib.request.urlopen(
            murl.replace("/generate", "/statsz"), timeout=30).read())
    finally:
        multi.stop()
        solo.stop()
    reg = stats["tenancy"]["adapters"]
    p95_multi, _ = best_p95["multi"]
    p95_solo, _ = best_p95["solo"]
    overhead = (
        (p95_multi - p95_solo) / p95_solo * 100 if p95_solo > 0 else 0.0
    )
    swap_sorted = sorted(swap_lat)
    swap_rec = {
        "metric": "serving_adapter_swap_overhead",
        "value": round(overhead, 2),
        "unit": "%",
        "p95_multi_ms": round(p95_multi, 2),
        "p95_solo_ms": round(p95_solo, 2),
        "adapters": len(adapters),
        "adapter_slots": 2,
        "adapters_resident": reg["resident"],
        "swap_p50_ms": round(quantile(swap_sorted, 0.5), 2),
        "resident_p50_ms": round(quantile(resident_lat, 0.5), 2),
        "swap_requests": len(swap_lat),
        "swap_loads": reg["loads"],
        "swap_evictions": reg["evictions"],
        "swap_restores": reg["restores"],
        "clients": clients,
        "requests": requests,
        "repeats": repeats,
        "host_cores": cores,
        "platform": device.platform,
        "device_kind": device.device_kind,
    }
    return [iso_rec, swap_rec]


def serve_replica(port: int, max_batch: int, max_wait_ms: float) -> int:
    """`--serve-replica` self-mode: one replica process. Every replica
    builds the SAME model from PRNGKey(0), so responses are
    byte-identical across the fleet — the property the router's
    failover and the bench's identity check both rest on."""
    import signal

    server = build_server(True, max_batch, max_wait_ms)
    server.start(port=port)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.stop()
    return 0


def _raw_post(base: str, body: dict, rid: str, stream: bool = False,
              timeout: float = 300.0) -> bytes:
    """POST /generate with a pinned X-Request-Id and return the exact
    response bytes. The replica embeds the request id in the payload, so
    byte-identity between the direct and routed paths holds only when
    both carry the same id."""
    path = "/generate?stream=1" if stream else "/generate"
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", "X-Request-Id": rid},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()


def drive_router(replicas: int, clients: int, requests: int, max_batch: int,
                 max_wait_ms: float, seed: int, smoke: bool) -> list[dict]:
    """ISSUE 10 records: aggregate req/s scaling behind the router vs one
    direct replica, router-added latency, and byte-identity across the
    two paths. Replicas are subprocesses (real parallelism, the fleet's
    actual deployment shape); the router runs in this process."""
    import os

    from polyaxon_tpu.serving.replicas import SubprocessReplica
    from polyaxon_tpu.serving.router import P2CBalancer, Router

    script = str(Path(__file__).resolve())

    def argv(port: int) -> list[str]:
        return [
            sys.executable, script, "--serve-replica", "--port", str(port),
            "--max-batch", str(max_batch), "--max-wait-ms", str(max_wait_ms),
        ]

    reps = [
        SubprocessReplica(argv, ready_timeout_s=300.0)
        for _ in range(replicas)
    ]
    router = None
    try:
        # parallel starts: each child pays its own jax import + compile
        urls: list = [None] * replicas
        errs: list = []

        def boot(i):
            try:
                urls[i] = reps[i].start()
            except Exception as e:  # noqa: BLE001 — surface after join
                errs.append(e)

        threads = [
            threading.Thread(target=boot, args=(i,)) for i in range(replicas)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

        router = Router(
            urls, balancer=P2CBalancer(seed=seed), poll_interval_s=0.5
        )
        router_url = f"http://127.0.0.1:{router.start(port=0)}"
        router.poll_once()

        rng = random.Random(seed)

        def body(req_seed: int, new: int = 6, temp: float = 0.8) -> dict:
            b = {
                "tokens": [[rng.randrange(MODEL_CFG["vocab_size"])
                            for _ in range(16)]],
                "maxNewTokens": new,
                "seed": req_seed,
            }
            if temp > 0:
                b.update(temperature=temp, topK=40)
            else:
                b["temperature"] = 0.0
            return b

        # warm every replica through every shape the passes will use:
        # the scaling pass coalesces up to max_batch rows, so each batch
        # bucket must compile now, not inside a timed window
        for base in urls:
            for burst in (1, max_batch):
                bodies = [body(s, new=6) for s in range(burst)]
                ws = [
                    threading.Thread(
                        target=_post, args=(base + "/generate", b)
                    )
                    for b in bodies
                ]
                for t in ws:
                    t.start()
                for t in ws:
                    t.join()
            _post(base + "/generate", body(0, new=16))
            _post(base + "/generate", body(0, new=6, temp=0.0))
            _raw_post(base, body(0, new=6), "warm-stream", stream=True)

        # --- byte-identity: greedy + sampled, streamed + not, same rid
        identical = True
        combos = [(t, s) for t in (0.0, 0.8) for s in (False, True)]
        for idx, (temp, stream) in enumerate(combos):
            b = body(1000 + idx, new=6, temp=temp)
            rid = f"bench-ident-{idx}"
            direct = _raw_post(urls[0], b, rid, stream=stream)
            routed = _raw_post(router_url, b, rid, stream=stream)
            identical = identical and direct == routed

        # --- router-added latency: interleaved sequential samples so
        # host-load drift hits both paths equally; min-of-repeats per
        # drive_trace_overhead's methodology
        ob = body(0, new=16)
        samples = 12 if smoke else 20
        best = None
        for _ in range(2):
            direct_ms, routed_ms = [], []
            for _ in range(samples):
                t0 = time.perf_counter()
                _post(urls[0] + "/generate", ob)
                direct_ms.append((time.perf_counter() - t0) * 1e3)
                t0 = time.perf_counter()
                _post(router_url + "/generate", ob)
                routed_ms.append((time.perf_counter() - t0) * 1e3)
            direct_ms.sort()
            routed_ms.sort()
            p = {
                "p50_direct_ms": round(quantile(direct_ms, 0.5), 2),
                "p95_direct_ms": round(quantile(direct_ms, 0.95), 2),
                "p50_router_ms": round(quantile(routed_ms, 0.5), 2),
                "p95_router_ms": round(quantile(routed_ms, 0.95), 2),
            }
            over = (
                (p["p95_router_ms"] - p["p95_direct_ms"])
                / p["p95_direct_ms"] * 100
            )
            if best is None or over < best[0]:
                best = (over, p)
        overhead_rec = {
            "metric": "router_latency_overhead",
            "value": round(best[0], 2),
            "unit": "%",
            **best[1],
            "samples": samples,
            "repeats": 2,
            "byte_identical": identical,
        }

        # --- aggregate scaling: the same closed-loop traffic once against
        # a single replica directly, once through the router over all N
        n_req = max(requests, 6 * clients)
        traffic = [body(i, new=6) for i in range(n_req)]

        def closed_loop(base: str) -> tuple[float, int, int]:
            shards = [traffic[i::clients] for i in range(clients)]
            done, errors = [], []
            lock = threading.Lock()

            def client(shard):
                for b in shard:
                    try:
                        _post(base + "/generate", b)
                        with lock:
                            done.append(1)
                    except Exception as e:  # noqa: BLE001 — count
                        with lock:
                            errors.append(f"{type(e).__name__}: {e}"[:200])

            ts = [
                threading.Thread(target=client, args=(s,), daemon=True)
                for s in shards if s
            ]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            return time.perf_counter() - t0, len(done), len(errors)

        single_wall, single_ok, single_err = closed_loop(urls[0])
        router_wall, router_ok, router_err = closed_loop(router_url)
        rps_single = single_ok / single_wall if single_wall > 0 else 0.0
        rps_router = router_ok / router_wall if router_wall > 0 else 0.0
        cores = len(os.sched_getaffinity(0))
        scale_rec = {
            "metric": "router_aggregate_speedup",
            "value": round(rps_router / rps_single, 2) if rps_single else None,
            "unit": "x",
            "replicas": replicas,
            "clients": clients,
            "requests": n_req,
            "req_per_sec_router": round(rps_router, 2),
            "req_per_sec_single_direct": round(rps_single, 2),
            "host_cores": cores,
            # two compute-bound replica processes cannot beat one on a
            # single core — the scaling gate needs real parallelism
            "gate_enforced": cores >= 2,
        }
        if single_err or router_err:
            scale_rec["errors"] = single_err + router_err
        # every router record carries the fleet's warm-KV picture, even
        # when the replicas run without a prefix cache (rate None) — the
        # field's presence is pinned by tests/test_benchmarks.py
        hit_rate = router.cluster_stats()["prefix_hit_rate"]
        scale_rec["cluster_prefix_hit_rate"] = hit_rate
        overhead_rec["cluster_prefix_hit_rate"] = hit_rate
        return [scale_rec, overhead_rec]
    finally:
        if router is not None:
            router.stop()
        for r in reps:
            try:
                r.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                r.kill()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--requests", type=int, default=96,
                    help="total requests per mode")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-wait-ms", type=float, default=20.0)
    ap.add_argument("--mode",
                    choices=("both", "batched", "per_request", "paged"),
                    default="both")
    ap.add_argument("--kv-pool-pages", type=int, default=256,
                    help="KV pool size for --mode paged / --shared-prefix")
    ap.add_argument("--shared-prefix", action="store_true",
                    help="run the prefix-reuse TTFT demonstration instead "
                         "of the traffic sweep")
    ap.add_argument("--speculate", action="store_true",
                    help="run the ISSUE 8 fast-decode demonstration "
                         "(speculative + int8 servers) instead of the "
                         "traffic sweep")
    ap.add_argument("--draft-tokens", type=int, default=8,
                    help="drafts per verify window for --speculate")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="run the ISSUE 9 tracing-overhead record "
                         "(trace on vs off, min-of-repeats) instead of "
                         "the traffic sweep")
    ap.add_argument("--repeats", type=int, default=3,
                    help="timed passes per config for --trace-overhead "
                         "and --federation-overhead")
    ap.add_argument("--history-overhead", action="store_true",
                    help="measure the metrics-history sampler's cost on "
                         "the serving path (history on vs off, "
                         "interleaved, min-of-repeats)")
    ap.add_argument("--federation-overhead", action="store_true",
                    help="run the ISSUE 13 observability-plane record "
                         "(router with stitching+federation on vs off, "
                         "min-of-repeats) instead of the traffic sweep")
    ap.add_argument("--interference", action="store_true",
                    help="run the ISSUE 14 chunked-prefill record: short-"
                         "request TTFT under a long-prompt mix, chunked "
                         "step scheduler vs one-blocking-execute")
    ap.add_argument("--disaggregated", action="store_true",
                    help="run the ISSUE 20 record: the interference "
                         "cohort across a prefill/decode pooled pair vs "
                         "a monolithic fleet, gated on live KV handoff "
                         "latency p95 and byte-identity across the split")
    ap.add_argument("--router", action="store_true",
                    help="run the ISSUE 10 horizontal-serving records "
                         "(replica processes behind serving/router.py) "
                         "instead of the traffic sweep")
    ap.add_argument("--affinity", action="store_true",
                    help="run the ISSUE 17 cluster-warm-KV record: "
                         "prefix-affinity routing TTFT vs a forced "
                         "re-route, plus the eviction→spill→restore "
                         "cycle on the holder")
    ap.add_argument("--tenants", action="store_true",
                    help="run the ISSUE 19 multi-tenant records: victim-"
                         "p95 isolation under a noisy-neighbor flood and "
                         "the adapter hot-swap overhead vs a plain LoRA "
                         "server")
    ap.add_argument("--replicas", type=int, default=2,
                    help="replica processes for --router")
    ap.add_argument("--serve-replica", action="store_true",
                    help=argparse.SUPPRESS)  # internal: replica self-mode
    ap.add_argument("--port", type=int, default=0,
                    help=argparse.SUPPRESS)  # internal: --serve-replica port
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="small CI configuration (4 clients, 12 requests)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.clients, args.requests = 4, 12

    # honor POLYAXON_JAX_PLATFORM=cpu BEFORE backend init (see
    # attention_bench.py — plain JAX_PLATFORMS loses to the TPU plugin)
    from polyaxon_tpu.utils.jax_platform import apply_platform_env

    apply_platform_env()

    if args.serve_replica:
        return serve_replica(args.port, args.max_batch, args.max_wait_ms)

    if args.router:
        recs = drive_router(
            args.replicas, args.clients, args.requests, args.max_batch,
            args.max_wait_ms, args.seed, args.smoke,
        )
        for rec in recs:
            print(json.dumps(rec), flush=True)
        scale, overhead = recs
        ok = overhead["byte_identical"] and not scale.get("errors")
        if args.smoke:
            # the smoke gates: scaling where physics allows it, router
            # overhead and byte-identity everywhere
            if overhead["value"] > 10.0:
                ok = False
            if scale["gate_enforced"] and (scale["value"] or 0) < 1.7:
                ok = False
        return 0 if ok else 1

    if args.tenants:
        recs = drive_tenants(
            args.clients, args.requests, args.max_batch, args.max_wait_ms,
            args.repeats, args.seed, args.smoke,
        )
        for rec in recs:
            print(json.dumps(rec), flush=True)
        iso, swap = recs
        # mechanism gates hold everywhere: the flood really shed, every
        # noisy shed was attributed to the tenant's own quota, the
        # uncapped victim never shed or errored, and the churn phase ran
        # real evict→spill→restore cycles; timing gates only in smoke
        # (and the isolation ratio only where the host has cores)
        ok = (
            iso["noisy_shed"] > 0
            and set(iso["noisy_shed_reasons"]) == {"tenant_quota"}
            and iso["victim_shed"] == 0
            and iso["victim_errors"] == 0
            and swap["swap_evictions"] >= 1
            and swap["swap_restores"] >= 1
        )
        if args.smoke:
            if swap["value"] > 10.0:
                ok = False
            if iso["gate_enforced"] and (iso["value"] or 0) > 3.0:
                ok = False
        return 0 if ok else 1

    if args.affinity:
        rec = drive_affinity(
            args.max_batch, args.max_wait_ms, args.seed, args.smoke,
        )
        print(json.dumps(rec), flush=True)
        # mechanism gates hold everywhere: the warm replay must have been
        # affinity-routed, the eviction must have spilled AND restored,
        # and every path must agree byte-for-byte; TTFT gates only where
        # the host has cores to express them
        ok = (
            rec["affinity_hits"] >= 2
            and rec["spills"] >= 1
            and rec["spill_restores"] >= 1
            and rec["byte_identical"]
            and (rec["cluster_prefix_hit_rate"] or 0) > 0
        )
        if args.smoke and rec["gate_enforced"]:
            if (rec["value"] or 0) < 1.2 or (rec["restore_speedup"] or 0) < 1.0:
                ok = False
        return 0 if ok else 1

    if args.disaggregated:
        rounds, shorts = (2, 3) if args.smoke else (4, 4)
        rec = drive_disaggregated(
            rounds, shorts, args.max_batch, args.max_wait_ms, args.seed,
            args.smoke,
        )
        print(json.dumps(rec), flush=True)
        # mechanism gates hold everywhere: the pooled pair must have run
        # REAL handoffs (a pair that quietly decodes monolithically is
        # not evidence), every lease must have completed, and the split
        # may not change a byte; the latency gate needs cores
        ok = (
            rec["handoff_exports"] >= 1
            and rec["handoff_imports"] >= 1
            and rec["handoff_fallbacks"] == 0
            and rec["lease_completed"] >= 1
            and rec["byte_identical"]
        )
        if args.smoke and rec["gate_enforced"]:
            if rec["value"] is None or rec["value"] > 250.0:
                ok = False
        return 0 if ok else 1

    if args.interference:
        rounds, shorts = (2, 3) if args.smoke else (4, 4)
        rec = drive_interference(
            rounds, shorts, args.max_batch, args.max_wait_ms,
            args.kv_pool_pages, args.seed,
        )
        print(json.dumps(rec), flush=True)
        # the record must show the step scheduler actually ran (chunks
        # landed); the >=2x TTFT gate needs cores the host may not have
        ok = rec["prefill_chunks"] > 0 and rec["steps"] > 0
        if args.smoke and rec["gate_enforced"] and (rec["value"] or 0) < 2.0:
            ok = False
        return 0 if ok else 1

    if args.shared_prefix:
        warm = 4 if args.smoke else 12
        rec = drive_shared_prefix(
            warm, args.max_batch, args.max_wait_ms, args.kv_pool_pages,
            args.seed,
        )
        print(json.dumps(rec), flush=True)
        return 0 if rec["prefix_hit_rate"] > 0 else 1

    if args.federation_overhead:
        rec = drive_federation_overhead(
            make_traffic(args.requests, args.seed), args.clients,
            args.max_batch, args.max_wait_ms, args.repeats, args.seed,
        )
        rec["trace_seed"] = args.seed
        print(json.dumps(rec), flush=True)
        # the record must demonstrate the observability plane is near
        # free on the routed path AND that it actually ran (federated
        # series present); only the smoke configuration gates on cost
        ok = rec["federated_series"] and rec["cluster_aggregates"]
        if args.smoke and rec["value"] > 5.0:
            ok = False
        return 0 if ok else 1

    if args.history_overhead:
        rec = drive_history_overhead(
            make_traffic(args.requests, args.seed), args.clients,
            args.max_batch, args.max_wait_ms, args.repeats,
        )
        rec["trace_seed"] = args.seed
        print(json.dumps(rec), flush=True)
        # the record must demonstrate history capture is near free AND
        # that it actually sampled; only the smoke configuration gates
        # on cost (full runs just report)
        ok = rec["history_samples"] > 0
        if args.smoke and rec["value"] > 5.0:
            ok = False
        return 0 if ok else 1

    if args.trace_overhead:
        rec = drive_trace_overhead(
            make_traffic(args.requests, args.seed), args.clients,
            args.max_batch, args.max_wait_ms, args.repeats,
        )
        rec["trace_seed"] = args.seed
        print(json.dumps(rec), flush=True)
        # the record must demonstrate tracing is effectively free; only
        # the smoke configuration gates (full runs just report)
        return 1 if args.smoke and rec["value"] > 5.0 else 0

    if args.speculate:
        recs = drive_fast_decode(
            4 if args.smoke else 12, args.draft_tokens, args.kv_pool_pages,
        )
        for rec in recs:
            print(json.dumps(rec), flush=True)
        spec = recs[0]
        # the demonstration must actually demonstrate: drafts accepted
        # and outputs untouched by speculation
        ok = spec["identical_outputs"] and spec["accepted"] > 0
        return 0 if ok else 1

    traffic = make_traffic(args.requests, args.seed)
    modes = (
        ("per_request", "batched") if args.mode == "both" else (args.mode,)
    )
    recs = {}
    for mode in modes:
        recs[mode] = drive(
            mode, traffic, args.clients, args.max_batch, args.max_wait_ms,
            kv_pool_pages=args.kv_pool_pages,
        )
        recs[mode]["trace_seed"] = args.seed
        print(json.dumps(recs[mode]), flush=True)
    if len(recs) == 2 and recs["per_request"]["value"] > 0:
        print(
            json.dumps(
                {
                    "metric": "serving_batched_speedup",
                    "value": round(
                        recs["batched"]["value"] / recs["per_request"]["value"],
                        2,
                    ),
                    "unit": "x",
                    "clients": args.clients,
                    "requests": args.requests,
                    "compiles_batched": recs["batched"]["compile_count"],
                    "compiles_per_request": recs["per_request"]["compile_count"],
                    "platform": recs["batched"]["platform"],
                }
            ),
            flush=True,
        )
    failed = [m for m, r in recs.items() if r.get("errors")]
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
