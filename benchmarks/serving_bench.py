"""Serving fast-path benchmark: live HTTP server under concurrent clients.

Measures the two layers ISSUE 2 added to `serving/` end to end, over the
wire, against the same server code `polyaxon serve` runs:

  * per_request mode (`ServingConfig(batching=False)`) — the legacy path:
    one exact-shape jitted program per request signature, one device
    dispatch per request. A randomized traffic mix recompiles constantly.
  * batched mode — shape-bucketed compile cache (prompts LEFT-pad up a
    geometric ladder; `prompt_lengths`/seeds are runtime [B] args) plus a
    decode worker coalescing compatible requests up to `max_batch` /
    `max_wait_ms`.

Each mode drives its own server with N concurrent clients posting
randomized (prompt_len, max_new, seed) requests, then reads GET /statsz.
Prints one JSON line per mode plus a speedup line, in the same schema
family as the other benches (tests/test_bench_script.py pins it):

  {"metric": "serving_requests_per_sec", "value": ..., "unit": "req/s",
   "mode": "batched", "clients": 16, "requests": 96, "p50_ms": ...,
   "p95_ms": ..., "compile_count": 4, "batches": ...,
   "mean_batch_occupancy": ..., "platform": ..., "device_kind": ...}
  {"metric": "serving_batched_speedup", "value": 3.1, "unit": "x", ...}

  python benchmarks/serving_bench.py                 # full: 16 clients
  python benchmarks/serving_bench.py --smoke         # CI smoke: 4 clients
  python benchmarks/serving_bench.py --mode batched  # one side only
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from polyaxon_tpu.telemetry import quantile  # noqa: E402 (needs sys.path)

MODEL_CFG = {
    "preset": "tiny", "seq_len": 128, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 256,
}


def _post(url: str, body: dict, timeout: float = 300.0) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def make_traffic(n_requests: int, seed: int) -> list[dict]:
    """Deterministic randomized request mix. Lengths are drawn from a
    modest pool of distinct values — enough variety that the exact-shape
    baseline keeps recompiling, small enough that the full run finishes
    on CPU (every distinct (P, new) pair is ~one XLA compile there)."""
    rng = random.Random(seed)
    lengths = rng.sample(range(4, 49), 12)
    news = [4, 6, 8]
    out = []
    for i in range(n_requests):
        plen = rng.choice(lengths)
        out.append(
            {
                "tokens": [
                    [rng.randrange(MODEL_CFG["vocab_size"]) for _ in range(plen)]
                ],
                "maxNewTokens": rng.choice(news),
                "temperature": 0.8,
                "topK": 40,
                "seed": i,
            }
        )
    return out


def build_server(batching: bool, max_batch: int, max_wait_ms: float):
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    bundle = build_model("transformer_lm", MODEL_CFG)
    params = bundle.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return ModelServer(
        bundle.module,
        params,
        model_name="serving-bench",
        config=ServingConfig(
            batching=batching, max_batch=max_batch, max_wait_ms=max_wait_ms
        ),
    )


def drive(mode: str, traffic: list[dict], clients: int, max_batch: int,
          max_wait_ms: float) -> dict:
    """Run one server in `mode`, fire the traffic from `clients` threads,
    return the stats record."""
    server = build_server(mode == "batched", max_batch, max_wait_ms)
    port = server.start(port=0)
    url = f"http://127.0.0.1:{port}/generate"
    # round-robin the SAME traffic across client threads so both modes see
    # an identical request multiset regardless of thread scheduling
    shards = [traffic[i::clients] for i in range(clients)]
    latencies: list[float] = []
    errors: list[str] = []
    lock = threading.Lock()

    def client(shard: list[dict]):
        for body in shard:
            t0 = time.perf_counter()
            try:
                out = _post(url, body)
                dt = time.perf_counter() - t0
                row = out["tokens"][0]
                want = len(body["tokens"][0]) + body["maxNewTokens"]
                if len(row) != want:
                    raise AssertionError(
                        f"row length {len(row)} != prompt+new {want}"
                    )
                with lock:
                    latencies.append(dt)
            except Exception as e:  # noqa: BLE001 — count, keep driving
                with lock:
                    errors.append(f"{type(e).__name__}: {e}"[:200])

    threads = [
        threading.Thread(target=client, args=(s,), daemon=True)
        for s in shards if s
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    stats = json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statsz", timeout=30
        ).read()
    )
    server.stop()

    import jax

    device = jax.devices()[0]
    lat_ms = sorted(l * 1e3 for l in latencies)
    rec = {
        "metric": "serving_requests_per_sec",
        "value": round(len(latencies) / wall, 2) if wall > 0 else 0.0,
        "unit": "req/s",
        "mode": mode,
        "clients": clients,
        "requests": len(latencies),
        "wall_s": round(wall, 2),
        "p50_ms": round(quantile(lat_ms, 0.5), 1) if lat_ms else None,
        "p95_ms": round(quantile(lat_ms, 0.95), 1) if lat_ms else None,
        "compile_count": stats["compile_count"],
        "batches": stats["batches"],
        "mean_batch_occupancy": stats["mean_batch_occupancy"],
        "platform": device.platform,
        "device_kind": device.device_kind,
    }
    if errors:
        rec["errors"] = len(errors)
        rec["first_error"] = errors[0]
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--requests", type=int, default=96,
                    help="total requests per mode")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-wait-ms", type=float, default=20.0)
    ap.add_argument("--mode", choices=("both", "batched", "per_request"),
                    default="both")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="small CI configuration (4 clients, 12 requests)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.clients, args.requests = 4, 12

    # honor POLYAXON_JAX_PLATFORM=cpu BEFORE backend init (see
    # attention_bench.py — plain JAX_PLATFORMS loses to the TPU plugin)
    from polyaxon_tpu.utils.jax_platform import apply_platform_env

    apply_platform_env()

    traffic = make_traffic(args.requests, args.seed)
    modes = (
        ("per_request", "batched") if args.mode == "both" else (args.mode,)
    )
    recs = {}
    for mode in modes:
        recs[mode] = drive(
            mode, traffic, args.clients, args.max_batch, args.max_wait_ms
        )
        print(json.dumps(recs[mode]), flush=True)
    if len(recs) == 2 and recs["per_request"]["value"] > 0:
        print(
            json.dumps(
                {
                    "metric": "serving_batched_speedup",
                    "value": round(
                        recs["batched"]["value"] / recs["per_request"]["value"],
                        2,
                    ),
                    "unit": "x",
                    "clients": args.clients,
                    "requests": args.requests,
                    "compiles_batched": recs["batched"]["compile_count"],
                    "compiles_per_request": recs["per_request"]["compile_count"],
                    "platform": recs["batched"]["platform"],
                }
            ),
            flush=True,
        )
    failed = [m for m, r in recs.items() if r.get("errors")]
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
