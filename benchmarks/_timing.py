"""Shared timing helper for the benchmark scripts (one methodology:
warmup call excluded, mean over iters, device-synced)."""

from __future__ import annotations

import time


def time_call(fn, *args, iters: int = 20) -> float:
    """Mean wall time per call over `iters` calls; one warmup call runs
    first so compile time is excluded."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters
