"""Shared timing helper for the benchmark scripts (one methodology:
warmup call excluded, mean over iters, device-synced).

Sync is a scalar FETCH, not jax.block_until_ready: under the axon TPU
tunnel block_until_ready returns before the device work finishes
(measured r5: 0.5 ms/call "timing" vs 221 ms/call real for a seq-4096
attention), silently inflating every number. Pulling one element of the
output forces completion of the whole dependency chain. The fetch's own
round-trip is measured afterwards (everything already done) and
subtracted, so tunnel latency doesn't bill against the kernel."""

from __future__ import annotations

import time


def _sync(out) -> float:
    """Force completion of `out`'s computation: fetch one element.

    Assumes everything being timed flows into ONE jitted executable whose
    outputs include this leaf: the fetch barriers that executable's whole
    dependency chain because the device runs its program to completion
    before materializing any output. Work dispatched by OTHER executables
    (or donated-buffer side effects) is not ordered before this fetch — a
    benchmark that interleaves several jit calls must fetch from the last
    one, or fall back to jax.block_until_ready on all of them."""
    import jax

    leaf = jax.tree.leaves(out)[0]
    return float(leaf.ravel()[0])


def summarize(samples_s) -> dict:
    """Distribution summary (count/mean/p50/p95/p99) of per-call latency
    samples. Delegates to `polyaxon_tpu.telemetry.summarize` — the one
    percentile implementation, shared with the servers' /statsz — so the
    benches and the serving layer can never disagree on what a percentile
    means. Bench scripts run with the repo root on sys.path, so the
    package import resolves."""
    from polyaxon_tpu.telemetry import summarize as _summarize

    return _summarize(list(samples_s))


def time_call(fn, *args, iters: int = 20) -> float:
    """Mean wall time per call over `iters` calls; one warmup call runs
    first so compile time is excluded."""
    out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    dt = time.perf_counter() - t0
    # fetch round-trip with no pending work — pure tunnel/transfer cost
    t0 = time.perf_counter()
    _sync(out)
    rtt = time.perf_counter() - t0
    return max(dt - rtt, 1e-9) / iters
