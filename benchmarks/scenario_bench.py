"""Scenario engine bench: named-scenario records + twin calibration.

Emits one JSON line per named scenario (twin mode: shed rate, p99,
hung, leaked pages — the fast, deterministic view of every scenario in
the registry), then validates the twin against the REAL serving stack:
a live 2-replica router rig replays a fixed-shape calibration trace,
`PhaseCosts.fit` extracts per-phase costs from the replicas' /metricsz
scrapes (warmup compiles subtracted via a baseline scrape), the twin
re-runs the same trace on those costs, and the disagreement is pinned:

  {"metric": "sim_vs_real_calibration_error", "value": ...,
   "pass": value <= 0.25, ...}

Finally the acceptance headliner: a million-request diurnal soak
through the twin, wall-clock pinned under 60 seconds on the 1-core CI
box.

  python benchmarks/scenario_bench.py            # full configuration
  python benchmarks/scenario_bench.py --smoke    # CI configuration
  python benchmarks/scenario_bench.py --smoke --twin-only  # no rig
  python benchmarks/scenario_bench.py --metricsz-out /tmp/m.txt
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from polyaxon_tpu.telemetry import parse_prometheus_text  # noqa: E402

CAL_PROMPT_LEN = 24  # one shape -> one bucket -> one compile pair, so
CAL_MAX_NEW = 12     # the fitted costs are steady-state, not compile noise


def twin_records(smoke: bool) -> list[dict]:
    """One record per named scenario, twin mode — deterministic, fast."""
    from polyaxon_tpu.scenarios.registry import SCENARIOS, run_twin

    out = []
    for name, scn in SCENARIOS.items():
        if scn.twin_only:
            continue  # the soak record below IS its record (wall pinned)
        t0 = time.perf_counter()
        res = run_twin(scn, smoke=smoke)
        wall = time.perf_counter() - t0
        s = res["summary"]
        rec = {
            "metric": "scenario_twin",
            "scenario": name,
            "value": s["shed_rate"],
            "unit": "shed_rate",
            "offered": s["offered"],
            "ok": s["ok"],
            "shed": s["shed"],
            "disconnected": s["disconnected"],
            "error": s["error"],
            "hung": s["hung"],
            "kv_pages_leaked": s["kv_pages_leaked"],
            "p99_ms": s["latency_ms"]["p99"],
            "slo_burn": None,  # twin models no SLO engine; real runs do
            "sim_duration_s": s["sim_duration_s"],
            "wall_s": round(wall, 2),
            "trace_seed": res["seed"],
            "pass": res["pass"],
        }
        if not res["pass"]:
            rec["failures"] = [
                v["detail"] for v in res["assertions"] if not v["ok"]
            ]
        out.append(rec)
    return out


def calibrate(smoke: bool, metricsz_out: str | None) -> list[dict]:
    """Real-stack calibration: replay a fixed-shape trace against a live
    2-replica rig, fit PhaseCosts from the scrapes, re-run the twin on
    the same trace, pin the disagreement."""
    from polyaxon_tpu.scenarios.driver import replay
    from polyaxon_tpu.scenarios.registry import (
        RIG_MODEL_CFG, _wait_drained, build_rig, calibration_error,
    )
    from polyaxon_tpu.scenarios.traces import body_for, flood
    from polyaxon_tpu.scenarios.twin import PhaseCosts, ServingTwin, TwinConfig

    n = 16 if smoke else 60
    rps = 4.0 if smoke else 8.0
    vocab = RIG_MODEL_CFG["vocab_size"]
    rig = build_rig(replicas=2)
    try:
        # warm EVERY replica's compile cache with the calibration shape,
        # then scrape the baseline so fit() sees only steady-state costs
        warm = next(iter(flood(
            99, n=1, rps=1.0, prompt_len=CAL_PROMPT_LEN, max_new=CAL_MAX_NEW
        )))
        for url in rig.mgr.endpoints():
            req = urllib.request.Request(
                url + "/generate",
                data=json.dumps(body_for(warm, vocab)).encode(),
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=300.0).read()
        baseline = rig.replica_metricsz()

        records = list(flood(
            1, n=n, rps=rps, prompt_len=CAL_PROMPT_LEN, max_new=CAL_MAX_NEW
        ))
        report = replay(records, rig.url, vocab_size=vocab,
                        rid_prefix="cal")
        texts = [t for t in _wait_drained(rig) if t]
        if metricsz_out:
            Path(metricsz_out).write_text("\n".join(texts))
        real = report.summary()
        slo_burn = max(
            (parse_prometheus_text(t).value("slo_burn_rate", 0.0)
             for t in texts),
            default=0.0,
        )
        costs = PhaseCosts.fit(
            texts,
            mean_prompt_tokens=CAL_PROMPT_LEN,
            mean_new_tokens=CAL_MAX_NEW,
            baseline_texts=baseline,
        )
        # the twin models the SERVER: hold it to the server-measured
        # latency (delta over the warmup baseline), not the client-side
        # ledger mean, which adds HTTP + client-thread scheduling
        # overhead the twin deliberately does not simulate
        def _delta(name: str) -> float:
            return (
                sum(parse_prometheus_text(t).value(name) for t in texts)
                - sum(parse_prometheus_text(t).value(name) for t in baseline)
            )

        lat_n = _delta("serving_request_seconds_count")
        server_mean_ms = (
            _delta("serving_request_seconds_sum") / lat_n * 1e3
            if lat_n else None
        )
    finally:
        rig.stop()

    twin = ServingTwin(
        TwinConfig(replicas=2, max_batch=4, max_queue=64,
                   kv_pool_pages=96, kv_page_tokens=8),
        costs,
    ).run(iter(records))
    real_cmp = dict(real)
    if server_mean_ms is not None:
        real_cmp["latency_ms"] = {**real["latency_ms"], "mean": server_mean_ms}
    err = calibration_error(twin, real_cmp)
    real_rec = {
        "metric": "scenario_real",
        "scenario": "flood_calibration",
        "value": real["shed_rate"],
        "unit": "shed_rate",
        "offered": real["offered"],
        "ok": real["ok"],
        "shed": real["shed"],
        "error": real["error"],
        "hung": real["hung"],
        "p50_ms": real["latency_ms"]["p50"],
        "p99_ms": real["latency_ms"]["p99"],
        "mean_ms": real["latency_ms"]["mean"],
        "slo_burn": round(slo_burn, 3),
        "trace_seed": 1,
        "pass": real["hung"] == 0 and real["error"] == 0,
    }
    cal_rec = {
        "metric": "sim_vs_real_calibration_error",
        "value": round(err, 4),
        "unit": "max(|shed gap|, rel server-side mean-latency gap)",
        "requests": n,
        "twin_mean_ms": twin["latency_ms"]["mean"],
        "real_server_mean_ms": server_mean_ms,
        "real_client_mean_ms": real["latency_ms"]["mean"],
        "twin_shed_rate": twin["shed_rate"],
        "real_shed_rate": real["shed_rate"],
        "costs": {
            "prefill_ms_per_token": round(costs.prefill_ms_per_token, 4),
            "decode_step_ms": round(costs.decode_step_ms, 4),
            "batch_overhead_ms": round(costs.batch_overhead_ms, 4),
        },
        "pass": err <= 0.25,
    }
    return [real_rec, cal_rec]


def soak_record() -> dict:
    """The acceptance headliner: 1M requests through the twin, <60s."""
    from polyaxon_tpu.scenarios.registry import SCENARIOS, run_twin

    t0 = time.perf_counter()
    res = run_twin(SCENARIOS["million_user_soak"])
    wall = time.perf_counter() - t0
    s = res["summary"]
    return {
        "metric": "scenario_twin_soak_wall_s",
        "value": round(wall, 2),
        "unit": "s",
        "requests": s["offered"],
        "sim_hours": round(s["sim_duration_s"] / 3600.0, 2),
        "hung": s["hung"],
        "kv_pages_leaked": s["kv_pages_leaked"],
        "shed_rate": s["shed_rate"],
        "pass": wall < 60.0 and res["pass"],
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="small CI configuration")
    ap.add_argument("--twin-only", action="store_true",
                    help="skip the real-rig calibration (no jax, no "
                         "compiles): twin records + the soak pin only")
    ap.add_argument("--metricsz-out", default=None,
                    help="write the calibration rig's final /metricsz "
                         "scrapes here (CI gates grep it)")
    args = ap.parse_args(argv)

    recs = twin_records(args.smoke)
    if not args.twin_only:
        # honor POLYAXON_JAX_PLATFORM=cpu BEFORE backend init (see
        # attention_bench.py — plain JAX_PLATFORMS loses to the TPU plugin)
        from polyaxon_tpu.utils.jax_platform import apply_platform_env

        apply_platform_env()
        recs.extend(calibrate(args.smoke, args.metricsz_out))
    recs.append(soak_record())
    ok = True
    for rec in recs:
        print(json.dumps(rec), flush=True)
        ok = ok and rec.get("pass", True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
