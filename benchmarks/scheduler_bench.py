"""Fleet-scheduler benchmark: a seeded synthetic workload replayed through
the REAL admission stack (Fleet + QuotaManager + AdmissionController)
under a simulated clock — fully deterministic, zero real waiting.

  python benchmarks/scheduler_bench.py                  # default workload
  python benchmarks/scheduler_bench.py --seed 7 --jobs 200 --topology 8x8
  python benchmarks/scheduler_bench.py --smoke          # tier-1 quick pass

Reports one JSON line: makespan, queue-wait p50/p95, chip utilization,
preemption count, event count. Same seed → byte-identical report (the
scheduler reads time only from SimClock; see polyaxon_tpu/scheduler/
clock.py). Invariants — quotas never exceeded at any instant, gang
reservations all-or-nothing and non-overlapping — are asserted at EVERY
simulation event, so this doubles as a property check on real scheduler
code, not a toy model of it.

--watch-bench (PR 11) replays the workload to POPULATE the store, then
races the two agent idle loops against each other on the resulting
state: the pre-event-log loop (one `list_runs()` directory scan per
iteration, O(runs)) vs the cursor loop (`wait_events(cursor, timeout=0)`,
O(new events) — O(1) when idle). The report carries the measured
speedup (gate: >= 10x at 10k runs) and `no_dir_scans: true`, asserted
from the store's own scan counter staying flat across the watch phase.

  python benchmarks/scheduler_bench.py --watch-bench --jobs 10000 \
      --topology 16x16
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from polyaxon_tpu.schemas.quota import V1QuotaSpec  # noqa: E402
from polyaxon_tpu.scheduler.sim import (  # noqa: E402
    FleetSimulator,
    synthetic_workload,
)


def run_bench(
    seed: int, n_jobs: int, topology: str, check_every_event: bool
) -> dict:
    jobs = synthetic_workload(seed, n_jobs, topology=topology)
    quotas = [
        V1QuotaSpec(scope="alpha", max_chips=12, weight=2.0),
        V1QuotaSpec(scope="beta", max_chips=8, weight=1.0),
        # gamma: no quota — only capacity bounds it
    ]
    sim = FleetSimulator(
        jobs,
        topology=topology,
        quotas=quotas,
        invariant_fn=(
            (lambda s: s.check_invariants()) if check_every_event else None
        ),
    )
    try:
        report = sim.run()
    finally:
        shutil.rmtree(sim.home, ignore_errors=True)
    report["seed"] = seed
    report["topology"] = topology
    return report


def run_watch_bench(
    seed: int,
    n_jobs: int,
    topology: str,
    *,
    window_s: float = 1.0,
    min_speedup: float = 10.0,
) -> dict:
    """Populate the store via the simulator, then measure both agent idle
    loops on the SAME populated store. Timing uses perf_counter directly:
    benchmarks own their methodology (see scripts/lint_telemetry.py)."""
    import time

    jobs = synthetic_workload(seed, n_jobs, topology=topology)
    # durable_store=False: fsync throttles POPULATION only — both measured
    # loops are read-side and identical under either setting
    sim = FleetSimulator(jobs, topology=topology, durable_store=False)
    try:
        sim.run()
        store = sim.store
        n_runs = len(store.list_runs())

        # baseline: the pre-PR-11 agent idle loop — a full O(runs)
        # directory scan per wakeup
        t0 = time.perf_counter()
        polls = 0
        while time.perf_counter() - t0 < window_s:
            store.list_runs()
            polls += 1
        poll_rate = polls / (time.perf_counter() - t0)

        # cursor loop: drain the committed history once, then steady-state
        # — each iteration asks "anything after my cursor?" and touches
        # only the index tail, never a run directory
        history = 0
        cursor = "0:0"
        while True:
            batch, cursor = store.read_events_since(cursor, limit=10000)
            history += len(batch)
            if len(batch) < 10000:
                break
        scans_before = store.scans
        t0 = time.perf_counter()
        waits = 0
        while time.perf_counter() - t0 < window_s:
            _, cursor = store.wait_events(cursor, timeout=0)
            waits += 1
        watch_rate = waits / (time.perf_counter() - t0)
        no_dir_scans = store.scans == scans_before
    finally:
        shutil.rmtree(sim.home, ignore_errors=True)

    speedup = watch_rate / poll_rate if poll_rate else float("inf")
    return {
        "mode": "watch-bench",
        "seed": seed,
        "topology": topology,
        "jobs": n_jobs,
        "runs": n_runs,
        "history_events": history,
        "poll_iters_per_s": round(poll_rate, 1),
        "watch_iters_per_s": round(watch_rate, 1),
        "speedup": round(speedup, 1),
        "min_speedup": min_speedup,
        "no_dir_scans": no_dir_scans,
        "ok": bool(no_dir_scans and speedup >= min_speedup),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--jobs", type=int, default=120)
    p.add_argument("--topology", default="4x4")
    p.add_argument(
        "--smoke",
        action="store_true",
        help="small deterministic workload for tier-1 CI (~1s)",
    )
    p.add_argument(
        "--no-check",
        action="store_true",
        help="skip per-event invariant assertions (pure timing)",
    )
    p.add_argument(
        "--watch-bench",
        action="store_true",
        help="event-log agent-loop throughput: cursor waits vs O(runs) "
        "polling on the populated store (gate: >=10x, zero dir scans)",
    )
    args = p.parse_args(argv)
    if args.smoke:
        args.jobs = min(args.jobs, 40)
    if args.watch_bench:
        report = run_watch_bench(
            args.seed,
            args.jobs,
            args.topology,
            window_s=0.2 if args.smoke else 1.0,
            # tiny smoke stores scan fast enough that the ratio is noise;
            # the full 10k gate keeps the real bar
            min_speedup=2.0 if args.smoke else 10.0,
        )
        print(json.dumps(report, sort_keys=True))
        if not report["ok"]:
            print(
                f"FAIL: watch speedup {report['speedup']}x "
                f"(need >= {report['min_speedup']}x) "
                f"no_dir_scans={report['no_dir_scans']}",
                file=sys.stderr,
            )
            return 1
        return 0
    report = run_bench(
        args.seed, args.jobs, args.topology, check_every_event=not args.no_check
    )
    print(json.dumps(report, sort_keys=True))
    # a healthy schedule finishes every non-unschedulable job
    expected = report["jobs"] - report["unschedulable"]
    if report["succeeded"] != expected:
        print(
            f"FAIL: {report['succeeded']}/{expected} schedulable jobs "
            "finished",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
