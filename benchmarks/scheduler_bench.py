"""Fleet-scheduler benchmark: a seeded synthetic workload replayed through
the REAL admission stack (Fleet + QuotaManager + AdmissionController)
under a simulated clock — fully deterministic, zero real waiting.

  python benchmarks/scheduler_bench.py                  # default workload
  python benchmarks/scheduler_bench.py --seed 7 --jobs 200 --topology 8x8
  python benchmarks/scheduler_bench.py --smoke          # tier-1 quick pass

Reports one JSON line: makespan, queue-wait p50/p95, chip utilization,
preemption count, event count. Same seed → byte-identical report (the
scheduler reads time only from SimClock; see polyaxon_tpu/scheduler/
clock.py). Invariants — quotas never exceeded at any instant, gang
reservations all-or-nothing and non-overlapping — are asserted at EVERY
simulation event, so this doubles as a property check on real scheduler
code, not a toy model of it.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from polyaxon_tpu.schemas.quota import V1QuotaSpec  # noqa: E402
from polyaxon_tpu.scheduler.sim import (  # noqa: E402
    FleetSimulator,
    synthetic_workload,
)


def run_bench(
    seed: int, n_jobs: int, topology: str, check_every_event: bool
) -> dict:
    jobs = synthetic_workload(seed, n_jobs, topology=topology)
    quotas = [
        V1QuotaSpec(scope="alpha", max_chips=12, weight=2.0),
        V1QuotaSpec(scope="beta", max_chips=8, weight=1.0),
        # gamma: no quota — only capacity bounds it
    ]
    sim = FleetSimulator(
        jobs,
        topology=topology,
        quotas=quotas,
        invariant_fn=(
            (lambda s: s.check_invariants()) if check_every_event else None
        ),
    )
    try:
        report = sim.run()
    finally:
        shutil.rmtree(sim.home, ignore_errors=True)
    report["seed"] = seed
    report["topology"] = topology
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--jobs", type=int, default=120)
    p.add_argument("--topology", default="4x4")
    p.add_argument(
        "--smoke",
        action="store_true",
        help="small deterministic workload for tier-1 CI (~1s)",
    )
    p.add_argument(
        "--no-check",
        action="store_true",
        help="skip per-event invariant assertions (pure timing)",
    )
    args = p.parse_args(argv)
    if args.smoke:
        args.jobs = min(args.jobs, 40)
    report = run_bench(
        args.seed, args.jobs, args.topology, check_every_event=not args.no_check
    )
    print(json.dumps(report, sort_keys=True))
    # a healthy schedule finishes every non-unschedulable job
    expected = report["jobs"] - report["unschedulable"]
    if report["succeeded"] != expected:
        print(
            f"FAIL: {report['succeeded']}/{expected} schedulable jobs "
            "finished",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
