"""Serving overload benchmark: deliberate 5x-capacity traffic.

Measures the resilience layer ISSUE 5 added to `serving/` the only way
that means anything — by overloading a live server and checking what it
does about it. The bench calibrates the server's decode capacity (one
full-batch group timed after warmup), then fires single-row requests at
`--overload` times that rate, every request carrying a deadline. A
healthy server under overload must:

  * hang nothing — every request gets SOME answer (200 / 503 / 504);
  * shed — over capacity, a bounded queue MUST refuse work (503 with
    Retry-After) or drop expired entries before dispatch (504);
  * keep admitted latency bounded — a request it chose to serve finishes
    within deadline + one group execution (it was dispatched before its
    deadline and decode takes one group), not after an unbounded queue
    wait.

Prints one JSON line in the same schema family as the other benches:

  {"metric": "serving_overload_goodput", "value": ..., "unit": "req/s",
   "offered_rps": ..., "capacity_rps": ..., "ok": ..., "shed_503": ...,
   "deadline_504": ..., "hung": 0, "shed_rate": ...,
   "admitted_p99_ms": ..., "deadline_ms": ..., "group_ms": ..., ...}

Exit 1 when any acceptance bound fails (hung requests, zero sheds, or
admitted p99 over the bound).

  python benchmarks/serving_overload_bench.py             # 150 requests
  python benchmarks/serving_overload_bench.py --smoke     # CI: 40
  python benchmarks/serving_overload_bench.py --metricsz-out /tmp/m.txt
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from polyaxon_tpu.telemetry import quantile  # noqa: E402 (needs sys.path)

MODEL_CFG = {
    "preset": "tiny", "seq_len": 128, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 256,
}
PROMPT_LEN = 16   # one shape -> one bucket -> one compile; capacity is
MAX_NEW = 24      # then a pure decode-rate property, not a compile race.
                  # 24 new tokens keeps a group slow enough (~100ms on
                  # CPU) that offered load stresses the QUEUE, not the
                  # TCP accept path


def _post(url: str, body: dict, timeout: float) -> tuple[int, dict]:
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read())
        except Exception:  # noqa: BLE001
            payload = {}
        return e.code, payload


def _bodies(trace_seed: int, n: int) -> list[dict]:
    """Request bodies from the scenario engine's seeded `single_shape`
    trace generator (ISSUE 16): one fixed shape — one bucket, one
    compile — so capacity stays a pure decode-rate property, and the
    workload is a replayable trace (`trace_seed` in the record)."""
    from polyaxon_tpu.scenarios.traces import body_for, single_shape

    return [
        body_for(rec, MODEL_CFG["vocab_size"])
        for rec in single_shape(
            trace_seed, n=n, prompt_len=PROMPT_LEN, max_new=MAX_NEW
        )
    ]


def build_server(max_batch: int, max_queue: int, breaker_threshold: int):
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    bundle = build_model("transformer_lm", MODEL_CFG)
    params = bundle.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return ModelServer(
        bundle.module,
        params,
        model_name="overload-bench",
        config=ServingConfig(
            max_batch=max_batch,
            max_wait_ms=2.0,
            max_queue=max_queue,
            # the deadline budget rides on each request body (deadlineMs)
            # — it is derived from the measured group time, which does
            # not exist yet at config time
            breaker_threshold=breaker_threshold,
            request_timeout_s=60.0,
        ),
    )


def calibrate(url: str, trace_seed: int, max_batch: int) -> float:
    """Seconds one full decode group takes, measured after the compile
    is warm: a max_batch-row body is exactly one coalesced group."""
    # a distinct trace stream so calibration prompts differ from the
    # driven ones (same role the shared rng draws played before)
    warm, body = _bodies(trace_seed + 999_331, n=2)
    _post(url, warm, timeout=300.0)  # pays the XLA compile
    body["tokens"] = body["tokens"] * max_batch
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        code, _ = _post(url, body, timeout=300.0)
        dt = time.perf_counter() - t0
        if code == 200:
            best = min(best, dt)
    if best == float("inf"):
        raise RuntimeError("calibration requests failed")
    return best


def drive(args) -> dict:
    server = build_server(
        args.max_batch, args.max_queue, args.breaker_threshold
    )
    # time every decode group the server actually runs: the latency bound
    # must be judged against the group times of THIS run, not a calibration
    # taken on an idle box — on a CI host the suite runs beside us and
    # stretches decode well past the calibrated figure
    group_times_s: list[float] = []
    recording = threading.Event()
    inner_execute = server._coalescer._execute

    def timed_execute(batch):
        t0 = time.perf_counter()
        try:
            return inner_execute(batch)
        finally:
            if recording.is_set():
                group_times_s.append(time.perf_counter() - t0)

    server._coalescer._execute = timed_execute
    port = server.start(port=0)
    url = f"http://127.0.0.1:{port}/generate"
    group_s = calibrate(url, args.seed, args.max_batch)
    recording.set()  # calibration/compile groups stay out of the sample
    capacity_rps = args.max_batch / group_s
    offered_rps = capacity_rps * args.overload
    # deadline: a few group-times of queueing allowed, then the request is
    # dead — floor keeps CPU-jitter from making every request stillborn
    deadline_ms = max(200.0, 3.0 * group_s * 1e3)

    bodies = [
        {**body, "deadlineMs": deadline_ms}
        for body in _bodies(args.seed, args.requests)
    ]
    offsets = [i / offered_rps for i in range(args.requests)]
    lock = threading.Lock()
    outcomes = {"ok": 0, "shed_503": 0, "deadline_504": 0,
                "hung": 0, "error": 0}
    ok_latency_ms: list[float] = []
    first_error: list[str] = []
    start = time.perf_counter() + 0.05  # common epoch for the schedule

    def fire(body: dict, offset: float):
        delay = start + offset - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t0 = time.perf_counter()
        try:
            code, _ = _post(url, body, timeout=deadline_ms / 1e3 + 30.0)
        except Exception as e:  # noqa: BLE001 — a hang IS the finding
            with lock:
                outcomes["hung"] += 1
                if not first_error:
                    first_error.append(f"{type(e).__name__}: {e}"[:200])
            return
        dt_ms = (time.perf_counter() - t0) * 1e3
        with lock:
            if code == 200:
                outcomes["ok"] += 1
                ok_latency_ms.append(dt_ms)
            elif code == 503:
                outcomes["shed_503"] += 1
            elif code == 504:
                outcomes["deadline_504"] += 1
            else:
                outcomes["error"] += 1
                if not first_error:
                    first_error.append(f"http {code}")

    threads = [
        threading.Thread(target=fire, args=(b, o), daemon=True)
        for b, o in zip(bodies, offsets)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    metricsz = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metricsz", timeout=30
    ).read().decode()
    stats = json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statsz", timeout=30
        ).read()
    )
    server.stop()
    if args.metricsz_out:
        Path(args.metricsz_out).write_text(metricsz)

    import jax

    device = jax.devices()[0]
    group_ms = group_s * 1e3
    # worst group this run actually executed — the honest decode cost
    # under whatever contention the host threw at us
    worst_group_ms = max(group_times_s) * 1e3 if group_times_s else group_ms
    shed = outcomes["shed_503"] + outcomes["deadline_504"]
    # admitted-and-served p99 bound: dispatched before deadline + one
    # group of decode (the worst one observed). The slack term absorbs
    # HTTP/thread scheduling jitter on top.
    bound_ms = deadline_ms + worst_group_ms + max(250.0, worst_group_ms)
    p99 = quantile(sorted(ok_latency_ms), 0.99) if ok_latency_ms else None
    rec = {
        "metric": "serving_overload_goodput",
        "value": round(outcomes["ok"] / wall, 2) if wall > 0 else 0.0,
        "unit": "req/s",
        "overload": args.overload,
        "offered_rps": round(offered_rps, 2),
        "capacity_rps": round(capacity_rps, 2),
        "requests": args.requests,
        **outcomes,
        "shed_rate": round(shed / args.requests, 3),
        "admitted_p50_ms": (
            round(quantile(sorted(ok_latency_ms), 0.5), 1)
            if ok_latency_ms else None
        ),
        "admitted_p99_ms": round(p99, 1) if p99 is not None else None,
        "deadline_ms": round(deadline_ms, 1),
        "group_ms": round(group_ms, 1),
        "worst_group_ms": round(worst_group_ms, 1),
        "bound_ms": round(bound_ms, 1),
        "worker_restarts": stats.get("worker_restarts"),
        "breaker": stats.get("breaker"),
        "platform": device.platform,
        "device_kind": device.device_kind,
        "trace_seed": args.seed,
        "trace_generator": "single_shape",
    }
    if first_error:
        rec["first_error"] = first_error[0]

    failures = []
    if outcomes["hung"] or outcomes["error"]:
        failures.append(
            f"{outcomes['hung']} hung / {outcomes['error']} errored — "
            "overload must shed, never strand"
        )
    if shed == 0:
        failures.append(
            f"zero sheds at {args.overload}x capacity — the queue bound "
            "or deadline admission is not engaging"
        )
    if p99 is not None and p99 > bound_ms:
        failures.append(
            f"admitted p99 {p99:.0f}ms > bound {bound_ms:.0f}ms "
            "(deadline + worst observed group + slack) — queueing is "
            "unbounded"
        )
    rec["pass"] = not failures
    if failures:
        rec["failures"] = failures
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=150)
    ap.add_argument("--overload", type=float, default=5.0,
                    help="offered load as a multiple of calibrated capacity")
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=8)
    ap.add_argument("--breaker-threshold", type=int, default=5)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="small CI configuration (40 requests)")
    ap.add_argument("--metricsz-out", default=None,
                    help="write the server's final /metricsz text here "
                         "(CI gates grep it)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.requests = min(args.requests, 40)

    # honor POLYAXON_JAX_PLATFORM=cpu BEFORE backend init (see
    # attention_bench.py — plain JAX_PLATFORMS loses to the TPU plugin)
    from polyaxon_tpu.utils.jax_platform import apply_platform_env

    apply_platform_env()

    rec = drive(args)
    print(json.dumps(rec), flush=True)
    return 0 if rec["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
