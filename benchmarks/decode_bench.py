"""Decode throughput: KV-cache generation tokens/sec on the current device.

Measures the serving-side half of the framework (models/generate.py):
prefill latency and steady-state decode tok/s for a chip-sized LM, plus
beam-search overhead. Prints one JSON line per config.

  python benchmarks/decode_bench.py            # default sweep
  POLYAXON_JAX_PLATFORM=cpu python benchmarks/decode_bench.py  # smoke
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    from polyaxon_tpu.utils.jax_platform import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.models.generate import beam_search, generate

    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    if on_tpu:
        cfg = {
            "dim": 2048, "n_layers": 8, "n_heads": 16, "n_kv_heads": 16,
            "vocab_size": 32768, "seq_len": 2048,
        }
        batch, prompt_len, max_new = 8, 512, 256
    else:
        cfg = {
            "dim": 128, "n_layers": 2, "n_heads": 4, "n_kv_heads": 4,
            "vocab_size": 1024, "seq_len": 256,
        }
        batch, prompt_len, max_new = 2, 32, 16

    bundle = build_model("transformer_lm", cfg)
    rng = jax.random.PRNGKey(0)
    params = bundle.module.init(
        {"params": rng}, jnp.zeros((batch, 8), jnp.int32), train=False
    )["params"]
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params,
    )
    prompt = jax.random.randint(
        rng, (batch, prompt_len), 0, cfg["vocab_size"], dtype=jnp.int32
    )

    from _timing import time_call

    def timed(fn, *args):
        return time_call(fn, *args, iters=3)

    def gen_fn(n):
        return jax.jit(
            lambda p, pr, s: generate(
                bundle.module, p, pr, max_new_tokens=n,
                temperature=0.8, top_k=40, seed=s,
            )
        )

    seed = jnp.asarray(0, jnp.int32)
    # prefill cost = a 1-new-token generation; steady-state decode is the
    # marginal cost of the remaining max_new-1 tokens
    dt_prefill = timed(gen_fn(1), params, prompt, seed)
    dt = timed(gen_fn(max_new), params, prompt, seed)
    decode_dt = max(dt - dt_prefill, 1e-9)
    print(json.dumps({
        "metric": "decode_tokens_per_sec",
        "value": round(batch * (max_new - 1) / decode_dt, 1),
        "unit": "tok/s",
        "device_kind": device.device_kind,
        "model": f"dim={cfg['dim']} L={cfg['n_layers']}",
        "batch": batch, "prompt_len": prompt_len, "max_new": max_new,
        "prefill_ms": round(dt_prefill * 1e3, 2),
        "per_token_ms": round(decode_dt / (max_new - 1) * 1e3, 3),
        "end_to_end_s": round(dt, 3),
    }), flush=True)

    nb = 4
    b = jax.jit(
        lambda p, pr: beam_search(
            bundle.module, p, pr, max_new_tokens=max_new, num_beams=nb,
        )
    )
    dtb = timed(b, params, prompt)
    print(json.dumps({
        "metric": "beam4_decode_tokens_per_sec",
        "value": round(batch * max_new / dtb, 1),
        "unit": "tok/s",
        "device_kind": device.device_kind,
        "beams": nb,
        "vs_sampling": round(dt / dtb, 3),
    }), flush=True)


if __name__ == "__main__":
    main()
