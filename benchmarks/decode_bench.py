"""Decode throughput: KV-cache generation tokens/sec on the current device.

Measures the serving-side half of the framework (models/generate.py):
prefill latency and steady-state decode tok/s, swept over GQA ratios
(n_kv_heads) and cache lengths, plus beam-search overhead on the base
config. The GQA sweep is what prices the grouped decode cache
(models/generate.py keeps K/V at kv width — cache bytes shrink by
heads/n_kv_heads; the sweep shows what that buys in tok/s on real HBM).

Prints one JSON line per config, schema pinned by
tests/test_benchmarks.py::test_decode_bench_schema:

  {"metric": "decode_tokens_per_sec", "value": N, "unit": "tok/s",
   "platform": "...", "device_kind": "...", "n_heads": H, "n_kv_heads": K,
   "cache_len": S, "kv_cache_bytes": B, "batch": b, "prompt_len": p,
   "max_new": n, "prefill_ms": ..., "per_token_ms": ..., ...}

  python benchmarks/decode_bench.py            # default sweep
  POLYAXON_JAX_PLATFORM=cpu python benchmarks/decode_bench.py  # smoke
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def kv_cache_bytes(cfg: dict, batch: int, cache_len: int) -> int:
    """bf16 K+V cache footprint for a grouped cache held at kv width."""
    head_dim = cfg["dim"] // cfg["n_heads"]
    return 2 * 2 * cfg["n_layers"] * batch * cache_len * cfg["n_kv_heads"] * head_dim


def sweep_configs(on_tpu: bool):
    """(cfg, batch, prompt_len, max_new, is_base) per line. The base
    config (first) also runs beam search; the rest isolate one axis:
    GQA ratio at fixed cache_len, then cache_len at fixed GQA ratio."""
    if on_tpu:
        base = {
            "dim": 2048, "n_layers": 8, "n_heads": 16, "n_kv_heads": 16,
            "vocab_size": 32768, "seq_len": 2048,
        }
        batch, prompt_len, max_new = 8, 512, 256
        kv_sweep = (8, 4, 1)
        len_sweep = (4096, 8192)
    else:
        base = {
            "dim": 128, "n_layers": 2, "n_heads": 4, "n_kv_heads": 4,
            "vocab_size": 1024, "seq_len": 256,
        }
        batch, prompt_len, max_new = 2, 32, 16
        kv_sweep = (1,)
        len_sweep = (512,)
    yield base, batch, prompt_len, max_new, True
    for kv in kv_sweep:
        cfg = dict(base, n_kv_heads=kv)
        yield cfg, batch, prompt_len, max_new, False
    for cache_len in len_sweep:
        # long caches at the most-grouped ratio — the config a serving
        # deployment would actually run; prompt fills half the cache
        cfg = dict(base, n_kv_heads=kv_sweep[-1], seq_len=cache_len)
        yield cfg, batch, cache_len // 2, max_new, False


def main():
    from polyaxon_tpu.utils.jax_platform import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.models.generate import beam_search, generate

    from _timing import time_call

    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"

    def timed(fn, *args):
        return time_call(fn, *args, iters=3)

    for cfg, batch, prompt_len, max_new, is_base in sweep_configs(on_tpu):
        bundle = build_model("transformer_lm", cfg)
        rng = jax.random.PRNGKey(0)
        params = bundle.module.init(
            {"params": rng}, jnp.zeros((batch, 8), jnp.int32), train=False
        )["params"]
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            params,
        )
        prompt = jax.random.randint(
            rng, (batch, prompt_len), 0, cfg["vocab_size"], dtype=jnp.int32
        )

        def gen_fn(n):
            return jax.jit(
                lambda p, pr, s: generate(
                    bundle.module, p, pr, max_new_tokens=n,
                    temperature=0.8, top_k=40, seed=s,
                )
            )

        seed = jnp.asarray(0, jnp.int32)
        # prefill cost = a 1-new-token generation; steady-state decode is
        # the marginal cost of the remaining max_new-1 tokens
        try:
            dt_prefill = timed(gen_fn(1), params, prompt, seed)
            dt = timed(gen_fn(max_new), params, prompt, seed)
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            print(json.dumps({
                "metric": "decode_tokens_per_sec",
                "n_kv_heads": cfg["n_kv_heads"], "cache_len": cfg["seq_len"],
                "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)
            continue
        decode_dt = max(dt - dt_prefill, 1e-9)
        print(json.dumps({
            "metric": "decode_tokens_per_sec",
            "value": round(batch * (max_new - 1) / decode_dt, 1),
            "unit": "tok/s",
            "platform": device.platform,
            "device_kind": device.device_kind,
            "model": f"dim={cfg['dim']} L={cfg['n_layers']}",
            "n_heads": cfg["n_heads"],
            "n_kv_heads": cfg["n_kv_heads"],
            "cache_len": cfg["seq_len"],
            "kv_cache_bytes": kv_cache_bytes(cfg, batch, cfg["seq_len"]),
            "batch": batch, "prompt_len": prompt_len, "max_new": max_new,
            "prefill_ms": round(dt_prefill * 1e3, 2),
            "per_token_ms": round(decode_dt / (max_new - 1) * 1e3, 3),
            "end_to_end_s": round(dt, 3),
        }), flush=True)

        if not is_base:
            continue
        nb = 4
        b = jax.jit(
            lambda p, pr: beam_search(
                bundle.module, p, pr, max_new_tokens=max_new, num_beams=nb,
            )
        )
        dtb = timed(b, params, prompt)
        print(json.dumps({
            "metric": "beam4_decode_tokens_per_sec",
            "value": round(batch * max_new / dtb, 1),
            "unit": "tok/s",
            "platform": device.platform,
            "device_kind": device.device_kind,
            "beams": nb,
            "vs_sampling": round(dt / dtb, 3),
        }), flush=True)


if __name__ == "__main__":
    main()
