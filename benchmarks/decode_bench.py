"""Decode throughput: KV-cache generation tokens/sec on the current device.

Measures the serving-side half of the framework (models/generate.py):
prefill latency and steady-state decode tok/s, swept over GQA ratios
(n_kv_heads) and cache lengths, plus beam-search overhead on the base
config. The GQA sweep is what prices the grouped decode cache
(models/generate.py keeps K/V at kv width — cache bytes shrink by
heads/n_kv_heads; the sweep shows what that buys in tok/s on real HBM).

Prints one JSON line per config, schema pinned by
tests/test_benchmarks.py::test_decode_bench_schema:

  {"metric": "decode_tokens_per_sec", "value": N, "unit": "tok/s",
   "platform": "...", "device_kind": "...", "n_heads": H, "n_kv_heads": K,
   "cache_len": S, "kv_cache_bytes": B, "batch": b, "prompt_len": p,
   "max_new": n, "prefill_ms": ..., "per_token_ms": ..., "ttft_ms": ...}

The base config also reports the block-paged decode path (ISSUE 6):

  {"metric": "paged_decode_tokens_per_sec", "value": N, "unit": "tok/s",
   "page_tokens": t, "pool_pages": p, "kv_pool_bytes": B,
   "ttft_ms": ..., "per_token_ms": ..., "cache_donated": true}

`cache_donated` asserts the prefill→decode buffer donation
(jit_paged_prefill/jit_paged_chunk use donate_argnums on the pool): on
TPU the donated input buffer MUST be invalidated (hard assert); CPU
ignores donation, so there it is report-only.

With ≥2 visible devices the base config also reports the ISSUE 10
tensor-parallel record — the same paged decode under the named 2-D
serving mesh (`batch`×`model`, projection kernels sharded over `model`):

  {"metric": "tp_decode_tokens_per_sec", "value": N, "unit": "tok/s",
   "mesh": {"batch": b, "model": m}, "mesh_devices": d,
   "per_token_ms": ..., "single_chip_per_token_ms": ...,
   "per_token_speedup_vs_single_chip": ..., "cache_donated": true}

On TPU the record hard-asserts that per-token latency beats the
single-chip paged record at fixed batch and that donation survives
sharding; on CPU fake devices (POLYAXON_NUM_CPU_DEVICES) it is
report-only. Single-device hosts skip the record.

The base config also reports the ISSUE 8 fast-decode paths:

  {"metric": "speculative_decode_tokens_per_sec", "value": N,
   "unit": "tok/s", "draft_tokens": K, "accept_rate": ...,
   "tokens_per_step": ..., "baseline_tokens_per_sec": ...,
   "speedup_vs_baseline": ..., "compiled_programs": 2,
   "identical_to_baseline": true}

run on a copy-friendly workload (a cyclic prompt the model continues
verbatim — the weights are crafted so greedy decode replays the cycle,
standing in for the repetitive text a trained LM copies). The n-gram
drafter then accepts near-fully, which is the regime speculation is
for; accept_rate is measured, not assumed. Baseline is the fused
single-token `generate` scan on the SAME model and prompt.

  {"metric": "int8_decode_tokens_per_sec", "value": N, "unit": "tok/s",
   "decode_weight_bytes_fp": B, "decode_weight_bytes_int8": b,
   "hbm_reduction": ..., "top1_agreement": ..., "logit_max_abs_delta": ...,
   "baseline_tokens_per_sec": ...}

pins the weight-only int8 path (models/quant.py): per-output-channel
scales on the seven projection kernels, mixed int8×activation matmuls,
and the greedy-decode quality check against the full-precision model.

  python benchmarks/decode_bench.py            # default sweep
  python benchmarks/decode_bench.py --smoke    # tiny sweep on any backend
  POLYAXON_JAX_PLATFORM=cpu python benchmarks/decode_bench.py  # smoke
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def kv_cache_bytes(cfg: dict, batch: int, cache_len: int) -> int:
    """bf16 K+V cache footprint for a grouped cache held at kv width."""
    head_dim = cfg["dim"] // cfg["n_heads"]
    return 2 * 2 * cfg["n_layers"] * batch * cache_len * cfg["n_kv_heads"] * head_dim


def sweep_configs(on_tpu: bool):
    """(cfg, batch, prompt_len, max_new, is_base) per line. The base
    config (first) also runs beam search; the rest isolate one axis:
    GQA ratio at fixed cache_len, then cache_len at fixed GQA ratio."""
    if on_tpu:
        base = {
            "dim": 2048, "n_layers": 8, "n_heads": 16, "n_kv_heads": 16,
            "vocab_size": 32768, "seq_len": 2048,
        }
        batch, prompt_len, max_new = 8, 512, 256
        kv_sweep = (8, 4, 1)
        len_sweep = (4096, 8192)
    else:
        base = {
            "dim": 128, "n_layers": 2, "n_heads": 4, "n_kv_heads": 4,
            "vocab_size": 1024, "seq_len": 256,
        }
        batch, prompt_len, max_new = 2, 32, 16
        kv_sweep = (1,)
        len_sweep = (512,)
    yield base, batch, prompt_len, max_new, True
    for kv in kv_sweep:
        cfg = dict(base, n_kv_heads=kv)
        yield cfg, batch, prompt_len, max_new, False
    for cache_len in len_sweep:
        # long caches at the most-grouped ratio — the config a serving
        # deployment would actually run; prompt fills half the cache
        cfg = dict(base, n_kv_heads=kv_sweep[-1], seq_len=cache_len)
        yield cfg, batch, cache_len // 2, max_new, False


def _paged_timing(bundle, params, cfg, batch, prompt_len, max_new, device):
    """One paged-decode measurement: TTFT (prefill + first sample),
    steady-state per-token latency through the page tables, and the
    donation probe (the prefill cache buffer must be consumed in place —
    hard-asserted on TPU, report-only on CPU). Shared by the single-chip
    record and the tensor-parallel record, which differ only in the
    params' sharding and the active mesh."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.models.generate import (
        jit_paged_chunk,
        jit_paged_prefill,
        make_paged_cache,
    )
    from polyaxon_tpu.models.kv_pages import PagedKVLayout

    pt = max(8, min(128, cfg["seq_len"] // 8))
    window = prompt_len + max_new
    n_pages = -(-window // pt)
    layout = PagedKVLayout(
        page_tokens=pt, pool_pages=batch * n_pages + 1
    )
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg["vocab_size"],
        dtype=jnp.int32,
    )
    pads = jnp.zeros((batch,), jnp.int32)
    seeds = jnp.arange(batch, dtype=jnp.int32)
    # page 0 = scratch; each row owns a disjoint stripe of the pool
    tables = jnp.asarray(
        1 + np.arange(batch * n_pages, dtype=np.int32).reshape(batch, n_pages)
    )
    pf = jit_paged_prefill(
        bundle.module, kv_layout=layout, prefix_len=0, temperature=0.8,
        top_k=40,
    )
    steps = max_new - 1
    cf = (
        jit_paged_chunk(
            bundle.module, steps=steps, kv_layout=layout, prefix_len=0,
            temperature=0.8, top_k=40, eos_id=None,
        )
        if steps > 0
        else None
    )

    def fresh():
        return make_paged_cache(bundle.module, params, layout)

    # donation check: the pool buffer fed to prefill must be invalidated
    # (consumed in place) — TPU hard-asserts, CPU ignores donation
    probe = fresh()
    probe_leaf = jax.tree.leaves(probe)[0]
    cache, first = pf(params, probe, prompt, pads, tables, seeds)
    jax.block_until_ready(first)
    donated = bool(probe_leaf.is_deleted())
    if device.platform == "tpu":
        assert donated, (
            "paged prefill cache was copied, not donated — "
            "donate_argnums regression"
        )
    # TTFT: prefill + first sampled token, end to end
    t0 = _time.perf_counter()
    cache2, first2 = pf(params, fresh(), prompt, pads, tables, seeds)
    jax.block_until_ready(first2)
    ttft_ms = (_time.perf_counter() - t0) * 1e3
    per_token_ms = None
    toks_per_sec = None
    if cf is not None:
        done = jnp.zeros((batch,), bool)
        pos = jnp.asarray(prompt_len, jnp.int32)
        g = jnp.asarray(1, jnp.int32)

        def decode(cache, tok, done):
            return cf(params, cache, tok, done, pads, tables, seeds, pos, g)

        cache2, toks, done = decode(cache2, first2, done)  # warm compile
        jax.block_until_ready(toks)
        t0 = _time.perf_counter()
        cache2, toks, done = decode(cache2, toks[:, -1], done)
        jax.block_until_ready(toks)
        dt = _time.perf_counter() - t0
        per_token_ms = dt / steps * 1e3
        toks_per_sec = batch * steps / dt
    head_dim = cfg["dim"] // cfg["n_heads"]
    kv_pool_bytes = (
        2 * 2 * cfg["n_layers"] * layout.pool_pages * pt
        * cfg["n_kv_heads"] * head_dim
    )
    return {
        "page_tokens": pt,
        "pool_pages": layout.pool_pages,
        "kv_pool_bytes": kv_pool_bytes,
        "ttft_ms": round(ttft_ms, 2),
        "per_token_ms": round(per_token_ms, 3) if per_token_ms else None,
        "toks_per_sec": round(toks_per_sec, 1) if toks_per_sec else None,
        "cache_donated": donated,
    }


def run_paged(bundle, params, cfg, batch, prompt_len, max_new, device, timed):
    """Paged-decode record for the base config: TTFT (prefill + first
    sample), steady-state tok/s through the page tables, and the donation
    assertion (the prefill cache buffer must be consumed in place)."""
    t = _paged_timing(bundle, params, cfg, batch, prompt_len, max_new, device)
    rec = {
        "metric": "paged_decode_tokens_per_sec",
        "value": t["toks_per_sec"],
        "unit": "tok/s",
        "platform": device.platform,
        "device_kind": device.device_kind,
        "model": f"dim={cfg['dim']} L={cfg['n_layers']}",
        "page_tokens": t["page_tokens"],
        "pool_pages": t["pool_pages"],
        "kv_pool_bytes": t["kv_pool_bytes"],
        "batch": batch, "prompt_len": prompt_len, "max_new": max_new,
        "ttft_ms": t["ttft_ms"],
        "per_token_ms": t["per_token_ms"],
        "cache_donated": t["cache_donated"],
    }
    print(json.dumps(rec), flush=True)
    return rec


def run_tensor_parallel(bundle, params, cfg, batch, prompt_len, max_new,
                        device, single_rec):
    """ISSUE 10 record: the SAME paged decode under the named 2-D serving
    mesh (`batch`×`model`) — the seven projection kernels shard over
    `model` via the bundle's sharding rules, concurrent rows split over
    `batch`, and the page-table path is unchanged. On TPU the record must
    prove the point of tensor parallelism (per-token latency improves vs
    the single-chip paged record at fixed batch) and donation must
    survive sharding; on CPU fake devices the collectives run over
    shared memory, so both are report-only there. Emitted only when the
    visible device count supports a model axis ≥ 2."""
    import jax

    from polyaxon_tpu.parallel.mesh import decode_mesh
    from polyaxon_tpu.parallel.ring import set_current_mesh
    from polyaxon_tpu.parallel.sharding import param_shardings

    n_dev = jax.device_count()
    axes = {
        "batch": 2 if (n_dev >= 4 and batch % 2 == 0) else 1,
        "model": 2,
    }
    mesh = decode_mesh(axes)
    set_current_mesh(mesh)  # constrain() in the blocks needs it at trace
    try:
        tp_params = jax.device_put(
            params, param_shardings(params, bundle.sharding_rules, mesh)
        )
        t = _paged_timing(
            bundle, tp_params, cfg, batch, prompt_len, max_new, device
        )
    finally:
        set_current_mesh(None)  # later records measure the single-chip path
    single_ptm = (single_rec or {}).get("per_token_ms")
    if device.platform == "tpu":
        assert t["cache_donated"], (
            "TP paged prefill cache was copied, not donated — sharding "
            "broke donate_argnums"
        )
        if single_ptm and t["per_token_ms"]:
            assert t["per_token_ms"] < single_ptm, (
                f"tensor parallelism did not improve per-token latency: "
                f"{t['per_token_ms']}ms sharded vs {single_ptm}ms single-chip"
            )
    print(json.dumps({
        "metric": "tp_decode_tokens_per_sec",
        "value": t["toks_per_sec"],
        "unit": "tok/s",
        "platform": device.platform,
        "device_kind": device.device_kind,
        "model": f"dim={cfg['dim']} L={cfg['n_layers']}",
        "mesh": {ax: mesh.shape.get(ax, 1) for ax in ("batch", "model")},
        "mesh_devices": int(mesh.devices.size),
        "batch": batch, "prompt_len": prompt_len, "max_new": max_new,
        "ttft_ms": t["ttft_ms"],
        "per_token_ms": t["per_token_ms"],
        "single_chip_per_token_ms": single_ptm,
        "per_token_speedup_vs_single_chip": (
            round(single_ptm / t["per_token_ms"], 2)
            if single_ptm and t["per_token_ms"] else None
        ),
        "cache_donated": t["cache_donated"],
    }), flush=True)


CYCLE = tuple(range(1, 9))  # the copy-friendly workload's token cycle


def cyclic_copy_params(params, cfg, pattern=CYCLE):
    """Rebuild `params` so greedy decode continues `pattern` verbatim:
    o_proj/down_proj are zeroed (every block becomes the residual
    identity), pattern token i embeds to basis vector e_i, and lm_head
    maps e_i to a single logit spike on pattern[i+1]. The model then
    deterministically replays the cycle — a stand-in for the repetitive
    text a trained LM copies, which is the workload speculative decoding
    exists for. The n-gram drafter sees the real pipeline end to end;
    nothing about speculation itself is mocked."""
    import jax.numpy as jnp
    import numpy as np

    def rebuild(tree):
        out = {}
        for k, v in tree.items():
            if hasattr(v, "items"):
                if k in ("o_proj", "down_proj") and "kernel" in v:
                    out[k] = {
                        n: (jnp.zeros_like(a) if n == "kernel" else a)
                        for n, a in v.items()
                    }
                else:
                    out[k] = rebuild(v)
            else:
                out[k] = v
        return out

    params = rebuild(params)
    emb = np.zeros(params["embed"]["embedding"].shape, np.float32)
    head = np.zeros(params["lm_head"]["kernel"].shape, np.float32)
    p = len(pattern)
    for i, t in enumerate(pattern):
        emb[t, i] = 1.0
        head[i, pattern[(i + 1) % p]] = 1.0
    dt = params["embed"]["embedding"].dtype
    params["embed"]["embedding"] = jnp.asarray(emb, dt)
    params["lm_head"]["kernel"] = jnp.asarray(
        head, params["lm_head"]["kernel"].dtype
    )
    return params


def run_speculative(bundle, cfg, batch, prompt_len, max_new, device):
    """Speculation record on the copy-friendly workload: fused baseline
    generate vs spec_generate (n-gram draft + batched verify windows) on
    the same crafted-cycle model, greedy, byte-identity asserted."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.models.spec_decode import (
        jit_spec_prefill,
        jit_spec_verify,
        spec_generate,
    )
    from polyaxon_tpu.models.generate import generate

    params = bundle.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((batch, 8), jnp.int32), train=False,
    )["params"]
    params = cyclic_copy_params(params, cfg)
    prompt = jnp.asarray(
        np.tile(
            np.asarray(CYCLE, np.int32),
            (batch, -(-prompt_len // len(CYCLE))),
        )[:, :prompt_len]
    )
    P = int(prompt.shape[1])

    base = jax.jit(
        lambda p, pr: generate(
            bundle.module, p, pr, max_new_tokens=max_new, temperature=0.0
        )
    )
    out = base(params, prompt)
    jax.block_until_ready(out)
    t0 = _time.perf_counter()
    iters = 3
    for _ in range(iters):
        out = base(params, prompt)
        jax.block_until_ready(out)
    base_tps = batch * max_new / ((_time.perf_counter() - t0) / iters)

    K = 8
    # exactly two programs per (temperature, top_k, K): one prefill, one
    # verify — the ladder the serving compile cache keys on
    pf = jit_spec_prefill(bundle.module, temperature=0.0, top_k=None)
    vf = jit_spec_verify(
        bundle.module, temperature=0.0, top_k=None, eos_id=None
    )

    def spec(stats):
        return spec_generate(
            bundle.module, params, prompt, max_new_tokens=max_new,
            draft_tokens=K, temperature=0.0, prefill_fn=pf, verify_fn=vf,
            stats=stats,
        )

    sout = spec({})
    jax.block_until_ready(sout)
    t0 = _time.perf_counter()
    stats = {}
    for _ in range(iters):
        stats = {}
        sout = spec(stats)
        jax.block_until_ready(sout)
    tps = batch * max_new / ((_time.perf_counter() - t0) / iters)
    identical = bool((np.asarray(sout) == np.asarray(out)).all())
    assert identical, "speculative output diverged from fused generate"
    accept_rate = stats["accepted"] / max(stats["proposed"], 1)
    print(json.dumps({
        "metric": "speculative_decode_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tok/s",
        "platform": device.platform,
        "device_kind": device.device_kind,
        "model": f"dim={cfg['dim']} L={cfg['n_layers']}",
        "draft_tokens": K,
        "accept_rate": round(accept_rate, 3),
        "tokens_per_step": round(
            (max_new - 1) / max(stats["windows"], 1), 2
        ),
        "windows": stats["windows"],
        "baseline_tokens_per_sec": round(base_tps, 1),
        "speedup_vs_baseline": round(tps / base_tps, 2),
        "compiled_programs": 2,
        "batch": batch, "prompt_len": P, "max_new": max_new,
        "identical_to_baseline": identical,
    }), flush=True)


def run_int8(bundle, params, cfg, batch, prompt_len, max_new, device):
    """int8 weight-only record: decode-weight HBM footprint before/after
    quantize-on-load, greedy top-1 agreement against the fp model, and
    the single-forward logit delta."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.models.generate import generate
    from polyaxon_tpu.models.quant import decode_weight_bytes, quantize_module

    target_fp, _total = decode_weight_bytes(params)
    qmodule, qparams, saved = quantize_module(bundle.module, params)
    target_int8 = target_fp - saved
    prompt = jax.random.randint(
        jax.random.PRNGKey(3), (batch, prompt_len), 1, cfg["vocab_size"],
        dtype=jnp.int32,
    )

    def gen(module):
        return jax.jit(
            lambda p, pr: generate(
                module, p, pr, max_new_tokens=max_new, temperature=0.0
            )
        )

    fp_fn, q_fn = gen(bundle.module), gen(qmodule)
    fp_out = fp_fn(params, prompt)
    q_out = q_fn(qparams, prompt)
    jax.block_until_ready((fp_out, q_out))
    agree = float(
        (np.asarray(fp_out)[:, prompt_len:] == np.asarray(q_out)[:, prompt_len:])
        .mean()
    )
    logits_fp = bundle.module.apply(
        {"params": params}, prompt, train=False
    ).astype(jnp.float32)
    logits_q = qmodule.apply(
        {"params": qparams}, prompt, train=False
    ).astype(jnp.float32)
    delta = float(jnp.max(jnp.abs(logits_fp - logits_q)))

    def tps(fn, p):
        t0 = _time.perf_counter()
        iters = 3
        for _ in range(iters):
            out = fn(p, prompt)
            jax.block_until_ready(out)
        return batch * max_new / ((_time.perf_counter() - t0) / iters)

    base_tps, q_tps = tps(fp_fn, params), tps(q_fn, qparams)
    print(json.dumps({
        "metric": "int8_decode_tokens_per_sec",
        "value": round(q_tps, 1),
        "unit": "tok/s",
        "platform": device.platform,
        "device_kind": device.device_kind,
        "model": f"dim={cfg['dim']} L={cfg['n_layers']}",
        "decode_weight_bytes_fp": int(target_fp),
        "decode_weight_bytes_int8": int(target_int8),
        "hbm_reduction": round(saved / max(target_fp, 1), 3),
        "top1_agreement": round(agree, 4),
        "logit_max_abs_delta": round(delta, 4),
        "baseline_tokens_per_sec": round(base_tps, 1),
        "batch": batch, "prompt_len": prompt_len, "max_new": max_new,
    }), flush=True)


def run_draft_model(bundle, cfg, batch, prompt_len, max_new, device):
    """ISSUE 15 cyclic record: the same copy-friendly workload as
    run_speculative, but proposed by a REAL draft model (layer-truncated
    from the target via models/draft.build_draft) instead of the n-gram
    index. On the crafted-cycle weights every block is the residual
    identity, so the truncated draft computes exactly the target function
    and the accept rate is the ceiling — the regime the ≥1.3x speedup
    gate holds the draft path to. Drafter construction (its prefill)
    is inside the timed loop: serving pays it per group too.

    The target is deepened to 8 layers for this record: a draft only
    pays when it is a small FRACTION of the target, and on the 2-layer
    smoke config the shared full-width lm_head alone makes a 1-layer
    draft cost ~a full target step — no draft model can win there, on
    any hardware. 8 target layers vs 1 draft layer is the regime the
    feature models (a much-deeper target), and the blocks are identity
    either way so the crafted cycle is unchanged."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.models.draft import ModelDrafter, build_draft
    from polyaxon_tpu.models.generate import generate
    from polyaxon_tpu.models.spec_decode import (
        jit_spec_prefill,
        jit_spec_verify,
        spec_generate,
    )

    cfg = dict(cfg, n_layers=max(8, cfg["n_layers"]))
    bundle = build_model("transformer_lm", cfg)
    params = bundle.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((batch, 8), jnp.int32), train=False,
    )["params"]
    params = cyclic_copy_params(params, cfg)
    prompt = jnp.asarray(
        np.tile(
            np.asarray(CYCLE, np.int32),
            (batch, -(-prompt_len // len(CYCLE))),
        )[:, :prompt_len]
    )
    P = int(prompt.shape[1])
    lengths = np.full(batch, P, np.int64)

    base = jax.jit(
        lambda p, pr: generate(
            bundle.module, p, pr, max_new_tokens=max_new, temperature=0.0
        )
    )
    out = base(params, prompt)
    jax.block_until_ready(out)
    iters = 3
    t0 = _time.perf_counter()
    for _ in range(iters):
        out = base(params, prompt)
        jax.block_until_ready(out)
    base_tps = batch * max_new / ((_time.perf_counter() - t0) / iters)

    K = 8
    dmodule, dparams, derived = build_draft(
        bundle.module, params, overrides={"n_layers": 1}
    )
    pf = jit_spec_prefill(bundle.module, temperature=0.0, top_k=None)
    vf = jit_spec_verify(
        bundle.module, temperature=0.0, top_k=None, eos_id=None
    )
    from polyaxon_tpu.models.draft import jit_draft_prefill

    dpf = jit_draft_prefill(dmodule)
    propose_fns: dict = {}

    def spec(stats):
        drafter = ModelDrafter(
            dmodule, dparams, prompt, lengths,
            seeds=np.zeros(batch, np.int32), temperature=0.0,
            prefill_fn=dpf, propose_fns=propose_fns,
        )
        return spec_generate(
            bundle.module, params, prompt, max_new_tokens=max_new,
            draft_tokens=K, temperature=0.0, prefill_fn=pf, verify_fn=vf,
            stats=stats, drafter=drafter,
        )

    sout = spec({})
    jax.block_until_ready(sout)
    stats = {}
    t0 = _time.perf_counter()
    for _ in range(iters):
        stats = {}
        sout = spec(stats)
        jax.block_until_ready(sout)
    tps = batch * max_new / ((_time.perf_counter() - t0) / iters)
    identical = bool((np.asarray(sout) == np.asarray(out)).all())
    assert identical, "draft-model speculative output diverged from generate"
    accept_rate = stats["accepted"] / max(stats["proposed"], 1)
    speedup = tps / base_tps
    assert speedup >= 1.3, (
        f"draft-model speculation lost its speedup gate on the "
        f"copy-friendly workload: {speedup:.2f}x < 1.3x"
    )
    print(json.dumps({
        "metric": "draft_model_decode_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tok/s",
        "platform": device.platform,
        "device_kind": device.device_kind,
        "model": f"dim={cfg['dim']} L={cfg['n_layers']}",
        "draft_tokens": K,
        "draft_layers": int(dmodule.cfg.n_layers),
        "target_layers": int(cfg["n_layers"]),
        "draft_params_derived": bool(derived),
        "accept_rate": round(accept_rate, 3),
        "windows": stats["windows"],
        "baseline_tokens_per_sec": round(base_tps, 1),
        "speedup_vs_baseline": round(speedup, 2),
        "batch": batch, "prompt_len": P, "max_new": max_new,
        "identical_to_baseline": identical,
    }), flush=True)


class _AlwaysPlain:
    """Controller stub pinned to k=0: spec_generate degenerates to the
    width-1 host-stepped plain decode — the serving engine's actual
    plain cadence, which is the fair comparator for 'speculation off'."""

    def window_k(self):
        return 0

    def observe(self, *a, **k):
        pass

    def tick_plain(self, *a, **k):
        pass


def run_adaptive(bundle, cfg, batch, prompt_len, max_new, device):
    """ISSUE 15 high-entropy record: randomly initialized weights at
    temperature 1.0 — the workload where the n-gram drafter's accept
    rate collapses and fixed-K speculation is pure verify overhead. Four
    measurements on the SAME prompt and per-row seeds, all asserted
    byte-identical to the fused generate scan:

      * plain        — width-1 host-stepped decode (k pinned to 0), the
                       serving engine's speculation-off cadence;
      * n-gram spec  — PR 8's fixed-K path, which must measurably LOSE;
      * adaptive     — draft model + AdaptiveSpecController, which must
                       shrink K and auto-disable, landing within 0.95x
                       of plain (overhead bounded) and above n-gram.

    The fused single-program scan rides along as a reference field; it
    is not the gate because no host-stepped serving path can amortize
    its per-token dispatch the way one fused scan does."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.models.draft import (
        ModelDrafter,
        build_draft,
        jit_draft_prefill,
    )
    from polyaxon_tpu.models.generate import generate
    from polyaxon_tpu.models.spec_decode import (
        jit_spec_prefill,
        jit_spec_verify,
        spec_generate,
    )
    from polyaxon_tpu.serving.adaptive import AdaptiveSpecController

    params = bundle.module.init(
        {"params": jax.random.PRNGKey(7)},
        jnp.zeros((batch, 8), jnp.int32), train=False,
    )["params"]
    prompt = jax.random.randint(
        jax.random.PRNGKey(11), (batch, prompt_len), 1, cfg["vocab_size"],
        dtype=jnp.int32,
    )
    P = int(prompt.shape[1])
    lengths = np.full(batch, P, np.int64)
    seeds = np.arange(batch, dtype=np.int32) + 3
    temperature, top_k = 1.0, None
    iters = 3

    fused = jax.jit(
        lambda p, pr, s: generate(
            bundle.module, p, pr, max_new_tokens=max_new,
            temperature=temperature, top_k=top_k, seed=s,
        )
    )
    ref = fused(params, prompt, jnp.asarray(seeds))
    jax.block_until_ready(ref)
    t0 = _time.perf_counter()
    for _ in range(iters):
        ref = fused(params, prompt, jnp.asarray(seeds))
        jax.block_until_ready(ref)
    fused_tps = batch * max_new / ((_time.perf_counter() - t0) / iters)
    ref_np = np.asarray(ref)

    K = 4
    pf = jit_spec_prefill(bundle.module, temperature=temperature, top_k=top_k)
    vf = jit_spec_verify(
        bundle.module, temperature=temperature, top_k=top_k, eos_id=None
    )

    def timed(run):
        out = run({})  # warm the compile ladder
        jax.block_until_ready(out)
        stats = {}
        t0 = _time.perf_counter()
        for _ in range(iters):
            stats = {}
            out = run(stats)
            jax.block_until_ready(out)
        tps = batch * max_new / ((_time.perf_counter() - t0) / iters)
        assert (np.asarray(out) == ref_np).all(), (
            "host-stepped decode diverged from the fused scan"
        )
        return tps, stats

    def plain(stats):
        return spec_generate(
            bundle.module, params, prompt, max_new_tokens=max_new,
            draft_tokens=K, temperature=temperature, top_k=top_k,
            seeds=seeds, prefill_fn=pf, verify_fn=vf, stats=stats,
            controller=_AlwaysPlain(),
        )

    def ngram(stats):
        return spec_generate(
            bundle.module, params, prompt, max_new_tokens=max_new,
            draft_tokens=K, temperature=temperature, top_k=top_k,
            seeds=seeds, prefill_fn=pf, verify_fn=vf, stats=stats,
        )

    # the no-trained-draft fallback: a randomly initialized draft
    # (models/draft.init_draft_params) — its proposals are honest model
    # samples that almost never match the target, which is exactly the
    # traffic shape that must drive the controller to auto-disable
    from polyaxon_tpu.models.draft import init_draft_params

    dmodule, _, _ = build_draft(
        bundle.module, params, overrides={"n_layers": 1}
    )
    dparams = init_draft_params(dmodule, seed=99)
    dpf = jit_draft_prefill(dmodule)
    propose_fns: dict = {}
    controllers = []
    # one drafter reused across iterations: its cache frontier is a pure
    # function of the generation index, so restarting from start_g=1
    # simply overwrites the same slots — and serving stops building
    # drafters entirely once the controller disables speculation (groups
    # admit plain), so rebuilding per run would overstate steady state
    drafter = ModelDrafter(
        dmodule, dparams, prompt, lengths, seeds=seeds,
        temperature=temperature, top_k=top_k,
        prefill_fn=dpf, propose_fns=propose_fns,
    )

    def adaptive(stats):
        # probe small and decide fast: k starts at 2 so the losing bet is
        # cheap, window=2 proposals per decision so the ramp-down spends
        # only a handful of windows (2 -> 1 -> off), reprobe effectively
        # off so the record captures the disabled steady state
        ctl = AdaptiveSpecController(
            k_init=2, k_min=1, k_max=K, window=2, reprobe=10**9
        )
        controllers.append(ctl)
        return spec_generate(
            bundle.module, params, prompt, max_new_tokens=max_new,
            draft_tokens=K, temperature=temperature, top_k=top_k,
            seeds=seeds, prefill_fn=pf, verify_fn=vf, stats=stats,
            drafter=drafter, controller=ctl,
        )

    plain_tps, _pstats = timed(plain)
    ngram_tps, nstats = timed(ngram)
    adaptive_tps, astats = timed(adaptive)
    ctl = controllers[-1]
    engaged = bool(ctl.auto_disabled or ctl.stats()["disables"] > 0)

    ngram_accept = nstats["accepted"] / max(nstats["proposed"], 1)
    vs_plain = adaptive_tps / plain_tps
    vs_ngram = adaptive_tps / ngram_tps
    assert engaged, (
        "adaptive controller never disabled speculation on the "
        "high-entropy workload"
    )
    assert vs_plain >= 0.95, (
        f"adaptive speculation overhead unbounded: {vs_plain:.2f}x of "
        f"plain decode (gate 0.95x)"
    )
    assert vs_ngram > 1.0, (
        f"adaptive path did not beat fixed-K n-gram speculation on "
        f"high-entropy traffic: {vs_ngram:.2f}x"
    )
    print(json.dumps({
        "metric": "adaptive_spec_decode_tokens_per_sec",
        "value": round(adaptive_tps, 1),
        "unit": "tok/s",
        "platform": device.platform,
        "device_kind": device.device_kind,
        "model": f"dim={cfg['dim']} L={cfg['n_layers']}",
        "draft_tokens": K,
        "plain_tokens_per_sec": round(plain_tps, 1),
        "ngram_tokens_per_sec": round(ngram_tps, 1),
        "fused_tokens_per_sec": round(fused_tps, 1),
        "ngram_accept_rate": round(ngram_accept, 3),
        "adaptive_vs_plain": round(vs_plain, 3),
        "adaptive_vs_ngram_speedup": round(vs_ngram, 3),
        "auto_disable_engaged": engaged,
        "effective_k_final": int(ctl.effective_k),
        "spec_windows": int(astats.get("windows", 0)),
        "batch": batch, "prompt_len": P, "max_new": max_new,
        "identical_to_baseline": True,
    }), flush=True)


def run_int8_kv(bundle, cfg, batch, prompt_len, max_new, device):
    """ISSUE 15 int8-KV record: the paged pool stored int8-per-slot with
    f32 scales (PagedKVLayout.kv_quant). Three claims, all measured on
    the pool the record reports:

      * capacity — at EQUAL pool bytes the quantized pool holds
        `dense_equivalent_rows` full prompt+decode rows, gated ≥1.9x the
        fp pool's count (f32 params: per-slot K+V shrink from 4·hd to
        hd+4 bytes per kv head);
      * composition — chunked prefill (two slices through
        jit_paged_prefill_chunk) is byte-identical to one-shot prefill
        on the quantized pool: quantization is per-slot, so write order
        cannot change the payload;
      * prefix reuse — a row prefilled against another row's quantized
        prefix pages (prefix_len > 0) decodes byte-identically to the
        same row prefilled from scratch.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.models.generate import (
        jit_paged_chunk,
        jit_paged_prefill,
        jit_paged_prefill_chunk,
        make_paged_cache,
    )
    from polyaxon_tpu.models.kv_pages import PagedKVLayout

    # f32 params: the capacity claim is about the POOL dtype, so keep
    # activations/weights at full precision (a bf16 baseline would halve
    # the fp pool too and understate the win)
    params = bundle.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((batch, 8), jnp.int32), train=False,
    )["params"]
    prompt = jax.random.randint(
        jax.random.PRNGKey(5), (batch, prompt_len), 1, cfg["vocab_size"],
        dtype=jnp.int32,
    )
    # pages strictly smaller than the prompt so the prefix-reuse pass
    # below has a non-empty suffix to prefill past the shared page
    pt = max(8, min(32, prompt_len // 2))
    window = prompt_len + max_new
    n_pages = -(-window // pt)
    pool_pages = batch * n_pages + 1
    lay_fp = PagedKVLayout(page_tokens=pt, pool_pages=pool_pages)
    lay_q = PagedKVLayout(
        page_tokens=pt, pool_pages=pool_pages, kv_quant="int8"
    )

    def pool_bytes(layout):
        cache = make_paged_cache(bundle.module, params, layout)
        return sum(
            l.size * l.dtype.itemsize for l in jax.tree.leaves(cache)
        ), cache

    bytes_fp, _ = pool_bytes(lay_fp)
    bytes_q, _ = pool_bytes(lay_q)
    per_page_q = bytes_q / pool_pages
    pages_per_row = n_pages
    rows_fp = (pool_pages - 1) // pages_per_row
    # equal-byte budget: how many pages (then full rows) the quantized
    # pool fits in the fp pool's HBM footprint
    pages_q_equal = int(bytes_fp // per_page_q)
    dense_equivalent_rows = (pages_q_equal - 1) // pages_per_row
    rows_ratio = dense_equivalent_rows / max(rows_fp, 1)

    pads = jnp.zeros((batch,), jnp.int32)
    seeds = jnp.arange(batch, dtype=jnp.int32)
    tables = jnp.asarray(
        1 + np.arange(batch * n_pages, dtype=np.int32).reshape(
            batch, n_pages
        )
    )
    pf = jit_paged_prefill(
        bundle.module, kv_layout=lay_q, prefix_len=0, temperature=0.0,
        top_k=None,
    )
    steps = max_new - 1
    cf = jit_paged_chunk(
        bundle.module, steps=steps, kv_layout=lay_q, prefix_len=0,
        temperature=0.0, top_k=None, eos_id=None,
    )

    def decode_stream(cache, first):
        done = jnp.zeros((batch,), bool)
        cache, toks, _ = cf(
            params, cache, first, done, pads, tables, seeds,
            jnp.asarray(prompt_len, jnp.int32), jnp.asarray(1, jnp.int32),
        )
        return np.concatenate(
            [np.asarray(first)[:, None], np.asarray(toks)], axis=1
        )

    # one-shot prefill on the quantized pool (timed below)
    cache, first = pf(
        params, make_paged_cache(bundle.module, params, lay_q),
        prompt, pads, tables, seeds,
    )
    one_shot = decode_stream(cache, first)

    # chunked prefill: two slices, then the SAME decode — byte-identical
    half = prompt_len // 2
    pcf = jit_paged_prefill_chunk(bundle.module, kv_layout=lay_q)
    pcf_final = jit_paged_prefill_chunk(
        bundle.module, kv_layout=lay_q, final=True
    )
    zeros = jnp.zeros((batch,), jnp.int32)
    cache = make_paged_cache(bundle.module, params, lay_q)
    cache = pcf(
        params, cache, prompt[:, :half], pads, zeros, tables, seeds,
        jnp.asarray(0, jnp.int32),
    )
    cache, first_c = pcf_final(
        params, cache, prompt[:, half:], pads, zeros, tables, seeds,
        jnp.asarray(half, jnp.int32),
    )
    chunked = decode_stream(cache, first_c)
    chunked_identical = bool((chunked == one_shot).all())
    assert chunked_identical, (
        "chunked prefill diverged from one-shot on the int8 KV pool"
    )

    # prefix reuse: each row's first page (written by the full prefill)
    # becomes a shared prefix for a second pass that prefills only the
    # suffix — quantized prefix pages are read in place (COW: suffix
    # writes target slots >= prefix_len), and the sampled first token
    # must not change
    L = pt  # one full page of shared prefix
    suffix_pages = n_pages - 1
    lay_q2 = PagedKVLayout(
        page_tokens=pt, pool_pages=pool_pages + batch * suffix_pages,
        kv_quant="int8",
    )
    pf2 = jit_paged_prefill(
        bundle.module, kv_layout=lay_q2, prefix_len=0, temperature=0.0,
        top_k=None,
    )
    pf2_pre = jit_paged_prefill(
        bundle.module, kv_layout=lay_q2, prefix_len=L, temperature=0.0,
        top_k=None,
    )
    cache2 = make_paged_cache(bundle.module, params, lay_q2)
    cache2, first_a = pf2(params, cache2, prompt, pads, tables, seeds)
    # reuse pass: keep each row's prefix page, land the suffix on fresh
    # pages past the original stripes — the prefix KV is only ever read
    reuse_tables = np.asarray(tables).copy()
    reuse_tables[:, 1:] = pool_pages + np.arange(
        batch * suffix_pages, dtype=np.int32
    ).reshape(batch, suffix_pages)
    cache2, first_b = pf2_pre(
        params, cache2, prompt[:, L:], pads, jnp.asarray(reuse_tables),
        seeds,
    )
    prefix_identical = bool(
        (np.asarray(first_b) == np.asarray(first_a)).all()
    )
    assert prefix_identical, (
        "prefix-page reuse diverged on the int8 KV pool"
    )

    # steady-state decode tok/s through the quantized pool
    iters = 3
    cache, first = pf(
        params, make_paged_cache(bundle.module, params, lay_q),
        prompt, pads, tables, seeds,
    )
    done = jnp.zeros((batch,), bool)
    pos = jnp.asarray(prompt_len, jnp.int32)
    g = jnp.asarray(1, jnp.int32)
    cache, toks, done = cf(
        params, cache, first, done, pads, tables, seeds, pos, g
    )
    jax.block_until_ready(toks)
    t0 = _time.perf_counter()
    for _ in range(iters):
        cache, toks, done = cf(
            params, cache, toks[:, -1], done, pads, tables, seeds, pos, g
        )
        jax.block_until_ready(toks)
    tps = batch * steps / ((_time.perf_counter() - t0) / iters)

    assert rows_ratio >= 1.9, (
        f"int8 KV pool holds only {rows_ratio:.2f}x the fp rows per "
        f"HBM byte (gate 1.9x)"
    )
    print(json.dumps({
        "metric": "int8_kv_decode_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tok/s",
        "platform": device.platform,
        "device_kind": device.device_kind,
        "model": f"dim={cfg['dim']} L={cfg['n_layers']}",
        "kv_quant": "int8",
        "page_tokens": pt,
        "pool_pages": pool_pages,
        "kv_pool_bytes": int(bytes_q),
        "kv_pool_bytes_fp": int(bytes_fp),
        "bytes_ratio": round(bytes_fp / bytes_q, 3),
        "rows_fp": int(rows_fp),
        "dense_equivalent_rows": int(dense_equivalent_rows),
        "rows_per_byte_vs_fp": round(rows_ratio, 3),
        "chunked_prefill_identical": chunked_identical,
        "prefix_reuse_identical": prefix_identical,
        "batch": batch, "prompt_len": prompt_len, "max_new": max_new,
    }), flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sweep regardless of backend (CI)")
    args = ap.parse_args(argv)

    from polyaxon_tpu.utils.jax_platform import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.models.generate import beam_search, generate

    from _timing import time_call

    device = jax.devices()[0]
    on_tpu = device.platform == "tpu" and not args.smoke

    def timed(fn, *args):
        return time_call(fn, *args, iters=3)

    for cfg, batch, prompt_len, max_new, is_base in sweep_configs(on_tpu):
        bundle = build_model("transformer_lm", cfg)
        rng = jax.random.PRNGKey(0)
        params = bundle.module.init(
            {"params": rng}, jnp.zeros((batch, 8), jnp.int32), train=False
        )["params"]
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            params,
        )
        prompt = jax.random.randint(
            rng, (batch, prompt_len), 0, cfg["vocab_size"], dtype=jnp.int32
        )

        def gen_fn(n):
            return jax.jit(
                lambda p, pr, s: generate(
                    bundle.module, p, pr, max_new_tokens=n,
                    temperature=0.8, top_k=40, seed=s,
                )
            )

        seed = jnp.asarray(0, jnp.int32)
        # prefill cost = a 1-new-token generation; steady-state decode is
        # the marginal cost of the remaining max_new-1 tokens
        try:
            dt_prefill = timed(gen_fn(1), params, prompt, seed)
            dt = timed(gen_fn(max_new), params, prompt, seed)
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            print(json.dumps({
                "metric": "decode_tokens_per_sec",
                "n_kv_heads": cfg["n_kv_heads"], "cache_len": cfg["seq_len"],
                "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)
            continue
        decode_dt = max(dt - dt_prefill, 1e-9)
        print(json.dumps({
            "metric": "decode_tokens_per_sec",
            "value": round(batch * (max_new - 1) / decode_dt, 1),
            "unit": "tok/s",
            "platform": device.platform,
            "device_kind": device.device_kind,
            "model": f"dim={cfg['dim']} L={cfg['n_layers']}",
            "n_heads": cfg["n_heads"],
            "n_kv_heads": cfg["n_kv_heads"],
            "cache_len": cfg["seq_len"],
            "kv_cache_bytes": kv_cache_bytes(cfg, batch, cfg["seq_len"]),
            "batch": batch, "prompt_len": prompt_len, "max_new": max_new,
            "prefill_ms": round(dt_prefill * 1e3, 2),
            "per_token_ms": round(decode_dt / (max_new - 1) * 1e3, 3),
            # dense decode emits nothing until the whole batch finishes:
            # its TTFT is the 1-token end-to-end time (the paged record
            # below is what streaming actually delivers)
            "ttft_ms": round(dt_prefill * 1e3, 2),
            "end_to_end_s": round(dt, 3),
        }), flush=True)

        if not is_base:
            continue
        paged_rec = None
        try:
            paged_rec = run_paged(
                bundle, params, cfg, batch, prompt_len, max_new, device,
                timed,
            )
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            print(json.dumps({
                "metric": "paged_decode_tokens_per_sec",
                "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)
        if jax.device_count() >= 2:
            # tensor-parallel record (ISSUE 10) — needs a model axis of 2;
            # single-device hosts (the CI smoke env) skip it entirely
            try:
                run_tensor_parallel(
                    bundle, params, cfg, batch, prompt_len, max_new,
                    device, paged_rec,
                )
            except Exception as e:  # noqa: BLE001 — report, keep sweeping
                print(json.dumps({
                    "metric": "tp_decode_tokens_per_sec",
                    "error": f"{type(e).__name__}: {e}"[:200],
                }), flush=True)
        try:
            # speculation amortizes over windows: give it a decode long
            # enough to leave the prefill-dominated regime (the smoke
            # sweep's max_new=16 is 2 windows — too short to measure)
            run_speculative(
                bundle, cfg, batch, prompt_len,
                min(max(max_new, 192), cfg["seq_len"] - prompt_len), device,
            )
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            print(json.dumps({
                "metric": "speculative_decode_tokens_per_sec",
                "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)
        try:
            run_int8(
                bundle, params, cfg, batch, prompt_len, max_new, device,
            )
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            print(json.dumps({
                "metric": "int8_decode_tokens_per_sec",
                "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)
        spec_new = min(max(max_new, 192), cfg["seq_len"] - prompt_len - 16)
        try:
            # ISSUE 15: draft-model speculation on the cyclic workload —
            # same decode length as the n-gram record above
            run_draft_model(
                bundle, cfg, batch, prompt_len, spec_new, device,
            )
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            print(json.dumps({
                "metric": "draft_model_decode_tokens_per_sec",
                "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)
        try:
            # ISSUE 15: the high-entropy record — adaptive K must bound
            # the overhead where fixed-K speculation loses
            run_adaptive(
                bundle, cfg, batch, prompt_len, spec_new, device,
            )
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            print(json.dumps({
                "metric": "adaptive_spec_decode_tokens_per_sec",
                "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)
        try:
            # ISSUE 15: int8 KV pool capacity + identity record
            run_int8_kv(
                bundle, cfg, batch, prompt_len, max_new, device,
            )
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            print(json.dumps({
                "metric": "int8_kv_decode_tokens_per_sec",
                "error": f"{type(e).__name__}: {e}"[:200],
            }), flush=True)
        nb = 4
        b = jax.jit(
            lambda p, pr: beam_search(
                bundle.module, p, pr, max_new_tokens=max_new, num_beams=nb,
            )
        )
        dtb = timed(b, params, prompt)
        print(json.dumps({
            "metric": "beam4_decode_tokens_per_sec",
            "value": round(batch * max_new / dtb, 1),
            "unit": "tok/s",
            "platform": device.platform,
            "device_kind": device.device_kind,
            "beams": nb,
            "vs_sampling": round(dt / dtb, 3),
        }), flush=True)


if __name__ == "__main__":
    main()
