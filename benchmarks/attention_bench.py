"""Attention-backend micro-bench: XLA einsum+softmax vs the Pallas flash
kernel, fwd and fwd+bwd, across sequence lengths on the current device.

Informs the transformer's default `attention:` backend (SURVEY.md §5 long-
context obligation): the XLA path materializes the [B,H,S,S] score matrix
(O(S^2) HBM traffic), the flash kernel streams KV blocks through VMEM
(O(S) memory). The crossover is what this measures on real hardware.

  python benchmarks/attention_bench.py            # default sweep
  python benchmarks/attention_bench.py 1024 8192  # explicit seq lengths

On TPU each seq also runs a grouped-query config (kv_heads = heads/4) —
the flash kernel consumes grouped KV natively via its grid index maps, so
this is the compiled-Mosaic validation of those grids on real hardware.

Prints one JSON line per (seq, kv_heads, backend, mode) with tokens/sec
and ms/call; schema pinned by tests/test_benchmarks.py.
"""

from __future__ import annotations

import argparse
import json
import sys
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


from _timing import time_call as _time_call  # noqa: E402 — shared methodology


def _already_captured(out_path: Path) -> set:
    """(seq, kv_heads, backend, mode) rows already landed in --out —
    a resumed sweep (tunnel died mid-run) skips them instead of
    duplicating lines. Error rows don't count: they get retried."""
    done = set()
    if not out_path.exists():
        return done
    for line in out_path.read_text().splitlines():
        try:
            r = json.loads(line)
        except ValueError:
            continue
        if "mode" in r and "error" not in r:
            done.add((r["seq"], r["kv_heads"], r["backend"], r["mode"]))
    return done


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("seqs", nargs="*", type=int, help="explicit seq lengths")
    ap.add_argument(
        "--out", default=None,
        help="ALSO append each result line to this file as it is produced "
             "— point it at the final committed .jsonl, not a temp file, "
             "so a run killed mid-sweep (tunnel window closing) still "
             "leaves every completed measurement on disk where the "
             "evidence commit finds it; a re-run resumes past them",
    )
    args = ap.parse_args()
    # honor POLYAXON_JAX_PLATFORM=cpu BEFORE backend init — plain
    # JAX_PLATFORMS loses to the axon TPU plugin, and a dead tunnel
    # otherwise blocks ~25 min in native init
    from polyaxon_tpu.utils.jax_platform import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.ops.attention import dot_product_attention

    sink = None
    done = set()
    if args.out:
        out_path = Path(args.out)
        done = _already_captured(out_path)
        out_path.parent.mkdir(parents=True, exist_ok=True)
        # line-buffered append: each completed measurement hits the disk
        # before the next one starts
        sink = open(out_path, "a", buffering=1)

    def emit(rec: dict):
        line = json.dumps(rec)
        print(line, flush=True)
        if sink is not None:
            sink.write(line + "\n")

    seqs = args.seqs or [512, 1024, 2048, 4096, 8192]
    device = jax.devices()[0]
    batch, heads, head_dim = 4, 16, 128
    on_tpu = device.platform == "tpu"
    backends = ("xla", "flash")
    kv_sweep = (heads, heads // 4)
    if not on_tpu:
        # CPU runs the Pallas kernel in interpret mode (minutes per call) —
        # the backend comparison is only meaningful on the chip anyway
        seqs = [s for s in seqs if s <= 512]
        batch, backends, kv_sweep = 2, ("xla",), (heads,)

    for seq in seqs:
      for kv_heads in kv_sweep:
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(
            key, (batch, seq, heads, head_dim), jnp.bfloat16
        )
        k, v = (
            jax.random.normal(
                jax.random.fold_in(key, i),
                (batch, seq, kv_heads, head_dim),
                jnp.bfloat16,
            )
            for i in (1, 2)
        )
        for backend in backends:
            try:
                fwd = jax.jit(
                    partial(
                        dot_product_attention, causal=True, backend=backend
                    )
                )

                def loss(q, k, v):
                    return (
                        dot_product_attention(
                            q, k, v, causal=True, backend=backend
                        )
                        .astype(jnp.float32)
                        .sum()
                    )

                bwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                for mode, fn in (("fwd", fwd), ("fwd+bwd", bwd)):
                    if (seq, kv_heads, backend, mode) in done:
                        continue  # resumed sweep: already on disk
                    dt = _time_call(fn, q, k, v)
                    emit(
                        {
                            "seq": seq,
                            "backend": backend,
                            "mode": mode,
                            "ms_per_call": round(dt * 1e3, 3),
                            "tokens_per_sec": round(batch * seq / dt, 1),
                            "platform": device.platform,
                            "device_kind": device.device_kind,
                            "batch": batch,
                            "heads": heads,
                            "kv_heads": kv_heads,
                            "head_dim": head_dim,
                        }
                    )
            except Exception as e:  # noqa: BLE001 — report, keep sweeping
                emit(
                    {
                        "seq": seq,
                        "kv_heads": kv_heads,
                        "backend": backend,
                        "error": f"{type(e).__name__}: {e}"[:200],
                    }
                )


if __name__ == "__main__":
    main()
