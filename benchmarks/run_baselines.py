"""BASELINE.md harness: measure every north-star config on this machine.

Runs each BASELINE config through the framework's own Trainer (the loop
`polyaxon run` drives), times a warm re-run (compile excluded), and prints
one JSON line per config plus a markdown table ready for BASELINE.md.

  python benchmarks/run_baselines.py                 # all configs
  python benchmarks/run_baselines.py resnet50 bert   # subset
  python benchmarks/run_baselines.py --update-baseline  # rewrite BASELINE.md

Sizes are chip-sized on TPU (the judged numbers) and tiny on CPU (harness
smoke). Device kind and MFU (analytic FLOPs over peak bf16) are recorded so
numbers are comparable across rounds.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

def _peak(device_kind: str):
    from polyaxon_tpu.utils.tpu_info import peak_bf16_flops

    return peak_bf16_flops(device_kind)


def _program(model, data, optimizer, train):
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec, V1ModelSpec, V1OptimizerSpec, V1Program, V1TrainSpec,
    )

    return V1Program(
        model=V1ModelSpec(**model),
        data=V1DataSpec(**data),
        optimizer=V1OptimizerSpec(**optimizer),
        train=V1TrainSpec(**train),
    )


def _configs(on_tpu: bool) -> dict:
    """name → (program kwargs, unit, items_per_step fn, flops_per_item)."""
    if on_tpu:
        return {
            "mnist_mlp": dict(
                model={"name": "mlp", "config": {"hidden": [512, 256], "num_classes": 10, "input_dim": 784}},
                data={"name": "mnist", "batch_size": 128},
                optimizer={"name": "adamw", "learning_rate": 1e-3},
                train={"steps": 100, "log_every": 100, "precision": "float32"},
                unit="examples/sec", per_step=128, flops_per_item=None,
            ),
            "resnet50": dict(
                model={"name": "resnet50", "config": {"num_classes": 1000}},
                data={"name": "synthetic_imagenet", "batch_size": 128},
                optimizer={"name": "sgd", "learning_rate": 0.1,
                           "config": {"momentum": 0.9, "nesterov": True}},
                train={"steps": 20, "log_every": 20, "precision": "mixed"},
                unit="images/sec", per_step=128,
                flops_per_item=3 * 4.09e9,  # fwd 4.09 GFLOP @224 + ~2x bwd
            ),
            "bert_base": dict(
                model={"name": "bert", "config": {"preset": "bert-base", "seq_len": 128}},
                data={"name": "synthetic_mlm", "batch_size": 64,
                      "config": {"seq_len": 128, "vocab_size": 30522}},
                optimizer={"name": "adamw", "learning_rate": 1e-4},
                train={"steps": 30, "log_every": 30, "precision": "mixed"},
                unit="tokens/sec", per_step=64 * 128,
                flops_per_item=6 * 110e6,  # 6N per token, N≈110M
            ),
            "llama_lora": dict(
                model={"name": "llama", "config": {
                    "variant": "1b", "max_len": 1024,
                    # single v5e chip: the [4, 1024, 128256] logits (f32
                    # fwd + dlogits bwd) alone exceed HBM headroom next to
                    # the 1.24B base — fused head+CE keeps them virtual,
                    # flash attention streams KV instead of materializing
                    # [B, H, S, S] scores (observed OOM at seq 1024, r5)
                    "fused_lm_loss": True, "attention": "flash",
                    "lora": {"rank": 16, "alpha": 32,
                             "targets": ["q_proj", "k_proj", "v_proj", "o_proj"]}}},
                data={"name": "synthetic_text", "batch_size": 4,
                      "config": {"seq_len": 1024, "vocab_size": 128256}},
                optimizer={"name": "adamw", "learning_rate": 2e-4},
                train={"steps": 10, "log_every": 10, "precision": "mixed",
                       "remat": True},
                unit="tokens/sec", per_step=4 * 1024,
                flops_per_item=6 * 1.24e9,  # 6N per token, N≈1.24B (grads flow through the frozen base)
            ),
        }
    # CPU smoke tier: prove the harness end-to-end in seconds
    return {
        "mnist_mlp": dict(
            model={"name": "mlp", "config": {"hidden": [64], "num_classes": 10, "input_dim": 784}},
            data={"name": "mnist", "batch_size": 32},
            optimizer={"name": "adamw", "learning_rate": 1e-3},
            train={"steps": 20, "log_every": 20, "precision": "float32"},
            unit="examples/sec", per_step=32, flops_per_item=None,
        ),
        "resnet50": dict(
            model={"name": "resnet50",
                   "config": {"num_classes": 10, "image_size": 64}},
            data={"name": "synthetic_imagenet", "batch_size": 4,
                  "config": {"image_size": 64, "num_classes": 10}},
            optimizer={"name": "sgd", "learning_rate": 0.1},
            train={"steps": 3, "log_every": 3, "precision": "float32"},
            unit="images/sec", per_step=4, flops_per_item=None,
        ),
        "bert_base": dict(
            model={"name": "bert", "config": {"dim": 128, "n_layers": 2, "n_heads": 4,
                                              "seq_len": 64, "vocab_size": 1024}},
            data={"name": "synthetic_mlm", "batch_size": 8,
                  "config": {"seq_len": 64, "vocab_size": 1024}},
            optimizer={"name": "adamw", "learning_rate": 1e-4},
            train={"steps": 5, "log_every": 5, "precision": "float32"},
            unit="tokens/sec", per_step=8 * 64, flops_per_item=None,
        ),
        "llama_lora": dict(
            model={"name": "llama", "config": {
                "dim": 128, "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
                "vocab_size": 1024, "seq_len": 128,
                "lora": {"rank": 4, "alpha": 8, "targets": ["q_proj", "v_proj"]}}},
            data={"name": "synthetic_text", "batch_size": 4,
                  "config": {"seq_len": 128, "vocab_size": 1024}},
            optimizer={"name": "adamw", "learning_rate": 2e-4},
            train={"steps": 5, "log_every": 5, "precision": "float32"},
            unit="tokens/sec", per_step=4 * 128, flops_per_item=None,
        ),
    }


def bench_training(name: str, cfg: dict, device) -> dict:
    from polyaxon_tpu.runtime.trainer import Trainer

    program = _program(cfg["model"], cfg["data"], cfg["optimizer"], cfg["train"])
    steps = cfg["train"]["steps"]
    trainer = Trainer(program, devices=[device])
    trainer.run()  # compile + warm
    t0 = time.perf_counter()
    result = trainer.run()
    dt = time.perf_counter() - t0
    rate = steps * cfg["per_step"] / dt
    mfu = None
    peak = _peak(device.device_kind)
    if cfg["flops_per_item"] and peak:
        mfu = round(cfg["flops_per_item"] * rate / peak, 4)
    return {
        "config": name,
        "value": round(rate, 1),
        "unit": cfg["unit"],
        "mfu": mfu,
        "device_kind": device.device_kind,
        "final_loss": round(result.history[-1]["loss"], 4) if result.history else None,
    }


def bench_tuner(device, on_tpu: bool) -> dict:
    """Polytune trials/hour: a ViT grid sweep (BASELINE config #4 shape)
    driven by the sweep driver; wall-clock per completed trial."""
    import os
    import tempfile

    os.environ.setdefault("POLYAXON_HOME", tempfile.mkdtemp(prefix="plx-bench-"))
    import yaml

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.tuner.driver import run_sweep

    if on_tpu:
        model_cfg = {"preset": "vit-s16", "num_classes": 1000}
        data = {"name": "synthetic_imagenet", "batchSize": 64}
        steps, n_trials = 10, 4
    else:
        model_cfg = {"dim": 64, "n_layers": 2, "n_heads": 4, "patch": 8,
                     "image_size": 32, "num_classes": 10}
        data = {"name": "synthetic_imagenet", "batchSize": 4,
                "config": {"image_size": 32, "num_classes": 10}}
        steps, n_trials = 2, 2
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "vit-sweep-bench",
        "matrix": {"kind": "grid", "params": {"lr": {"kind": "choice", "value": [1e-3, 3e-4, 1e-4, 3e-3][:n_trials]}}},
        "component": {
            "kind": "component",
            "name": "vit",
            "inputs": [{"name": "lr", "type": "float", "value": 1e-3}],
            "run": {
                "kind": "jaxjob",
                "program": {
                    "model": {"name": "vit", "config": model_cfg},
                    "data": data,
                    "optimizer": {"name": "adamw", "learningRate": "{{ params.lr }}"},
                    "train": {"steps": steps, "logEvery": steps, "precision": "mixed" if on_tpu else "float32"},
                },
            },
        },
    }
    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
        yaml.safe_dump(spec, f)
        path = f.name
    op = read_polyaxonfile(path)
    t0 = time.perf_counter()
    summary = run_sweep(op, devices=[device])
    dt = time.perf_counter() - t0
    done = len(summary.get("trials") or []) or n_trials
    return {
        "config": "polytune_vit_sweep",
        "value": round(done / (dt / 3600.0), 1),
        "unit": "trials/hour",
        "mfu": None,
        "device_kind": device.device_kind,
        "final_loss": None,
    }


# separate marker pairs per section: a CPU run must never overwrite (or be
# mistaken for) chip evidence, and vice versa — each run only rewrites the
# section matching the device it ran on
_SECTIONS = {
    "tpu": (
        "<!-- baselines:tpu:begin -->",
        "<!-- baselines:tpu:end -->",
        "### TPU-measured (perf evidence)",
    ),
    "cpu": (
        "<!-- baselines:cpu:begin -->",
        "<!-- baselines:cpu:end -->",
        "### CPU smoke tier (proves the pipeline runs — NOT perf evidence)",
    ),
}


def _is_tpu_row(row: dict) -> bool:
    return "cpu" not in str(row.get("device_kind", "cpu")).lower()


def _existing_rows(section_text: str) -> dict[str, str]:
    """config name → rendered table line, parsed back out of a section so a
    partial run (e.g. `run_baselines.py resnet50`) merges instead of
    clobbering the other configs' rows."""
    rows: dict[str, str] = {}
    for line in section_text.splitlines():
        line = line.strip()
        if line.startswith("|") and not line.startswith(("|---", "| Config")):
            name = line.split("|")[1].strip()
            if name:
                rows[name] = line
    return rows


def update_baseline_md(rows: list[dict]):
    md = REPO / "BASELINE.md"
    text = md.read_text()
    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    groups: dict[str, list[dict]] = {"tpu": [], "cpu": []}
    for r in rows:
        if r.get("error"):
            # an errored config must never become (or overwrite) an
            # evidence row — the canary runs this unattended on chip
            print(f"skipping errored row: {r['config']}", file=sys.stderr)
            continue
        groups["tpu" if _is_tpu_row(r) else "cpu"].append(r)
    for key in ("tpu", "cpu"):
        if not groups[key]:
            continue  # preserve the other section's existing rows
        begin, end, title = _SECTIONS[key]
        merged: dict[str, str] = {}
        if begin in text:
            merged = _existing_rows(text.split(begin)[1].split(end)[0])
        for r in groups[key]:
            merged[r["config"]] = (
                f"| {r['config']} | {r['value']:,} | {r['unit']} | "
                f"{r['mfu'] if r['mfu'] is not None else '—'} | "
                f"{r['device_kind']} | "
                f"{r['final_loss'] if r['final_loss'] is not None else '—'} |"
            )
        table = [
            "",
            title,
            "",
            f"Measured by `benchmarks/run_baselines.py`, last update {stamp}:",
            "",
            "| Config | Value | Unit | MFU | Device | Final loss |",
            "|---|---|---|---|---|---|",
            *merged.values(),
        ]
        block = begin + "\n" + "\n".join(table) + "\n" + end
        if begin in text:
            pre = text.split(begin)[0]
            post = text.split(end)[1]
            text = pre + block + post
        else:
            text = text.rstrip() + "\n\n" + block + "\n"
    md.write_text(text)
    print(f"updated {md}", file=sys.stderr)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("configs", nargs="*", help="subset of config names")
    ap.add_argument("--update-baseline", action="store_true")
    args = ap.parse_args()

    from polyaxon_tpu.utils.jax_platform import apply_platform_env

    try:
        apply_platform_env()
    except Exception as e:  # noqa: BLE001
        print(f"baselines: ignoring platform env: {e}", file=sys.stderr)
    import jax

    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    configs = _configs(on_tpu)
    wanted = args.configs or [*configs, "polytune"]

    rows = []
    for name in wanted:
        t0 = time.perf_counter()
        try:
            if name in ("polytune", "polytune_vit_sweep"):
                row = bench_tuner(device, on_tpu)
            else:
                row = bench_training(name, configs[name], device)
        except Exception as e:  # noqa: BLE001 — one bad config never kills the sweep
            row = {"config": name, "value": 0.0, "unit": "—", "mfu": None,
                   "device_kind": device.device_kind, "final_loss": None,
                   "error": f"{type(e).__name__}: {e}"}
        row["wall_s"] = round(time.perf_counter() - t0, 1)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.update_baseline:
        update_baseline_md(rows)


if __name__ == "__main__":
    main()
