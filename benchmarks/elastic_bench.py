"""Elastic-training benchmark: checkpoint stall, time-to-resume, and
steps lost per preemption — the three costs ISSUE 7's tiers + gang
resize are supposed to bound.

  python benchmarks/elastic_bench.py             # full seeded sweep
  python benchmarks/elastic_bench.py --smoke     # tier-1 quick pass
  python benchmarks/elastic_bench.py --seeds 5 --steps 16

Three measurements, one JSON line each (schema pinned by
tests/test_benchmarks.py):

- **checkpoint_stall_ms** — a clean run with two-tier checkpointing; the
  stall is read from the trainer's `trainer.checkpoint_stall_ms`
  histogram (the span around the async save call), NOT a second clock,
  so the benchmark reports exactly what /metricsz exports.

- **steps_lost_per_preemption** — seeded `kill_mid_run` scenarios (a
  kill checkpoints nothing, unlike cooperative eviction): lost work per
  death is `kill_step - resumed_step`, which multi-tier boundary saves
  bound by `checkpoint_every`. Time-to-resume is the wall time from the
  RETRYING transition to the `resumed` event (backoff excluded by
  zeroing the retry delay).

- **elastic_resize** — the shrink→grow round trip through the REAL
  admission stack under SimClock: a full-fleet elastic job yields to a
  higher-priority arrival by shrinking instead of waiting, then grows
  back when the chips free. Reports grant history, queue-wait total
  (must be 0: the ladder never parks), and makespan versus a rigid run
  that would have waited.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _train_op(name: str, *, steps: int, checkpoint_every: int,
              local_dir: str, max_retries: int = 0, backoff: float = 0.0):
    from polyaxon_tpu.schemas.operation import V1Operation

    return V1Operation.model_validate(
        {
            "kind": "operation",
            "name": name,
            "component": {
                "kind": "component",
                "name": "c",
                "termination": {
                    "maxRetries": max_retries,
                    "backoff": backoff,
                    "jitter": 0,
                },
                "run": {
                    "kind": "jaxjob",
                    "program": {
                        "model": {
                            "name": "mlp",
                            "config": {
                                "input_dim": 8,
                                "num_classes": 2,
                                "hidden": [8],
                            },
                        },
                        "data": {
                            "name": "synthetic",
                            "batchSize": 8,
                            "config": {"shape": [8], "num_classes": 2},
                        },
                        "optimizer": {"name": "sgd", "learningRate": 0.01},
                        "train": {
                            "steps": steps,
                            "logEvery": 1,
                            "precision": "float32",
                            "checkpointEvery": checkpoint_every,
                            "checkpointLocalDir": local_dir,
                        },
                    },
                },
            },
        }
    )


def _execute(op, home: str):
    from polyaxon_tpu.compiler import compile_operation
    from polyaxon_tpu.runtime import Executor
    from polyaxon_tpu.store import RunStore

    store = RunStore(home)
    compiled = compile_operation(op)
    status = Executor(store, devices=None).execute(compiled)
    return store, compiled.run_uuid, getattr(status, "value", str(status))


def bench_checkpoint_stall(steps: int, checkpoint_every: int) -> dict:
    """A clean two-tier run; the stall histogram is the evidence that the
    async save + background upload keep the step loop moving."""
    from polyaxon_tpu.telemetry import get_registry

    home = tempfile.mkdtemp(prefix="elastic-bench-")
    local = tempfile.mkdtemp(prefix="elastic-bench-fast-")
    try:
        op = _train_op("stall", steps=steps,
                       checkpoint_every=checkpoint_every, local_dir=local)
        _store, _uuid, status = _execute(op, home)
        hist = get_registry().histogram("trainer.checkpoint_stall_ms")
        tier_writes = get_registry().counter("checkpoint.tier_writes").value
        summary = hist.summary()
        return {
            "metric": "checkpoint_stall_ms",
            "status": status,
            "boundaries": hist.count,
            "stall_p50_ms": summary["p50"],
            "stall_p95_ms": summary["p95"],
            "stall_max_ms": summary["max"],
            "tier_writes": tier_writes,
        }
    finally:
        shutil.rmtree(home, ignore_errors=True)
        shutil.rmtree(local, ignore_errors=True)


def bench_steps_lost(seeds: list[int], steps: int,
                     checkpoint_every: int) -> dict:
    """Seeded kills (the worst case: nothing is flushed on the way down).
    Lost steps per death must stay <= checkpoint_every; time-to-resume is
    the RETRYING→resumed wall time."""
    from polyaxon_tpu import chaos
    from polyaxon_tpu.chaos import FaultPlan

    lost: list[int] = []
    resume_ms: list[float] = []
    for seed in seeds:
        plan = FaultPlan.kill_mid_run(
            seed, steps=steps, min_step=checkpoint_every
        )
        home = tempfile.mkdtemp(prefix="elastic-bench-")
        local = tempfile.mkdtemp(prefix="elastic-bench-fast-")
        try:
            op = _train_op(
                f"kill-{seed}", steps=steps,
                checkpoint_every=checkpoint_every, local_dir=local,
                max_retries=1,
            )
            with chaos.active(plan):
                store, uuid, status = _execute(op, home)
            if status != "succeeded":
                return {"metric": "steps_lost_per_preemption",
                        "error": f"seed {seed} ended {status}"}
            resumed = [
                e for e in store.read_events(uuid) if e["kind"] == "resumed"
            ]
            resumed_step = resumed[0]["step"] if resumed else 0
            resumed_ts = resumed[0]["ts"] if resumed else None
            lost.append(plan.params["kill_step"] - resumed_step)
            retrying = [
                c for c in store.get_status(uuid)["conditions"]
                if c["type"] == "retrying"
            ]
            if retrying and resumed_ts is not None:
                resume_ms.append(
                    max(0.0, (resumed_ts - retrying[0]["ts"]) * 1000.0)
                )
        finally:
            shutil.rmtree(home, ignore_errors=True)
            shutil.rmtree(local, ignore_errors=True)
    n = len(resume_ms)
    return {
        "metric": "steps_lost_per_preemption",
        "preemptions": len(lost),
        "checkpoint_every": checkpoint_every,
        "steps_lost_mean": sum(lost) / len(lost) if lost else None,
        "steps_lost_max": max(lost) if lost else None,
        "bound_held": bool(lost) and max(lost) <= checkpoint_every,
        "time_to_resume_ms_mean": (sum(resume_ms) / n) if n else None,
        "time_to_resume_ms_max": max(resume_ms) if n else None,
    }


def bench_elastic_resize(duration: float = 8.0) -> dict:
    """Deterministic shrink→grow round trip in sim time: quantifies what
    the halving ladder buys over parking in WAIT."""
    from polyaxon_tpu.scheduler.sim import FleetSimulator, SimJob

    def scenario():
        elastic = SimJob("elastic", duration=duration, arrival=0.0,
                         chips=4, min_chips=1)
        rigid = SimJob("rigid", duration=duration / 2, arrival=2.0,
                       chips=2, priority=1)
        return elastic, rigid

    elastic, rigid = scenario()
    sim = FleetSimulator([elastic, rigid], chips=4,
                         invariant_fn=lambda s: s.check_invariants())
    try:
        report = sim.run()
    finally:
        shutil.rmtree(sim.home, ignore_errors=True)

    # counterfactual: the same workload with a RIGID victim — after the
    # eviction it parks in WAIT until the whole block frees
    victim, arrival = scenario()
    victim.min_chips = None
    rigid_sim = FleetSimulator([victim, arrival], chips=4)
    try:
        rigid_report = rigid_sim.run()
    finally:
        shutil.rmtree(rigid_sim.home, ignore_errors=True)

    return {
        "metric": "elastic_resize",
        "grants": elastic.grants,
        "resizes": report["elastic_resizes"],
        "preemptions": elastic.preemptions,
        "elastic_wait_total_s": sum(elastic.waits),
        "elastic_makespan_s": report["makespan_s"],
        "rigid_makespan_s": rigid_report["makespan_s"],
        "rigid_wait_total_s": sum(victim.waits),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seeds", type=int, default=3,
                   help="number of seeded kill scenarios")
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--checkpoint-every", type=int, default=2)
    p.add_argument("--smoke", action="store_true",
                   help="small deterministic pass for tier-1 CI")
    args = p.parse_args(argv)
    if args.smoke:
        args.seeds, args.steps = 1, 6

    records = [
        bench_checkpoint_stall(args.steps, args.checkpoint_every),
        bench_steps_lost(list(range(args.seeds)), args.steps,
                         args.checkpoint_every),
        bench_elastic_resize(),
    ]
    ok = True
    for r in records:
        print(json.dumps(r, sort_keys=True))
        if "error" in r:
            ok = False
    lost = next(r for r in records
                if r["metric"] == "steps_lost_per_preemption")
    if "error" not in lost and not lost["bound_held"]:
        print("FAIL: steps lost exceeded checkpoint_every", file=sys.stderr)
        ok = False
    resize = next(r for r in records if r["metric"] == "elastic_resize")
    if resize["elastic_wait_total_s"] != 0.0:
        print("FAIL: elastic run parked in WAIT", file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
