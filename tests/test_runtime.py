"""End-to-end slice tests: trainer, executor, store, tracking, CLI.

Multi-device behavior runs on the virtual 8-device CPU mesh from conftest
(SURVEY.md §4: execute on a fake slice, not just golden-render)."""

import json

import jax
import numpy as np
import pytest

from polyaxon_tpu.compiler import compile_operation
from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.runtime import Executor
from polyaxon_tpu.runtime.trainer import Trainer
from polyaxon_tpu.schemas.run_kinds import V1Program
from polyaxon_tpu.store import RunStore


def make_program(**train_overrides):
    train = {"steps": 10, "logEvery": 5, "precision": "float32", "seed": 0}
    train.update(train_overrides)
    return V1Program.model_validate(
        {
            "model": {"name": "mlp", "config": {"hidden": [32], "input_dim": 16, "num_classes": 4}},
            "data": {"name": "synthetic", "batchSize": 32, "config": {"shape": [16], "num_classes": 4}},
            "optimizer": {"name": "adamw", "learningRate": 0.01},
            "train": train,
        }
    )


class TestTrainer:
    def test_loss_descends_single_device(self):
        logs = []
        t = Trainer(make_program(steps=30), mesh_axes={"data": 1},
                    devices=jax.devices()[:1], log_fn=lambda s, m: logs.append((s, m)))
        result = t.run()
        assert result.history[0]["loss"] > result.history[-1]["loss"]
        assert logs and logs[-1][0] == 30

    def test_dp_over_8_devices_matches_single_device(self):
        """Same seed → same loss trajectory whether batch is sharded 8-way
        or runs on one device: the SPMD step is numerically the program."""
        r1 = Trainer(make_program(), mesh_axes={"data": 8}).run()
        r2 = Trainer(make_program(), mesh_axes={"data": 1}, devices=jax.devices()[:1]).run()
        np.testing.assert_allclose(
            [h["loss"] for h in r1.history],
            [h["loss"] for h in r2.history],
            rtol=2e-4,
        )

    def test_fsdp_and_model_axes(self):
        r = Trainer(make_program(), mesh_axes={"data": 2, "fsdp": 2, "model": 2}).run()
        assert r.history[-1]["loss"] < r.history[0]["loss"]
        # params actually sharded over fsdp/model axes
        t = Trainer(make_program(steps=1), mesh_axes={"data": 2, "fsdp": 2, "model": 2})
        kernel = t.state.params["dense_0"]["kernel"]
        assert len(kernel.sharding.device_set) > 1

    def test_mixed_precision_bf16(self):
        r = Trainer(make_program(precision="mixed", steps=10), mesh_axes={"data": 8}).run()
        assert r.history[-1]["loss"] < r.history[0]["loss"]

    def test_checkpoint_retention_keep(self, tmp_path):
        """checkpointKeep bounds on-disk checkpoints: a frequent-save run
        must not fill the artifact store."""
        import re

        from polyaxon_tpu.runtime.checkpoint import close_all

        ckdir = tmp_path / "ck-keep"
        p = make_program(steps=8, checkpointEvery=2, checkpointKeep=2)
        t = Trainer(p, mesh_axes={"data": 8}, checkpoint_dir=str(ckdir))
        t.run()
        close_all()  # flush async saves + release the manager
        steps = sorted(
            int(d.name) for d in ckdir.iterdir() if re.fullmatch(r"\d+", d.name)
        )
        assert steps == [6, 8], steps  # only the newest `keep` survive

    def test_checkpoint_keep_survives_resume(self, tmp_path):
        """Resume touches the manager before the first save; checkpointKeep
        must flow through restore or the cached manager pins the default
        retention and silently overrides the spec."""
        import re

        from polyaxon_tpu.runtime.checkpoint import close_all

        ckdir = tmp_path / "ck-resume-keep"
        p = make_program(steps=4, checkpointEvery=2, checkpointKeep=4)
        Trainer(p, mesh_axes={"data": 8}, checkpoint_dir=str(ckdir)).run()
        close_all()
        p2 = make_program(steps=10, checkpointEvery=2, checkpointKeep=4, resume=True)
        t2 = Trainer(p2, mesh_axes={"data": 8}, checkpoint_dir=str(ckdir))
        assert t2.restore() == 4  # manager first touched by resume
        t2.run()
        close_all()
        steps = sorted(
            int(d.name) for d in ckdir.iterdir() if re.fullmatch(r"\d+", d.name)
        )
        assert steps == [4, 6, 8, 10], steps  # keep=4 honored, not default 3

    def test_checkpoint_resume(self, tmp_path):
        ckdir = str(tmp_path / "ck")
        p = make_program(steps=10, checkpointEvery=5)
        t1 = Trainer(p, mesh_axes={"data": 8}, checkpoint_dir=ckdir)
        t1.run()
        p2 = make_program(steps=15, checkpointEvery=5, resume=True)
        t2 = Trainer(p2, mesh_axes={"data": 8}, checkpoint_dir=ckdir)
        start = t2.restore()
        assert start == 10
        assert int(t2.state.step) == 10


class TestExecutorAndStore:
    def test_mnist_yaml_end_to_end(self, tmp_home):
        op = read_polyaxonfile("examples/mnist.yaml", params={"steps": 6, "batch_size": 32})
        store = RunStore()
        compiled = compile_operation(op)
        status = Executor(store, devices=jax.devices()[:1]).execute(compiled)
        assert status == "succeeded"
        metrics = store.read_metrics(compiled.run_uuid)
        assert metrics and metrics[-1]["step"] == 6
        statuses = [c["type"] for c in store.get_status(compiled.run_uuid)["conditions"]]
        assert statuses == [
            "created", "compiled", "queued", "scheduled", "starting", "running", "succeeded",
        ]

    def test_failed_run_records_reason(self, tmp_home):
        op = read_polyaxonfile("examples/mnist.yaml")
        # unknown model name → compile passes (registry checked at runtime), run fails
        op.component.run.program.model.name = "no-such-model"
        compiled = compile_operation(op)
        status = Executor(RunStore()).execute(compiled)
        assert status == "failed"
        st = RunStore().get_status(compiled.run_uuid)
        assert "no-such-model" in st["conditions"][-1]["message"]

    def test_container_job_subprocess(self, tmp_home):
        from polyaxon_tpu.schemas import V1Operation

        op = V1Operation.model_validate(
            {
                "kind": "operation",
                "name": "echo",
                "component": {
                    "kind": "component",
                    "run": {"kind": "job", "container": {"command": ["echo", "hello-{{ globals.uuid }}"]}},
                },
            }
        )
        store = RunStore()
        compiled = compile_operation(op)
        assert Executor(store).execute(compiled) == "succeeded"
        assert f"hello-{compiled.run_uuid}" in store.read_logs(compiled.run_uuid)

    def test_retry_on_failure(self, tmp_home):
        from polyaxon_tpu.schemas import V1Operation

        op = V1Operation.model_validate(
            {
                "kind": "operation",
                "name": "flaky",
                "component": {
                    "kind": "component",
                    "termination": {"maxRetries": 2},
                    "run": {"kind": "job", "container": {"command": ["false"]}},
                },
            }
        )
        store = RunStore()
        compiled = compile_operation(op)
        assert Executor(store).execute(compiled) == "failed"
        types = [c["type"] for c in store.get_status(compiled.run_uuid)["conditions"]]
        assert types.count("retrying") == 2


class TestTracking:
    def test_standalone_tracked_run(self, tmp_home):
        from polyaxon_tpu import tracking

        run = tracking.Run(name="nb", project="p1")
        run.log_metrics(step=1, loss=0.5)
        run.log_metrics(step=2, loss=0.25)
        run.log_outputs(best_loss=0.25)
        run.end()
        store = RunStore()
        assert store.get_status(run.uuid)["status"] == "succeeded"
        assert [m["loss"] for m in store.read_metrics(run.uuid)] == [0.5, 0.25]
        events = store.read_events(run.uuid)
        assert events[0]["outputs"] == {"best_loss": 0.25}

    def test_attach_via_env(self, tmp_home, monkeypatch):
        from polyaxon_tpu import tracking

        store = RunStore()
        store.create_run("abc123", "r", "p", {})
        monkeypatch.setenv("POLYAXON_RUN_UUID", "abc123")
        run = tracking.Run()
        run.log_metric("m", 1.0, step=0)
        assert store.read_metrics("abc123")[0]["m"] == 1.0


class TestCli:
    def test_run_and_ops(self, tmp_home):
        from click.testing import CliRunner

        from polyaxon_tpu.cli.main import cli

        runner = CliRunner()
        res = runner.invoke(
            cli, ["run", "-f", "examples/mnist.yaml", "-P", "steps=4", "-P", "batch_size=16"]
        )
        assert res.exit_code == 0, res.output
        res = runner.invoke(cli, ["ops", "ls"])
        assert "succeeded" in res.output
        uid = res.output.split()[0]
        res = runner.invoke(cli, ["ops", "metrics", "-uid", uid])
        assert json.loads(res.output.splitlines()[-1])["step"] == 4
        res = runner.invoke(cli, ["ops", "statuses", "-uid", uid])
        assert "succeeded" in res.output

    def test_check(self, tmp_home):
        from click.testing import CliRunner

        from polyaxon_tpu.cli.main import cli

        res = CliRunner().invoke(cli, ["check", "-f", "examples/resnet50.yaml"])
        assert res.exit_code == 0, res.output
        spec = json.loads(res.output)
        # mesh -1 resolved against the 2x4 tpu slice
        assert spec["component"]["run"]["mesh"] == {"data": 8}

    @pytest.mark.slow
    def test_ops_compare(self, tmp_home):
        from click.testing import CliRunner

        from polyaxon_tpu.cli.main import cli

        runner = CliRunner()
        uids = []
        for lr in ("0.001", "0.01"):
            res = runner.invoke(
                cli,
                ["run", "-f", "examples/mnist.yaml", "-P", "steps=3",
                 "-P", "batch_size=16", "-P", f"lr={lr}"],
            )
            assert res.exit_code == 0, res.output
            uids.append(res.output.split("run ")[1][:8])
        res = runner.invoke(
            cli, ["ops", "compare", "--uid", uids[0], "--uid", uids[1]]
        )
        assert res.exit_code == 0, res.output
        assert "param.lr" in res.output and "loss" in res.output
        assert "0.001" in res.output and "0.01" in res.output
        res = runner.invoke(cli, ["ops", "compare", "--uid", uids[0]])
        assert res.exit_code != 0 and "at least two" in res.output


def test_grad_accum_matches_full_batch(tmp_home):
    """gradAccum=4 over a batch of 32 must take the same first optimizer
    step as one full-batch update (same data, float32, SGD) — accumulation
    is exact, not approximate."""
    import jax
    import numpy as np

    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    def prog(accum):
        return V1Program(
            model=V1ModelSpec(
                name="mlp", config={"input_dim": 8, "num_classes": 2, "hidden": [4]}
            ),
            data=V1DataSpec(
                name="synthetic", batch_size=32,
                config={"shape": [8], "num_classes": 2},
            ),
            optimizer=V1OptimizerSpec(name="sgd", learning_rate=0.1),
            train=V1TrainSpec(
                steps=1, log_every=1, precision="float32", seed=3,
                grad_accum=accum, donate_state=False,
            ),
        )

    dev = [jax.devices()[0]]
    t_full = Trainer(prog(None), devices=dev)
    t_acc = Trainer(prog(4), devices=dev)
    r_full = t_full.run()
    r_acc = t_acc.run()
    # same seed → same data stream → identical first-step loss and params
    assert abs(r_full.history[0]["loss"] - r_acc.history[0]["loss"]) < 1e-5
    for a, b in zip(
        jax.tree.leaves(t_full.state.params), jax.tree.leaves(t_acc.state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_grad_accum_trains_on_mesh(tmp_home):
    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    program = V1Program(
        model=V1ModelSpec(
            name="mlp", config={"input_dim": 16, "num_classes": 4, "hidden": [8]}
        ),
        data=V1DataSpec(
            name="synthetic", batch_size=32, config={"shape": [16], "num_classes": 4}
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=0.01),
        train=V1TrainSpec(steps=20, log_every=20, precision="float32", grad_accum=2),
    )
    result = Trainer(program, mesh_axes={"data": -1}).run()
    first, last = result.history[0], result.history[-1]
    assert last["loss"] == last["loss"]  # finite
    assert last["loss"] < 1.6  # descending on the learnable stream


@pytest.mark.slow
@pytest.mark.parametrize("policy", ["nothing", "dots", "dots_no_batch"])
def test_remat_policies_compile_and_train(tmp_home, policy):
    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    program = V1Program(
        model=V1ModelSpec(
            name="transformer_lm", config={"preset": "tiny", "seq_len": 32}
        ),
        data=V1DataSpec(
            name="synthetic_text", batch_size=8,
            config={"seq_len": 32, "vocab_size": 4096},
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
        train=V1TrainSpec(
            steps=2, log_every=2, precision="float32", remat_policy=policy
        ),
    )
    result = Trainer(program, mesh_axes={"data": -1}).run()
    assert result.history[-1]["loss"] == result.history[-1]["loss"]


def test_service_runs_until_stopped(tmp_home, tmp_path):
    """Services stay RUNNING until a stop lands (then STOPPED, process
    terminated); self-exit is a failure, not success."""
    import threading
    import time

    import yaml

    from polyaxon_tpu.client import RunClient
    from polyaxon_tpu.schemas.lifecycle import V1Statuses

    def svc_op(cmd):
        spec = {
            "version": 1.1,
            "kind": "operation",
            "name": "svc",
            "component": {
                "kind": "component",
                "name": "svc",
                "run": {
                    "kind": "service",
                    "ports": [7777],
                    "container": {"command": ["sh", "-c", cmd]},
                },
            },
        }
        p = tmp_path / "svc.yaml"
        p.write_text(yaml.safe_dump(spec))
        from polyaxon_tpu.polyaxonfile import read_polyaxonfile

        return read_polyaxonfile(str(p))

    client = RunClient()
    results = {}
    op = svc_op('echo "serving on $POLYAXON_SERVICE_PORT"; sleep 60')

    def _run():
        results["uuid"] = client.create(op, queue=False)

    t = threading.Thread(target=_run)
    t.start()
    deadline = time.time() + 30
    uuid = None
    while time.time() < deadline:
        runs = client.list()
        if runs and runs[0]["status"] == V1Statuses.RUNNING:
            uuid = runs[0]["uuid"]
            break
        time.sleep(0.2)
    assert uuid, "service never reached RUNNING"
    time.sleep(1.0)
    client.stop(uuid)
    t.join(timeout=30)
    assert not t.is_alive()
    assert client.get(uuid)["status"] == V1Statuses.STOPPED
    assert "serving on 7777" in client.logs(uuid)

    # a service that exits by itself FAILED, even with exit code 0
    uuid2 = client.create(svc_op("true"), queue=False)
    assert client.get(uuid2)["status"] == V1Statuses.FAILED
    assert "exited unexpectedly" in client.logs(uuid2)


def test_mesh_model_axis_mismatch_friendly_error(tmp_home):
    """A model axis that doesn't divide n_heads fails with a config error,
    not an opaque XLA sharding crash."""
    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1Program,
    )

    program = V1Program(
        model=V1ModelSpec(
            name="transformer_lm",
            config={"dim": 96, "n_layers": 2, "n_heads": 3, "n_kv_heads": 3,
                    "vocab_size": 256, "seq_len": 32},
        ),
        data=V1DataSpec(
            name="synthetic_text", batch_size=8,
            config={"seq_len": 32, "vocab_size": 256},
        ),
    )
    with pytest.raises(ValueError, match="n_heads .3. is not divisible"):
        Trainer(program, mesh_axes={"model": 2, "data": 4})

    with pytest.raises(ValueError, match="no\\s+.?experts"):
        Trainer(program, mesh_axes={"expert": 2, "data": 4})


def test_data_model_shape_mismatch_is_clear():
    """A dataset whose feature shape disagrees with the model must fail at
    build time with a config-level message, not a flax scope error deep in
    the first apply."""
    import pytest

    from polyaxon_tpu.runtime.trainer import Trainer

    p = make_program()
    p.model.config = {"input_dim": 16, "num_classes": 4, "hidden": [32]}
    p.data.config = {"shape": [32], "num_classes": 4}
    with pytest.raises(ValueError, match="data/model shape mismatch"):
        Trainer(p, mesh_axes={"data": 8})

    # flattening models compare by element count, not tuple equality:
    # (28,28,1) into an mlp expecting (784,) is a valid, working config
    p2 = make_program(steps=1, logEvery=1)
    p2.model.config = {"input_dim": 784, "num_classes": 10, "hidden": [16]}
    p2.data = p2.data.model_copy(update={"name": "mnist", "config": {"flat": False}})
    Trainer(p2, mesh_axes={"data": 8})  # must not raise
