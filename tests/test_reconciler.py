"""Reconciliation loop: fake-cluster pod phases drive run lifecycle —
the reference operator's reconcile duty (SURVEY.md §3 stack (d))."""

import yaml

from polyaxon_tpu.connections.schemas import ConnectionCatalog
from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.scheduler.agent import Agent
from polyaxon_tpu.scheduler.reconciler import (
    ClusterSubmitter,
    Reconciler,
    aggregate_pods,
)
from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.store.local import RunStore


class FakeCluster:
    """Dict-driven stand-in for the k8s API: tests mutate pod phases."""

    def __init__(self):
        self.submitted: dict[str, list[dict]] = {}
        self.pods: dict[str, list[dict]] = {}
        self.deleted: list[str] = []

    def submit(self, run_uuid, manifests):
        self.submitted[run_uuid] = manifests
        # a fresh gang comes up Pending, one pod per job completion
        job = next(m for m in manifests if m["kind"] == "Job")
        n = int(job["spec"].get("completions") or 1)
        self.pods[run_uuid] = [
            {"name": f"w-{i}", "phase": "Pending"} for i in range(n)
        ]

    def status(self, run_uuid):
        return {"pods": self.pods.get(run_uuid, [])}

    def delete(self, run_uuid):
        self.deleted.append(run_uuid)
        self.pods.pop(run_uuid, None)

    def set_all(self, run_uuid, phase):
        for p in self.pods.get(run_uuid, []):
            p["phase"] = phase


SPEC = {
    "version": 1.1,
    "kind": "operation",
    "name": "clusterjob",
    "component": {
        "kind": "component",
        "name": "clusterjob",
        "termination": {"maxRetries": 1},
        "run": {
            "kind": "jaxjob",
            "replicas": 2,
            "container": {"image": "img", "command": ["train"]},
        },
    },
}


def _submit(tmp_path, store, cluster):
    p = tmp_path / "op.yaml"
    p.write_text(yaml.safe_dump(SPEC))
    op = read_polyaxonfile(str(p))
    agent = Agent(
        store=store,
        submit_fn=ClusterSubmitter(store, cluster, ConnectionCatalog()),
    )
    uuid = agent.submit(op)
    agent.drain()
    return uuid


def test_aggregate_pods():
    assert aggregate_pods([]) is None
    assert aggregate_pods([{"phase": "Pending"}]) is None
    assert aggregate_pods([{"phase": "Running"}, {"phase": "Pending"}]) == V1Statuses.RUNNING
    assert aggregate_pods([{"phase": "Succeeded"}] * 2) == V1Statuses.SUCCEEDED
    assert (
        aggregate_pods([{"phase": "Succeeded"}, {"phase": "Failed"}])
        == V1Statuses.FAILED
    )


def test_pod_transitions_drive_lifecycle(tmp_home, tmp_path):
    store, cluster = RunStore(), FakeCluster()
    uuid = _submit(tmp_path, store, cluster)
    assert store.get_status(uuid)["status"] == V1Statuses.SCHEDULED
    assert uuid in cluster.submitted

    rec = Reconciler(store, cluster)
    assert rec.tick() == []  # all Pending: nothing to conclude

    cluster.set_all(uuid, "Running")
    assert rec.tick() == [(uuid, V1Statuses.RUNNING)]
    assert store.get_status(uuid)["status"] == V1Statuses.RUNNING

    cluster.set_all(uuid, "Succeeded")
    assert rec.tick() == [(uuid, V1Statuses.SUCCEEDED)]
    conds = [c["type"] for c in store.get_status(uuid)["conditions"]]
    assert conds[-1] == "succeeded"
    assert rec.tick() == []  # terminal: reconciler leaves it alone


def test_gang_failure_restarts_then_fails(tmp_home, tmp_path):
    store, cluster = RunStore(), FakeCluster()
    uuid = _submit(tmp_path, store, cluster)
    rec = Reconciler(store, cluster)

    cluster.set_all(uuid, "Running")
    rec.tick()
    cluster.pods[uuid][0]["phase"] = "Failed"  # one worker dies

    # maxRetries=1 → first failure: delete + QUEUED; the resubmit is
    # deferred to the next tick (real deletes are asynchronous)
    assert rec.tick() == [(uuid, V1Statuses.QUEUED)]
    assert cluster.deleted == [uuid]
    assert rec.tick() == [(uuid, V1Statuses.SCHEDULED)]
    assert all(p["phase"] == "Pending" for p in cluster.pods[uuid])
    types = [c["type"] for c in store.get_status(uuid)["conditions"]]
    assert "retrying" in types

    # second failure exhausts retries → FAILED
    cluster.set_all(uuid, "Running")
    rec.tick()
    cluster.set_all(uuid, "Failed")
    assert rec.tick() == [(uuid, V1Statuses.FAILED)]
    assert store.get_status(uuid)["status"] == V1Statuses.FAILED


def test_preemption_restarts_without_burning_retries(tmp_home, tmp_path):
    """Spot-slice preemptions resubmit indefinitely and never consume the
    maxRetries budget; a real crash afterwards still respects it."""
    store, cluster = RunStore(), FakeCluster()
    uuid = _submit(tmp_path, store, cluster)
    rec = Reconciler(store, cluster)

    for round_ in range(3):  # preempt three times: always rescheduled
        cluster.set_all(uuid, "Running")
        rec.tick()
        for p in cluster.pods[uuid]:
            p["phase"], p["reason"] = "Failed", "Preempted"
        assert rec.tick() == [(uuid, V1Statuses.QUEUED)], f"round {round_}"
        assert rec.tick() == [(uuid, V1Statuses.SCHEDULED)], f"round {round_}"
    meta = store.get_status(uuid).get("meta", {})
    assert int(meta.get("cluster_attempts") or 0) == 0  # budget untouched

    # a genuine crash consumes the single retry, then fails
    cluster.set_all(uuid, "Running")
    rec.tick()
    cluster.pods[uuid][0].update(phase="Failed", reason="Error")
    assert rec.tick() == [(uuid, V1Statuses.QUEUED)]
    assert rec.tick() == [(uuid, V1Statuses.SCHEDULED)]
    cluster.set_all(uuid, "Running")
    rec.tick()
    cluster.pods[uuid][0].update(phase="Failed", reason="Error")
    assert rec.tick() == [(uuid, V1Statuses.FAILED)]


def test_stop_propagates_to_cluster(tmp_home, tmp_path):
    """Stopping a cluster-submitted run tears the gang down and settles
    STOPPING → STOPPED via the reconciler."""
    store, cluster = RunStore(), FakeCluster()
    uuid = _submit(tmp_path, store, cluster)
    rec = Reconciler(store, cluster)
    cluster.set_all(uuid, "Running")
    rec.tick()
    assert store.get_status(uuid)["status"] == V1Statuses.RUNNING

    assert store.request_stop(uuid) == V1Statuses.STOPPING
    assert rec.tick() == [(uuid, V1Statuses.STOPPED)]
    assert cluster.deleted == [uuid]
    assert store.get_status(uuid)["status"] == V1Statuses.STOPPED
    assert rec.tick() == []  # idempotent once settled


def test_agent_serve_reconciles_cluster_runs(tmp_home, tmp_path):
    """A serving agent with a ClusterSubmitter reconciles pod status in its
    own loop — submit, pods succeed, run reaches SUCCEEDED, loop exits."""
    import threading

    store, cluster = RunStore(), FakeCluster()
    p = tmp_path / "op.yaml"
    p.write_text(yaml.safe_dump(SPEC))
    op = read_polyaxonfile(str(p))
    agent = Agent(
        store=store,
        submit_fn=ClusterSubmitter(store, cluster, ConnectionCatalog()),
    )
    uuid = agent.submit(op)

    import time as _time

    hard_stop = _time.time() + 45  # serve() must exit even if the run wedges

    def _done():
        return (
            store.get_status(uuid).get("status") in ("succeeded", "failed")
            or _time.time() > hard_stop
        )

    # daemon: an assertion failure below must not leave a live non-daemon
    # thread keeping the interpreter (and CI) alive forever
    t = threading.Thread(
        target=lambda: agent.serve(poll_interval=0.05, stop_when=_done),
        daemon=True,
    )
    t.start()
    # let the agent submit, then simulate the cluster finishing the gang
    deadline = __import__("time").time() + 20
    while uuid not in cluster.pods and __import__("time").time() < deadline:
        __import__("time").sleep(0.05)
    cluster.set_all(uuid, "Running")
    __import__("time").sleep(0.2)
    cluster.set_all(uuid, "Succeeded")
    t.join(timeout=20)
    assert not t.is_alive()
    assert store.get_status(uuid)["status"] == V1Statuses.SUCCEEDED


def test_reconciler_queue_scoping(tmp_home, tmp_path):
    """Two queue-filtered agents share one store: each reconciler only
    drives runs routed through its own queues — the other agent's runs are
    invisible to it (no double delete/submit, no double attempt-bump)."""
    store, cluster = RunStore(), FakeCluster()
    submit = ClusterSubmitter(store, cluster, ConnectionCatalog())
    agent = Agent(store=store, submit_fn=submit)
    uuids = {}
    for qname in ("a", "b"):
        spec = dict(SPEC, queue=qname, name=f"job-{qname}")
        p = tmp_path / f"op-{qname}.yaml"
        p.write_text(yaml.safe_dump(spec))
        uuids[qname] = agent.submit(read_polyaxonfile(str(p)))
    agent.drain()

    rec_a = Reconciler(store, cluster, queues=["a"])
    cluster.set_all(uuids["a"], "Succeeded")
    cluster.set_all(uuids["b"], "Succeeded")
    changed = dict(rec_a.tick())
    assert uuids["a"] in changed
    assert uuids["b"] not in changed
    assert store.get_status(uuids["a"])["status"] == V1Statuses.SUCCEEDED
    # queue-b run untouched until ITS agent's reconciler ticks
    assert store.get_status(uuids["b"])["status"] == V1Statuses.SCHEDULED
    rec_b = Reconciler(store, cluster, queues=["b"])
    assert dict(rec_b.tick()) == {uuids["b"]: V1Statuses.SUCCEEDED}


def test_two_scoped_agents_share_a_store(tmp_home, tmp_path):
    """Two serve() agents with disjoint --queue filters on one store: each
    reconciles only its own gang to completion; neither double-drives the
    other's runs (the cluster sees exactly one submit per run)."""
    import threading
    import time as _time

    store, cluster = RunStore(), FakeCluster()
    submit = ClusterSubmitter(store, cluster, ConnectionCatalog())
    front = Agent(store=store, submit_fn=submit)  # enqueue-only frontend
    uuids = {}
    for qname in ("qa", "qb"):
        spec = dict(SPEC, queue=qname, name=f"svc-{qname}")
        p = tmp_path / f"{qname}.yaml"
        p.write_text(yaml.safe_dump(spec))
        uuids[qname] = front.submit(read_polyaxonfile(str(p)))

    hard_stop = _time.time() + 45

    def _done():
        return _time.time() > hard_stop or all(
            store.get_status(u).get("status") in ("succeeded", "failed")
            for u in uuids.values()
        )

    agents = [
        Agent(store=store, submit_fn=submit, queues=[q]) for q in ("qa", "qb")
    ]
    threads = [
        threading.Thread(
            target=lambda a=a: a.serve(poll_interval=0.05, stop_when=_done),
            daemon=True,
        )
        for a in agents
    ]
    for t in threads:
        t.start()
    deadline = _time.time() + 20
    while (
        not all(u in cluster.pods for u in uuids.values())
        and _time.time() < deadline
    ):
        _time.sleep(0.05)
    for u in uuids.values():
        cluster.set_all(u, "Running")
    _time.sleep(0.3)
    for u in uuids.values():
        cluster.set_all(u, "Succeeded")
    for t in threads:
        t.join(timeout=20)
    for q, u in uuids.items():
        assert store.get_status(u)["status"] == V1Statuses.SUCCEEDED, q
    # exactly one submit per run: no agent re-submitted the other's gang
    submits = [u for u in cluster.submitted]
    assert sorted(submits) == sorted(uuids.values())
    assert cluster.deleted == []  # no spurious teardown either
