"""ISSUE 14 coverage: chunked prefill + the prefill/decode step scheduler.

Three layers:

  * scheduler units — `StepScheduler` driven by a fake `StepEngine`:
    join-mid-flight fairness (a short request admitted during a long
    prefill finishes first), the max_step_tokens budget bounding every
    step, mid-flight deadline eviction between steps, and the classic
    blocking fallback for rows the engine cannot step;
  * end-to-end byte-identity over live HTTP — a chunkedPrefill server
    must return EXACTLY the tokens of the one-shot paged server: greedy
    and sampled, plain and speculative, streamed and not, cold and warm
    (shared-prefix reuse);
  * chaos — a seeded kill between prefill chunks fails only that row,
    releases its partially-built page-table state (zero leaked pages,
    zero stuck reservations), and the step loop keeps serving;
  * config plumbing — V1ServingSpec chunked fields validate and reach
    ServingConfig, and the CLI replica argv layers only the flags
    actually given (one flag must not reset other spec pins).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from polyaxon_tpu.serving.batching import (
    DeadlineExceededError,
    GroupKey,
    PendingRequest,
)
from polyaxon_tpu.serving.steps import RowStep, StepEngine, StepScheduler

pytestmark = pytest.mark.serving

CFG = {
    "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
}

KEY = GroupKey(32, 16, 0.8, 40, None)


# ------------------------------------------------------- scheduler units
def _req(key=KEY, plen=3, seed=0, deadline_ms=None, on_finish=None):
    deadline = (
        time.monotonic() + deadline_ms / 1e3
        if deadline_ms is not None else None
    )
    return PendingRequest(
        tokens=[1] * plen, prompt_len=plen, max_new=4, seed=seed, key=key,
        deadline=deadline, on_finish=on_finish,
    )


class FakeEngine(StepEngine):
    """Logical-state engine: rows carry a chunk countdown and a decode
    countdown; `gates` lets a test hold a specific row's prefill open to
    submit another request mid-flight deterministically."""

    def __init__(self, chunks=1, decode_steps=2, chunk_tokens=4,
                 supported=None, decode_sleep=0.0, finish_on_prefill=None):
        self.chunks = chunks
        self.decode_steps = decode_steps
        self.chunk_tokens = chunk_tokens
        self.supported = supported or (lambda r: True)
        self.decode_sleep = decode_sleep
        self.finish_on_prefill = finish_on_prefill or (lambda r: False)
        self.gates: dict[int, threading.Event] = {}
        self.log: list[tuple] = []

    def supports(self, req):
        return self.supported(req)

    def begin(self, req):
        req.step = RowStep(
            phase="prefill", next_chunk=self.chunk_tokens, cost=1
        )
        req.chunks_left = (
            self.chunks(req) if callable(self.chunks) else self.chunks
        )
        req.decode_left = self.decode_steps

    def prefill_chunk(self, req):
        gate = self.gates.get(req.seed)
        if gate is not None and not gate.wait(5.0):
            raise TimeoutError("test gate never released")
        self.log.append(("prefill", req.seed))
        req.chunks_left -= 1
        if req.chunks_left <= 0:
            if self.finish_on_prefill(req):
                # the real engine's EOS-as-first-token / maxNewTokens<=1
                # path: the row finishes straight out of its final slice,
                # phase prefill -> done without ever decoding
                req.step.phase = "done"
                req.finish(result=list(req.tokens))
            else:
                req.step.phase = "decode"
        return req.step.next_chunk

    def lanes(self, rows):
        return [rows] if rows else []

    def decode(self, lane):
        if self.decode_sleep:
            time.sleep(self.decode_sleep)
        self.log.append(("decode", tuple(r.seed for r in lane)))
        for r in lane:
            r.decode_left -= 1
            if r.decode_left <= 0:
                r.step.phase = "done"
                r.finish(result=list(r.tokens))
        return len(lane)


def test_scheduler_validates_budgets():
    eng = FakeEngine()
    with pytest.raises(ValueError):
        StepScheduler(lambda b: None, eng, prefill_chunk_tokens=0)
    with pytest.raises(ValueError):
        StepScheduler(lambda b: None, eng, max_step_tokens=0)


def test_short_request_joins_mid_flight_and_finishes_first():
    # a long prefill (6 chunks) is in the step loop; a short request
    # submitted mid-prefill must interleave and finish FIRST — the exact
    # head-of-line scenario the scheduler exists to kill
    eng = FakeEngine(chunks=lambda r: 6 if r.seed == 1 else 1)
    gate = threading.Event()
    eng.gates[1] = gate
    order = []
    s = StepScheduler(lambda b: None, eng, max_wait_ms=0)
    s.start()
    try:
        long_r = _req(seed=1, on_finish=lambda r: order.append(r.seed))
        s.submit(long_r)
        for _ in range(200):  # long request reached the step loop?
            if s.prefill_queue_depth and s._prefilling:
                break
            time.sleep(0.005)
        short_r = _req(seed=2, on_finish=lambda r: order.append(r.seed))
        s.submit(short_r)
        gate.set()  # release the long prefill's first chunk
        assert short_r.done.wait(5) and long_r.done.wait(5)
        assert order == [2, 1], order
        # the long prefill really arrived in slices, interleaved
        assert [e for e in eng.log if e == ("prefill", 1)] == [
            ("prefill", 1)
        ] * 6
        assert s.depth == 0 and s.prefill_queue_depth == 0
    finally:
        s.stop()


def test_step_tokens_never_exceed_budget():
    eng = FakeEngine(chunks=1, decode_steps=2, chunk_tokens=2)
    steps = []

    def observer(event, **ctx):
        if event == "step":
            steps.append(ctx["tokens"])

    s = StepScheduler(
        lambda b: None, eng, max_step_tokens=3, max_wait_ms=0,
        observer=observer,
    )
    s.start()
    try:
        rows = [_req(seed=i) for i in range(5)]
        for r in rows:
            s.submit(r)
        for r in rows:
            assert r.done.wait(5)
            assert r.result is not None
    finally:
        s.stop()
    assert steps and max(steps) <= 3, steps
    assert s.steps_run >= 5  # 5 rows through a 3-token budget take turns


def test_expired_midflight_row_is_evicted_between_steps():
    # the row is decoding when its deadline passes: it must 504 between
    # steps (PR 5 semantics) without wedging the loop
    eng = FakeEngine(chunks=1, decode_steps=10_000, decode_sleep=0.02)
    s = StepScheduler(lambda b: None, eng, max_wait_ms=0)
    s.start()
    try:
        r = _req(seed=1, deadline_ms=80.0)
        s.submit(r)
        assert r.done.wait(5)
        assert isinstance(r.error, DeadlineExceededError)
        assert s.evicted_midflight == 1 and s.deadline_dropped == 1
        assert s.depth == 0
        # the loop survived: a fresh unexpired row still completes
        eng2_row = _req(seed=2)
        eng2_row.max_new = 4
        eng_saved = eng.decode_sleep
        eng.decode_sleep = 0.0
        eng.decode_steps = 1
        s.submit(eng2_row)
        assert eng2_row.done.wait(5) and eng2_row.result is not None
        eng.decode_sleep = eng_saved
    finally:
        s.stop()


def test_unsupported_rows_fall_back_to_classic_blocking_steps():
    batches = []

    def execute(batch):
        batches.append([r.seed for r in batch])
        for r in batch:
            r.finish(result=list(r.tokens))

    eng = FakeEngine(supported=lambda r: False)
    s = StepScheduler(execute, eng, max_wait_ms=0)
    s.start()
    try:
        rows = [_req(seed=i) for i in (1, 2)]
        for r in rows:
            s.submit(r)
        for r in rows:
            assert r.done.wait(5) and r.result is not None
    finally:
        s.stop()
    assert sorted(x for b in batches for x in b) == [1, 2]
    assert not eng.log  # the engine never saw the beam rows


def test_row_finishing_in_final_prefill_slice_resolves_depth():
    # REVIEW high: when the engine finishes a row straight out of its
    # final prefill slice (EOS as first token, maxNewTokens <= 1) the
    # scheduler must still resolve it — a leak here accumulates
    # _outstanding (+1 per such row) until depth hits max_queue and
    # EVERY subsequent submit sheds queue_full, forever
    eng = FakeEngine(finish_on_prefill=lambda r: True)
    s = StepScheduler(lambda b: None, eng, max_wait_ms=0, max_queue=4)
    s.start()
    try:
        rows = [_req(seed=i) for i in range(8)]  # 2x max_queue
        for r in rows:
            s.submit(r)
            assert r.done.wait(5) and r.result is not None
        deadline = time.monotonic() + 5.0
        while s.depth and time.monotonic() < deadline:
            time.sleep(0.005)
        assert s.depth == 0 and s.idle
        assert all(e[0] == "prefill" for e in eng.log)  # never decoded
    finally:
        s.stop()


def test_classic_rows_do_not_starve_under_sustained_step_load():
    # REVIEW medium: a beam (classic) row used to run only when BOTH
    # step pools were empty, so sustained steppable load starved it
    # indefinitely. It must now get a forced exclusive step after at
    # most CLASSIC_STARVE_STEPS steppable steps.
    executed = []

    def execute(batch):
        executed.append([r.seed for r in batch])
        for r in batch:
            r.finish(result=list(r.tokens))

    eng = FakeEngine(
        chunks=1, decode_steps=100_000, supported=lambda r: r.seed != 9
    )
    s = StepScheduler(execute, eng, max_wait_ms=0)
    s.start()
    try:
        stepper = _req(seed=1)
        s.submit(stepper)
        for _ in range(200):  # the stepper holds the loop busy?
            if s._decoding or s._prefilling:
                break
            time.sleep(0.005)
        classic = _req(seed=9)
        s.submit(classic)
        assert classic.done.wait(5) and classic.result is not None
        assert not stepper.done.is_set()  # the steppable row kept going
        assert s.classic_forced_steps >= 1
    finally:
        s.stop()
    assert executed == [[9]]


def test_fail_active_skips_already_resolved_rows():
    # REVIEW low: after a worker crash the watchdog fails AND resolves
    # the in-flight rows, but they are still sitting in the pools when a
    # stop arrives (the done-row sweep runs after the stop check).
    # _fail_active must not resolve them again, or _outstanding
    # undercounts and drain() reports idle with requests unresolved.
    from polyaxon_tpu.serving.batching import ServerClosingError

    eng = FakeEngine()
    s = StepScheduler(lambda b: None, eng, max_wait_ms=0)
    crashed = _req(seed=1)
    crashed.finish(error=RuntimeError("watchdog already failed this row"))
    live = _req(seed=2)
    s._decoding.extend([crashed, live])
    s._outstanding = 2  # the live row + one request still parked upstream
    s._fail_active(ServerClosingError("going down"))
    assert live.done.is_set()
    assert s.depth == 1  # exactly the live row resolved, not len(active)


# -------------------------------------------------- end-to-end identity
def _build():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    b = build_model("transformer_lm", CFG)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


def _server(module, params, **overrides):
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    cfg = ServingConfig(**{
        "max_batch": 4, "max_wait_ms": 2.0, "kv_pool_pages": 64,
        "kv_page_tokens": 8, "stream_chunk_tokens": 3, **overrides,
    })
    return ModelServer(module, params, model_name="tiny", config=cfg)


CHUNKED = {
    "chunked_prefill": True, "prefill_chunk_tokens": 8,
    "max_step_tokens": 32,
}


@pytest.fixture(scope="module")
def servers():
    module, params = _build()
    classic = _server(module, params)
    chunked = _server(module, params, **CHUNKED)
    pc, ph = classic.start(port=0), chunked.start(port=0)
    yield {
        "classic": pc, "chunked": ph, "module": module, "params": params,
        "chunked_server": chunked,
    }
    classic.stop()
    chunked.stop()


def _post(port, body, path="/generate", timeout=120):
    import http.client

    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request("POST", path, json.dumps(body))
    r = c.getresponse()
    out = r.read()
    c.close()
    return r.status, out


def _stats(port):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statsz", timeout=60
    ).read())


def _body(n_rows=3, prefix=16, suffix=6, max_new=8, seed=123):
    rng = np.random.RandomState(0)
    shared = rng.randint(1, 100, size=prefix).tolist()
    prompts = [
        shared + rng.randint(1, 100, size=suffix).tolist()
        for _ in range(n_rows)
    ]
    return prompts, {
        "tokens": prompts, "maxNewTokens": max_new, "temperature": 0.8,
        "topK": 40, "eosId": 5, "seed": seed,
    }


def test_chunked_matches_one_shot_over_http(servers):
    _, body = _body()
    s1, o1 = _post(servers["classic"], body)
    s2, o2 = _post(servers["chunked"], body)
    assert s1 == 200 and s2 == 200, (s1, s2, o1, o2)
    assert json.loads(o1)["tokens"] == json.loads(o2)["tokens"]
    # greedy too — temperature 0 exercises the argmax path at the
    # prefill boundary
    g = dict(body, temperature=0.0)
    _, ga = _post(servers["classic"], g)
    _, gb = _post(servers["chunked"], g)
    assert json.loads(ga)["tokens"] == json.loads(gb)["tokens"]
    # a ragged final chunk (suffix not a multiple of prefillChunkTokens)
    # and a single-chunk prompt both hold
    one = dict(body, tokens=[body["tokens"][0][:5]], maxNewTokens=3)
    _, oa = _post(servers["classic"], one)
    _, ob = _post(servers["chunked"], one)
    assert json.loads(oa)["tokens"] == json.loads(ob)["tokens"]
    st = _stats(servers["chunked"])
    assert st["chunked"]["enabled"] and st["chunked"]["prefill_chunks"] > 0


def test_chunked_warm_prefix_reuse_identical(servers):
    _, body = _body(seed=321)
    _, cold = _post(servers["chunked"], body)
    hits0 = _stats(servers["chunked"])["kv"]["prefix"]["hits"]
    _, warm = _post(servers["chunked"], body)
    assert json.loads(cold)["tokens"] == json.loads(warm)["tokens"]
    # the warm pass really rode the prefix cache through the chunked path
    assert _stats(servers["chunked"])["kv"]["prefix"]["hits"] > hits0
    # and warm chunked == warm classic
    _, classic = _post(servers["classic"], body)
    assert json.loads(classic)["tokens"] == json.loads(warm)["tokens"]


def test_chunked_stream_matches_non_streamed(servers):
    prompts, body = _body(seed=77)
    _, plain = _post(servers["chunked"], body)
    status, raw = _post(servers["chunked"], body, path="/generate?stream=1")
    assert status == 200, raw
    rows: dict[int, list[int]] = {}
    for line in raw.decode().splitlines():
        if line.startswith("data: "):
            ev = json.loads(line[6:])
            if "tokens" in ev and "row" in ev:
                rows.setdefault(ev["row"], []).extend(ev["tokens"])
    full = [prompts[i] + rows[i] for i in range(len(prompts))]
    assert full == json.loads(plain)["tokens"]


def test_chunked_speculative_matches_one_shot(servers):
    module, params = servers["module"], servers["params"]
    spec_c = _server(module, params, speculate=True, draft_tokens=3)
    spec_h = _server(module, params, speculate=True, draft_tokens=3,
                     **CHUNKED)
    pc, ph = spec_c.start(port=0), spec_h.start(port=0)
    try:
        _, body = _body(seed=55)
        s1, o1 = _post(pc, body)
        s2, o2 = _post(ph, body)
        assert s1 == 200 and s2 == 200, (o1, o2)
        assert json.loads(o1)["tokens"] == json.loads(o2)["tokens"]
        # and speculation under chunking still equals the plain servers
        _, o3 = _post(servers["classic"], body)
        assert json.loads(o3)["tokens"] == json.loads(o2)["tokens"]
    finally:
        spec_c.stop()
        spec_h.stop()


def test_chaos_kill_between_prefill_chunks_releases_pages(servers):
    # a kill on the SECOND prefill chunk fails only that row; its
    # half-built page-table state must return to the pool (PR 5 "no
    # leaked pages") and the step loop must keep serving
    from polyaxon_tpu.chaos import injector
    from polyaxon_tpu.chaos.plan import Fault, FaultPlan

    port = servers["chunked"]
    _, body = _body(n_rows=1, prefix=16, suffix=6, max_new=4, seed=9)
    s0, _ = _post(port, body)  # warm: shapes compiled, prefix cached
    assert s0 == 200
    kv0 = _stats(port)["kv"]
    plan = FaultPlan(
        [Fault("serving.prefill_chunk", "kill", at=1,
               message="chaos: killed between prefill chunks")],
        seed=9,
    )
    with injector.active(plan):
        s1, o1 = _post(port, body)
    assert s1 >= 500, (s1, o1)  # the row failed, mapped to an error
    # zero leaked pages: used/reserved match the post-warmup baseline
    # (the prefix cache legitimately retains its pages)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        kv1 = _stats(port)["kv"]
        if (kv1["pages_used"] == kv0["pages_used"]
                and kv1["pages_reserved"] == kv0["pages_reserved"]):
            break
        time.sleep(0.05)
    assert kv1["pages_used"] == kv0["pages_used"], (kv0, kv1)
    assert kv1["pages_reserved"] == kv0["pages_reserved"], (kv0, kv1)
    # the loop survived the injected death: same request now succeeds
    s2, o2 = _post(port, body)
    assert s2 == 200, o2


# ------------------------------------------------------- config plumbing
def test_serving_spec_chunked_fields_reach_config():
    from polyaxon_tpu.schemas.run_kinds import V1ServingSpec

    spec = V1ServingSpec(
        chunkedPrefill=True, kvPoolPages=64,
        prefillChunkTokens=16, maxStepTokens=96, maxBatch=3,
    )
    cfg = spec.to_config()
    assert cfg.chunked_prefill is True
    assert cfg.prefill_chunk_tokens == 16
    assert cfg.max_step_tokens == 96
    assert cfg.max_batch == 3  # neighbours untouched
    # defaults stay off — 513 seed tests and compile-count pins ride the
    # classic group loop unless a spec opts in
    assert V1ServingSpec().to_config().chunked_prefill is False


def test_serving_spec_chunked_validation():
    from polyaxon_tpu.schemas.run_kinds import V1ServingSpec

    with pytest.raises(ValueError, match="prefillChunkTokens"):
        V1ServingSpec(prefillChunkTokens=0)
    with pytest.raises(ValueError, match="maxStepTokens"):
        V1ServingSpec(maxStepTokens=0)
    with pytest.raises(ValueError, match="kvPoolPages"):
        V1ServingSpec(chunkedPrefill=True)  # needs the paged pool
    # {{param}} templates still parse
    assert V1ServingSpec(prefillChunkTokens="{{chunk}}")


def test_serve_cli_flags_layer_without_resetting_pins():
    # the replica child argv is the CLI's serialization of the override
    # dict: ONLY flags actually given appear, so a spec's other pins
    # survive `from_run(config_overrides=...)` layering untouched
    import dataclasses

    from polyaxon_tpu.cli.main import _serve_child_argv
    from polyaxon_tpu.schemas.run_kinds import V1ServingSpec

    argv = _serve_child_argv(
        "uid", 9000, None,
        {"max_step_tokens": 96, "chunked_prefill": True}, None,
    )
    assert "--max-step-tokens" in argv and "--chunked-prefill" in argv
    assert "--prefill-chunk-tokens" not in argv  # not given, not reset
    assert "--max-batch" not in argv
    argv_off = _serve_child_argv("uid", 9000, None,
                                 {"chunked_prefill": False}, None)
    assert "--no-chunked-prefill" in argv_off

    # and the layering itself: one override must not reset other pins
    base = V1ServingSpec(
        chunkedPrefill=True, kvPoolPages=64, prefillChunkTokens=16,
        maxBatch=3,
    ).to_config()
    layered = dataclasses.replace(base, max_step_tokens=96)
    assert layered.prefill_chunk_tokens == 16
    assert layered.chunked_prefill is True
    assert layered.max_batch == 3
    assert layered.max_step_tokens == 96
