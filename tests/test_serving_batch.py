"""Serving fast path (ISSUE 2): shape-bucketed decode + continuous batching.

Three layers, tested at three levels:
  * pure units — bucket ladders and the DecodeCoalescer worker loop with a
    fake executor (no jax);
  * model level — LEFT-padded bucketed decode must be row-for-row
    IDENTICAL to the unbucketed path, and per-row seeds must be
    reproducible and invariant to bucket width / batch composition;
  * server level — the compile cache must be bounded by the bucket ladder
    across a randomized shape sweep, and the live benchmark smoke must
    drive real HTTP traffic through both modes.
"""

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from polyaxon_tpu.serving.batching import (
    DecodeCoalescer,
    GroupKey,
    PendingRequest,
    ServingConfig,
    batch_bucket,
    bucket_for,
    bucket_ladder,
    choose_buckets,
)

pytestmark = pytest.mark.serving

REPO = Path(__file__).resolve().parent.parent


# --------------------------------------------------------------- ladders
def test_bucket_ladder_geometric_capped():
    assert bucket_ladder(32, 128) == (32, 64, 128)
    assert bucket_ladder(32, 100) == (32, 64, 100)  # hi always included
    assert bucket_ladder(32, 8) == (8,)  # lo clamps down to hi
    assert bucket_ladder(1, 1) == (1,)
    with pytest.raises(ValueError):
        bucket_ladder(4, 0)


def test_bucket_for():
    assert bucket_for(1, (32, 64)) == 32
    assert bucket_for(33, (32, 64)) == 64
    assert bucket_for(65, (32, 64)) is None


def test_choose_buckets_never_overflows_cache():
    pl, nl = (32, 64), (16, 32, 64)
    assert choose_buckets(3, 5, pl, nl, 64) == (32, 16)
    # rounding both up would overflow seq_len 64: degrade prompt to exact
    assert choose_buckets(40, 10, pl, nl, 64) == (40, 16)
    # even exact prompt + bucketed new overflows: degrade new too
    assert choose_buckets(60, 4, pl, nl, 64) == (60, 4)
    for plen in range(1, 60):
        for new in range(1, 65 - plen):
            pb, nb = choose_buckets(plen, new, pl, nl, 64)
            assert pb >= plen and nb >= new
            assert pb + nb <= 64, (plen, new, pb, nb)


def test_batch_bucket_pow2_capped():
    assert [batch_bucket(n, 8) for n in (1, 2, 3, 5, 8, 9)] == [
        1, 2, 4, 8, 8, 8,
    ]
    assert batch_bucket(3, 1) == 1


# ------------------------------------------------------------- coalescer
KEY_A = GroupKey(32, 16, 0.8, 40, None)
KEY_B = GroupKey(64, 16, 0.8, 40, None)


def _req(key, plen=3, seed=0):
    return PendingRequest(
        tokens=[1] * plen, prompt_len=plen, max_new=4, seed=seed, key=key
    )


def _ok_executor(batches):
    def execute(batch):
        batches.append(batch)
        for r in batch:
            r.finish(result=list(r.tokens))

    return execute


def test_coalescer_full_batch_flushes_immediately():
    batches = []
    c = DecodeCoalescer(_ok_executor(batches), max_batch=2, max_wait_ms=5000)
    r1, r2 = _req(KEY_A, seed=1), _req(KEY_A, seed=2)
    c.start()
    t0 = time.monotonic()
    c.submit(r1)
    c.submit(r2)
    assert r1.done.wait(10) and r2.done.wait(10)
    # a full batch must NOT sit out the 5s window
    assert time.monotonic() - t0 < 2.0
    c.stop()
    assert len(batches) == 1 and batches[0] == [r1, r2]
    assert c.batches_run == 1 and c.rows_run == 2


def test_coalescer_flushes_partial_batch_on_max_wait():
    batches = []
    c = DecodeCoalescer(_ok_executor(batches), max_batch=8, max_wait_ms=50)
    r1, r2 = _req(KEY_A, seed=1), _req(KEY_A, seed=2)
    c.start()
    t0 = time.monotonic()
    c.submit(r1)
    c.submit(r2)
    assert r2.done.wait(10)
    elapsed = time.monotonic() - t0
    c.stop()
    # partial batch (2 < 8) waited for the window, then coalesced BOTH
    assert len(batches) == 1 and len(batches[0]) == 2
    assert elapsed >= 0.03, f"flushed after {elapsed * 1e3:.1f}ms, before max_wait"


def test_coalescer_groups_by_key_oldest_first():
    batches = []
    c = DecodeCoalescer(_ok_executor(batches), max_batch=8, max_wait_ms=0)
    reqs = [_req(KEY_A, seed=1), _req(KEY_B, seed=2), _req(KEY_A, seed=3)]
    for r in reqs:  # enqueue BEFORE the worker runs — deterministic drain
        c.submit(r)
    c.start()
    for r in reqs:
        assert r.done.wait(10)
    c.stop()
    assert [[r.seed for r in b] for b in batches] == [[1, 3], [2]]


def test_coalescer_scatters_executor_error_to_all_rows():
    def boom(batch):
        raise RuntimeError("device exploded")

    c = DecodeCoalescer(boom, max_batch=4, max_wait_ms=0)
    r1, r2 = _req(KEY_A), _req(KEY_A, seed=1)
    c.start()
    c.submit(r1)
    c.submit(r2)
    assert r1.done.wait(10) and r2.done.wait(10)
    c.stop()
    assert "exploded" in str(r1.error) and "exploded" in str(r2.error)
    assert r1.result is None


def test_coalescer_stop_fails_parked_requests():
    c = DecodeCoalescer(_ok_executor([]), max_batch=4, max_wait_ms=1000)
    r = _req(KEY_A)
    c.submit(r)  # worker never started — request is parked
    c.stop()
    assert r.done.is_set() and "shutting down" in str(r.error)
    with pytest.raises(RuntimeError):
        c.submit(_req(KEY_A))


# ------------------------------------------------- model-level equivalence
def _setup(**cfg_overrides):
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    cfg = {
        "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
        "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
    }
    cfg.update(cfg_overrides)
    b = build_model("transformer_lm", cfg)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((2, 64), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


def _row(length, seed=0):
    import jax
    import jax.numpy as jnp

    return jax.random.randint(
        jax.random.PRNGKey(100 + seed), (length,), 0, 128, dtype=jnp.int32
    )


def _left_pad(rows, width):
    import numpy as np

    out = np.zeros((len(rows), width), np.int32)
    for i, r in enumerate(rows):
        out[i, width - len(r):] = np.asarray(r)
    return out


@pytest.mark.parametrize(
    "scan",
    [False, pytest.param(True, marks=pytest.mark.slow)],
)
def test_bucketed_greedy_equals_unbucketed_per_length(scan):
    """The bucketing contract: LEFT-padding a row up to the bucket width
    (pad masked out of attention, positions offset) yields EXACTLY the
    unbucketed output — for every true length in the bucket, and for a
    mixed-length batch (each row independent of its neighbors)."""
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.models.generate import generate

    module, params = _setup(scan_layers=scan)
    P, max_new = 8, 4
    lengths = [1, 5, 8]
    refs = {}
    for L in lengths:
        row = _row(L, seed=L)
        refs[L] = np.asarray(
            generate(
                module, params, row[None, :], max_new_tokens=max_new,
                temperature=0.0,
            )
        )[0]
        padded = jnp.asarray(_left_pad([row], P))
        out = np.asarray(
            generate(
                module, params, padded, max_new_tokens=max_new,
                temperature=0.0, prompt_lengths=jnp.asarray([L]),
            )
        )
        np.testing.assert_array_equal(out[0, P - L:], refs[L])
    # mixed batch: every row still matches its solo reference
    rows = [_row(L, seed=L) for L in lengths]
    out = np.asarray(
        generate(
            module, params, jnp.asarray(_left_pad(rows, P)),
            max_new_tokens=max_new, temperature=0.0,
            prompt_lengths=jnp.asarray(lengths),
        )
    )
    for i, L in enumerate(lengths):
        np.testing.assert_array_equal(out[i, P - L:], refs[L])


def test_per_row_seeds_reproducible_and_bucket_invariant():
    """Per-row seed contract: a [B] seed vector makes each row's sample
    stream a function of (its seed, generation index) ONLY — reproducible
    across calls, distinct across seeds, and identical regardless of
    bucket width or which rows share the batch. This is what lets the
    coalescer merge strangers' requests without changing anyone's output."""
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.models.generate import generate

    module, params = _setup()
    L, max_new = 3, 4
    row = _row(L)

    def run(width, rows, lengths, seeds):
        return np.asarray(
            generate(
                module, params, jnp.asarray(_left_pad(rows, width)),
                max_new_tokens=max_new, temperature=0.8, top_k=40,
                seed=jnp.asarray(seeds, jnp.int32),
                prompt_lengths=jnp.asarray(lengths),
            )
        )

    solo = run(8, [row], [L], [7])
    again = run(8, [row], [L], [7])
    np.testing.assert_array_equal(solo, again)  # reproducible
    other = run(8, [row], [L], [8])
    assert not np.array_equal(solo, other)  # seed actually matters
    # bucket/batch invariance: same row+seed in a WIDER bucket, batched
    # with a stranger, generates the same tokens
    stranger = _row(6, seed=9)
    mixed = run(16, [row, stranger], [L, 6], [7, 11])
    np.testing.assert_array_equal(mixed[0, 16 - L:], solo[0, 8 - L:])


# ----------------------------------------------------- server compile cache
def test_compile_count_bounded_by_bucket_ladder():
    """Randomized shape sweep: the server must satisfy every request mix
    with at most |prompt ladder| x |max_new ladder| compiled programs
    (single-row direct calls — batch bucket is always 1)."""
    import random

    from polyaxon_tpu.serving.server import ModelServer

    module, params = _setup()
    server = ModelServer(
        module, params, config=ServingConfig(max_wait_ms=0.0)
    )
    rng = random.Random(0)
    shapes = set()
    for i in range(20):
        plen = rng.randint(1, 32)
        max_new = rng.randint(1, 12)
        shapes.add((plen, max_new))
        out = server.generate(
            {
                "tokens": [[rng.randrange(128) for _ in range(plen)]],
                "maxNewTokens": max_new,
                "temperature": 0.7,
                "topK": 20,
                "seed": i,
            }
        )
        assert len(out["tokens"][0]) == plen + max_new
    pl, nl = server._prompt_ladder, server._new_ladder
    bound = len(pl) * len(nl)
    assert len(shapes) > bound  # the sweep genuinely varied shapes
    assert 0 < server.compile_count <= bound, (
        f"{server.compile_count} compiles for {len(shapes)} distinct shapes "
        f"(ladder bound {bound})"
    )


def test_server_batched_http_path_coalesces(tmp_home):
    """End-to-end over HTTP: concurrent same-signature requests coalesce
    into shared batches, outputs are correct per request, and /statsz
    reports the occupancy."""
    import urllib.request

    from polyaxon_tpu.serving.server import ModelServer

    module, params = _setup()
    server = ModelServer(
        module, params, config=ServingConfig(max_batch=4, max_wait_ms=200.0)
    )
    port = server.start(port=0)
    results = {}
    errors = []

    def post(i, plen):
        body = {
            "tokens": [[(i + j) % 128 for j in range(plen)]],
            "maxNewTokens": 3, "temperature": 0.5, "topK": 10, "seed": i,
        }
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=300) as r:
                results[i] = json.loads(r.read())["tokens"][0]
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    try:
        # same signature, two true lengths in one bucket → coalescable
        threads = [
            threading.Thread(target=post, args=(i, 3 + (i % 2)), daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert not errors, errors
        for i in range(4):
            assert len(results[i]) == 3 + (i % 2) + 3
        stats = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statsz", timeout=30
            ).read()
        )
        assert stats["batching"] is True
        assert stats["requests"] == 4
        assert 1 <= stats["batches"] <= 4
        assert stats["compile_count"] >= 1
    finally:
        server.stop()


def test_serving_bench_smoke(tmp_home):
    """The tier-1-adjacent smoke: serving_bench --smoke must drive real
    HTTP traffic through BOTH modes and emit the pinned JSON schema."""
    import os

    env = dict(
        os.environ,
        POLYAXON_JAX_PLATFORM="cpu",
        POLYAXON_NUM_CPU_DEVICES="1",
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "benchmarks" / "serving_bench.py"),
         "--smoke"],
        env=env, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [
        json.loads(l)
        for l in proc.stdout.splitlines()
        if l.strip().startswith("{")
    ]
    by_mode = {
        r["mode"]: r
        for r in recs
        if r["metric"] == "serving_requests_per_sec"
    }
    assert set(by_mode) == {"per_request", "batched"}
    for r in by_mode.values():
        assert "errors" not in r, r
        assert r["value"] > 0 and r["requests"] == 12
        assert {"p50_ms", "p95_ms", "compile_count", "platform"} <= r.keys()
    # bucketing bounds compiles even at smoke scale; the baseline compiles
    # per exact shape so it must compile strictly more
    assert by_mode["batched"]["compile_count"] < by_mode["per_request"]["compile_count"]
    assert by_mode["batched"]["batches"] >= 1
    speedup = [r for r in recs if r["metric"] == "serving_batched_speedup"]
    assert speedup and speedup[0]["value"] > 0
