"""Scheduler tests: DAG topology/triggers/ops-context, queue persistence,
agent submit→drain — the control-plane loop without a cluster
(SURVEY.md §4: reference tests the scheduler state machine the same way)."""

import pytest

from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.runtime.executor import Executor
from polyaxon_tpu.scheduler import Agent, DagError, RunQueue, topo_order
from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.schemas.run_kinds import V1OperationRef
from polyaxon_tpu.store.local import RunStore


def _ref(name, deps=None, trigger=None):
    return V1OperationRef(
        name=name, depends_on=deps, trigger=trigger, component={"kind": "component"}
    )


def test_topo_order_waves():
    nodes = {
        "a": _ref("a"),
        "b": _ref("b", deps=["a"]),
        "c": _ref("c", deps=["a"]),
        "d": _ref("d", deps=["b", "c"]),
    }
    assert topo_order(nodes) == [["a"], ["b", "c"], ["d"]]


def test_topo_order_cycle_raises():
    nodes = {"a": _ref("a", deps=["b"]), "b": _ref("b", deps=["a"])}
    with pytest.raises(DagError, match="cycle"):
        topo_order(nodes)


def test_topo_order_unknown_dep_raises():
    with pytest.raises(DagError, match="unknown"):
        topo_order({"a": _ref("a", deps=["ghost"])})


MLP_COMPONENT = {
    "kind": "component",
    "name": "step",
    "inputs": [{"name": "lr", "type": "float", "value": 0.01}],
    "run": {
        "kind": "jaxjob",
        "program": {
            "model": {"name": "mlp", "config": {"input_dim": 16, "num_classes": 2, "hidden": [8]}},
            "data": {"name": "synthetic", "batchSize": 8, "config": {"shape": [16], "num_classes": 2}},
            "optimizer": {"name": "adamw", "learningRate": "{{ params.lr }}"},
            "train": {"steps": 2, "logEvery": 1, "precision": "float32"},
        },
    },
}


def _dag_yaml(tmp_path, text):
    p = tmp_path / "dag.yaml"
    p.write_text(text)
    return str(p)


def test_dag_executes_chain_with_ops_context(tmp_home, tmp_path):
    import json
    import yaml

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "pipeline",
        "component": {
            "kind": "component",
            "name": "pipeline",
            "run": {
                "kind": "dag",
                "operations": [
                    {"name": "first", "component": MLP_COMPONENT},
                    {
                        "name": "second",
                        "dependsOn": ["first"],
                        "component": MLP_COMPONENT,
                        # downstream consumes upstream's final loss as its lr
                        "params": {"lr": {"value": "{{ ops.first.outputs.loss }}"}},
                    },
                ],
            },
        },
    }
    path = _dag_yaml(tmp_path, yaml.safe_dump(spec))
    op = read_polyaxonfile(path)
    from polyaxon_tpu.compiler.resolver import compile_operation

    store = RunStore()
    compiled = compile_operation(op)
    status = Executor(store).execute(compiled)
    assert status == V1Statuses.SUCCEEDED
    runs = store.list_runs()
    assert len(runs) == 3  # dag + 2 children


def test_dag_upstream_failure_skips_downstream(tmp_home, tmp_path):
    import yaml

    bad = {
        "kind": "component",
        "name": "bad",
        "run": {"kind": "job", "container": {"command": ["false"]}},
    }
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "pipeline",
        "component": {
            "kind": "component",
            "name": "pipeline",
            "run": {
                "kind": "dag",
                "operations": [
                    {"name": "boom", "component": bad},
                    {"name": "after", "dependsOn": ["boom"], "component": MLP_COMPONENT},
                ],
            },
        },
    }
    op = read_polyaxonfile(_dag_yaml(tmp_path, yaml.safe_dump(spec)))
    from polyaxon_tpu.compiler.resolver import compile_operation

    store = RunStore()
    status = Executor(store).execute(compile_operation(op))
    assert status == V1Statuses.FAILED
    # only boom + dag ran; 'after' was never compiled into a run
    assert len(store.list_runs()) == 2


def test_dag_all_done_trigger_runs_after_failure(tmp_home, tmp_path):
    import yaml

    bad = {
        "kind": "component",
        "name": "bad",
        "run": {"kind": "job", "container": {"command": ["false"]}},
    }
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "pipeline",
        "component": {
            "kind": "component",
            "name": "pipeline",
            "run": {
                "kind": "dag",
                "operations": [
                    {"name": "boom", "component": bad},
                    {
                        "name": "cleanup",
                        "dependsOn": ["boom"],
                        "trigger": "all_done",
                        "component": MLP_COMPONENT,
                    },
                ],
            },
        },
    }
    op = read_polyaxonfile(_dag_yaml(tmp_path, yaml.safe_dump(spec)))
    from polyaxon_tpu.compiler.resolver import compile_operation

    store = RunStore()
    try:
        Executor(store).execute(compile_operation(op))
    except Exception:
        pass
    # cleanup DID run despite boom failing
    names = {r["name"] for r in store.list_runs()}
    assert any("cleanup" in n for n in names)


def test_queue_priority_and_persistence(tmp_home):
    store = RunStore()
    q = RunQueue(store)
    q.push("low", {"operation": {}}, priority=0)
    q.push("high", {"operation": {}}, priority=10)
    assert len(q) == 2
    # a second handle on the same home sees the same queue (persistence)
    q2 = RunQueue(RunStore())
    assert q2.pop()["uuid"] == "high"
    assert q.pop()["uuid"] == "low"
    assert q.pop() is None


def test_agent_submit_and_drain(tmp_home, tmp_path):
    import yaml

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "agent-run",
        "component": MLP_COMPONENT,
    }
    op = read_polyaxonfile(_dag_yaml(tmp_path, yaml.safe_dump(spec)))
    store = RunStore()
    agent = Agent(store=store)
    uid = agent.submit(op)
    assert store.get_status(uid)["status"] == V1Statuses.QUEUED
    assert agent.drain() == 1
    assert store.get_status(uid)["status"] == V1Statuses.SUCCEEDED
    assert len(agent.queue) == 0


# ------------------------------------------------------------ named queues
def test_named_queues_routing_priority_and_concurrency(tmp_home, tmp_path):
    """Operations route to their `queue:`; the agent drains queues in
    configured priority order; concurrency>1 runs a batch in parallel."""
    import time

    import yaml

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.scheduler.agent import Agent
    from polyaxon_tpu.scheduler.queue import QueueRegistry
    from polyaxon_tpu.schemas.lifecycle import V1Statuses
    from polyaxon_tpu.store.local import RunStore

    def op(name, queue, cmd):
        spec = {
            "version": 1.1,
            "kind": "operation",
            "name": name,
            "queue": queue,
            "component": {
                "kind": "component",
                "name": name,
                "run": {"kind": "job", "container": {"command": ["sh", "-c", cmd]}},
            },
        }
        p = tmp_path / f"{name}.yaml"
        p.write_text(yaml.safe_dump(spec))
        return read_polyaxonfile(str(p))

    store = RunStore()
    registry = QueueRegistry(store)
    registry.set_queue("urgent", concurrency=1, priority=10)
    registry.set_queue("bulk", concurrency=2, priority=0)

    agent = Agent(store=store)
    slow = agent.submit(op("slow-a", "bulk", "sleep 0.5; echo a"))
    slow2 = agent.submit(op("slow-b", "bulk", "sleep 0.5; echo b"))
    hot = agent.submit(op("hot", "urgent", "echo hot"))

    stats = {s["name"]: s for s in registry.stats()}
    assert stats["urgent"]["pending"] == 1 and stats["bulk"]["pending"] == 2
    assert registry.names()[0] == "urgent"  # priority order

    assert agent.drain() == 3
    for uuid in (slow, slow2, hot):
        assert store.get_status(uuid)["status"] == V1Statuses.SUCCEEDED

    def cond_ts(uuid, kind):
        return [
            c for c in store.get_status(uuid)["conditions"] if c["type"] == kind
        ][0]["ts"]

    # the two 0.5s bulk jobs overlapped (concurrency=2): each started
    # before the other finished — robust against slow CI, unlike wall-clock
    assert cond_ts(slow, "running") < cond_ts(slow2, "succeeded")
    assert cond_ts(slow2, "running") < cond_ts(slow, "succeeded")

    # urgent (priority 10) was claimed before the bulk batch
    hot_done = [
        c for c in store.get_status(hot)["conditions"] if c["type"] == "succeeded"
    ][0]["ts"]
    bulk_done = [
        c for c in store.get_status(slow)["conditions"] if c["type"] == "succeeded"
    ][0]["ts"]
    assert hot_done <= bulk_done


def test_inline_create_respects_queue_routing(tmp_home, tmp_path):
    """create(queue=False) must execute the run even when the op routes to
    a named queue (regression: inline drain used to look only at default)."""
    import yaml

    from polyaxon_tpu.client import RunClient
    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.schemas.lifecycle import V1Statuses

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "routed",
        "queue": "special",
        "component": {
            "kind": "component",
            "name": "routed",
            "run": {"kind": "job", "container": {"command": ["sh", "-c", "echo r"]}},
        },
    }
    p = tmp_path / "routed.yaml"
    p.write_text(yaml.safe_dump(spec))
    client = RunClient()
    uuid = client.create(read_polyaxonfile(str(p)), queue=False)
    assert client.get(uuid)["status"] == V1Statuses.SUCCEEDED

    # clones inherit the queue routing from the stored spec
    r = client.restart(uuid, queue=True)
    from polyaxon_tpu.scheduler.queue import RunQueue
    from polyaxon_tpu.store.local import RunStore

    assert any(e["uuid"] == r for e in RunQueue(RunStore(), name="special").peek_all())


def test_concurrency_zero_pauses_queue(tmp_home, tmp_path):
    import yaml

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.scheduler.agent import Agent
    from polyaxon_tpu.scheduler.queue import QueueRegistry
    from polyaxon_tpu.store.local import RunStore

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "paused",
        "queue": "paused-q",
        "component": {
            "kind": "component",
            "name": "paused",
            "run": {"kind": "job", "container": {"command": ["true"]}},
        },
    }
    p = tmp_path / "p.yaml"
    p.write_text(yaml.safe_dump(spec))
    store = RunStore()
    QueueRegistry(store).set_queue("paused-q", concurrency=0)
    agent = Agent(store=store)
    uid = agent.submit(read_polyaxonfile(str(p)))
    assert agent.drain() == 0  # paused: nothing claimed
    assert store.get_status(uid)["status"] == V1Statuses.QUEUED
    QueueRegistry(store).set_queue("paused-q", concurrency=1)
    assert agent.drain() == 1
    assert store.get_status(uid)["status"] == V1Statuses.SUCCEEDED


def test_dag_sweep_node_feeds_best_params_downstream(tmp_home, tmp_path):
    """The sweep-then-train-best pipeline: a DAG node with a matrix runs
    through the tuner and downstream nodes consume the winner via
    {{ ops.<name>.outputs.best.<param> }}."""
    import yaml

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "sweep-then-train",
        "component": {
            "kind": "component",
            "name": "sweep-then-train",
            "run": {
                "kind": "dag",
                "operations": [
                    {
                        "name": "search",
                        "component": MLP_COMPONENT,
                        "matrix": {
                            "kind": "grid",
                            "params": {
                                "lr": {"kind": "choice", "value": [0.05, 1.0e-09]}
                            },
                        },
                    },
                    {
                        "name": "final",
                        "dependsOn": ["search"],
                        "component": MLP_COMPONENT,
                        "params": {
                            "lr": {"value": "{{ ops.search.outputs.best.lr }}"}
                        },
                    },
                ],
            },
        },
    }
    path = _dag_yaml(tmp_path, yaml.safe_dump(spec))
    op = read_polyaxonfile(path)
    from polyaxon_tpu.compiler.resolver import compile_operation

    store = RunStore()
    compiled = compile_operation(op)
    status = Executor(store).execute(compiled)
    assert status == V1Statuses.SUCCEEDED
    log = store.read_logs(compiled.run_uuid)
    assert "sweep" in log and "best" in log
    # the winning lr (0.05 trains to much lower loss than 1e-9) reached the
    # final node's resolved spec
    final_uuid = None
    for r in store.list_runs():
        spec_ = store.read_spec(r["uuid"])
        if spec_.get("name") == "final":
            final_uuid = r["uuid"]
    assert final_uuid is not None
    assert store.read_spec(final_uuid)["params"]["lr"] == 0.05
