"""Sanitizer builds of the native gang supervisor (SURVEY.md §5 race
detection): TSan and ASan+UBSan binaries must build and survive the
stressful paths — gang teardown on partial failure and restart loops."""

import json
import subprocess
from pathlib import Path

import pytest

NATIVE_DIR = Path(__file__).resolve().parent.parent / "polyaxon_tpu" / "native"


def _build(target: str) -> Path:
    proc = subprocess.run(
        ["make", "-C", str(NATIVE_DIR), target], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stderr
    binary = NATIVE_DIR / f"polyaxon-launcher-{target}"
    assert binary.exists()
    return binary


@pytest.mark.parametrize("san", ["tsan", "asan"])
def test_sanitized_gang_restart_and_teardown(san):
    binary = _build(san)
    # restart loop: 2 workers, one fails fast, 2 restarts — exercises the
    # fork/exec/waitpid/kill paths where a data race or UB would live
    out = subprocess.run(
        [
            str(binary),
            "--num-workers", "2",
            "--max-restarts", "2",
            "--", "/bin/sh", "-c",
            'if [ "$JAX_PROCESS_ID" = 0 ]; then exit 7; else sleep 30; fi',
        ],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert out.returncode == 7, out.stderr
    assert "WARNING" not in out.stderr, out.stderr  # sanitizer reports
    assert "ERROR" not in out.stderr, out.stderr
    events = [json.loads(l) for l in out.stdout.splitlines()]
    assert [e["attempt"] for e in events if e["event"] == "gang_start"] == [0, 1, 2]
    assert events[-1] == {"event": "gang_done", "code": 7}


@pytest.mark.parametrize("san", ["tsan", "asan"])
def test_sanitized_timeout_path(san):
    binary = _build(san)
    out = subprocess.run(
        [str(binary), "--num-workers", "1", "--timeout", "1", "--",
         "/bin/sh", "-c", "sleep 30"],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert out.returncode == 124, out.stderr
    assert "WARNING" not in out.stderr and "ERROR" not in out.stderr, out.stderr
