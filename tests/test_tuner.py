"""Polytune tests: seeded managers produce deterministic schedules
(SURVEY.md §4: reference tests tuners with fixed seeds), hyperband bracket
math matches Li et al., and an end-to-end sweep finds the better config."""

import math

import numpy as np
import pytest

from polyaxon_tpu.schemas.matrix import parse_matrix
from polyaxon_tpu.tuner import (
    HyperbandManager,
    build_manager,
)
from polyaxon_tpu.tuner.early_stopping import (
    median_should_stop,
    metric_triggered,
    truncation_should_stop,
)
from polyaxon_tpu.tuner.placement import sub_slices
from polyaxon_tpu.tuner.space import from_unit, grid_configs, to_unit


PARAMS = {
    "lr": {"kind": "loguniform", "value": {"low": math.log(1e-4), "high": math.log(1e-1)}},
    "width": {"kind": "choice", "value": [64, 128, 256]},
}


def test_grid_enumeration_exact():
    m = parse_matrix(
        {
            "kind": "grid",
            "params": {
                "a": {"kind": "choice", "value": [1, 2]},
                "b": {"kind": "linspace", "value": {"start": 0.0, "stop": 1.0, "num": 3}},
            },
        }
    )
    mgr = build_manager(m)
    batch = mgr.suggest()
    assert mgr.done
    got = [(s.params["a"], s.params["b"]) for s in batch]
    assert got == [
        (1, 0.0), (1, 0.5), (1, 1.0),
        (2, 0.0), (2, 0.5), (2, 1.0),
    ]


def test_random_seeded_deterministic():
    spec = {"kind": "random", "params": PARAMS, "num_runs": 5, "seed": 7}
    a = [s.params for s in build_manager(parse_matrix(spec)).suggest()]
    b = [s.params for s in build_manager(parse_matrix(spec)).suggest()]
    assert a == b
    assert len(a) == 5
    for cfg in a:
        assert 1e-4 <= cfg["lr"] <= 1e-1
        assert cfg["width"] in (64, 128, 256)


def test_hyperband_bracket_math():
    """R=9, eta=3 → s_max=2; brackets (s=2: n=9,r=1), (s=1: n=5,r=3),
    (s=0: n=3,r=9) — the canonical Li et al. schedule."""
    m = parse_matrix(
        {
            "kind": "hyperband",
            "params": PARAMS,
            "maxIterations": 9,
            "eta": 3,
            "resource": {"name": "steps", "type": "int"},
            "metric": {"name": "loss", "optimization": "minimize"},
            "seed": 1,
        }
    )
    mgr = HyperbandManager(m)
    assert mgr.s_max == 2
    assert [mgr.bracket_n(s) for s in (2, 1, 0)] == [9, 5, 3]
    assert [mgr.bracket_r(s) for s in (2, 1, 0)] == [1.0, 3.0, 9.0]

    # bracket s=2 rung schedule: (9 cfgs @ r=1) -> (3 @ 3) -> (1 @ 9)
    batch = mgr.suggest()
    assert len(batch) == 9 and batch[0].resource == 1.0
    # feed objectives: config i gets objective -i (lower index better)
    mgr.observe([(s, -float(i)) for i, s in enumerate(batch)])
    rung1 = mgr.suggest()
    assert len(rung1) == 3 and rung1[0].resource == 3.0
    # promoted = the 3 best (indices 0,1,2 of the original batch)
    assert [r.params for r in rung1] == [b.params for b in batch[:3]]
    mgr.observe([(s, 0.0) for s in rung1])
    rung2 = mgr.suggest()
    assert len(rung2) == 1 and rung2[0].resource == 9.0
    mgr.observe([(s, 0.0) for s in rung2])
    # next bracket s=1
    b2 = mgr.suggest()
    assert len(b2) == 5 and b2[0].resource == 3.0 and b2[0].bracket == 1


def test_hyperband_full_run_terminates():
    m = parse_matrix(
        {
            "kind": "hyperband",
            "params": PARAMS,
            "maxIterations": 27,
            "eta": 3,
            "resource": {"name": "steps"},
            "metric": {"name": "loss", "optimization": "minimize"},
            "seed": 3,
        }
    )
    mgr = build_manager(m)
    total = 0
    rng = np.random.default_rng(0)
    for _ in range(100):
        if mgr.done:
            break
        batch = mgr.suggest()
        total += len(batch)
        mgr.observe([(s, float(rng.random())) for s in batch])
    assert mgr.done
    assert total > 30  # 4 brackets worth of trials


def test_bayes_warmup_then_model_based():
    m = parse_matrix(
        {
            "kind": "bayes",
            "params": PARAMS,
            "numInitialRuns": 4,
            "maxIterations": 3,
            "metric": {"name": "acc", "optimization": "maximize"},
            "seed": 5,
        }
    )
    mgr = build_manager(m)
    warmup = mgr.suggest()
    assert len(warmup) == 4
    # objective favors high lr
    mgr.observe([(s, math.log10(s.params["lr"])) for s in warmup])
    seen = []
    while not mgr.done:
        batch = mgr.suggest()
        assert len(batch) == 1
        seen.append(batch[0].params["lr"])
        mgr.observe([(batch[0], math.log10(batch[0].params["lr"]))])
    assert len(seen) == 3
    for lr in seen:
        assert 1e-4 <= lr <= 1e-1


def test_tpe_improves_on_quadratic():
    m = parse_matrix(
        {
            "kind": "hyperopt",
            "params": {"x": {"kind": "uniform", "value": {"low": 0.0, "high": 1.0}}},
            "numRuns": 40,
            "algorithm": "tpe",
            "metric": {"name": "obj", "optimization": "maximize"},
            "seed": 11,
        }
    )
    mgr = build_manager(m)
    xs = []
    while not mgr.done:
        batch = mgr.suggest()
        res = []
        for s in batch:
            x = s.params["x"]
            xs.append(x)
            res.append((s, -((x - 0.7) ** 2)))  # optimum at 0.7
        mgr.observe(res)
    late = xs[-10:]
    assert abs(np.mean(late) - 0.7) < 0.2  # concentrated near optimum


def test_mapping_and_iterative():
    m = parse_matrix({"kind": "mapping", "values": [{"a": 1}, {"a": 2}]})
    mgr = build_manager(m)
    assert [s.params for s in mgr.suggest()] == [{"a": 1}, {"a": 2}]
    assert mgr.done

    it = build_manager(
        parse_matrix({"kind": "iterative", "params": PARAMS, "maxIterations": 3, "seed": 2})
    )
    count = 0
    while not it.done:
        batch = it.suggest()
        count += len(batch)
        it.observe([(s, None) for s in batch])
    assert count == 3


def test_unit_encoding_roundtrip():
    from polyaxon_tpu.schemas.matrix import parse_matrix as _pm

    grid = _pm({"kind": "grid", "params": {
        "c": {"kind": "choice", "value": ["a", "b", "c"]},
    }}).params["c"]
    for v in ("a", "b", "c"):
        assert from_unit(grid, to_unit(grid, v)) == v


def test_early_stopping_policies():
    from polyaxon_tpu.schemas.matrix import (
        V1MedianStoppingPolicy,
        V1MetricEarlyStopping,
        V1TruncationStoppingPolicy,
    )

    es = [V1MetricEarlyStopping(metric="acc", value=0.95, optimization="maximize")]
    assert metric_triggered(es, {"acc": 0.96})
    assert not metric_triggered(es, {"acc": 0.5})
    assert not metric_triggered(es, {"loss": 0.1})

    med = V1MedianStoppingPolicy(evaluation_interval=1)
    assert median_should_stop(med, [0.1], [0.5, 0.6, 0.7], maximize=True)
    assert not median_should_stop(med, [0.9], [0.5, 0.6, 0.7], maximize=True)

    trunc = V1TruncationStoppingPolicy(percent=50.0)
    assert truncation_should_stop(trunc, 0.1, [0.1, 0.5, 0.6, 0.9], maximize=True)
    assert not truncation_should_stop(trunc, 0.9, [0.1, 0.5, 0.6, 0.9], maximize=True)


def test_sub_slice_placement():
    groups = sub_slices(2)
    assert len(groups) == 2
    assert all(len(g) == 4 for g in groups)
    flat = [d.id for g in groups for d in g]
    assert len(set(flat)) == 8  # disjoint cover

    # 3 trials on 8 devices: equal groups only — 3 groups of 2 (ragged tail
    # dropped), never unequal splits
    groups = sub_slices(3)
    assert all(len(g) == 2 for g in groups)


def test_sweep_end_to_end_grid(tmp_home):
    """Grid sweep over MLP lr: the sweep runs real trials and picks the
    better configuration."""
    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.store.local import RunStore
    from polyaxon_tpu.tuner import SweepDriver

    import textwrap, tempfile, os

    yaml_text = textwrap.dedent(
        """
        version: 1.1
        kind: operation
        name: mlp-sweep
        matrix:
          kind: grid
          params:
            lr:
              kind: choice
              value: [0.05, 1.0e-09]
        component:
          kind: component
          name: mlp-train
          inputs:
          - {name: lr, type: float, value: 0.001}
          run:
            kind: jaxjob
            program:
              model: {name: mlp, config: {input_dim: 32, num_classes: 4, hidden: [32]}}
              data: {name: synthetic, batchSize: 16, config: {shape: [32], num_classes: 4}}
              optimizer: {name: adamw, learningRate: "{{ params.lr }}"}
              train: {steps: 6, logEvery: 3, precision: float32}
        """
    )
    path = os.path.join(tempfile.mkdtemp(), "sweep.yaml")
    with open(path, "w") as f:
        f.write(yaml_text)
    op = read_polyaxonfile(path)
    store = RunStore()
    result = SweepDriver(op, store=store, log_fn=lambda *a: None).run()
    assert len(result.trials) == 2
    assert result.best is not None
    assert result.best.params["lr"] == 0.05  # learning beats a frozen lr
    statuses = [t.status for t in result.trials]
    assert all(s == "succeeded" for s in statuses)


# ------------------------------------------------------------- placement
def test_choose_block_shape_north_star():
    """v5e-32 is a 4x8 torus; 4 concurrent trials must each get a legal
    2x4 (v5e-8) sub-grid — the BASELINE north-star packing."""
    from polyaxon_tpu.tuner.placement import choose_block_shape

    assert sorted(choose_block_shape((4, 8), 4)) == [2, 4]
    assert choose_block_shape((4, 8), 1) == (4, 8)  # one trial: whole slice
    assert choose_block_shape((4, 8), 32) == (1, 1)
    assert choose_block_shape((4, 8), 100) == (1, 1)  # oversubscribed: 1 chip each
    # 3 trials on 4x8: no exact 3-way tiling exists; smallest sufficient is 4
    shape = choose_block_shape((4, 8), 3)
    tiles = (4 // shape[0]) * (8 // shape[1])
    assert tiles >= 3


def test_sub_slices_topology_tiles_are_disjoint_and_legal():
    import jax

    from polyaxon_tpu.tuner.placement import sub_slices

    devices = jax.devices()  # 8 virtual CPU devices, treat as a 2x4 torus
    groups = sub_slices(4, devices, topology=(2, 4))
    assert len(groups) == 4 and all(len(g) == 2 for g in groups)
    seen = {id(d) for g in groups for d in g}
    assert len(seen) == 8  # disjoint, covers the slice

    with pytest.raises(ValueError, match="topology"):
        sub_slices(2, devices, topology=(4, 4))  # 16 chips claimed, 8 present


def test_sweep_respects_declared_topology(tmp_home):
    """Driver picks grid placement when environment.resources.tpu.topology
    matches the device pool."""
    import jax

    from polyaxon_tpu.schemas.operation import V1Operation
    from polyaxon_tpu.tuner.driver import SweepDriver

    op = V1Operation.model_validate(
        {
            "name": "sweep",
            "matrix": {
                "kind": "grid",
                "concurrency": 4,
                "params": {"lr": {"kind": "choice", "value": [1, 2, 3, 4]}},
            },
            "component": {
                "kind": "component",
                "name": "c",
                "run": {
                    "kind": "job",
                    "container": {"command": ["true"]},
                    "environment": {
                        "resources": {"tpu": {"type": "v5e", "topology": "2x4"}}
                    },
                },
            },
        }
    )
    driver = SweepDriver(op, devices=jax.devices())
    assert driver._topology() == (2, 4)


# ------------------------------------------------------- turbo / baxus BO
def _drive(mgr, objective, rounds):
    """Run the manager protocol against a synthetic objective; returns the
    best observed value."""
    best = None
    for _ in range(rounds):
        if mgr.done:
            break
        batch = mgr.suggest()
        results = [(s, objective(s.params)) for s in batch]
        mgr.observe(results)
        for _, y in results:
            best = y if best is None else max(best, y)
    return best


def _bayes_matrix(n_params, algorithm, iters=20, **extra):
    from polyaxon_tpu.schemas.matrix import parse_matrix

    return parse_matrix(
        {
            "kind": "bayes",
            "algorithm": algorithm,
            "numInitialRuns": 5,
            "maxIterations": iters,
            "metric": {"name": "score", "optimization": "maximize"},
            "seed": 7,
            "params": {
                f"x{i}": {"kind": "uniform", "value": {"low": 0.0, "high": 1.0}}
                for i in range(n_params)
            },
            **extra,
        }
    )


def test_turbo_finds_local_optimum_and_shrinks_region():
    from polyaxon_tpu.tuner.managers import build_manager

    def bowl(params):  # max at x=0.7 on every axis
        return -sum((params[k] - 0.7) ** 2 for k in params)

    mgr = build_manager(_bayes_matrix(3, "turbo", iters=25))
    best = _drive(mgr, bowl, rounds=26)
    assert best is not None and best > -0.01, f"turbo best {best}"
    # trust region actually reacted: length moved, or counters advanced
    tr = mgr._tr
    assert tr.length != tr.length_init or (tr._succ + tr._fail) > 0

    # infrastructure-failure rounds (all objectives None) must NOT count
    # as evaluated misses: the region stays where it is
    length_before = tr.length
    mgr.observe([(s, None) for s in [mgr.suggest()[0]]] * (tr.fail_tol + 1))
    assert mgr._tr.length == length_before


def test_turbo_beats_global_gp_on_narrow_peak():
    """Seeded head-to-head on a needle-in-bowl objective in 6-D — the
    shaped case trust regions exist for."""
    from polyaxon_tpu.tuner.managers import build_manager

    def needle(params):
        d2 = sum((params[k] - 0.62) ** 2 for k in params)
        return -d2 - 0.5 * (d2 > 0.05)

    turbo = _drive(build_manager(_bayes_matrix(6, "turbo", iters=30)), needle, 31)
    gp = _drive(build_manager(_bayes_matrix(6, "gp", iters=30)), needle, 31)
    assert turbo is not None and gp is not None
    assert turbo >= gp - 1e-6, f"turbo {turbo} vs gp {gp}"


def test_baxus_splits_subspace_and_preserves_observations():
    import numpy as np

    from polyaxon_tpu.tuner.managers import BaxusBayesManager

    mgr = BaxusBayesManager(_bayes_matrix(8, "baxus", iters=40))
    assert mgr.target_dim == 2  # starts low-dimensional

    # exact re-expression invariant: embedding a point, splitting, and
    # embedding the carried-over point give the SAME input vector
    z = mgr._rng.uniform(-1, 1, mgr.target_dim)
    x_before = mgr._embed(z)
    mgr._Z.append(z)
    mgr._y.append(0.0)
    mgr._split_bins()
    x_after = mgr._embed(mgr._Z[0])
    np.testing.assert_allclose(x_before, x_after)
    assert mgr.target_dim == 4

    # a collapsing trust region drives dimension growth up to full D
    mgr2 = BaxusBayesManager(
        _bayes_matrix(
            8, "baxus", iters=60,
            trustRegion={"lengthInit": 0.6, "lengthMin": 0.5, "failTol": 1},
        )
    )

    def flat(params):  # no signal: every round is a failure → rapid splits
        return 0.0

    _drive(mgr2, flat, rounds=12)
    assert mgr2.target_dim == 8  # grew 2 → 4 → 8 on successive collapses


def test_baxus_optimizes_sparse_objective():
    from polyaxon_tpu.tuner.managers import build_manager

    def sparse(params):  # only 2 of 8 dims matter (x0 and x4 live in
        # different initial bins, so the d0=2 subspace can express the
        # optimum; same-bin pairs stay tied until trust-region collapse
        # triggers a split — that path is test_baxus_splits_subspace)
        return -((params["x0"] - 0.8) ** 2) - (params["x4"] - 0.3) ** 2

    mgr = build_manager(_bayes_matrix(8, "baxus", iters=30))
    best = _drive(mgr, sparse, rounds=31)
    assert best is not None and best > -0.05, f"baxus best {best}"


# ---------------------------------------------------------------- ASHA
def _asha(concurrency=1, max_iterations=20, eta=3, r_min=1, r_max=9, seed=1):
    return build_manager(
        parse_matrix(
            {
                "kind": "asha",
                "params": PARAMS,
                "maxIterations": max_iterations,
                "eta": eta,
                "minResource": r_min,
                "maxResource": r_max,
                "resource": {"name": "steps", "type": "int"},
                "metric": {"name": "loss", "optimization": "minimize"},
                "concurrency": concurrency,
                "seed": seed,
            }
        )
    )


def test_asha_promotes_asynchronously():
    """No rung barrier: as soon as a config sits in the top floor(n/eta) of
    its rung's FINISHED trials, the very next suggest() promotes it — while
    hyperband would still be waiting for the whole rung. eta=2, rungs at
    resource 1 -> 2 -> 4."""
    mgr = _asha(eta=2, r_min=1, r_max=4)
    seen = []
    # two rung-0 trials (scores 0, -1): floor(2/2)=1 -> best is promotable
    for score in (0.0, -1.0):
        (sug,) = mgr.suggest()
        assert sug.rung == 0 and sug.resource == 1.0
        seen.append(sug)
        mgr.observe([(sug, score)])
    (promo,) = mgr.suggest()
    assert promo.rung == 1 and promo.resource == 2.0
    assert promo.params == seen[0].params  # the best config advanced
    mgr.observe([(promo, 0.0)])
    # rung 1 has 1 finished: floor(1/2)=0 -> nothing promotable there;
    # rung 0's single top slot is already promoted -> grow rung 0 instead
    (a,) = mgr.suggest()
    assert a.rung == 0
    mgr.observe([(a, -2.0)])  # rung 0 finished: 0,-1,-2 -> floor(3/2)=1
    (b,) = mgr.suggest()
    assert b.rung == 0  # top-1 still the promoted config
    mgr.observe([(b, -3.0)])  # 4 finished -> floor(4/2)=2: -1 promotable
    (p2,) = mgr.suggest()
    assert p2.rung == 1 and p2.params == seen[1].params
    mgr.observe([(p2, -1.0)])
    # rung 1 now has 2 finished (0, -1): its best advances to the top rung
    (top,) = mgr.suggest()
    assert top.rung == 2 and top.resource == 4.0
    assert top.params == seen[0].params


def test_asha_budget_and_rung_cap():
    """The sweep stops at maxIterations executions; resources never exceed
    maxResource; failed trials (objective None) are never promoted."""
    mgr = _asha(concurrency=4, max_iterations=19, eta=2, r_min=1, r_max=4)
    total = 0
    rng = np.random.default_rng(0)
    while not mgr.done:
        batch = mgr.suggest()
        assert batch, "suggest returned empty before budget exhausted"
        total += len(batch)
        results = []
        for s in batch:
            assert s.resource <= 4.0
            # every 4th trial "fails"
            obj = None if total % 4 == 0 else float(rng.normal())
            results.append((s, obj))
        mgr.observe(results)
    assert total == 19
    table = mgr.best_rung_table()
    assert [row["resource"] for row in table] == [1.0, 2.0, 4.0]
    assert sum(row["finished"] for row in table) <= 19


@pytest.mark.slow
def test_asha_sweep_end_to_end(tmp_home, tmp_path):
    """ASHA through the real sweep driver: trials execute, the best config
    wins, and higher rungs re-run good configs at more steps."""
    import yaml

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.store.local import RunStore
    from polyaxon_tpu.tuner.driver import run_sweep

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "asha-mlp",
        "matrix": {
            "kind": "asha",
            "params": {
                "lr": {"kind": "choice", "value": [0.05, 1e-6]},
            },
            "maxIterations": 8,
            "eta": 2,
            "minResource": 4,
            "maxResource": 16,
            "resource": {"name": "steps", "type": "int"},
            "metric": {"name": "loss", "optimization": "minimize"},
            "seed": 3,
        },
        "component": {
            "kind": "component",
            "name": "asha-mlp",
            "inputs": [
                {"name": "lr", "type": "float"},
                {"name": "steps", "type": "int", "value": 4},
            ],
            "run": {
                "kind": "jaxjob",
                "program": {
                    "model": {"name": "mlp", "config": {"input_dim": 16, "num_classes": 4, "hidden": [32]}},
                    "data": {"name": "synthetic", "batchSize": 32, "config": {"shape": [16], "num_classes": 4}},
                    "optimizer": {"name": "adamw", "learningRate": "{{ params.lr }}"},
                    "train": {"steps": "{{ params.steps }}", "logEvery": 4, "precision": "float32"},
                },
            },
        },
    }
    p = tmp_path / "asha.yaml"
    p.write_text(yaml.safe_dump(spec))
    op = read_polyaxonfile(str(p))
    out = run_sweep(op, store=RunStore(), log_fn=lambda *a: None)
    assert len(out["trials"]) == 8
    assert out["best"] is not None
    # the healthy lr must win over the degenerate one
    assert out["best"]["params"]["lr"] == 0.05
    # async promotion happened: some trial ran at more than minResource
    assert any(t["params"]["steps"] > 4 for t in out["trials"])


def test_queued_sweep_executes_through_agent(tmp_home):
    """A matrix operation submitted to the AGENT (queue / POST /runs path)
    must run as a sweep under the queued run's uuid — regression: the
    matrix used to be silently dropped and one default-params run
    executed."""
    import os
    import tempfile
    import textwrap

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.scheduler.agent import Agent
    from polyaxon_tpu.store.local import RunStore

    yaml_text = textwrap.dedent(
        """
        version: 1.1
        kind: operation
        name: queued-sweep
        matrix:
          kind: grid
          params:
            lr: {kind: choice, value: [0.05, 0.001]}
        component:
          kind: component
          name: mlp-train
          inputs:
          - {name: lr, type: float, value: 0.001}
          run:
            kind: jaxjob
            program:
              model: {name: mlp, config: {input_dim: 32, num_classes: 4, hidden: [32]}}
              data: {name: synthetic, batchSize: 16, config: {shape: [32], num_classes: 4}}
              optimizer: {name: adamw, learningRate: "{{ params.lr }}"}
              train: {steps: 4, logEvery: 4, precision: float32}
        """
    )
    path = os.path.join(tempfile.mkdtemp(), "sweep.yaml")
    with open(path, "w") as f:
        f.write(yaml_text)
    store = RunStore()
    agent = Agent(store=store)
    uuid = agent.submit(read_polyaxonfile(path))
    agent.drain()

    assert store.get_status(uuid)["status"] == "succeeded"
    summaries = [
        e for e in store.read_events(uuid) if e["kind"] == "sweep_summary"
    ]
    assert summaries and summaries[0]["trials"] == 2  # the grid, not 1 run
    trial_runs = [r for r in store.list_runs() if r["uuid"] != uuid]
    assert len(trial_runs) == 2
    assert "sweep done" in store.read_logs(uuid)


def test_cluster_agent_rejects_queued_sweep(tmp_home, tmp_path):
    """A cluster-submitting agent must FAIL a queued sweep loudly, not
    silently train trials in-process on the control-plane host."""
    from tests.test_reconciler import FakeCluster

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.scheduler.agent import Agent
    from polyaxon_tpu.scheduler.reconciler import ClusterSubmitter
    from polyaxon_tpu.store.local import RunStore

    spec = """
version: 1.1
kind: operation
name: cluster-sweep
matrix:
  kind: grid
  params:
    lr: {kind: choice, value: [0.05, 0.001]}
component:
  kind: component
  name: mlp-train
  inputs:
  - {name: lr, type: float, value: 0.001}
  run:
    kind: jaxjob
    container: {image: img, command: [train]}
"""
    p = tmp_path / "sweep.yaml"
    p.write_text(spec)
    store = RunStore()
    agent = Agent(
        store=store, submit_fn=ClusterSubmitter(store, FakeCluster())
    )
    uuid = agent.submit(read_polyaxonfile(str(p)))
    agent.drain()
    status = store.get_status(uuid)
    assert status["status"] == "failed"
    assert "execution agent" in store.read_logs(uuid)


def test_sweep_with_no_objective_fails_not_succeeds(tmp_home, tmp_path):
    """A sweep whose trials never log the objective metric must settle
    FAILED — 'succeeded, best=None' hides a broken metric name."""
    import yaml

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.store.local import RunStore
    from polyaxon_tpu.tuner import SweepDriver

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "bad-metric-sweep",
        "matrix": {
            "kind": "hyperopt",
            "numRuns": 2,
            "metric": {"name": "no_such_metric", "optimization": "minimize"},
            "params": {"lr": {"kind": "uniform", "value": {"low": 0.001, "high": 0.01}}},
        },
        "component": {
            "kind": "component",
            "name": "mlp-train",
            "inputs": [{"name": "lr", "type": "float", "value": 0.001}],
            "run": {
                "kind": "jaxjob",
                "program": {
                    "model": {"name": "mlp", "config": {"input_dim": 16, "num_classes": 2, "hidden": [8]}},
                    "data": {"name": "synthetic", "batchSize": 8, "config": {"shape": [16], "num_classes": 2}},
                    "optimizer": {"name": "adamw", "learningRate": "{{ params.lr }}"},
                    "train": {"steps": 2, "logEvery": 2, "precision": "float32"},
                },
            },
        },
    }
    p = tmp_path / "sweep.yaml"
    p.write_text(yaml.safe_dump(spec))
    store = RunStore()
    result = SweepDriver(
        read_polyaxonfile(str(p)), store=store, log_fn=lambda *a: None
    ).run()
    assert result.best is None
    assert store.get_status(result.sweep_uuid)["status"] == "failed"
    msg = store.get_status(result.sweep_uuid)["conditions"][-1]["message"]
    assert "no_such_metric" in msg


def test_stopped_sweep_settles_stopped(tmp_home, tmp_path):
    """A stop request on the sweep run halts the loop and settles STOPPED
    (not an illegal-transition crash, not SUCCEEDED)."""
    import yaml

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.schemas.lifecycle import V1Statuses
    from polyaxon_tpu.store.local import RunStore
    from polyaxon_tpu.tuner import SweepDriver

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "stopped-sweep",
        "matrix": {
            "kind": "grid",
            "params": {"lr": {"kind": "choice", "value": [0.01, 0.02]}},
        },
        "component": {
            "kind": "component",
            "name": "mlp-train",
            "inputs": [{"name": "lr", "type": "float", "value": 0.001}],
            "run": {
                "kind": "jaxjob",
                "program": {
                    "model": {"name": "mlp", "config": {"input_dim": 16, "num_classes": 2, "hidden": [8]}},
                    "data": {"name": "synthetic", "batchSize": 8, "config": {"shape": [16], "num_classes": 2}},
                    "optimizer": {"name": "adamw", "learningRate": "{{ params.lr }}"},
                    "train": {"steps": 2, "logEvery": 2, "precision": "float32"},
                },
            },
        },
    }
    p = tmp_path / "sweep.yaml"
    p.write_text(yaml.safe_dump(spec))
    store = RunStore()
    # seed the sweep record pre-stopped (a client's stop raced the agent)
    import uuid as _uuid

    sweep_uuid = _uuid.uuid4().hex
    store.create_run(sweep_uuid, "stopped-sweep", "default", {})
    for s in (V1Statuses.COMPILED, V1Statuses.QUEUED, V1Statuses.SCHEDULED,
              V1Statuses.RUNNING, V1Statuses.STOPPING):
        store.set_status(sweep_uuid, s)
    result = SweepDriver(
        read_polyaxonfile(str(p)), store=store, sweep_uuid=sweep_uuid,
        log_fn=lambda *a: None,
    ).run()
    assert result.trials == []  # halted before launching anything
    assert store.get_status(sweep_uuid)["status"] == "stopped"


@pytest.mark.slow
def test_stop_during_final_batch_settles_stopped(tmp_home, tmp_path):
    """A stop that lands DURING the last batch (loop exits via mgr.done
    without re-reaching the stop check) must still settle STOPPED — the
    illegal stopping->succeeded transition used to strand the run
    non-terminal forever."""
    import yaml

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.store.local import RunStore
    from polyaxon_tpu.tuner import SweepDriver

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "late-stop-sweep",
        "matrix": {
            "kind": "grid",
            "params": {"lr": {"kind": "choice", "value": [0.01, 0.02]}},
        },
        "component": {
            "kind": "component",
            "name": "mlp-train",
            "inputs": [{"name": "lr", "type": "float", "value": 0.001}],
            "run": {
                "kind": "jaxjob",
                "program": {
                    "model": {"name": "mlp", "config": {"input_dim": 16, "num_classes": 2, "hidden": [8]}},
                    "data": {"name": "synthetic", "batchSize": 8, "config": {"shape": [16], "num_classes": 2}},
                    "optimizer": {"name": "adamw", "learningRate": "{{ params.lr }}"},
                    "train": {"steps": 2, "logEvery": 2, "precision": "float32"},
                },
            },
        },
    }
    p = tmp_path / "sweep.yaml"
    p.write_text(yaml.safe_dump(spec))
    store = RunStore()
    driver = SweepDriver(read_polyaxonfile(str(p)), store=store,
                         log_fn=lambda *a: None)
    stopped_once = []

    def stopping_log(*args):
        # fire the stop at the first trial launch — mid final batch
        if not stopped_once:
            stopped_once.append(True)
            store.request_stop(driver.sweep_uuid)

    driver.log = stopping_log
    result = driver.run()
    assert store.get_status(result.sweep_uuid)["status"] == "stopped"
