"""ISSUE 17 crash honesty: the tiered KV spill must survive kills,
corruption, and admission races without leaking pages, wedging a reader,
or ever serving wrong bytes.

Four layers:

  * SpillManager units — RAM/disk round-trips are byte-identical, RAM
    overflow demotes to CRC-framed disk segments, and the heal pass
    honors the eventlog crash contract: torn tails truncate, incomplete
    segments delete (ignorable), corrupt segments quarantine to
    `<seg>.corrupt` (clean miss, never a wedge);
  * chaos at `kv.spill` — a kill after the meta frame leaves an
    ignorable segment; a kill after the payload frames leaves a
    COMPLETE, restorable one; a scrambled tail heals back to the last
    whole frame. Mid-spill death is always restorable-or-ignorable.
  * chaos at `kv.restore` + the lost-admission race — a kill mid-restore
    and an insert that loses a forced hash collision must both return
    every page the restore held: zero leaked pages, zero stuck
    reservations, no pending device writes.
  * live HTTP — a prefix evicted to the spill tier and hit again decodes
    byte-identically (restore, not re-prefill), and a warm request
    re-routed to a different replica still decodes byte-identically
    (affinity is a placement hint, never a correctness input).
"""

import hashlib
import json
import urllib.request

import numpy as np
import pytest

from polyaxon_tpu.chaos.injector import (
    SimulatedKill,
    active,
    corrupt_segment_frame,
)
from polyaxon_tpu.chaos.plan import Fault, FaultPlan
from polyaxon_tpu.models.kv_pages import page_hashes
from polyaxon_tpu.serving.spill import SpillManager, SpillPayload

pytestmark = pytest.mark.serving

PT = 8  # page_tokens used throughout


# ---------------------------------------------------- payload helpers
def _payload(n_pages=2, seed=0, first_token=1):
    """A synthetic spilled entry: n_pages full pages of tokens and two
    KV leaves of random bytes per page."""
    rng = np.random.RandomState(seed)
    tokens = tuple(range(first_token, first_token + n_pages * PT))
    hashes = tuple(page_hashes(tokens, PT))
    pages = [
        [rng.randn(PT, 2, 4).astype(np.float32) for _ in range(2)]
        for _ in range(n_pages)
    ]
    return SpillPayload(tokens, hashes, pages)


def _same_bytes(a: SpillPayload, b: SpillPayload) -> bool:
    if a.tokens != b.tokens or a.hashes != b.hashes:
        return False
    if len(a.pages) != len(b.pages):
        return False
    return all(
        np.array_equal(x, y)
        for pa, pb in zip(a.pages, b.pages)
        for x, y in zip(pa, pb)
    )


# ---------------------------------------------------- SpillManager units
def test_ram_roundtrip_byte_identical():
    sm = SpillManager(ram_bytes=1 << 20)
    p = _payload()
    assert sm.put(p)
    h = p.hashes[-1]
    assert h in sm.heads()
    assert sm.has(h, p.tokens)
    # verified content: a forced collision (same head, other tokens)
    # reads as a miss, exactly like PrefixCache
    assert not sm.has(h, tuple(t + 1 for t in p.tokens))
    got = sm.take(h, p.tokens)
    assert got is not None and _same_bytes(p, got)
    assert not sm.has(h, p.tokens) and sm.restored_ram == 1


def test_ram_overflow_demotes_to_disk_and_restores(tmp_path):
    p1, p2 = _payload(seed=1, first_token=1), _payload(seed=2, first_token=1000)
    sm = SpillManager(ram_bytes=p1.nbytes + 1, dir_path=str(tmp_path))
    assert sm.put(p1) and sm.put(p2)
    # LRU (p1) demoted to a CRC-framed segment, p2 stayed resident
    assert sm.ram_entries == 1 and sm.disk_entries == 1
    segs = list(tmp_path.glob("*.seg"))
    assert len(segs) == 1
    got = sm.take(p1.hashes[-1], p1.tokens)
    assert got is not None and _same_bytes(p1, got)
    assert sm.restored_disk == 1
    # the consumed segment is gone from disk too
    assert not list(tmp_path.glob("*.seg"))


def test_disk_budget_drops_oldest(tmp_path):
    p1, p2 = _payload(seed=1, first_token=1), _payload(seed=2, first_token=1000)
    sm = SpillManager(dir_path=str(tmp_path), dir_bytes=p1.nbytes + 1)
    assert sm.put(p1) and sm.put(p2)
    assert sm.disk_entries == 1 and sm.dropped == 1
    assert not sm.has(p1.hashes[-1], p1.tokens)
    assert sm.has(p2.hashes[-1], p2.tokens)


def test_heal_truncates_torn_tail(tmp_path):
    p = _payload(seed=3)
    sm = SpillManager(dir_path=str(tmp_path))
    assert sm.put(p)
    (seg,) = tmp_path.glob("*.seg")
    # the torn tail a power cut leaves: garbage after the last whole frame
    with open(seg, "ab") as f:
        f.write(b"\x7fgarbage-torn-tail")
    sm2 = SpillManager(dir_path=str(tmp_path))
    assert sm2.has(p.hashes[-1], p.tokens)
    got = sm2.take(p.hashes[-1], p.tokens)
    assert got is not None and _same_bytes(p, got)


def test_corrupt_segment_quarantines_clean_miss(tmp_path):
    p = _payload(seed=4)
    sm = SpillManager(dir_path=str(tmp_path))
    assert sm.put(p)
    (seg,) = tmp_path.glob("*.seg")
    corrupt_segment_frame(str(seg))
    sm2 = SpillManager(dir_path=str(tmp_path))
    # bit rot reads as a clean miss, never a wedge or wrong KV
    assert sm2.quarantined == 1
    assert not sm2.has(p.hashes[-1], p.tokens)
    assert list(tmp_path.glob("*.seg.corrupt")) and not list(
        tmp_path.glob("*.seg")
    )
    # the quarantined file is inert: a THIRD heal pass ignores it
    sm3 = SpillManager(dir_path=str(tmp_path))
    assert sm3.quarantined == 0 and sm3.disk_entries == 0
    # and the directory stays writable after quarantine
    assert sm3.put(p) and sm3.has(p.hashes[-1], p.tokens)


def test_kill_after_meta_frame_is_ignorable(tmp_path):
    p = _payload(seed=5)
    sm = SpillManager(dir_path=str(tmp_path))
    plan = FaultPlan([Fault(point="kv.spill", action="kill", at=0)])
    with active(plan), pytest.raises(SimulatedKill):
        sm.put(p)  # died after the meta frame, before any payload frame
    sm2 = SpillManager(dir_path=str(tmp_path))
    # meta-only segment: incomplete, deleted, a clean miss — never torn
    assert sm2.incomplete >= 1 and sm2.disk_entries == 0
    assert not sm2.has(p.hashes[-1], p.tokens)
    assert sm2.put(p)  # directory still fully usable


def test_kill_after_payload_frames_is_restorable(tmp_path):
    p = _payload(seed=6)
    sm = SpillManager(dir_path=str(tmp_path))
    # at=1: the second kv.spill hit — every frame flushed, index not yet
    plan = FaultPlan([Fault(point="kv.spill", action="kill", at=1)])
    with active(plan), pytest.raises(SimulatedKill):
        sm.put(p)
    sm2 = SpillManager(dir_path=str(tmp_path))
    got = sm2.take(p.hashes[-1], p.tokens)
    assert got is not None and _same_bytes(p, got)


def test_scrambled_tail_mid_spill_heals_restorable(tmp_path):
    p = _payload(seed=7)
    sm = SpillManager(dir_path=str(tmp_path))
    plan = FaultPlan(
        [Fault(point="kv.spill", action="scramble_tail", at=1)], seed=11
    )
    with active(plan), pytest.raises(SimulatedKill):
        sm.put(p)
    sm2 = SpillManager(dir_path=str(tmp_path))
    got = sm2.take(p.hashes[-1], p.tokens)
    assert got is not None and _same_bytes(p, got)


# ------------------------------------------- KVCacheManager restore races
CFG = {
    "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
}
LADDERS = ((32,), (8,))


@pytest.fixture(scope="module")
def model():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    b = build_model("transformer_lm", CFG)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


def _collide_hash(prev, chunk):
    # token ids 100 apart hash identically — a forced chain collision
    canon = tuple(int(t) % 100 for t in chunk)
    return hashlib.blake2b(
        repr((prev, canon)).encode(), digest_size=16
    ).hexdigest()


def _manager(model, **kw):
    from polyaxon_tpu.serving.kv import KVCacheManager

    module, params = model
    return KVCacheManager(
        module, params, pool_pages=16, page_tokens=PT,
        spill_ram_bytes=1 << 20, **kw,
    )


def _spill_payload_for(mgr, tokens):
    """A restorable spill entry whose per-page leaf shapes match the
    manager's cache leaves (page-sliced), so a queued restore could
    actually flush."""
    import jax

    hashes = tuple(page_hashes(tokens, PT, mgr.prefix.hash_fn))
    scanned = bool(getattr(mgr.module.cfg, "scan_layers", False))
    shapes = [
        (tuple(leaf.shape[0:1]) + tuple(leaf.shape[2:]))
        if scanned else tuple(leaf.shape[1:])
        for leaf in jax.tree.leaves(mgr.cache)
    ]
    pages = [
        [np.zeros(s, np.float32) for s in shapes]
        for _ in range(len(tokens) // PT)
    ]
    return SpillPayload(tuple(tokens), hashes, pages)


def test_kill_mid_restore_leaks_zero_pages(model):
    mgr = _manager(model)
    prompt = tuple(range(1, 17))  # two full pages
    mgr._spill.put(_spill_payload_for(mgr, prompt))
    used0, reserved0 = mgr.pool.used, mgr.pool.reserved
    plan = FaultPlan([Fault(point="kv.restore", action="kill", at=0)])
    with active(plan), pytest.raises(SimulatedKill):
        mgr.plan_row(list(prompt) + [77], 4, *LADDERS, 64)
    # the death mid-restore returned every page the restore held
    assert mgr.pool.used == used0 and mgr.pool.reserved == reserved0
    assert mgr.stats()["spill"]["pending_restores"] == 0
    assert mgr.active_rows == 0
    # and the manager still serves: the same row admits cleanly after
    p = mgr.plan_row(list(prompt) + [77], 4, *LADDERS, 64)
    mgr.release(p)
    assert mgr.pool.used == used0 and mgr.pool.reserved == reserved0


def test_lost_admission_race_aborts_without_leak(model):
    mgr = _manager(model, hash_fn=_collide_hash)
    # two token streams, same chain hashes (ids 100 apart): B occupies
    # every chain slot in the live cache, A sits in the spill tier
    a = tuple(range(1, 17))
    b = (101,) + tuple(range(2, 17))
    assert page_hashes(a, PT, _collide_hash) == page_hashes(b, PT, _collide_hash)
    pages_b = mgr.pool.alloc(2)
    assert mgr.prefix.insert(b[:PT], pages_b[:1])
    assert mgr.prefix.insert(b, pages_b)
    mgr.pool.unref(pages_b)  # entries hold their own refs now
    mgr._spill.put(_spill_payload_for(mgr, a))
    used0, reserved0 = mgr.pool.used, mgr.pool.reserved
    # admitting A finds its spilled prefix, restores, then loses every
    # insert to B's occupied slots — the restore must cancel cleanly
    p = mgr.plan_row(list(a) + [77], 4, *LADDERS, 64)
    assert mgr.restore_aborted == 1
    assert mgr.stats()["spill"]["pending_restores"] == 0
    # A got no prefix (collision reads as a miss, first writer wins)
    assert p.prefix_len == 0 and p.prefix_entry is None
    mgr.release(p)
    assert mgr.pool.used == used0 and mgr.pool.reserved == reserved0


# ------------------------------------------------------- live HTTP layer
def _server(model, **overrides):
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    module, params = model
    cfg = ServingConfig(**{
        "max_batch": 4, "max_wait_ms": 2.0, "kv_pool_pages": 24,
        "kv_page_tokens": PT, "spill_ram_bytes": 32 << 20, **overrides,
    })
    return ModelServer(module, params, model_name="tiny", config=cfg)


def _post(port, body, timeout=120):
    import http.client

    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request("POST", "/generate", json.dumps(body))
    r = c.getresponse()
    out = r.read()
    c.close()
    return r.status, out


def _stats(port):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statsz", timeout=60
    ).read())


def _greedy(tokens, seed=7):
    return {
        "tokens": [list(tokens)], "maxNewTokens": 6, "temperature": 0.0,
        "seed": seed,
    }


def _prompts(n, plen=49, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 100, size=plen).tolist() for _ in range(n)]


def test_http_evict_spill_restore_byte_identical(model):
    srv = _server(model)
    port = srv.start(port=0)
    try:
        target, *flood = _prompts(7)
        s, cold = _post(port, _greedy(target))
        assert s == 200, cold
        # distinct prompts force harvest to demote the target's entries
        # into the spill tier (pool: 24 pages, each prompt caches 6)
        for f in flood:
            s, _ = _post(port, _greedy(f))
            assert s == 200
        st = _stats(port)["kv"]["spill"]
        assert st["spills"] >= 1, st
        hits0 = _stats(port)["kv"]["prefix"]["hits"]
        s, warm = _post(port, _greedy(target))
        assert s == 200
        st = _stats(port)["kv"]["spill"]
        # the repeat rode a RESTORE (spill tier -> pool -> prefix hit),
        # not a cold re-prefill — and decoded the exact same bytes
        assert st["restores"] >= 1, st
        assert _stats(port)["kv"]["prefix"]["hits"] > hits0
        assert json.loads(cold)["tokens"] == json.loads(warm)["tokens"]
    finally:
        srv.stop()


def test_http_reroute_warm_byte_identical(model):
    from polyaxon_tpu.serving.router import Router

    s1, s2 = _server(model), _server(model)
    p1, p2 = s1.start(port=0), s2.start(port=0)
    router = Router(
        [f"http://127.0.0.1:{p1}", f"http://127.0.0.1:{p2}"],
        poll_interval_s=60.0,
    )
    rport = router.start(port=0)
    try:
        target = _prompts(1, seed=9)[0]
        s, cold = _post(rport, _greedy(target))
        assert s == 200, cold
        router.poll_once()  # pick up the holder's /kvz advertisement
        s, warm = _post(rport, _greedy(target))
        assert s == 200
        # affinity steered the repeat to the replica that cached it
        assert router.stats()["affinity"]["hits"] >= 1
        assert json.loads(cold)["tokens"] == json.loads(warm)["tokens"]
        # forced re-route: posting straight to EACH replica covers both
        # the holder (warm) and the sibling (cold re-prefill) — placement
        # is a latency hint, never a correctness input
        for p in (p1, p2):
            s, rerouted = _post(p, _greedy(target))
            assert s == 200
            assert json.loads(cold)["tokens"] == json.loads(rerouted)["tokens"]
    finally:
        router.stop()
        s1.stop()
        s2.stop()
