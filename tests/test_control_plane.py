"""Presets, cache, hooks, schedules, joins — the operation-level control
plane features (SURVEY.md §2 spec rows beyond the core run path)."""

import datetime as dt
import os

import pytest
import yaml

from polyaxon_tpu.compiler.resolver import (
    CompilationError,
    compile_operation,
    spec_fingerprint,
)
from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.runtime.executor import Executor
from polyaxon_tpu.scheduler import (
    Agent,
    ScheduleRegistry,
    query_runs,
    resolve_joins,
)
from polyaxon_tpu.scheduler.schedules import cron_matches, next_fire_time
from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.schemas.operation import V1Operation, V1Schedule
from polyaxon_tpu.store.local import RunStore

FAST_JOB = {
    "kind": "component",
    "name": "fast",
    "run": {"kind": "job", "container": {"command": ["true"]}},
}


def _op(tmp_path, spec, params=None, fname="op.yaml"):
    p = tmp_path / fname
    p.write_text(yaml.safe_dump(spec))
    return read_polyaxonfile(str(p), params=params)


# ------------------------------------------------------------------ presets
def test_presets_merge(tmp_home, tmp_path):
    presets_dir = tmp_home / "presets"
    presets_dir.mkdir(parents=True)
    (presets_dir / "gpu-defaults.yaml").write_text(
        yaml.safe_dump(
            {
                "termination": {"maxRetries": 3},
                "tags": ["preset-tag"],
            }
        )
    )
    op = _op(
        tmp_path,
        {
            "version": 1.1,
            "kind": "operation",
            "name": "p",
            "presets": ["gpu-defaults"],
            "component": FAST_JOB,
        },
    )
    compiled = compile_operation(op)
    assert compiled.component.termination.max_retries == 3


def test_presets_do_not_override_op(tmp_home, tmp_path):
    presets_dir = tmp_home / "presets"
    presets_dir.mkdir(parents=True)
    (presets_dir / "t.yaml").write_text(
        yaml.safe_dump({"termination": {"maxRetries": 3}})
    )
    op = _op(
        tmp_path,
        {
            "version": 1.1,
            "kind": "operation",
            "name": "p",
            "presets": ["t"],
            "termination": {"maxRetries": 7},
            "component": FAST_JOB,
        },
    )
    compiled = compile_operation(op)
    assert compiled.component.termination.max_retries == 7  # op wins


def test_missing_preset_raises(tmp_home, tmp_path):
    op = _op(
        tmp_path,
        {
            "version": 1.1,
            "kind": "operation",
            "name": "p",
            "presets": ["nope"],
            "component": FAST_JOB,
        },
    )
    with pytest.raises(CompilationError, match="preset 'nope'"):
        compile_operation(op)


# ------------------------------------------------------------------ cache
def test_cache_hit_reuses_results(tmp_home, tmp_path):
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "cached",
        "cache": {},
        "component": {
            "kind": "component",
            "name": "c",
            "run": {
                "kind": "job",
                "container": {"command": ["sh", "-c", "echo did-work"]},
            },
        },
    }
    store = RunStore()
    c1 = compile_operation(_op(tmp_path, spec))
    assert Executor(store).execute(c1) == V1Statuses.SUCCEEDED
    c2 = compile_operation(_op(tmp_path, spec, fname="op2.yaml"))
    assert spec_fingerprint(c1) == spec_fingerprint(c2)
    assert Executor(store).execute(c2) == V1Statuses.SUCCEEDED
    # second run never executed the container: it linked the first run
    events = store.read_events(c2.run_uuid)
    assert any(e.get("kind") == "cache_hit" for e in events)
    assert "did-work" not in store.read_logs(c2.run_uuid)


def test_cache_miss_on_param_change(tmp_home, tmp_path):
    base = {
        "version": 1.1,
        "kind": "operation",
        "name": "cached",
        "cache": {},
        "component": {
            "kind": "component",
            "name": "c",
            "inputs": [{"name": "x", "type": "int", "value": 1}],
            "run": {
                "kind": "job",
                "container": {"command": ["true"]},
            },
        },
    }
    store = RunStore()
    c1 = compile_operation(_op(tmp_path, base))
    Executor(store).execute(c1)
    c2 = compile_operation(_op(tmp_path, base, params={"x": 2}, fname="b.yaml"))
    Executor(store).execute(c2)
    assert not any(
        e.get("kind") == "cache_hit" for e in store.read_events(c2.run_uuid)
    )


# ------------------------------------------------------------------ hooks
def test_hook_fires_on_success(tmp_home, tmp_path):
    hook_file = tmp_path / "notify.yaml"
    hook_file.write_text(
        yaml.safe_dump(
            {
                "version": 1.1,
                "kind": "component",
                "name": "notify",
                "inputs": [
                    {"name": "status", "type": "str", "value": "none"},
                    {"name": "run_uuid", "type": "str", "value": ""},
                ],
                "run": {
                    "kind": "job",
                    "container": {
                        "command": ["sh", "-c", "echo hook-ran-{{ params.status }}"]
                    },
                },
            }
        )
    )
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "with-hook",
        "hooks": [{"pathRef": str(hook_file), "trigger": "succeeded"}],
        "component": FAST_JOB,
    }
    store = RunStore()
    compiled = compile_operation(_op(tmp_path, spec))
    assert Executor(store).execute(compiled) == V1Statuses.SUCCEEDED
    runs = store.list_runs()
    hook_runs = [r for r in runs if r["name"] == "with-hook-hook"]
    assert hook_runs
    logs = store.read_logs(hook_runs[0]["uuid"])
    assert "hook-ran" in logs


def test_hook_skipped_on_wrong_trigger(tmp_home, tmp_path):
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "with-hook",
        "hooks": [{"hubRef": "notifier", "trigger": "failed"}],
        "component": FAST_JOB,
    }
    store = RunStore()
    compiled = compile_operation(_op(tmp_path, spec))
    Executor(store).execute(compiled)
    events = store.read_events(compiled.run_uuid)
    assert not any(e.get("kind") == "notification" for e in events)


# ------------------------------------------------------------------ schedules
def test_cron_matcher():
    t = dt.datetime(2026, 7, 29, 14, 30)  # Wednesday
    assert cron_matches("30 14 * * *", t)
    assert cron_matches("*/15 * * * *", t)
    assert cron_matches("30 14 29 7 3", t)
    assert not cron_matches("31 14 * * *", t)
    assert not cron_matches("30 14 * * 0", t)  # not Sunday


def test_interval_schedule_next_fire():
    s = V1Schedule(kind="interval", frequency=3600)
    now = dt.datetime(2026, 7, 29, 12, 0)
    first = next_fire_time(s, now, None)
    assert first == now + dt.timedelta(seconds=3600)
    second = next_fire_time(s, first, first)
    assert second == first + dt.timedelta(seconds=3600)


def test_schedule_registry_tick(tmp_home, tmp_path):
    op = _op(
        tmp_path,
        {
            "version": 1.1,
            "kind": "operation",
            "name": "scheduled-job",
            "schedule": {"kind": "interval", "frequency": 60, "maxRuns": 2},
            "component": FAST_JOB,
        },
    )
    store = RunStore()
    registry = ScheduleRegistry(store)
    registry.add(op)
    agent = Agent(store=store)
    now = dt.datetime.now()
    assert registry.tick(agent, now) == 0  # not due yet
    assert registry.tick(agent, now + dt.timedelta(seconds=61)) == 1
    assert registry.tick(agent, now + dt.timedelta(seconds=200)) == 1
    # maxRuns=2 exhausted: registry empties
    assert registry.list() == []
    assert agent.drain() == 2
    statuses = [store.get_status(r["uuid"])["status"] for r in store.list_runs()]
    assert statuses.count(V1Statuses.SUCCEEDED) == 2


# ------------------------------------------------------------------ joins
def _seed_runs(store):
    for i, (loss, status, tag) in enumerate(
        [(0.1, "succeeded", "sweep"), (0.5, "succeeded", "sweep"), (0.3, "failed", "sweep")]
    ):
        uuid = f"{i:032x}"
        store.create_run(uuid, f"r{i}", "default", {}, tags=[tag])
        store.log_metrics(uuid, 1, {"loss": loss})
        for s in ("compiled", "queued", "scheduled", "starting", "running", status):
            store.set_status(uuid, s)
    return store


def test_query_runs_filters_and_sorts(tmp_home):
    store = _seed_runs(RunStore())
    got = query_runs(store, "status:succeeded tag:sweep", sort="metrics.loss")
    assert [r["metrics"]["loss"] for r in got] == [0.1, 0.5]
    got = query_runs(store, "metrics.loss:<0.4", sort="-metrics.loss")
    assert [r["metrics"]["loss"] for r in got] == [0.3, 0.1]


def test_resolve_joins_injects_params(tmp_home, tmp_path):
    store = _seed_runs(RunStore())
    op = _op(
        tmp_path,
        {
            "version": 1.1,
            "kind": "operation",
            "name": "ensemble",
            "joins": [
                {
                    "query": "status:succeeded",
                    "sort": "metrics.loss",
                    "limit": 2,
                    "params": {
                        "uuids": {"ref": "runs.uuid"},
                        "losses": {"ref": "runs.outputs.loss"},
                    },
                }
            ],
            "component": FAST_JOB,
        },
    )
    resolved = resolve_joins(op, store)
    assert resolved.joins is None
    assert resolved.params["losses"].value == [0.1, 0.5]
    assert len(resolved.params["uuids"].value) == 2


def test_cache_hits_on_agent_path(tmp_home, tmp_path):
    """Fingerprint meta is recorded at submit time, so queued runs cache."""
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "agent-cached",
        "cache": {},
        "component": FAST_JOB,
    }
    store = RunStore()
    agent = Agent(store=store)
    u1 = agent.submit(_op(tmp_path, spec))
    u2 = agent.submit(_op(tmp_path, spec, fname="again.yaml"))
    assert agent.drain() == 2
    assert store.get_status(u2)["status"] == V1Statuses.SUCCEEDED
    assert any(e.get("kind") == "cache_hit" for e in store.read_events(u2))


def test_cron_dom_dow_or_semantics():
    # '0 0 1 * 1': midnight on the 1st OR on Mondays (standard cron OR rule)
    assert cron_matches("0 0 1 * 1", dt.datetime(2026, 7, 1, 0, 0))   # a Wednesday, dom matches
    assert cron_matches("0 0 1 * 1", dt.datetime(2026, 7, 6, 0, 0))   # a Monday, dow matches
    assert not cron_matches("0 0 1 * 1", dt.datetime(2026, 7, 7, 0, 0))  # neither
