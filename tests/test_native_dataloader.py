"""Native C++ token loader (native/dataloader.cpp): correctness vs the
corpus, multi-host disjointness, determinism, dataset-registry fallback,
and sanitizer builds of the prefetch ring (SURVEY.md §5 race detection —
the worker/consumer queue is exactly the code that wants TSan)."""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from polyaxon_tpu.data import build_data
from polyaxon_tpu.native.dataloader import NativeTokenLoader

NATIVE_DIR = Path(__file__).resolve().parent.parent / "polyaxon_tpu" / "native"


def _arange_corpus(tmp_path, n=4096, dtype=np.uint16, name="c.bin"):
    # token value == offset: a window's first token IS its start position
    path = tmp_path / name
    np.arange(n, dtype=dtype).tofile(path)
    return path


def test_windows_match_corpus_and_residue_class(tmp_path):
    path = _arange_corpus(tmp_path)
    with NativeTokenLoader(
        path, seq_len=32, batch_size=8, seed=7, process_index=1, process_count=4
    ) as ld:
        assert ld.corpus_tokens == 4096
        for _ in range(20):
            b = next(ld)
            starts = b["inputs"][:, 0]
            assert (starts % 4 == 1).all()  # this process's residue class
            for r in range(8):
                s = starts[r]
                assert (b["inputs"][r] == np.arange(s, s + 32)).all()
                assert (b["labels"][r] == np.arange(s + 1, s + 33)).all()


def test_npy_header_offset_and_int32(tmp_path):
    path = tmp_path / "c.npy"
    np.save(path, np.arange(2048, dtype=np.int32))
    with NativeTokenLoader(path, seq_len=16, batch_size=4, seed=1) as ld:
        b = next(ld)
        s = b["inputs"][:, 0]
        for r in range(4):
            assert (b["inputs"][r] == np.arange(s[r], s[r] + 16)).all()


def test_same_seed_same_stream(tmp_path):
    path = _arange_corpus(tmp_path)
    with NativeTokenLoader(path, seq_len=8, batch_size=4, seed=3) as a, \
         NativeTokenLoader(path, seq_len=8, batch_size=4, seed=3) as b:
        for _ in range(6):
            assert (next(a)["inputs"] == next(b)["inputs"]).all()
    with NativeTokenLoader(path, seq_len=8, batch_size=4, seed=4) as c, \
         NativeTokenLoader(path, seq_len=8, batch_size=4, seed=5) as d:
        assert not all(
            (next(c)["inputs"] == next(d)["inputs"]).all() for _ in range(4)
        )


def test_registry_uses_native_loader_and_python_fallback(tmp_path):
    path = _arange_corpus(tmp_path)
    spec = build_data(
        "token_file", 4, {"path": str(path), "seq_len": 32}, seed=1
    )
    assert spec.meta["loader"] == "native"
    batch = next(spec.iterator)
    assert batch["inputs"].shape == (4, 32)

    spec_py = build_data(
        "token_file", 4,
        {"path": str(path), "seq_len": 32, "loader": "python"}, seed=1,
    )
    assert spec_py.meta["loader"] == "python"
    assert next(spec_py.iterator)["inputs"].shape == (4, 32)


def test_open_errors_are_clean(tmp_path):
    with pytest.raises(FileNotFoundError):
        NativeTokenLoader(tmp_path / "nope.bin", seq_len=8, batch_size=2)
    tiny = _arange_corpus(tmp_path, n=4, name="tiny.bin")
    with pytest.raises(RuntimeError, match="smaller than one window"):
        NativeTokenLoader(tiny, seq_len=64, batch_size=2)


_SAN_DRIVER = """
import sys
sys.path.insert(0, {repo!r})
from polyaxon_tpu.native.dataloader import NativeTokenLoader
# 4 worker threads + consumer hammering the ring: the contended path
with NativeTokenLoader(
    {path!r}, seq_len=64, batch_size=8, seed=1, n_threads=4, queue_depth=3,
    lib_name={lib!r},
) as ld:
    for _ in range(200):
        next(ld)
print("SAN-OK")
"""


@pytest.mark.parametrize("san", ["asan", "tsan"])
def test_sanitized_prefetch_ring(san, tmp_path):
    """Build the loader under ASan/UBSan and TSan and hammer the ring from
    a child interpreter (the sanitizer runtime must be preloaded)."""
    lib = f"libptl-dataloader-{san}.so"
    proc = subprocess.run(
        ["make", "-C", str(NATIVE_DIR), lib], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stderr
    runtime = {"asan": "libasan.so", "tsan": "libtsan.so"}[san]
    preload = subprocess.run(
        ["g++", f"-print-file-name={runtime}"], capture_output=True, text=True
    ).stdout.strip()
    if not preload or not Path(preload).is_absolute():
        pytest.skip(f"{runtime} not available to preload")
    path = _arange_corpus(tmp_path, n=65536)
    repo = str(NATIVE_DIR.parent.parent)
    out = subprocess.run(
        [sys.executable, "-c",
         _SAN_DRIVER.format(repo=repo, path=str(path), lib=lib)],
        capture_output=True,
        text=True,
        timeout=120,
        env={
            "PATH": "/usr/bin:/bin",
            "LD_PRELOAD": preload,
            # leak checking sees the interpreter's own allocations; the
            # loader's lifecycle is covered by close() in the driver
            "ASAN_OPTIONS": "detect_leaks=0",
        },
    )
    assert "SAN-OK" in out.stdout, f"stdout={out.stdout}\nstderr={out.stderr}"
    for marker in ("ERROR: AddressSanitizer", "WARNING: ThreadSanitizer"):
        assert marker not in out.stderr, out.stderr


def test_hosts_decorrelated_not_token_shifted(tmp_path):
    """Hosts share one config seed; the loader must mix process_index into
    the RNG or every host draws the SAME index sequence in its residue
    class — global batches would be token-shifted near-duplicates."""
    path = _arange_corpus(tmp_path, n=65536)
    with NativeTokenLoader(
        path, seq_len=8, batch_size=16, seed=9, process_index=0, process_count=2
    ) as h0, NativeTokenLoader(
        path, seq_len=8, batch_size=16, seed=9, process_index=1, process_count=2
    ) as h1:
        s0 = next(h0)["inputs"][:, 0] // 2  # j index within residue class
        s1 = next(h1)["inputs"][:, 0] // 2
        assert (s0 != s1).any(), "hosts drew identical window indices"
