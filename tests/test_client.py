"""Client SDK (RunClient/ProjectClient, local + HTTP transports) and the
layered settings manager."""

import json

import pytest
import yaml

from polyaxon_tpu.client import ClientError, ProjectClient, RunClient
from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.store.local import RunStore
from polyaxon_tpu.streams import BackgroundServer

FAST_OP = {
    "version": 1.1,
    "kind": "operation",
    "name": "client-job",
    "component": {
        "kind": "component",
        "name": "c",
        "run": {"kind": "job", "container": {"command": ["sh", "-c", "echo out-line"]}},
    },
}


def _op(tmp_path, spec=FAST_OP):
    p = tmp_path / "op.yaml"
    p.write_text(yaml.safe_dump(spec))
    return read_polyaxonfile(str(p))


def test_run_client_create_and_read(tmp_home, tmp_path):
    client = RunClient()
    uuid = client.create(_op(tmp_path), queue=False)
    assert client.get(uuid)["status"] == V1Statuses.SUCCEEDED
    assert "out-line" in client.logs(uuid)
    assert any(c["type"] == "succeeded" for c in client.statuses(uuid))
    assert client.list()[0]["uuid"] == uuid


def test_run_client_queued_then_wait(tmp_home, tmp_path):
    import threading

    from polyaxon_tpu.scheduler import Agent

    client = RunClient()
    uuid = client.create(_op(tmp_path), queue=True)
    assert client.get(uuid)["status"] == V1Statuses.QUEUED
    t = threading.Thread(target=lambda: Agent(store=client.store).drain())
    t.start()
    status = client.wait(uuid, timeout=60)
    t.join()
    assert status == V1Statuses.SUCCEEDED


def test_run_client_http_transport(tmp_home, tmp_path):
    local = RunClient()
    uuid = local.create(_op(tmp_path), queue=False)
    with BackgroundServer(local.store) as srv:
        remote = RunClient(base_url=f"http://127.0.0.1:{srv.port}")
        assert remote.get(uuid)["status"] == "succeeded"
        assert "out-line" in remote.logs(uuid)
        assert remote.list()[0]["uuid"] == uuid
        uuid2 = remote.create(_op(tmp_path))  # write side: POST /runs
        assert remote.get(uuid2)["status"] == V1Statuses.QUEUED


def test_project_client(tmp_home, tmp_path):
    store = RunStore()
    projects = ProjectClient(store)
    projects.create("vision", "image models")
    with pytest.raises(ClientError):
        projects.create("vision")
    client = RunClient(store=store, project="vision")
    client.create(_op(tmp_path), queue=False)
    got = projects.get("vision")
    assert got["runs"] == 1
    names = [p["name"] for p in projects.list()]
    assert "vision" in names


def test_settings_layering(tmp_path, monkeypatch):
    from polyaxon_tpu import settings

    monkeypatch.setenv("POLYAXON_CONFIG_DIR", str(tmp_path))
    monkeypatch.delenv("POLYAXON_PROJECT", raising=False)
    assert settings.get("project") == "default"
    settings.set_value("project", "from-file")
    assert settings.get("project") == "from-file"
    monkeypatch.setenv("POLYAXON_PROJECT", "from-env")
    assert settings.get("project") == "from-env"  # env wins
    with pytest.raises(KeyError):
        settings.get("nope")
    data = json.loads((tmp_path / "config.json").read_text())
    assert data == {"project": "from-file"}


def test_http_write_side_end_to_end(tmp_home, tmp_path):
    """SURVEY.md §3 boundary #1 over the wire: remote create → agent
    executes → remote reads → remote stop of a queued run."""
    import threading

    from polyaxon_tpu.scheduler import Agent

    store = RunStore()
    with BackgroundServer(store) as srv:
        remote = RunClient(base_url=f"http://127.0.0.1:{srv.port}")
        uuid = remote.create(_op(tmp_path))
        assert remote.get(uuid)["status"] == V1Statuses.QUEUED
        t = threading.Thread(target=lambda: Agent(store=store).drain())
        t.start()
        status = remote.wait(uuid, timeout=60)
        t.join()
        assert status == V1Statuses.SUCCEEDED
        assert "out-line" in remote.logs(uuid)

        # stop a queued run remotely; the agent must then skip it
        uuid2 = remote.create(_op(tmp_path))
        remote.stop(uuid2)
        assert remote.get(uuid2)["status"] == V1Statuses.STOPPED
        Agent(store=store).drain()
        assert remote.get(uuid2)["status"] == V1Statuses.STOPPED

        # bad spec → 400 with detail, not a server crash
        with pytest.raises(ClientError, match="400"):
            remote._http.post("/runs", {"operation": {"kind": "nope"}})
        with pytest.raises(ClientError, match="400"):
            remote._http.post("/runs", {})


def test_stop_while_running_cooperative(tmp_home, tmp_path):
    """A stop landing mid-run halts training at the next log point and the
    run ends STOPPED — not SUCCEEDED, and with no illegal-transition crash."""
    import threading
    import time

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "long",
        "component": {
            "kind": "component",
            "name": "long",
            "run": {
                "kind": "jaxjob",
                "program": {
                    "model": {
                        "name": "mlp",
                        "config": {"input_dim": 16, "num_classes": 4, "hidden": [8]},
                    },
                    "data": {
                        "name": "synthetic",
                        "batchSize": 8,
                        "config": {"shape": [16], "num_classes": 4},
                    },
                    "train": {"steps": 2000, "logEvery": 1, "precision": "float32"},
                },
            },
        },
    }
    client = RunClient()
    results = {}

    def _run():
        results["uuid"] = client.create(_op(tmp_path, spec), queue=False)

    t = threading.Thread(target=_run)
    t.start()
    deadline = time.time() + 60
    uuid = None
    while time.time() < deadline:
        runs = client.list()
        if runs and runs[0]["status"] == V1Statuses.RUNNING:
            uuid = runs[0]["uuid"]
            break
        time.sleep(0.2)
    assert uuid, "run never reached RUNNING"
    client.stop(uuid)
    t.join(timeout=60)
    assert not t.is_alive(), "executor did not observe the stop"
    assert results["uuid"] == uuid
    assert client.get(uuid)["status"] == V1Statuses.STOPPED
