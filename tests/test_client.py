"""Client SDK (RunClient/ProjectClient, local + HTTP transports) and the
layered settings manager."""

import json

import pytest
import yaml

from polyaxon_tpu.client import ClientError, ProjectClient, RunClient
from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.store.local import RunStore
from polyaxon_tpu.streams import BackgroundServer

FAST_OP = {
    "version": 1.1,
    "kind": "operation",
    "name": "client-job",
    "component": {
        "kind": "component",
        "name": "c",
        "run": {"kind": "job", "container": {"command": ["sh", "-c", "echo out-line"]}},
    },
}


def _op(tmp_path, spec=FAST_OP):
    p = tmp_path / "op.yaml"
    p.write_text(yaml.safe_dump(spec))
    return read_polyaxonfile(str(p))


def test_run_client_create_and_read(tmp_home, tmp_path):
    client = RunClient()
    uuid = client.create(_op(tmp_path), queue=False)
    assert client.get(uuid)["status"] == V1Statuses.SUCCEEDED
    assert "out-line" in client.logs(uuid)
    assert any(c["type"] == "succeeded" for c in client.statuses(uuid))
    assert client.list()[0]["uuid"] == uuid


def test_run_client_queued_then_wait(tmp_home, tmp_path):
    import threading

    from polyaxon_tpu.scheduler import Agent

    client = RunClient()
    uuid = client.create(_op(tmp_path), queue=True)
    assert client.get(uuid)["status"] == V1Statuses.QUEUED
    t = threading.Thread(target=lambda: Agent(store=client.store).drain())
    t.start()
    status = client.wait(uuid, timeout=60)
    t.join()
    assert status == V1Statuses.SUCCEEDED


def test_run_client_http_transport(tmp_home, tmp_path):
    local = RunClient()
    uuid = local.create(_op(tmp_path), queue=False)
    with BackgroundServer(local.store) as srv:
        remote = RunClient(base_url=f"http://127.0.0.1:{srv.port}")
        assert remote.get(uuid)["status"] == "succeeded"
        assert "out-line" in remote.logs(uuid)
        assert remote.list()[0]["uuid"] == uuid
        uuid2 = remote.create(_op(tmp_path))  # write side: POST /runs
        assert remote.get(uuid2)["status"] == V1Statuses.QUEUED


def test_project_client(tmp_home, tmp_path):
    store = RunStore()
    projects = ProjectClient(store)
    projects.create("vision", "image models")
    with pytest.raises(ClientError):
        projects.create("vision")
    client = RunClient(store=store, project="vision")
    client.create(_op(tmp_path), queue=False)
    got = projects.get("vision")
    assert got["runs"] == 1
    names = [p["name"] for p in projects.list()]
    assert "vision" in names


def test_settings_layering(tmp_path, monkeypatch):
    from polyaxon_tpu import settings

    monkeypatch.setenv("POLYAXON_CONFIG_DIR", str(tmp_path))
    monkeypatch.delenv("POLYAXON_PROJECT", raising=False)
    assert settings.get("project") == "default"
    settings.set_value("project", "from-file")
    assert settings.get("project") == "from-file"
    monkeypatch.setenv("POLYAXON_PROJECT", "from-env")
    assert settings.get("project") == "from-env"  # env wins
    with pytest.raises(KeyError):
        settings.get("nope")
    data = json.loads((tmp_path / "config.json").read_text())
    assert data == {"project": "from-file"}


def test_http_write_side_end_to_end(tmp_home, tmp_path):
    """SURVEY.md §3 boundary #1 over the wire: remote create → agent
    executes → remote reads → remote stop of a queued run."""
    import threading

    from polyaxon_tpu.scheduler import Agent

    store = RunStore()
    with BackgroundServer(store) as srv:
        remote = RunClient(base_url=f"http://127.0.0.1:{srv.port}")
        uuid = remote.create(_op(tmp_path))
        assert remote.get(uuid)["status"] == V1Statuses.QUEUED
        t = threading.Thread(target=lambda: Agent(store=store).drain())
        t.start()
        status = remote.wait(uuid, timeout=60)
        t.join()
        assert status == V1Statuses.SUCCEEDED
        assert "out-line" in remote.logs(uuid)
        # resolved spec over the wire (ops compare reads params from it)
        assert remote.spec(uuid).get("runUuid") == uuid

        # stop a queued run remotely; the agent must then skip it
        uuid2 = remote.create(_op(tmp_path))
        remote.stop(uuid2)
        assert remote.get(uuid2)["status"] == V1Statuses.STOPPED
        Agent(store=store).drain()
        assert remote.get(uuid2)["status"] == V1Statuses.STOPPED

        # bad spec → 400 with detail, not a server crash
        with pytest.raises(ClientError, match="400"):
            remote._http.post("/runs", {"operation": {"kind": "nope"}})
        with pytest.raises(ClientError, match="400"):
            remote._http.post("/runs", {})


def test_stop_while_running_cooperative(tmp_home, tmp_path):
    """A stop landing mid-run halts training at the next log point and the
    run ends STOPPED — not SUCCEEDED, and with no illegal-transition crash."""
    import threading
    import time

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "long",
        "component": {
            "kind": "component",
            "name": "long",
            "run": {
                "kind": "jaxjob",
                "program": {
                    "model": {
                        "name": "mlp",
                        "config": {"input_dim": 16, "num_classes": 4, "hidden": [8]},
                    },
                    "data": {
                        "name": "synthetic",
                        "batchSize": 8,
                        "config": {"shape": [16], "num_classes": 4},
                    },
                    "train": {"steps": 2000, "logEvery": 1, "precision": "float32"},
                },
            },
        },
    }
    client = RunClient()
    results = {}

    def _run():
        results["uuid"] = client.create(_op(tmp_path, spec), queue=False)

    t = threading.Thread(target=_run)
    t.start()
    deadline = time.time() + 60
    uuid = None
    while time.time() < deadline:
        runs = client.list()
        if runs and runs[0]["status"] == V1Statuses.RUNNING:
            uuid = runs[0]["uuid"]
            break
        time.sleep(0.2)
    assert uuid, "run never reached RUNNING"
    client.stop(uuid)
    t.join(timeout=60)
    assert not t.is_alive(), "executor did not observe the stop"
    assert results["uuid"] == uuid
    assert client.get(uuid)["status"] == V1Statuses.STOPPED


PROGRAM_OP = {
    "version": 1.1,
    "kind": "operation",
    "name": "trainable",
    "params": {"lr": {"value": 0.01}},
    "component": {
        "kind": "component",
        "name": "trainable",
        "cache": {"disable": False},
        "inputs": [
            {"name": "steps", "type": "int", "value": 6},
            {"name": "lr", "type": "float"},
        ],
        "run": {
            "kind": "jaxjob",
            "program": {
                "model": {
                    "name": "mlp",
                    "config": {"input_dim": 8, "num_classes": 2, "hidden": [4]},
                },
                "data": {
                    "name": "synthetic",
                    "batchSize": 8,
                    "config": {"shape": [8], "num_classes": 2},
                },
                "optimizer": {"name": "adamw", "learningRate": "{{ params.lr }}"},
                "train": {
                    "steps": "{{ params.steps }}",
                    "logEvery": 2,
                    "checkpointEvery": 2,
                    "precision": "float32",
                },
            },
        },
    },
}


@pytest.mark.slow
def test_restart_resume_copy(tmp_home, tmp_path):
    client = RunClient()
    src = client.create(_op(tmp_path, PROGRAM_OP), queue=False)
    assert client.get(src)["status"] == V1Statuses.SUCCEEDED
    assert client.metrics(src)[-1]["step"] == 6

    # restart: fresh outputs, full re-run from step 1 — params from the
    # stored spec are re-supplied (required input lr has no default) and the
    # component's cache must NOT short-circuit the clone to stale results
    r = client.restart(src, queue=False)
    assert client.get(r)["status"] == V1Statuses.SUCCEEDED
    assert client.metrics(r)[0]["step"] <= 2
    assert client.get(r)["meta"]["clone_kind"] == "restart"
    assert not any(e.get("kind") == "cache_hit" for e in client.events(r))

    # copy: outputs seeded from the source before execution
    c = client.copy(src, queue=False)
    assert client.get(c)["status"] == V1Statuses.SUCCEEDED
    assert any("checkpoints" in a for a in client.artifacts(c))

    # resume: inherits checkpoints and continues from the saved step —
    # first logged metric is past the source's final step? no: same total
    # steps, so resume restores step 6 and has nothing left; metrics empty
    # is legal. Assert lineage + restored step via events instead.
    # resuming a non-terminal run is refused (torn-checkpoint protection)
    live = client.create(_op(tmp_path, PROGRAM_OP), queue=True)  # still QUEUED
    with pytest.raises(ClientError, match="wait for a terminal status"):
        client.resume(live)

    rs = client.resume(src, queue=False)
    assert client.get(rs)["status"] == V1Statuses.SUCCEEDED
    events = client.events(src)
    kinds = [e.get("kind") for e in events]
    assert kinds.count("lineage") == 3  # restart, copy, resume all recorded
    clone_kinds = {e.get("clone_kind") for e in events if e.get("kind") == "lineage"}
    assert clone_kinds == {"restart", "copy", "resume"}
    summary = [e for e in client.events(rs) if e.get("kind") == "run_summary"]
    assert summary  # resumed run completed and summarized


def test_delete_run_local_and_http(tmp_home, tmp_path):
    client = RunClient()
    done = client.create(_op(tmp_path), queue=False)
    queued = client.create(_op(tmp_path), queue=True)

    # active (queued) runs are protected
    with pytest.raises(ValueError, match="stop it before deleting"):
        client.delete(queued)
    client.stop(queued)

    # deleting a stopped-but-still-queued run purges its queue entry: a
    # later agent drain must NOT resurrect it
    client.delete(queued)
    from polyaxon_tpu.scheduler import Agent

    Agent(store=client.store).drain()
    assert all(r["uuid"] != queued for r in client.list())
    queued = client.create(_op(tmp_path), queue=True)
    client.stop(queued)

    with BackgroundServer(client.store) as srv:
        remote = RunClient(base_url=f"http://127.0.0.1:{srv.port}")
        remote.delete(done)
        assert all(r["uuid"] != done for r in remote.list())
        with pytest.raises(ClientError, match="404"):
            remote.delete(done)  # already gone
    client.delete(queued)
    assert client.list() == []


def test_cli_run_against_remote_control_plane(tmp_home, tmp_path, monkeypatch):
    """POLYAXON_STREAMS_URL routes `polyaxon run` through the HTTP control
    plane: server enqueues, agent executes, CLI watches over the wire."""
    import threading

    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli
    from polyaxon_tpu.scheduler import Agent

    store = RunStore()
    p = tmp_path / "op.yaml"
    p.write_text(yaml.safe_dump(FAST_OP))
    with BackgroundServer(store) as srv:
        monkeypatch.setenv("POLYAXON_STREAMS_URL", f"http://127.0.0.1:{srv.port}")
        t = threading.Thread(
            target=lambda: Agent(store=store).serve(
                poll_interval=0.1,
                stop_when=lambda: bool(
                    store.list_runs()
                    and store.list_runs()[0]["status"]
                    in ("succeeded", "failed")
                ),
            )
        )
        t.start()
        res = CliRunner().invoke(cli, ["run", "-f", str(p), "--watch"])
        t.join(timeout=30)
        assert res.exit_code == 0, res.output
        assert "created on http://127.0.0.1" in res.output
        assert "finished: succeeded" in res.output
        assert "out-line" in res.output

        # ops verbs ride the same remote control plane
        uid = res.output.split()[1]
        res = CliRunner().invoke(cli, ["ops", "ls"])
        assert uid in res.output and "succeeded" in res.output
        res = CliRunner().invoke(cli, ["ops", "metrics", "-uid", uid])
        assert res.exit_code == 0
        res = CliRunner().invoke(cli, ["ops", "statuses", "-uid", uid])
        assert "succeeded" in res.output
        res = CliRunner().invoke(cli, ["ops", "stop", "-uid", uid])  # no-op on done
        assert res.exit_code == 0
        res = CliRunner().invoke(cli, ["ops", "logs", "-uid", uid])
        assert "out-line" in res.output
        res = CliRunner().invoke(cli, ["ops", "delete", "-uid", uid, "--yes"])
        assert res.exit_code == 0, res.output
        res = CliRunner().invoke(cli, ["ops", "ls"])
        assert uid not in res.output

        # schedules/sweeps are refused (they'd target the wrong store)
        sweep = dict(FAST_OP)
        sweep["matrix"] = {"kind": "mapping", "values": [{"x": 1}]}
        p2 = tmp_path / "sweep.yaml"
        p2.write_text(yaml.safe_dump(sweep))
        res = CliRunner().invoke(cli, ["run", "-f", str(p2)])
        assert res.exit_code != 0 and "remote control plane" in res.output


@pytest.mark.slow
def test_restart_of_sweep_sweeps_again(tmp_home, tmp_path):
    """ops restart of a sweep run must run a SWEEP again — the clone used
    to drop the matrix and silently train one default-params run."""
    import textwrap

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.scheduler.agent import Agent

    yaml_text = textwrap.dedent(
        """
        version: 1.1
        kind: operation
        name: restartable-sweep
        matrix:
          kind: grid
          params:
            lr: {kind: choice, value: [0.05, 0.001]}
        component:
          kind: component
          name: mlp-train
          inputs:
          - {name: lr, type: float, value: 0.001}
          run:
            kind: jaxjob
            program:
              model: {name: mlp, config: {input_dim: 16, num_classes: 2, hidden: [8]}}
              data: {name: synthetic, batchSize: 8, config: {shape: [16], num_classes: 2}}
              optimizer: {name: adamw, learningRate: "{{ params.lr }}"}
              train: {steps: 2, logEvery: 2, precision: float32}
        """
    )
    p = tmp_path / "sweep.yaml"
    p.write_text(yaml_text)
    store = RunStore()
    agent = Agent(store=store)
    uuid = agent.submit(read_polyaxonfile(str(p)))
    agent.drain()
    assert store.get_status(uuid)["status"] == "succeeded"

    client = RunClient()
    new_uuid = client.restart(uuid)
    agent.drain()
    assert store.get_status(new_uuid)["status"] == "succeeded"
    summaries = [
        e for e in store.read_events(new_uuid) if e["kind"] == "sweep_summary"
    ]
    assert summaries and summaries[0]["trials"] == 2  # swept, not 1 run
    # the suggestions must actually REACH the trials' resolved specs —
    # cloning the interpolated component would freeze every trial at the
    # default lr while params claim otherwise
    lrs = set()
    for r in store.list_runs():
        meta = store.get_status(r["uuid"]).get("meta", {})
        if meta.get("sweep") == new_uuid:
            spec = store.read_spec(r["uuid"])
            opt = spec["component"]["run"]["program"]["optimizer"]
            lrs.add(float(opt["learningRate"]))
    assert lrs == {0.05, 0.001}, lrs


def test_sweep_delete_requires_cascade(tmp_home, tmp_path):
    """Deleting a sweep run refuses without cascade (no orphan trials);
    with cascade the sweep AND its trials go, all-or-nothing."""
    import textwrap

    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.scheduler.agent import Agent

    yaml_text = textwrap.dedent(
        """
        version: 1.1
        kind: operation
        name: del-sweep
        matrix:
          kind: grid
          params:
            lr: {kind: choice, value: [0.05, 0.001]}
        component:
          kind: component
          name: mlp-train
          inputs:
          - {name: lr, type: float, value: 0.001}
          run:
            kind: jaxjob
            program:
              model: {name: mlp, config: {input_dim: 16, num_classes: 2, hidden: [8]}}
              data: {name: synthetic, batchSize: 8, config: {shape: [16], num_classes: 2}}
              optimizer: {name: adamw, learningRate: "{{ params.lr }}"}
              train: {steps: 2, logEvery: 2, precision: float32}
        """
    )
    p = tmp_path / "sweep.yaml"
    p.write_text(yaml_text)
    store = RunStore()
    agent = Agent(store=store)
    uuid = agent.submit(read_polyaxonfile(str(p)))
    agent.drain()

    client = RunClient()
    with pytest.raises(ValueError, match="cascade"):
        client.delete(uuid)
    assert store.get_status(uuid)  # untouched

    client.delete(uuid, cascade=True)
    assert store.get_status(uuid) == {}
    leftovers = [
        r for r in store.list_runs()
        if (store.get_status(r["uuid"]).get("meta") or {}).get("sweep") == uuid
    ]
    assert leftovers == []
