"""Client SDK (RunClient/ProjectClient, local + HTTP transports) and the
layered settings manager."""

import json

import pytest
import yaml

from polyaxon_tpu.client import ClientError, ProjectClient, RunClient
from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.store.local import RunStore
from polyaxon_tpu.streams import BackgroundServer

FAST_OP = {
    "version": 1.1,
    "kind": "operation",
    "name": "client-job",
    "component": {
        "kind": "component",
        "name": "c",
        "run": {"kind": "job", "container": {"command": ["sh", "-c", "echo out-line"]}},
    },
}


def _op(tmp_path, spec=FAST_OP):
    p = tmp_path / "op.yaml"
    p.write_text(yaml.safe_dump(spec))
    return read_polyaxonfile(str(p))


def test_run_client_create_and_read(tmp_home, tmp_path):
    client = RunClient()
    uuid = client.create(_op(tmp_path), queue=False)
    assert client.get(uuid)["status"] == V1Statuses.SUCCEEDED
    assert "out-line" in client.logs(uuid)
    assert any(c["type"] == "succeeded" for c in client.statuses(uuid))
    assert client.list()[0]["uuid"] == uuid


def test_run_client_queued_then_wait(tmp_home, tmp_path):
    import threading

    from polyaxon_tpu.scheduler import Agent

    client = RunClient()
    uuid = client.create(_op(tmp_path), queue=True)
    assert client.get(uuid)["status"] == V1Statuses.QUEUED
    t = threading.Thread(target=lambda: Agent(store=client.store).drain())
    t.start()
    status = client.wait(uuid, timeout=60)
    t.join()
    assert status == V1Statuses.SUCCEEDED


def test_run_client_http_transport(tmp_home, tmp_path):
    local = RunClient()
    uuid = local.create(_op(tmp_path), queue=False)
    with BackgroundServer(local.store) as srv:
        remote = RunClient(base_url=f"http://127.0.0.1:{srv.port}")
        assert remote.get(uuid)["status"] == "succeeded"
        assert "out-line" in remote.logs(uuid)
        assert remote.list()[0]["uuid"] == uuid
        with pytest.raises(ClientError):
            remote.create(_op(tmp_path))  # mutations need local store


def test_project_client(tmp_home, tmp_path):
    store = RunStore()
    projects = ProjectClient(store)
    projects.create("vision", "image models")
    with pytest.raises(ClientError):
        projects.create("vision")
    client = RunClient(store=store, project="vision")
    client.create(_op(tmp_path), queue=False)
    got = projects.get("vision")
    assert got["runs"] == 1
    names = [p["name"] for p in projects.list()]
    assert "vision" in names


def test_settings_layering(tmp_path, monkeypatch):
    from polyaxon_tpu import settings

    monkeypatch.setenv("POLYAXON_CONFIG_DIR", str(tmp_path))
    monkeypatch.delenv("POLYAXON_PROJECT", raising=False)
    assert settings.get("project") == "default"
    settings.set_value("project", "from-file")
    assert settings.get("project") == "from-file"
    monkeypatch.setenv("POLYAXON_PROJECT", "from-env")
    assert settings.get("project") == "from-env"  # env wins
    with pytest.raises(KeyError):
        settings.get("nope")
    data = json.loads((tmp_path / "config.json").read_text())
    assert data == {"project": "from-file"}
