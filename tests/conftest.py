"""Test harness: fake an 8-device TPU slice on CPU so sharding/collective
tests run without hardware (SURVEY.md §4: the reference tests multi-node by
golden-rendering specs; we additionally execute on a virtual mesh)."""

import os

# Must be set before jax import anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest


@pytest.fixture()
def tmp_home(tmp_path, monkeypatch):
    """Isolated POLYAXON_HOME so tests never touch the real run store."""
    home = tmp_path / "polyaxon_home"
    monkeypatch.setenv("POLYAXON_HOME", str(home))
    return home
