"""Test harness: fake an 8-device TPU slice on CPU so sharding/collective
tests run without hardware (SURVEY.md §4: the reference tests multi-node by
golden-rendering specs; we additionally execute on a virtual mesh).

The axon TPU-tunnel plugin pre-sets JAX_PLATFORMS=axon and wins over env
vars, so platform selection must go through jax.config before backends
initialize — conftest import time is early enough.
"""

import os

# XLA reads XLA_FLAGS at backend init; setting it here (before any jax
# import below triggers backend creation) works on every jax version,
# including those without the jax_num_cpu_devices config option.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # jax < 0.5 has no such option; XLA_FLAGS covers it
    pass

# children spawned by tests (multi-process distributed harness) inherit these
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_NUM_CPU_DEVICES"] = "8"

import faulthandler  # noqa: E402
import signal  # noqa: E402
import sys  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402

# Per-test wall-clock guard (pytest-timeout isn't in the image): a hung
# collective/rendezvous must fail the one test, not the whole suite run.
# Two layers: SIGALRM raises a clean TimeoutError for Python-level hangs;
# a watchdog thread hard-exits for native hangs (a blocked XLA rendezvous
# never returns to the bytecode loop, so a Python signal handler can't fire)
# after dumping all thread stacks.
_TEST_TIMEOUT_S = int(os.environ.get("POLYAXON_TEST_TIMEOUT", "420"))


@pytest.fixture(autouse=True)
def _test_alarm():
    done = threading.Event()

    def _watchdog():
        if not done.wait(_TEST_TIMEOUT_S + 60):
            sys.stderr.write(
                f"\n=== test watchdog: native hang > {_TEST_TIMEOUT_S + 60}s, "
                "dumping stacks and exiting ===\n"
            )
            faulthandler.dump_traceback()
            os._exit(70)

    watchdog = threading.Thread(target=_watchdog, daemon=True)
    watchdog.start()

    if not hasattr(signal, "SIGALRM"):  # non-POSIX fallback: watchdog only
        yield
        done.set()
        return

    def _on_alarm(signum, frame):
        raise TimeoutError(f"test exceeded {_TEST_TIMEOUT_S}s wall-clock guard")

    old = signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(_TEST_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
        done.set()


@pytest.fixture()
def tmp_home(tmp_path, monkeypatch):
    """Isolated POLYAXON_HOME so tests never touch the real run store."""
    home = tmp_path / "polyaxon_home"
    monkeypatch.setenv("POLYAXON_HOME", str(home))
    return home
