"""Test harness: fake an 8-device TPU slice on CPU so sharding/collective
tests run without hardware (SURVEY.md §4: the reference tests multi-node by
golden-rendering specs; we additionally execute on a virtual mesh).

The axon TPU-tunnel plugin pre-sets JAX_PLATFORMS=axon and wins over env
vars, so platform selection must go through jax.config before backends
initialize — conftest import time is early enough.
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import os  # noqa: E402

# children spawned by tests (multi-process distributed harness) inherit these
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_NUM_CPU_DEVICES"] = "8"

import pytest  # noqa: E402


@pytest.fixture()
def tmp_home(tmp_path, monkeypatch):
    """Isolated POLYAXON_HOME so tests never touch the real run store."""
    home = tmp_path / "polyaxon_home"
    monkeypatch.setenv("POLYAXON_HOME", str(home))
    return home
