"""The driver-facing evidence scripts must always emit parseable output:
bench.py one JSON line with the contract fields, decode/attention benches
one JSON object per config. These are the round's scorecard inputs — a
regression here silently voids the perf evidence."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.slow  # each drives a real (small) training loop


def _run(script, env_extra, timeout=420):
    import os

    env = dict(
        os.environ,
        POLYAXON_JAX_PLATFORM="cpu",
        POLYAXON_NUM_CPU_DEVICES="1",
        **env_extra,
    )
    return subprocess.run(
        [sys.executable, str(REPO / script)],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_bench_emits_contract_line(tmp_home):
    proc = _run("bench.py", {"POLYAXON_BENCH_TIMEOUT": "360"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    assert lines, proc.stdout
    rec = json.loads(lines[-1])
    assert rec["metric"] == "transformer_tokens_per_sec"
    assert rec["unit"] == "tok/s"
    assert rec["value"] > 0
    assert rec["vs_baseline"] > 0
    assert "device_kind" in rec and "bare_tokens_per_sec" in rec


def test_decode_bench_emits_json(tmp_home):
    proc = _run("benchmarks/decode_bench.py", {})
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [
        json.loads(l)
        for l in proc.stdout.splitlines()
        if l.strip().startswith("{")
    ]
    metrics = {r["metric"] for r in recs}
    assert "decode_tokens_per_sec" in metrics
    assert "beam4_decode_tokens_per_sec" in metrics
    for r in recs:
        assert r["value"] > 0, r
