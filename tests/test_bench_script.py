"""The driver-facing evidence scripts must always emit parseable output:
bench.py one JSON line with the contract fields, decode/attention benches
one JSON object per config. These are the round's scorecard inputs — a
regression here silently voids the perf evidence."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.slow  # each drives a real (small) training loop


def _run(script, env_extra, timeout=420):
    import os

    env = dict(
        os.environ,
        POLYAXON_JAX_PLATFORM="cpu",
        POLYAXON_NUM_CPU_DEVICES="1",
        **env_extra,
    )
    return subprocess.run(
        [sys.executable, str(REPO / script)],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_bench_emits_contract_line(tmp_home):
    proc = _run("bench.py", {"POLYAXON_BENCH_TIMEOUT": "360"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    assert lines, proc.stdout
    rec = json.loads(lines[-1])
    assert rec["metric"] == "transformer_tokens_per_sec"
    assert rec["unit"] == "tok/s"
    assert rec["value"] > 0
    assert rec["vs_baseline"] > 0
    assert "device_kind" in rec and "bare_tokens_per_sec" in rec


def test_decode_bench_emits_json(tmp_home):
    proc = _run("benchmarks/decode_bench.py", {})
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [
        json.loads(l)
        for l in proc.stdout.splitlines()
        if l.strip().startswith("{")
    ]
    metrics = {r["metric"] for r in recs}
    assert "decode_tokens_per_sec" in metrics
    assert "beam4_decode_tokens_per_sec" in metrics
    for r in recs:
        assert "error" not in r, r
        assert r["value"] > 0, r
        assert r["platform"] in ("cpu", "tpu")
    decode = [r for r in recs if r["metric"] == "decode_tokens_per_sec"]
    # the sweep must characterize the grouped cache: at least one GQA row
    # (kv < q heads) and one extended-cache row, each pricing its cache
    for r in decode:
        assert {"n_kv_heads", "cache_len", "kv_cache_bytes"} <= r.keys(), r
        assert r["kv_cache_bytes"] > 0
    assert any(r["n_kv_heads"] < r["n_heads"] for r in decode)
    base_len = decode[0]["cache_len"]
    assert any(r["cache_len"] > base_len for r in decode)
    # grouping shrinks the cache: bytes scale with n_kv_heads at equal len
    by_len = [r for r in decode if r["cache_len"] == base_len]
    mha = max(by_len, key=lambda r: r["n_kv_heads"])
    gqa = min(by_len, key=lambda r: r["n_kv_heads"])
    assert gqa["kv_cache_bytes"] * mha["n_kv_heads"] == pytest.approx(
        mha["kv_cache_bytes"] * gqa["n_kv_heads"]
    )


def test_attention_bench_emits_json(tmp_home):
    proc = _run("benchmarks/attention_bench.py", {})
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [
        json.loads(l)
        for l in proc.stdout.splitlines()
        if l.strip().startswith("{")
    ]
    assert recs
    for r in recs:
        assert "error" not in r, r
        assert {"seq", "backend", "mode", "kv_heads", "platform"} <= r.keys(), r
        assert r["tokens_per_sec"] > 0


def test_update_baseline_md_sections_merge_and_skip(tmp_path, monkeypatch):
    """The BASELINE.md updater is consumed UNATTENDED by the TPU canary:
    pin its contract — device sections are isolated, rows merge by config
    across partial runs, errored rows never become evidence."""
    import benchmarks.run_baselines as rb

    md = tmp_path / "BASELINE.md"
    md.write_text("# header\n")
    monkeypatch.setattr(rb, "REPO", tmp_path)

    def row(config, value, device, error=None):
        r = {"config": config, "value": value, "unit": "tok/s", "mfu": None,
             "device_kind": device, "final_loss": 1.0}
        if error:
            r["error"] = error
        return r

    # a TPU run writes the tpu section only
    rb.update_baseline_md([row("bert", 100.0, "TPU v5 lite")])
    text = md.read_text()
    assert "TPU-measured" in text and "| bert | 100.0 |" in text
    assert "CPU smoke" not in text

    # a CPU run adds its own section without touching the TPU rows
    rb.update_baseline_md([row("bert", 5.0, "cpu"), row("mnist", 9.0, "cpu")])
    text = md.read_text()
    assert "| bert | 100.0 |" in text  # TPU row preserved
    assert "| bert | 5.0 |" in text and "| mnist | 9.0 |" in text

    # partial re-run merges by config; errored rows are skipped
    rb.update_baseline_md([
        row("mnist", 11.0, "cpu"),
        row("bert", 0.0, "cpu", error="OOM"),
    ])
    text = md.read_text()
    assert "| mnist | 11.0 |" in text  # updated
    assert "| bert | 5.0 |" in text  # untouched by the errored row
    assert "| bert | 0.0 |" not in text
    assert "| bert | 100.0 |" in text  # TPU section still intact
