"""PR 11: crash-consistent event-log control plane.

Covers the framing + recovery contract (torn tails truncated, corrupt
segments quarantined — never a wedged poll), the single-writer lease
closing the set_status lost-update window, group commit, compaction
crash windows, watch cursors (no gaps, no duplicates, across writer
restarts), the chaos scenarios, migration, and the store_* metrics.
"""

import json
import struct
import threading
import time
import urllib.request

import pytest

from polyaxon_tpu.chaos import injector
from polyaxon_tpu.chaos.injector import SimulatedKill
from polyaxon_tpu.chaos.plan import Fault, FaultPlan
from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.store.eventlog import (
    EventLog,
    _Batcher,
    _Slot,
    frame,
    scan_frames,
)
from polyaxon_tpu.store.local import STORE_FORMAT, RunStore

RUN = "aaaabbbbccccdddd"


def make_log(home, **kw):
    kw.setdefault("wall", time.time)
    kw.setdefault("mono", time.monotonic)
    return EventLog(home, **kw)


def make_store(tmp_path, name="store"):
    return RunStore(tmp_path / name)


def counter_value(name):
    from polyaxon_tpu.telemetry import get_registry

    return get_registry().counter(name).value


def drive(store, run=RUN, upto=V1Statuses.RUNNING):
    """Create a run and walk it along the legal ladder up to `upto`."""
    store.create_run(run, "r-" + run[:4], "default", {"op": 1})
    for s in (
        V1Statuses.COMPILED,
        V1Statuses.QUEUED,
        V1Statuses.SCHEDULED,
        V1Statuses.STARTING,
        V1Statuses.RUNNING,
    ):
        store.set_status(run, s)
        if s == upto:
            break
    return run


# ------------------------------------------------------------- framing


def test_frame_roundtrip_clean():
    payloads = [b"alpha", b"{}", b"x" * 1000]
    data = b"".join(frame(p) for p in payloads)
    got, verdict, end = scan_frames(data)
    assert got == payloads
    assert verdict == "clean"
    assert end == len(data)


def test_scan_partial_header_is_torn():
    data = frame(b"whole") + b"\x01\x02"
    got, verdict, end = scan_frames(data)
    assert got == [b"whole"]
    assert verdict == "torn"
    assert end == len(frame(b"whole"))


def test_scan_partial_payload_is_torn():
    whole = frame(b"whole")
    cut = frame(b"partially-written-record")[:-3]
    got, verdict, end = scan_frames(whole + cut)
    assert (got, verdict, end) == ([b"whole"], "torn", len(whole))


def test_scan_bad_crc_mid_data_is_corrupt():
    data = bytearray(frame(b"first") + frame(b"second"))
    data[struct.calcsize("<II")] ^= 0xFF  # flip a byte of "first"
    got, verdict, end = scan_frames(bytes(data))
    assert (got, verdict, end) == ([], "corrupt", 0)


def test_scan_bad_crc_at_eof_is_torn():
    data = bytearray(frame(b"first") + frame(b"second"))
    data[-1] ^= 0xFF  # last byte of the last frame: a torn write
    got, verdict, _ = scan_frames(bytes(data))
    assert (got, verdict) == ([b"first"], "torn")


# ------------------------------------------------------- append + replay


def test_append_then_replay_identical(tmp_path):
    log = make_log(tmp_path)
    log.append(RUN, "create", {"cond": {"type": "created"}, "meta": {},
                               "name": "n", "project": "p"})
    log.append(RUN, "status", {"status": "running",
                               "cond": {"type": "running"}})
    log.append(RUN, "meta", {"entries": {"k": 1}})
    before = log.history(RUN)

    fresh = make_log(tmp_path)
    after = fresh.history(RUN)
    assert json.dumps(after, sort_keys=True) == json.dumps(
        before, sort_keys=True
    )
    doc = fresh.doc(RUN)
    assert doc["status"] == "running"
    assert doc["meta"] == {"k": 1}
    assert [c["type"] for c in doc["conditions"]] == ["created", "running"]


def test_sequence_numbers_globally_monotonic(tmp_path):
    log = make_log(tmp_path)
    for i in range(4):
        log.append(f"run-{i % 2}", "event", {"event": {"i": i}})
    entries, _ = log.read_since("0:0")
    seqs = [e["seq"] for e in entries]
    assert seqs == sorted(seqs)
    assert len(set(seqs)) == len(seqs) == 4
    assert {e["r"] for e in entries} == {"run-0", "run-1"}


def test_store_view_tracks_log(tmp_path):
    store = make_store(tmp_path)
    drive(store)
    status = store.get_status(RUN)  # the status.json materialized view
    assert status["status"] == V1Statuses.RUNNING
    assert [c["type"] for c in status["conditions"]][:2] == [
        V1Statuses.CREATED, V1Statuses.COMPILED,
    ]
    kinds = [r["kind"] for r in store.get_history(RUN)]
    assert kinds == ["create"] + ["status"] * 5


def test_illegal_transition_rejected_atomically(tmp_path):
    store = make_store(tmp_path)
    drive(store, upto=V1Statuses.QUEUED)
    with pytest.raises(ValueError, match="illegal status transition"):
        store.set_status(RUN, V1Statuses.SUCCEEDED)  # queued -/-> succeeded
    assert store.get_status(RUN)["status"] == V1Statuses.QUEUED
    # the rejected record must not occupy a sequence number slot visible
    # to cursors
    entries, _ = store.read_events_since("0:0")
    assert all(e.get("status") != "succeeded" for e in entries)


def test_set_meta_unknown_run_raises(tmp_path):
    store = make_store(tmp_path)
    with pytest.raises(KeyError):
        store.set_meta("feedfeedfeedfeed", attempt=1)


# ---------------------------------------------------- lost-update window


def test_concurrent_terminal_transitions_exactly_one_wins(tmp_path):
    """The PR 11 headline: two writers racing RUNNING -> terminal no
    longer last-write-wins through status.json — the log's lease +
    validate makes exactly one commit and the other fail loudly."""
    store = make_store(tmp_path)
    drive(store)
    barrier = threading.Barrier(2)
    errs, oks = [], []

    def flip(to):
        s = RunStore(tmp_path / "store")
        barrier.wait()
        try:
            s.set_status(RUN, to)
            oks.append(to)
        except ValueError as e:
            errs.append(str(e))

    threads = [
        threading.Thread(target=flip, args=(t,))
        for t in (V1Statuses.SUCCEEDED, V1Statuses.FAILED)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(oks) == 1 and len(errs) == 1
    assert "illegal status transition" in errs[0]
    doc = store.get_status(RUN)
    assert doc["status"] == oks[0]
    # exactly ONE terminal condition was appended
    terminal = [
        c for c in doc["conditions"]
        if c["type"] in ("succeeded", "failed")
    ]
    assert len(terminal) == 1


# --------------------------------------------------------- group commit


def test_group_commit_leader_flushes_followers_in_one_batch():
    release = threading.Event()
    entered = threading.Event()
    flushed = []

    def flush(batch):
        entered.set()
        if not flushed:  # first batch blocks until followers queue up
            release.wait(5)
        flushed.append(len(batch))
        for i, s in enumerate(batch):
            s.result = {"i": i}

    b = _Batcher(flush)
    threads = [
        threading.Thread(
            target=b.submit, args=(_Slot("r", "event", {}, None, False, True),)
        )
        for _ in range(6)
    ]
    threads[0].start()
    assert entered.wait(5)  # the leader is inside flush, holding the lock
    for t in threads[1:]:
        t.start()
    deadline = time.monotonic() + 5
    while b._queue == [] and time.monotonic() < deadline:
        time.sleep(0.01)  # followers enqueueing behind the blocked leader
    release.set()
    for t in threads:
        t.join(5)
    assert sum(flushed) == 6
    assert b.batches == len(flushed) <= 3  # followers shared batches
    assert b.max_batch >= 2


def test_log_pulses_pay_no_fsync(tmp_path):
    log = make_log(tmp_path, fsync=True)
    log.append(RUN, "create", {"cond": {"type": "created"}})
    durable_fsyncs = log.fsyncs
    for i in range(5):
        log.append(RUN, "log", {"n": i}, durable=False)
    assert log.fsyncs == durable_fsyncs
    assert log.appends == 6


# ------------------------------------------------------------- recovery


def test_torn_tail_truncated_and_counted(tmp_path):
    store = make_store(tmp_path)
    drive(store)
    before = store.get_history(RUN)
    seg = max((store.run_dir(RUN) / "log").glob("[0-9]*.seg"))
    clean_size = seg.stat().st_size
    with seg.open("ab") as f:
        f.write(b"\x07garbage-from-a-power-cut")

    recovered = counter_value("store.recovered_tails")
    fresh = make_store(tmp_path)
    fresh.recover(RUN)
    assert fresh.get_history(RUN) == before
    assert seg.stat().st_size == clean_size
    assert counter_value("store.recovered_tails") == recovered + 1
    # idempotent: a second recovery finds nothing to repair
    fresh.recover(RUN)
    assert counter_value("store.recovered_tails") == recovered + 1


def test_corrupt_segment_quarantined_not_wedged(tmp_path):
    store = make_store(tmp_path)
    drive(store)
    seg = max((store.run_dir(RUN) / "log").glob("[0-9]*.seg"))
    data = bytearray(seg.read_bytes())
    data[struct.calcsize("<II")] ^= 0xFF  # bit rot in the first frame
    seg.write_bytes(bytes(data))

    quarantined = counter_value("store.quarantined_segments")
    fresh = make_store(tmp_path)
    fresh.get_history(RUN)  # must answer, not raise
    assert fresh.get_status(RUN)["status"]  # poll not wedged either
    corrupt = seg.with_name(seg.name + ".corrupt")
    assert corrupt.exists() and corrupt.read_bytes() == bytes(data)
    assert counter_value("store.quarantined_segments") == quarantined + 1


def test_corrupt_snapshot_quarantined(tmp_path):
    store = make_store(tmp_path)
    drive(store)
    store.compact_run(RUN)
    snap = store.run_dir(RUN) / "log" / "snapshot.json"
    snap.write_text("\x00not json\x00")
    fresh = make_store(tmp_path)
    fresh.get_history(RUN)  # no wedge
    assert snap.with_name("snapshot.json.corrupt").exists()


def test_recover_refreshes_stale_view(tmp_path):
    store = make_store(tmp_path)
    drive(store)
    view = store.run_dir(RUN) / "status.json"
    view.write_text("\x00scribbled\x00")  # crash tore the non-durable view
    fresh = make_store(tmp_path)
    fresh.recover()
    assert fresh.get_status(RUN)["status"] == V1Statuses.RUNNING


# ------------------------------------------------------------ compaction


def test_compaction_preserves_history_drops_pulses(tmp_path):
    store = make_store(tmp_path)
    drive(store)
    for i in range(10):
        store.append_log(RUN, f"line {i}")
    before = store.get_history(RUN)
    compactions = counter_value("store.compactions")
    store.compact_run(RUN)
    assert counter_value("store.compactions") == compactions + 1
    logdir = store.run_dir(RUN) / "log"
    assert (logdir / "snapshot.json").exists()

    fresh = make_store(tmp_path)
    assert fresh.get_history(RUN) == before
    assert fresh.get_status(RUN)["status"] == V1Statuses.RUNNING
    # appends after compaction keep extending the same history
    fresh.set_status(RUN, V1Statuses.SUCCEEDED)
    assert [r["kind"] for r in fresh.get_history(RUN)] == [
        r["kind"] for r in before
    ] + ["status"]


def test_auto_compaction_threshold(tmp_path):
    log = make_log(tmp_path, compact_every=5, fsync=False)
    for i in range(6):
        log.append(RUN, "event", {"event": {"i": i}})
    assert (log._log_dir(RUN) / "snapshot.json").exists()
    fresh = make_log(tmp_path)
    assert len(fresh.history(RUN)) == 6


@pytest.mark.parametrize("point", ["store.compact", "store.compact.swapped"])
def test_compaction_crash_windows_replay_identical(tmp_path, point):
    store = make_store(tmp_path)
    drive(store)
    before = store.get_history(RUN)
    plan = FaultPlan([Fault(point, "kill")], seed=0)
    with injector.active(plan):
        with pytest.raises(SimulatedKill):
            store.compact_run(RUN)

    fresh = make_store(tmp_path)
    after = fresh.get_history(RUN)
    assert json.dumps(after, sort_keys=True) == json.dumps(
        before, sort_keys=True
    )
    seqs = [r["seq"] for r in after]
    assert len(set(seqs)) == len(seqs)  # post-swap replay didn't duplicate
    # the store keeps working after the interrupted compaction
    fresh.set_status(RUN, V1Statuses.SUCCEEDED)
    assert fresh.get_status(RUN)["status"] == V1Statuses.SUCCEEDED


def test_kill_mid_compaction_scenario_seeds(tmp_path):
    for seed in range(4):
        home = tmp_path / f"seed{seed}"
        store = RunStore(home)
        drive(store)
        before = store.get_history(RUN)
        plan = FaultPlan.kill_mid_compaction(seed)
        assert plan.params["kill_point"] in (
            "store.compact", "store.compact.swapped",
        )
        with injector.active(plan):
            with pytest.raises(SimulatedKill):
                store.compact_run(RUN)
        fresh = RunStore(home)
        assert json.dumps(fresh.get_history(RUN), sort_keys=True) == (
            json.dumps(before, sort_keys=True)
        )


# ------------------------------------------------------- chaos: appends


def _append_until_killed(store, plan, n=12):
    """Drive appends under an armed plan; returns (acked, killed)."""
    acked = []
    killed = False
    with injector.active(plan):
        for i in range(n):
            try:
                acked.append(
                    store.eventlog.append(RUN, "event", {"event": {"i": i}})
                )
            except SimulatedKill:
                killed = True
                break
    return acked, killed


def test_kill_mid_append_never_loses_committed(tmp_path):
    """Both halves of the commit protocol (before the frames land, after
    the index fsync): every acknowledged record survives the crash and
    replays byte-identically, in order."""
    for seed in range(6):
        home = tmp_path / f"seed{seed}"
        store = RunStore(home)
        store.create_run(RUN, "r", "default", {"op": 1})
        plan = FaultPlan.kill_mid_append(seed, window=8)
        acked, killed = _append_until_killed(store, plan)
        assert killed, "the seeded kill must land inside the window"

        fresh = RunStore(home)
        fresh.recover()
        got = fresh.get_history(RUN)
        # acked records form a strict prefix of the recovered history
        # (modulo the create record at the head); the in-flight record may
        # or may not have survived — it was never acknowledged
        acked_dump = [json.dumps(r, sort_keys=True) for r in acked]
        got_dump = [json.dumps(r, sort_keys=True) for r in got[1:]]
        assert got_dump[: len(acked_dump)] == acked_dump
        assert len(got_dump) <= len(acked_dump) + 1
        # and the store still accepts writes afterwards
        fresh.eventlog.append(RUN, "event", {"event": {"post": True}})
        assert fresh.get_history(RUN)[-1]["event"] == {"post": True}


def test_scrambled_tail_scenario_truncates_exactly(tmp_path):
    for seed in range(4):
        home = tmp_path / f"seed{seed}"
        store = RunStore(home)
        store.create_run(RUN, "r", "default", {"op": 1})
        plan = FaultPlan.scrambled_tail(seed, window=6)
        recovered = counter_value("store.recovered_tails")
        acked, killed = _append_until_killed(store, plan)
        assert killed

        fresh = RunStore(home)
        fresh.recover()
        # garbage landed INSTEAD of the dying append's frames: recovery
        # truncates back to exactly the acknowledged set
        got = [json.dumps(r, sort_keys=True) for r in fresh.get_history(RUN)[1:]]
        assert got == [json.dumps(r, sort_keys=True) for r in acked]
        assert counter_value("store.recovered_tails") > recovered


def test_corrupt_segment_scenario_quarantines(tmp_path):
    for seed in range(3):
        home = tmp_path / f"seed{seed}"
        store = RunStore(home)
        store.create_run(RUN, "r", "default", {"op": 1})
        plan = FaultPlan.corrupt_segment(seed, window=5)
        acked, killed = _append_until_killed(store, plan)
        assert not killed  # bit rot is silent

        fresh = RunStore(home)
        fresh.get_history(RUN)  # must not wedge
        logdir = home / "runs" / RUN / "log"
        assert list(logdir.glob("*.corrupt")), "segment was not quarantined"
        fresh.eventlog.append(RUN, "event", {"event": {"post": True}})


# --------------------------------------------------------------- cursors


def test_cursor_resumes_across_restart_no_gaps_no_dups(tmp_path):
    store = make_store(tmp_path)
    store.create_run(RUN, "r", "default", {"op": 1})
    for i in range(7):
        store.eventlog.append(RUN, "event", {"event": {"i": i}})
    seen = []
    cursor = "0:0"
    while True:  # paginate in small bites
        batch, cursor = store.read_events_since(cursor, limit=3)
        seen.extend(batch)
        if len(batch) < 3:
            break

    fresh = make_store(tmp_path)  # writer restart
    for i in range(7, 12):
        fresh.eventlog.append(RUN, "event", {"event": {"i": i}})
    batch, cursor = fresh.read_events_since(cursor)
    seen.extend(batch)
    seqs = [e["seq"] for e in seen]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    payload = [e["event"]["i"] for e in seen if e["kind"] == "event"]
    assert payload == list(range(12))


def test_misaligned_cursor_rescans_without_duplicates(tmp_path):
    store = make_store(tmp_path)
    store.create_run(RUN, "r", "default", {"op": 1})
    for i in range(3):
        store.eventlog.append(RUN, "event", {"event": {"i": i}})
    entries, _ = store.read_events_since("0:0")
    last = entries[1]
    bad = f"{last['seq']}:{7}"  # offset inside a frame: not a boundary
    got, _ = store.read_events_since(bad)
    assert [e["seq"] for e in got] == [
        e["seq"] for e in entries if e["seq"] > last["seq"]
    ]
    # offset beyond EOF (index was rebuilt shorter): full rescan, seq-dedup
    got, _ = store.read_events_since(f"{last['seq']}:999999")
    assert [e["seq"] for e in got] == [
        e["seq"] for e in entries if e["seq"] > last["seq"]
    ]


def test_wait_wakes_on_commit(tmp_path):
    store = make_store(tmp_path)
    store.create_run(RUN, "r", "default", {"op": 1})
    cursor = store.head_cursor()

    def commit():
        time.sleep(0.15)
        RunStore(tmp_path / "store").eventlog.append(
            RUN, "event", {"event": {"late": True}}
        )

    t = threading.Thread(target=commit)
    t0 = time.monotonic()
    t.start()
    events, cursor = store.wait_events(cursor, timeout=5.0)
    elapsed = time.monotonic() - t0
    t.join()
    assert [e["event"] for e in events] == [{"late": True}]
    assert elapsed < 3.0  # woke on commit, not on the timeout

    # caught up: the lag gauge reads zero
    from polyaxon_tpu.telemetry import get_registry

    assert get_registry().gauge("store.watch_cursor_lag").value == 0


def test_watch_yields_ordered_and_stops(tmp_path):
    store = make_store(tmp_path)
    drive(store, upto=V1Statuses.RUNNING)
    store.set_status(RUN, V1Statuses.SUCCEEDED)
    got = list(
        store.watch("0:0", timeout=0.05, stop=lambda: True)
    )
    assert [e["kind"] for e in got] == ["create"] + ["status"] * 6
    assert got[-1]["status"] == "succeeded"


def test_http_watch_long_poll(tmp_path, monkeypatch):
    from polyaxon_tpu.streams.server import BackgroundServer

    store = make_store(tmp_path)
    drive(store, upto=V1Statuses.QUEUED)
    with BackgroundServer(store) as srv:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(f"{base}/runs?watch=0:0&timeout=5") as r:
            body = json.loads(r.read())
        assert body["cursor"]
        kinds = [e["kind"] for e in body["events"]]
        assert kinds == ["create", "status", "status"]

        # caught-up cursor + tiny timeout: bounded empty response
        with urllib.request.urlopen(
            f"{base}/runs?watch={body['cursor']}&timeout=0.05"
        ) as r:
            again = json.loads(r.read())
        assert again["events"] == []

        # junk timeout is the client's fault
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/runs?watch=0:0&timeout=soon")
        assert err.value.code == 400


# ----------------------------------------------- agent loop steady state


def test_reconciler_ingests_from_cursor_without_scans(tmp_path):
    from polyaxon_tpu.scheduler.reconciler import Reconciler

    class NoCluster:
        def status(self, run_uuid):
            return {"pods": []}

        def delete(self, run_uuid):
            pass

    store = make_store(tmp_path)
    for i in range(3):
        uuid = f"run-{i:04d}{'0' * 8}"
        drive(store, run=uuid, upto=V1Statuses.SCHEDULED)
        # a cluster run: without manifests.json the reconciler (rightly)
        # retires it as not-its-business
        (store.run_dir(uuid) / "manifests.json").write_text("[]")
    rec = Reconciler(store, NoCluster())
    for _ in range(5):
        rec.tick()
    assert {u[:8] for u in rec._tracked} == {"run-0000", "run-0001", "run-0002"}
    assert store.scans == 0  # cursor ingest, not list_runs()

    # terminal runs retire from the working set via the same cursor feed
    store.set_status("run-0000" + "0" * 8, V1Statuses.STARTING)
    store.set_status("run-0000" + "0" * 8, V1Statuses.RUNNING)
    store.set_status("run-0000" + "0" * 8, V1Statuses.SUCCEEDED)
    rec.tick()
    assert not any(u.startswith("run-0000") for u in rec._tracked)
    assert store.scans == 0


# ------------------------------------------------------------- migration


def _legacy_run(home, run, status="running"):
    """Fabricate a pre-event-log run dir: status.json + events.jsonl,
    no log/ directory."""
    rd = home / "runs" / run
    rd.mkdir(parents=True)
    conds = [
        {"type": "created", "status": True, "reason": "", "message": "",
         "ts": 1.0},
        {"type": status, "status": True, "reason": "", "message": "",
         "ts": 2.0},
    ]
    (rd / "status.json").write_text(json.dumps(
        {"uuid": run, "status": status, "conditions": conds, "meta": {"a": 1}}
    ))
    (rd / "events.jsonl").write_text(
        json.dumps({"kind": "artifact", "ts": 1.5, "ref": "ckpt"}) + "\n"
    )
    with (home / "index.jsonl").open("a") as f:
        f.write(json.dumps({"uuid": run, "name": "legacy-" + run[:4],
                            "project": "default"}) + "\n")


def test_legacy_run_migrates_on_first_write(tmp_path):
    home = tmp_path / "store"
    home.mkdir()
    _legacy_run(home, RUN)
    store = RunStore(home)
    store.set_status(RUN, V1Statuses.SUCCEEDED)  # first touch migrates
    hist = store.get_history(RUN)
    assert [r["kind"] for r in hist] == ["create", "status", "event", "status"]
    assert hist[0]["cond"]["type"] == "created"
    assert hist[-1]["status"] == "succeeded"
    doc = store.get_status(RUN)
    assert doc["meta"] == {"a": 1}
    # migration is once-only: a reopen does not re-import
    assert len(RunStore(home).get_history(RUN)) == 4


def test_bulk_migrate_stamps_format_and_is_idempotent(tmp_path):
    home = tmp_path / "store"
    home.mkdir()
    for i in range(3):
        _legacy_run(home, f"legacy-{i:04d}{'0' * 7}")
    store = RunStore(home)
    assert store.store_format() == "1"
    assert store.migrate() == 3
    assert store.store_format() == STORE_FORMAT == "2"
    assert store.migrate() == 0  # second pass: nothing left to import
    entries, _ = store.read_events_since("0:0")
    assert len({e["r"] for e in entries}) == 3


# ----------------------------------------------------------- CLI surface


def test_cli_events_and_store_commands(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    monkeypatch.setenv("POLYAXON_HOME", str(tmp_path / "store"))
    store = make_store(tmp_path)
    drive(store, upto=V1Statuses.QUEUED)

    r = CliRunner().invoke(cli, ["events", RUN[:6]])
    assert r.exit_code == 0, r.output
    kinds = [json.loads(line)["kind"] for line in r.output.splitlines()]
    assert kinds == ["create", "status", "status"]

    r = CliRunner().invoke(cli, ["store", "migrate"])
    assert r.exit_code == 0 and "store format" in r.output

    r = CliRunner().invoke(cli, ["store", "recover"])
    assert r.exit_code == 0 and "recovered 1 run(s)" in r.output

    r = CliRunner().invoke(cli, ["events", "nope"])
    assert r.exit_code != 0 and "no run matching" in r.output


def test_cli_events_follow_exits_at_terminal(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    monkeypatch.setenv("POLYAXON_HOME", str(tmp_path / "store"))
    store = make_store(tmp_path)
    drive(store)

    def finish():
        time.sleep(0.15)
        RunStore(tmp_path / "store").set_status(RUN, V1Statuses.SUCCEEDED)

    t = threading.Thread(target=finish)
    t.start()
    r = CliRunner().invoke(
        cli, ["events", RUN, "--follow", "--timeout", "0.1"]
    )
    t.join()
    assert r.exit_code == 0, r.output
    last = json.loads(r.output.splitlines()[-1])
    assert last["status"] == "succeeded"


# -------------------------------------------------------------- metrics


def test_metricsz_exposes_store_series(tmp_path):
    from polyaxon_tpu.streams.server import BackgroundServer

    store = make_store(tmp_path)
    drive(store)
    store.compact_run(RUN)
    store.wait_events(store.head_cursor(), timeout=0)
    with BackgroundServer(store) as srv:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metricsz"
        ) as r:
            text = r.read().decode()
    for series in (
        "store_appends_total",
        "store_fsync_ms_bucket",
        "store_recovered_tails_total",
        "store_quarantined_segments_total",
        "store_compactions_total",
        "store_watch_cursor_lag",
    ):
        assert series in text, f"missing {series} in /metricsz"


def test_lint_pins_eventlog_to_injected_clocks(tmp_path):
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "lint_telemetry",
        Path(__file__).resolve().parent.parent / "scripts" / "lint_telemetry.py",
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)

    # the real tree is clean (eventlog.py imports no clock at all)
    repo = Path(__file__).resolve().parent.parent
    assert not [v for v in lint.violations(repo) if "eventlog" in v]

    # a synthetic tree with a raw clock in eventlog.py is flagged
    bad = tmp_path / "badrepo"
    mod = bad / "polyaxon_tpu" / "store"
    mod.mkdir(parents=True)
    (mod / "eventlog.py").write_text(
        "import time\n\ndef ts():\n    return time.time()\n"
    )
    hits = lint.violations(bad)
    assert any("eventlog.py" in h and "sequence number" in h for h in hits)
