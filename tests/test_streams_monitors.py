"""Streams HTTP service + system monitors + tracking client round trip."""

import json
import time
import urllib.request

import numpy as np

from polyaxon_tpu.store.local import RunStore
from polyaxon_tpu.streams import BackgroundServer
from polyaxon_tpu.tracking.monitors import SystemMonitor, host_metrics


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        body = r.read()
        # /metricsz is Prometheus text, everything else JSON
        if "json" in (r.headers.get("Content-Type") or ""):
            return r.status, json.loads(body)
        return r.status, body.decode()


def _seed_run(store, uuid="abc123def456"):
    store.create_run(uuid, "seeded", "default", {"kind": "test"})
    store.log_metrics(uuid, 1, {"loss": 0.5})
    store.log_metrics(uuid, 2, {"loss": 0.25})
    store.log_event(uuid, "run_summary", {"final_metrics": {"loss": 0.25}})
    store.append_log(uuid, "hello line 1")
    store.append_log(uuid, "hello line 2")
    (store.outputs_dir(uuid) / "model.txt").write_text("weights")
    return uuid


def test_streams_endpoints(tmp_home):
    store = RunStore()
    uuid = _seed_run(store)
    with BackgroundServer(store) as srv:
        code, health = _get(srv.port, "/healthz")
        assert code == 200 and health["status"] == "ok"

        code, runs = _get(srv.port, "/runs")
        assert code == 200 and runs[0]["uuid"] == uuid

        code, status = _get(srv.port, f"/runs/{uuid}/status")
        assert status["status"] == "created"

        code, metrics = _get(srv.port, f"/runs/{uuid}/metrics")
        assert [m["loss"] for m in metrics] == [0.5, 0.25]

        code, logs = _get(srv.port, f"/runs/{uuid}/logs")
        assert "hello line 1" in logs["logs"]
        offset = logs["offset"]
        store.append_log(uuid, "follow me")
        code, more = _get(srv.port, f"/runs/{uuid}/logs?offset={offset}")
        assert more["logs"].strip() == "follow me"  # tail-follow semantics

        code, artifacts = _get(srv.port, f"/runs/{uuid}/artifacts")
        assert artifacts["files"] == ["model.txt"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/runs/{uuid}/artifacts/model.txt"
        ) as r:
            assert r.read() == b"weights"

        # short-uuid resolution like the CLI
        code, status = _get(srv.port, f"/runs/{uuid[:8]}/status")
        assert code == 200


def test_streams_404_and_traversal_guard(tmp_home):
    store = RunStore()
    uuid = _seed_run(store)
    with BackgroundServer(store) as srv:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/runs/{uuid}/artifacts/../status.json"
            )
            raised = False
        except urllib.error.HTTPError as e:
            raised = e.code in (403, 404)
        assert raised


def test_streams_bad_int_params_are_400(tmp_home):
    store = RunStore()
    uuid = _seed_run(store)
    with BackgroundServer(store) as srv:
        for path in (
            f"/runs/{uuid}/logs?offset=abc",
            f"/runs/{uuid}/metrics?tail=xyz",
        ):
            try:
                urllib.request.urlopen(f"http://127.0.0.1:{srv.port}{path}")
                code = 200
            except urllib.error.HTTPError as e:
                code = e.code
                body = json.loads(e.read())
                assert "must be an integer" in body["error"]
            assert code == 400
        # well-formed params still work
        code, rows = _get(srv.port, f"/runs/{uuid}/metrics?tail=1")
        assert code == 200 and len(rows) == 1


def test_host_metrics_present():
    m = host_metrics()
    assert "sys.cpu_percent" in m and "sys.memory_percent" in m
    assert all(np.isfinite(v) for v in m.values())


def test_system_monitor_writes_to_store(tmp_home):
    store = RunStore()
    uuid = _seed_run(store, uuid="feedbeefcafe")
    with SystemMonitor(store, uuid, interval=0.2, include_devices=False):
        time.sleep(0.7)
    sys_metrics = [
        m for m in store.read_metrics(uuid) if "sys.cpu_percent" in m
    ]
    assert len(sys_metrics) >= 2


def test_tracking_client_roundtrip(tmp_home, monkeypatch):
    from polyaxon_tpu import tracking

    monkeypatch.delenv("POLYAXON_RUN_UUID", raising=False)
    run = tracking.init(name="standalone")
    run.log_metrics(step=1, loss=1.0)
    run.log_metrics(step=2, loss=0.5)
    run.log_outputs(final_loss=0.5)
    run.end()
    store = RunStore()
    assert store.get_status(run.uuid)["status"] == "succeeded"
    assert [m["loss"] for m in store.read_metrics(run.uuid)] == [1.0, 0.5]


def test_dashboard_serves_and_covers_the_api(tmp_home):
    """The dashboard page serves at / and wires every read endpoint it
    renders (sparklines need /metrics, follow needs /logs?offset, stop
    button needs POST /runs/<id>/stop) — a section silently dropping out
    of the HTML means the feature regressed."""
    import urllib.request

    store = RunStore()
    _seed_run(store)
    with BackgroundServer(store) as srv:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/", timeout=10
        ) as r:
            assert r.headers["Content-Type"].startswith("text/html")
            html = r.read().decode()
    for needle in (
        "sparkline",          # metric charts
        "/metrics",
        "logs?offset=",       # incremental follow
        "/stop",              # stop action
        "/artifacts",
        "/spec",
        "/events",
        "conditions",
        "esc(",               # escaping helper still in place
    ):
        assert needle in html, f"dashboard lost {needle!r}"


def test_openapi_spec_served_and_matches_router(tmp_home):
    """/openapi.json serves a valid spec whose documented paths all exist
    in the router (drives every documented GET against a seeded run)."""
    store = RunStore()
    uuid = _seed_run(store)
    with BackgroundServer(store) as srv:
        code, spec = _get(srv.port, "/openapi.json")
        assert code == 200 and spec["openapi"].startswith("3.")
        for path, ops in spec["paths"].items():
            if "get" not in ops or "{path}" in path:
                continue
            concrete = path.replace("{uuid}", uuid)
            code, _body = _get(srv.port, concrete)
            assert code == 200, f"{concrete} -> {code}"
        # the write-side routes are documented
        assert "post" in spec["paths"]["/runs"]
        assert "post" in spec["paths"]["/runs/{uuid}/stop"]
        assert "delete" in spec["paths"]["/runs/{uuid}"]
