"""Multi-process distributed runtime tests: the native C++ gang launcher
spawning real `jax.distributed` workers over CPU — the local stand-in for
a multi-host TPU pod (SURVEY.md §4: fake-slice CI harness)."""

import json
import os
import subprocess
import sys

import pytest

from polyaxon_tpu.native import free_port, launcher_path


def test_launcher_builds():
    path = launcher_path()
    assert os.path.exists(path)


def test_launcher_env_injection():
    out = subprocess.run(
        [
            launcher_path(),
            "--num-workers", "3",
            "--coordinator", "127.0.0.1:1234",
            "--env", "EXTRA=hello",
            "--", "/bin/sh", "-c",
            'echo "w=$JAX_PROCESS_ID n=$JAX_NUM_PROCESSES c=$JAX_COORDINATOR_ADDRESS e=$EXTRA"',
        ],
        capture_output=True,
        text=True,
    )
    assert out.returncode == 0
    lines = [l for l in out.stdout.splitlines() if l.startswith("w=")]
    assert sorted(lines) == [
        "w=0 n=3 c=127.0.0.1:1234 e=hello",
        "w=1 n=3 c=127.0.0.1:1234 e=hello",
        "w=2 n=3 c=127.0.0.1:1234 e=hello",
    ]


def test_launcher_gang_restart_and_exit_code():
    out = subprocess.run(
        [
            launcher_path(),
            "--num-workers", "2",
            "--max-restarts", "2",
            "--", "/bin/sh", "-c", "exit 7",
        ],
        capture_output=True,
        text=True,
    )
    assert out.returncode == 7
    events = [json.loads(l) for l in out.stdout.splitlines()]
    starts = [e for e in events if e["event"] == "gang_start"]
    assert [e["attempt"] for e in starts] == [0, 1, 2]
    assert events[-1] == {"event": "gang_done", "code": 7}


def test_launcher_gang_teardown_on_partial_failure():
    """One worker fails fast; the supervisor must terminate the healthy
    worker (gang semantics) instead of waiting out its sleep."""
    out = subprocess.run(
        [
            launcher_path(),
            "--num-workers", "2",
            "--", "/bin/sh", "-c",
            'if [ "$JAX_PROCESS_ID" = 0 ]; then exit 3; else sleep 30; fi',
        ],
        capture_output=True,
        text=True,
        timeout=15,  # well under the healthy worker's sleep
    )
    assert out.returncode == 3


def test_launcher_timeout():
    out = subprocess.run(
        [
            launcher_path(),
            "--num-workers", "1",
            "--timeout", "1",
            "--", "/bin/sh", "-c", "sleep 30",
        ],
        capture_output=True,
        text=True,
        timeout=15,
    )
    assert out.returncode == 124


def test_distributed_jaxjob_end_to_end(tmp_home, tmp_path):
    """2-process gang, jax.distributed over CPU: executor spawns the gang via
    the native launcher, chief logs metrics, run succeeds."""
    import yaml

    from polyaxon_tpu.compiler.resolver import compile_operation
    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.runtime.executor import Executor
    from polyaxon_tpu.schemas.lifecycle import V1Statuses
    from polyaxon_tpu.store.local import RunStore

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "dist",
        "component": {
            "kind": "component",
            "name": "dist",
            "run": {
                "kind": "jaxjob",
                "replicas": 2,
                "mesh": {"data": -1},
                "program": {
                    "model": {
                        "name": "mlp",
                        "config": {"input_dim": 32, "num_classes": 4, "hidden": [16]},
                    },
                    "data": {
                        "name": "synthetic",
                        "batchSize": 16,
                        "config": {"shape": [32], "num_classes": 4},
                    },
                    "optimizer": {"name": "adamw", "learningRate": 0.01},
                    "train": {"steps": 4, "logEvery": 2, "precision": "float32"},
                },
            },
        },
    }
    p = tmp_path / "dist.yaml"
    p.write_text(yaml.safe_dump(spec))
    # keep worker processes small: 2 cpu devices each -> 4 global
    os.environ["JAX_NUM_CPU_DEVICES"] = "2"
    try:
        store = RunStore()
        op = read_polyaxonfile(str(p))
        compiled = compile_operation(op, artifacts_root=str(store.runs_dir))
        status = Executor(store).execute(compiled)
        assert status == V1Statuses.SUCCEEDED
        metrics = store.read_metrics(compiled.run_uuid)
        assert metrics and metrics[-1]["step"] == 4
        events = store.read_events(compiled.run_uuid)
        summary = [e for e in events if e.get("kind") == "run_summary"]
        assert summary and summary[0]["num_processes"] == 2
        logs = store.read_logs(compiled.run_uuid)
        assert '"event":"gang_done","code":0' in logs
    finally:
        os.environ["JAX_NUM_CPU_DEVICES"] = "8"


def test_slice_health_check():
    from polyaxon_tpu.runtime.health import SliceHealthError, check_slice

    report = check_slice()
    assert report["devices"] == 8 and report["all_reduce_ok"]
    with pytest.raises(SliceHealthError, match="expected 16"):
        check_slice(expected_devices=16)


@pytest.mark.slow
def test_distributed_gang_with_model_axis(tmp_home, tmp_path):
    """2-process gang with a model (TP) axis: collectives cross process
    boundaries through jax.distributed, not just data-parallel allreduce."""
    import yaml

    from polyaxon_tpu.compiler.resolver import compile_operation
    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.runtime.executor import Executor
    from polyaxon_tpu.schemas.lifecycle import V1Statuses
    from polyaxon_tpu.store.local import RunStore

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "dist-tp",
        "component": {
            "kind": "component",
            "name": "dist-tp",
            "run": {
                "kind": "jaxjob",
                "replicas": 2,
                "mesh": {"data": 2, "model": 2},
                "program": {
                    "model": {
                        "name": "transformer_lm",
                        "config": {
                            "dim": 64, "n_layers": 2, "n_heads": 4,
                            "n_kv_heads": 4, "vocab_size": 512, "seq_len": 32,
                        },
                    },
                    "data": {
                        "name": "synthetic_text",
                        "batchSize": 8,
                        "config": {"seq_len": 32, "vocab_size": 512},
                    },
                    "optimizer": {"name": "adamw", "learningRate": 0.001},
                    "train": {"steps": 2, "logEvery": 2, "precision": "float32"},
                },
            },
        },
    }
    p = tmp_path / "dist_tp.yaml"
    p.write_text(yaml.safe_dump(spec))
    os.environ["JAX_NUM_CPU_DEVICES"] = "2"  # 2 devices/proc -> 4 global
    try:
        store = RunStore()
        compiled = compile_operation(
            read_polyaxonfile(str(p)), artifacts_root=str(store.runs_dir)
        )
        assert Executor(store).execute(compiled) == V1Statuses.SUCCEEDED
        metrics = store.read_metrics(compiled.run_uuid)
        assert metrics and metrics[-1]["step"] == 2
        events = store.read_events(compiled.run_uuid)
        health = [e for e in events if e.get("kind") == "slice_health"]
        assert health and health[0]["devices"] == 4  # global mesh assembled
    finally:
        os.environ["JAX_NUM_CPU_DEVICES"] = "8"


@pytest.mark.slow
def test_distributed_multislice_gang(tmp_home, tmp_path):
    """2 jax.distributed processes standing in for 2 TPU slices: the tpu
    block's `slices: 2` reaches the workers, whose hybrid mesh lays the
    data axis slice-major (process-contiguous device blocks), and one
    train step executes across the DCN-like process boundary."""
    import yaml

    from polyaxon_tpu.compiler.resolver import compile_operation
    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.runtime.executor import Executor
    from polyaxon_tpu.schemas.lifecycle import V1Statuses
    from polyaxon_tpu.store.local import RunStore

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "dist-multislice",
        "component": {
            "kind": "component",
            "name": "dist-multislice",
            "run": {
                "kind": "jaxjob",
                "replicas": 2,
                "mesh": {"data": 4},
                "program": {
                    "model": {
                        "name": "transformer_lm",
                        "config": {
                            "dim": 64, "n_layers": 2, "n_heads": 4,
                            "n_kv_heads": 4, "vocab_size": 512, "seq_len": 32,
                        },
                    },
                    "data": {
                        "name": "synthetic_text",
                        "batchSize": 8,
                        "config": {"seq_len": 32, "vocab_size": 512},
                    },
                    "optimizer": {"name": "adamw", "learningRate": 0.001},
                    "train": {"steps": 2, "logEvery": 2, "precision": "float32"},
                },
                "environment": {
                    "resources": {
                        "tpu": {"type": "v5e", "count": 2, "slices": 2}
                    }
                },
            },
        },
    }
    p = tmp_path / "dist_multislice.yaml"
    p.write_text(yaml.safe_dump(spec))
    prev = os.environ.get("JAX_NUM_CPU_DEVICES")
    os.environ["JAX_NUM_CPU_DEVICES"] = "2"  # 2 devices/proc -> 4 global
    try:
        store = RunStore()
        compiled = compile_operation(
            read_polyaxonfile(str(p)), artifacts_root=str(store.runs_dir)
        )
        assert Executor(store).execute(compiled) == V1Statuses.SUCCEEDED
        metrics = store.read_metrics(compiled.run_uuid)
        assert metrics and metrics[-1]["step"] == 2
    finally:
        if prev is None:
            os.environ.pop("JAX_NUM_CPU_DEVICES", None)
        else:
            os.environ["JAX_NUM_CPU_DEVICES"] = prev
