"""Reader robustness fuzz: random structural mutations of a valid spec
must surface as PolyaxonfileError (or validate), never any other
exception type — the CLI maps PolyaxonfileError to a clean message, so
anything else is a raw traceback in a user's face."""

import copy
import random

import pytest
import yaml

from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.polyaxonfile.reader import PolyaxonfileError

BASE = {
    "version": 1.1,
    "kind": "operation",
    "name": "fuzz-target",
    "params": {"lr": {"value": 0.001}},
    "component": {
        "kind": "component",
        "name": "fuzz-target",
        "inputs": [{"name": "lr", "type": "float"}],
        "termination": {"maxRetries": 1},
        "run": {
            "kind": "jaxjob",
            "replicas": 2,
            "mesh": {"data": 2},
            "environment": {
                "resources": {"tpu": {"type": "v5e", "topology": "2x4"}}
            },
            "program": {
                "model": {"name": "mlp", "config": {"input_dim": 8}},
                "data": {"name": "synthetic", "batchSize": 8},
                "optimizer": {"name": "adamw", "learningRate": "{{ params.lr }}"},
                "train": {"steps": 2},
            },
        },
    },
}

JUNK = [
    None, -1, 0, 3.5, "", "garbage", "{{ params.missing }}", [], {}, [1, 2],
    {"unexpected": True}, "2x", "vNaN", True, "  ", {"kind": "frobnicate"},
]


def _paths(node, prefix=()):
    """Every (path, container, key) location in the nested spec."""
    out = []
    if isinstance(node, dict):
        for k, v in node.items():
            out.append((prefix + (k,), node, k))
            out.extend(_paths(v, prefix + (k,)))
    elif isinstance(node, list):
        for i, v in enumerate(node):
            out.append((prefix + (i,), node, i))
            out.extend(_paths(v, prefix + (i,)))
    return out


@pytest.mark.parametrize("seed", range(6))
def test_random_mutations_fail_cleanly(tmp_path, seed):
    rng = random.Random(seed)
    for trial in range(40):
        spec = copy.deepcopy(BASE)
        for _ in range(rng.randint(1, 3)):
            # recompute per mutation: an earlier mutation may have detached
            # the subtree a stale location pointed into
            locations = _paths(spec)
            _, container, key = rng.choice(locations)
            action = rng.random()
            if action < 0.5:
                container[key] = rng.choice(JUNK)
            elif action < 0.8 and isinstance(container, dict):
                container.pop(key, None)
            elif isinstance(container, dict):
                container[f"fuzz_{rng.randint(0, 9)}"] = rng.choice(JUNK)
        p = tmp_path / f"fuzz_{seed}_{trial}.yaml"
        p.write_text(yaml.safe_dump(spec))
        try:
            read_polyaxonfile(str(p))
        except PolyaxonfileError:
            pass  # the designed failure mode
        except Exception as e:  # noqa: BLE001 — the assertion target
            raise AssertionError(
                f"mutation leaked {type(e).__name__} instead of "
                f"PolyaxonfileError (seed={seed}, trial={trial}):\n"
                f"{yaml.safe_dump(spec)}\n{e}"
            ) from e


def test_binary_and_deep_nesting_fail_cleanly(tmp_path):
    cases = {
        "binary.yaml": b"\x00\x01\x02\xff\xfe polyaxon",
        "deep.yaml": ("[" * 150 + "]" * 150).encode(),
        "empty.yaml": b"",
        "scalar.yaml": b"42",
        "anchor_bomb.yaml": b"a: &a [1]\nb: [*a, *a, *a]\nkind: operation",
    }
    for name, payload in cases.items():
        p = tmp_path / name
        p.write_bytes(payload)
        with pytest.raises(PolyaxonfileError):
            read_polyaxonfile(str(p))
