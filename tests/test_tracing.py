"""ISSUE 9 coverage: per-request tracing, the SLO burn-rate engine, and
the slow-request flight recorder.

Unit layer: RequestTrace/TraceRing semantics under a fake clock,
histogram exemplars + count_le, burn-rate math at explicit evaluate
times (the breach edge fires exactly once, re-arms after recovery),
FlightRecorder bundle layout + dump limit, V1SLOSpec validation, and
the server's error->reason/status mapping for every shed class.

Live-HTTP layer (pytest.mark.serving, tiny models): X-Request-Id
round-trips every status class with the pinned structured error schema,
SSE frames carry the id, coalesced rows share a decode-group span id,
the /tracez span timeline sums to the observed latency (the 10%%
acceptance bound), the tail sampler keeps a deadline shed alive under
an ok flood with a 4-slot ring, a seeded overload flips /sloz and
writes a flight-recorder bundle, and `polyaxon stats --slo --traces` /
`polyaxon trace` read the live surfaces.
"""

import http.client
import json
import threading

import numpy as np
import pytest

from polyaxon_tpu.telemetry import (
    AvailabilityObjective,
    FlightRecorder,
    LatencyObjective,
    MetricsRegistry,
    RequestTrace,
    SLOEngine,
    TraceRing,
    build_objectives,
    new_trace_id,
)

# ---------------------------------------------------------------- unit


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt
        return self.t


def test_new_trace_id_shape():
    a, b = new_trace_id(), new_trace_id()
    assert a != b
    assert len(a) == 16 and int(a, 16) >= 0  # 16 hex chars


def test_request_trace_spans_groups_and_idempotent_finish():
    clk = FakeClock()
    tr = RequestTrace("abc", clock=clk, model="tiny", stream=False)
    clk.tick(0.25)
    tr.add("queue_wait", start=100.0, dur_s=0.25)
    tr.annotate("kv_plan", pages=3)  # zero-duration, stamped "now"
    tr.set_group(7)
    tr.set_group(7)  # de-duplicated
    clk.tick(0.75)
    tr.add("decode", start=100.25, dur_s=0.75, tokens=8)
    assert not tr.finished
    tr.finish()
    assert tr.finished and tr.dur_s == pytest.approx(1.0)
    tr.finish("error", error="late")  # first finish wins
    d = tr.to_dict()
    assert d["id"] == "abc" and d["status"] == "ok"
    assert "error" not in d
    assert d["dur_ms"] == pytest.approx(1000.0)
    assert d["group_span_ids"] == [7]
    assert d["attrs"] == {"model": "tiny", "stream": False}
    names = [s["name"] for s in d["spans"]]
    assert names == ["queue_wait", "kv_plan", "decode"]
    qw, plan, dec = d["spans"]
    assert qw["start_s"] == pytest.approx(0.0)
    assert qw["dur_s"] == pytest.approx(0.25)
    assert plan["start_s"] == pytest.approx(0.25) and plan["dur_s"] == 0.0
    assert plan["attrs"] == {"pages": 3}
    assert dec["start_s"] == pytest.approx(0.25)
    # offsets are clamped: a span can never start before the trace
    early = tr.add("early", start=0.0, dur_s=0.1)
    assert early["start_s"] == 0.0


def test_request_trace_error_status():
    clk = FakeClock()
    tr = RequestTrace("bad", clock=clk)
    clk.tick(0.1)
    tr.finish("shed:deadline", error="deadline already expired")
    d = tr.to_dict()
    assert d["status"] == "shed:deadline"
    assert d["error"] == "deadline already expired"


def _tdict(tid, status="ok", dur_ms=1.0):
    return {
        "id": tid, "status": status, "dur_ms": dur_ms,
        "group_span_ids": [], "attrs": {}, "spans": [],
    }


def test_trace_ring_tail_sampling_retention():
    ring = TraceRing(capacity=4, error_capacity=4, slow_capacity=2)
    ring.record(_tdict("err-1", status="shed:deadline", dur_ms=5.0))
    ring.record(_tdict("slow-1", dur_ms=999.0))
    for i in range(20):  # the ok flood that must NOT evict err/slow
        ring.record(_tdict(f"ok-{i}", dur_ms=1.0))
    assert ring.get("err-1")["status"] == "shed:deadline"
    assert ring.get("slow-1")["dur_ms"] == 999.0
    assert ring.get("ok-3") is None  # recent window slid past it
    recent = ring.list(4, sort="recent")
    assert [t["id"] for t in recent] == ["ok-19", "ok-18", "ok-17", "ok-16"]
    assert ring.list(1, sort="slowest")[0]["id"] == "slow-1"
    assert [t["id"] for t in ring.list(10, sort="errors")] == ["err-1"]
    with pytest.raises(ValueError):
        ring.list(5, sort="bogus")
    st = ring.stats()
    assert st["recorded"] == 22 and st["capacity"] == 4
    assert st["errors"] == 1
    assert st["retained"] == len(ring) == len(ring.dump())
    # every retained trace is reachable by id
    for t in ring.dump():
        assert ring.get(t["id"]) is not None


def test_trace_ring_records_live_traces():
    clk = FakeClock()
    ring = TraceRing(capacity=8)
    tr = RequestTrace("live", clock=clk)
    clk.tick(0.5)
    tr.finish()
    ring.record(tr)  # RequestTrace objects are admitted via to_dict
    assert ring.get("live")["dur_ms"] == pytest.approx(500.0)


def test_histogram_exemplars_and_count_le():
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=[0.05, 0.1, 0.5])
    for _ in range(10):
        h.observe(0.01, exemplar="fast-req")
    for _ in range(10):
        h.observe(0.4, exemplar="slow-req")
    assert h.count == 20
    # interpolated cumulative count at the bucket edge is exact
    assert h.count_le(0.1) == pytest.approx(10.0)
    assert h.count_le(10.0) == pytest.approx(20.0)
    ex = h.exemplar(0.99)
    assert ex == {"value": 0.4, "trace_id": "slow-req"}


def test_availability_burn_math_and_breach_edge_fires_once():
    reg = MetricsRegistry()
    bad = reg.counter("bad")
    total = reg.counter("total")
    obj = AvailabilityObjective(
        "avail", 0.99, bad=[bad], total=[total], windows_s=(60.0, 300.0)
    )
    fired = []
    eng = SLOEngine([obj], reg, on_breach=fired.append, clock=lambda: 0.0)

    r = eng.evaluate(t=0.0)[0]  # baseline, no traffic
    assert r["burn_rate"] == 0.0 and not r["breached"]

    total.inc(100)
    r = eng.evaluate(t=30.0)[0]  # clean traffic burns nothing
    assert r["burn_rate"] == 0.0 and not fired

    bad.inc(5)
    total.inc(5)
    r = eng.evaluate(t=60.0)[0]
    # 5 bad / 105 total over a 1% budget -> ~4.76x in both windows
    assert r["burn_rate"] == pytest.approx(5 / 105 / 0.01)
    assert set(r["burn_rates"]) == {"60s", "300s"}
    assert r["breached"] is True
    assert len(fired) == 1 and fired[0]["name"] == "avail"

    snap = reg.snapshot()
    assert snap["slo.breached"] == 1.0
    assert snap["slo.burn_rate"] == pytest.approx(5 / 105 / 0.01)
    assert snap["slo.breached.avail"] == 1.0

    eng.evaluate(t=90.0)  # still breached: the edge must NOT re-fire
    assert len(fired) == 1

    # windows slide past the error burst -> recovery
    r = eng.evaluate(t=600.0)[0]
    assert not r["breached"]
    assert reg.snapshot()["slo.breached"] == 0.0

    bad.inc(2)
    total.inc(2)
    r = eng.evaluate(t=630.0)[0]  # a NEW burst re-arms the edge
    assert r["breached"] and len(fired) == 2


def test_breach_requires_every_window_and_real_traffic():
    reg = MetricsRegistry()
    bad = reg.counter("b")
    total = reg.counter("t")
    obj = AvailabilityObjective(
        "a", 0.99, bad=[bad], total=[total], windows_s=(10.0, 100.0)
    )
    eng = SLOEngine([obj], reg, clock=lambda: 0.0)
    eng.evaluate(t=0.0)
    bad.inc(10)
    total.inc(10)
    eng.evaluate(t=50.0)
    # short window slides clean while the long window still sees the
    # burst: effective burn = min across windows = 0 -> no breach
    r = eng.evaluate(t=70.0)[0]
    assert r["burn_rates"]["10s"] == 0.0
    assert r["burn_rates"]["100s"] > 1.0
    assert r["burn_rate"] == 0.0 and not r["breached"]


def test_latency_objective_counts_slow_requests():
    reg = MetricsRegistry()
    h = reg.histogram("req", buckets=[0.05, 0.1, 0.5])
    obj = LatencyObjective("p", 0.95, histogram=h, threshold_ms=100.0)
    for _ in range(10):
        h.observe(0.01)
    for _ in range(10):
        h.observe(0.4)
    b, t = obj.sample()
    assert (b, t) == (pytest.approx(10.0), 20.0)
    assert obj.describe()["threshold_ms"] == 100.0
    with pytest.raises(ValueError):
        LatencyObjective("x", 0.95, histogram=h, threshold_ms=0)


def test_objective_validation():
    reg = MetricsRegistry()
    c = reg.counter("c")
    with pytest.raises(ValueError):
        AvailabilityObjective("x", 1.5, bad=[c], total=[c])
    with pytest.raises(ValueError):
        AvailabilityObjective("x", 0.9, bad=[c], total=[c],
                              windows_s=(300.0, 60.0))
    with pytest.raises(ValueError):
        AvailabilityObjective("x", 0.9, bad=[c], total=[c],
                              burn_threshold=0.0)


def test_build_objectives_binds_kinds_and_rejects_unknown():
    reg = MetricsRegistry()
    bad, total = reg.counter("bad"), reg.counter("total")
    h = reg.histogram("lat")
    objs = build_objectives(
        [
            {"name": "avail", "kind": "availability", "objective": 0.999},
            {"name": "p99", "kind": "latency", "objective": 0.99,
             "threshold_ms": 250.0, "windows": [30.0, 120.0],
             "burn_threshold": 2.0},
        ],
        bad=[bad], total=[total], histogram=h,
    )
    assert isinstance(objs[0], AvailabilityObjective)
    assert isinstance(objs[1], LatencyObjective)
    assert objs[1].windows_s == (30.0, 120.0)
    assert objs[1].burn_threshold == 2.0
    with pytest.raises(ValueError):
        build_objectives(
            [{"name": "x", "kind": "throughput", "objective": 0.9}],
            bad=[bad], total=[total], histogram=h,
        )


def test_flight_recorder_bundle_layout_and_limit(tmp_path):
    ring = TraceRing(capacity=8)
    ring.record(_tdict("boom", status="error", dur_ms=50.0))
    ring.record(_tdict("fine", dur_ms=1.0))
    reg = MetricsRegistry()
    reg.counter("reqs").inc(3)
    fr = FlightRecorder(
        tmp_path, registry=reg, trace_ring=ring,
        state_fn=lambda: {"queue_depth": 2}, limit=2,
    )
    d = fr.dump({"name": "avail", "burn_rate": 7.0, "edge": True})
    assert d is not None and d.is_dir()
    breach = json.loads((d / "breach.json").read_text())
    assert breach["name"] == "avail" and "edge" not in breach
    # the picked trace is the most recent ERROR, linked from breach.json
    assert breach["trace_id"] == "boom"
    assert json.loads((d / "trace.json").read_text())["id"] == "boom"
    lines = (d / "traces.jsonl").read_text().splitlines()
    assert {json.loads(ln)["id"] for ln in lines} == {"boom", "fine"}
    assert json.loads((d / "metrics.json").read_text())["reqs"] == 3
    assert json.loads((d / "state.json").read_text()) == {"queue_depth": 2}
    assert fr.dump({"name": "avail"}) is not None
    assert fr.dump({"name": "avail"}) is None  # bounded per process
    assert len(fr.dumps) == 2


def test_v1_slo_spec_validation_and_to_config():
    from polyaxon_tpu.schemas.run_kinds import V1ObservabilitySpec, V1SLOSpec

    s = V1SLOSpec(name="availability")
    assert s.kind == "availability" and s.objective == 0.999
    cfg = s.to_config()
    assert cfg["name"] == "availability" and cfg["kind"] == "availability"
    assert "threshold_ms" not in cfg and "windows" not in cfg

    lat = V1SLOSpec.from_dict(
        {"name": "p99", "kind": "latency", "objective": 0.99,
         "thresholdMs": 250, "windows": [30, 120], "burnThreshold": 2}
    )
    cfg = lat.to_config()
    assert cfg["threshold_ms"] == 250 and cfg["windows"] == [30, 120]
    assert cfg["burn_threshold"] == 2

    with pytest.raises(ValueError):  # latency needs the split point
        V1SLOSpec(name="p", kind="latency")
    with pytest.raises(ValueError):  # thresholdMs is latency-only
        V1SLOSpec(name="a", threshold_ms=100)
    with pytest.raises(ValueError):
        V1SLOSpec(name="a", objective=1.2)
    with pytest.raises(ValueError):  # windows must ascend
        V1SLOSpec(name="a", windows=[300, 60])
    with pytest.raises(ValueError):
        V1SLOSpec(name="a", burn_threshold=0)

    obs = V1ObservabilitySpec.from_dict(
        {"slos": [{"name": "availability", "objective": 0.999}]}
    )
    assert obs.slos[0].name == "availability"


def test_error_reason_and_trace_status_cover_every_shed_class():
    from polyaxon_tpu.serving.batching import (
        DeadlineExceededError,
        ServerClosingError,
        ServingError,
        ShedError,
    )
    from polyaxon_tpu.serving.server import _error_reason, _trace_status

    for reason in ("queue_full", "breaker_open", "deadline", "draining",
                   "kv_pages"):
        e = ShedError("x", reason=reason)
        assert _error_reason(e) == reason
        assert _trace_status(e) == f"shed:{reason}"
    closing = ServerClosingError()
    assert _error_reason(closing) == "closing"
    assert _trace_status(closing) == "shed:closing"
    assert _error_reason(DeadlineExceededError("x")) == "deadline_exceeded"
    assert _trace_status(DeadlineExceededError("x")) == "deadline_exceeded"
    assert _error_reason(ServingError("x")) == "invalid_request"
    assert _trace_status(ServingError("x")) == "invalid_request"
    assert _error_reason(TimeoutError("x")) == "timeout"
    assert _trace_status(TimeoutError("x")) == "timeout"
    assert _error_reason(RuntimeError("x")) == "internal"
    assert _trace_status(RuntimeError("x")) == "error"
    assert _trace_status(None) == "ok"


# ----------------------------------------------------------- live HTTP

CFG = {
    "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
}

#: the structured error body every non-200 /generate response carries —
#: contract for log correlation; renaming a key silently breaks callers
ERROR_SCHEMA = {"error", "reason", "requestId"}


def _build():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    b = build_model("transformer_lm", CFG)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


def _server(module, params, **kw):
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    server_kw = {
        k: kw.pop(k)
        for k in ("slos", "debug_dir", "registry")
        if k in kw
    }
    cfg = ServingConfig(**{
        "max_batch": 4, "max_wait_ms": 2.0, "kv_page_tokens": 8,
        "stream_chunk_tokens": 3, **kw,
    })
    return ModelServer(
        module, params, model_name="tiny", config=cfg, **server_kw
    )


@pytest.fixture(scope="module")
def servers():
    module, params = _build()
    paged = _server(module, params, kv_pool_pages=64)
    port = paged.start(port=0)
    yield {"paged": port, "srv": paged, "module": module, "params": params}
    paged.stop()


def _post(port, body, headers=None, path="/generate", timeout=120):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request("POST", path, json.dumps(body), headers=headers or {})
    r = c.getresponse()
    raw = r.read()
    c.close()
    try:
        payload = json.loads(raw)
    except (ValueError, UnicodeDecodeError):
        payload = raw
    return r.status, payload, {k: v for k, v in r.getheaders()}


def _get(port, path, timeout=60):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request("GET", path)
    r = c.getresponse()
    raw = r.read()
    c.close()
    try:
        return r.status, json.loads(raw)
    except (ValueError, UnicodeDecodeError):
        return r.status, raw


def _body(n_rows=1, max_new=6, seed=123):
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(1, 100, size=12).tolist() for _ in range(n_rows)]
    return {
        "tokens": prompts, "maxNewTokens": max_new, "temperature": 0.0,
        "seed": seed,
    }


@pytest.mark.serving
def test_request_id_accept_or_assign(servers):
    # caller-supplied id is echoed in body AND header
    st, payload, hdrs = _post(
        servers["paged"], _body(), headers={"X-Request-Id": "my-req-1"}
    )
    assert st == 200, payload
    assert payload["requestId"] == "my-req-1"
    assert hdrs["X-Request-Id"] == "my-req-1"
    # no id supplied -> a fresh 16-hex id is assigned and echoed
    st, payload, hdrs = _post(servers["paged"], _body(seed=7))
    assert st == 200
    rid = hdrs["X-Request-Id"]
    assert len(rid) == 16 and int(rid, 16) >= 0
    assert payload["requestId"] == rid
    # the id resolves to a full span timeline on /tracez
    st, tr = _get(servers["paged"], "/tracez?id=my-req-1")
    assert st == 200 and tr["id"] == "my-req-1"
    assert tr["status"] == "ok" and tr["spans"]


@pytest.mark.serving
def test_structured_error_schema_400_503_504_500(servers, monkeypatch):
    port, srv = servers["paged"], servers["srv"]

    # 400 invalid: client error, pinned schema
    st, p, hdrs = _post(port, {"tokens": "nope"},
                        headers={"X-Request-Id": "bad-1"})
    assert st == 400 and set(p) == ERROR_SCHEMA, p
    assert p["reason"] == "invalid_request" and p["requestId"] == "bad-1"
    assert hdrs["X-Request-Id"] == "bad-1"

    # 503 deadline shed: Retry-After + reason from the shed class
    st, p, hdrs = _post(port, {**_body(), "deadlineMs": 1e-6},
                        headers={"X-Request-Id": "dead-1"})
    assert st == 503 and set(p) == ERROR_SCHEMA, p
    assert p["reason"] == "deadline" and p["requestId"] == "dead-1"
    assert int(hdrs["Retry-After"]) >= 1

    # 503 draining: admission closed while the server drains
    monkeypatch.setattr(srv, "_draining", True)
    st, p, _ = _post(port, _body())
    assert st == 503 and set(p) == ERROR_SCHEMA, p
    assert p["reason"] == "draining"
    monkeypatch.setattr(srv, "_draining", False)

    # 504 timeout and 500 internal: the handler looks handle_request up
    # on the server instance per call, so instance patching reaches it
    monkeypatch.setattr(
        srv, "handle_request",
        lambda body, request_id=None: (_ for _ in ()).throw(
            TimeoutError("decode timed out")
        ),
    )
    st, p, _ = _post(port, _body())
    assert st == 504 and set(p) == ERROR_SCHEMA, p
    assert p["reason"] == "timeout"

    monkeypatch.setattr(
        srv, "handle_request",
        lambda body, request_id=None: (_ for _ in ()).throw(
            RuntimeError("boom")
        ),
    )
    st, p, _ = _post(port, _body())
    assert st == 500 and set(p) == ERROR_SCHEMA, p
    assert p["reason"] == "internal" and "boom" in p["error"]


@pytest.mark.serving
def test_sse_frames_carry_request_id(servers):
    c = http.client.HTTPConnection("127.0.0.1", servers["paged"], timeout=120)
    c.request(
        "POST", "/generate?stream=1", json.dumps(_body(max_new=7)),
        headers={"X-Request-Id": "sse-1"},
    )
    r = c.getresponse()
    assert r.status == 200
    assert r.getheader("X-Request-Id") == "sse-1"
    events, buf = [], b""
    while True:
        data = r.read(64)
        if not data:
            break
        buf += data
        while b"\n\n" in buf:
            frame, buf = buf.split(b"\n\n", 1)
            events.append(json.loads(frame[len(b"data: "):]))
    c.close()
    assert events and events[-1].get("done") is True
    assert all(ev["requestId"] == "sse-1" for ev in events)
    st, tr = _get(servers["paged"], "/tracez?id=sse-1")
    assert st == 200 and tr["attrs"].get("stream") is True
    assert "stream_flush" in [s["name"] for s in tr["spans"]]


@pytest.mark.serving
def test_tracez_listing_and_errors(servers):
    st, data = _get(servers["paged"], "/tracez")
    assert st == 200 and data["traces"]
    assert {"recorded", "retained", "errors", "capacity"} <= data.keys()
    first = data["traces"][0]
    assert {"id", "status", "dur_ms", "spans"} <= first.keys()
    st, _ = _get(servers["paged"], "/tracez?id=no-such-trace")
    assert st == 404
    st, p = _get(servers["paged"], "/tracez?sort=bogus")
    assert st == 400 and "sort" in p["error"]
    st, data = _get(servers["paged"], "/tracez?n=1&sort=slowest")
    assert st == 200 and len(data["traces"]) == 1


@pytest.mark.serving
def test_span_timeline_sums_to_observed_latency(servers):
    st, _, _ = _post(servers["paged"], _body(seed=42, max_new=8),
                     headers={"X-Request-Id": "timeline-1"})
    assert st == 200
    st, tr = _get(servers["paged"], "/tracez?id=timeline-1")
    assert st == 200
    names = [s["name"] for s in tr["spans"]]
    for expected in ("admission", "queue_wait", "prefill", "decode",
                     "stream_flush"):
        assert expected in names, names
    # acceptance bound: the spans partition the request — their sum
    # lands within 10% of the latency the client observed
    span_ms = sum(s["dur_s"] for s in tr["spans"]) * 1e3
    assert tr["dur_ms"] > 0
    assert abs(span_ms - tr["dur_ms"]) <= 0.10 * tr["dur_ms"], (
        span_ms, tr["dur_ms"], names,
    )
    # every span starts inside the request window
    for s in tr["spans"]:
        assert 0.0 <= s["start_s"] * 1e3 <= tr["dur_ms"] + 1e-6


@pytest.mark.serving
def test_coalesced_rows_share_decode_group_span(servers):
    # a dedicated server with a generous coalescing window so two
    # concurrent single-row posts land in ONE decode group
    srv = _server(servers["module"], servers["params"],
                  kv_pool_pages=64, max_wait_ms=250.0)
    port = srv.start(port=0)
    try:
        results = {}

        def run(rid):
            body = _body(seed=9, max_new=5)
            results[rid] = _post(port, body,
                                 headers={"X-Request-Id": rid})

        threads = [
            threading.Thread(target=run, args=(rid,))
            for rid in ("co-a", "co-b")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(results[r][0] == 200 for r in results), results
        groups = {}
        for rid in ("co-a", "co-b"):
            st, tr = _get(port, f"/tracez?id={rid}")
            assert st == 200
            groups[rid] = set(tr["group_span_ids"])
            assert groups[rid], tr
        assert groups["co-a"] & groups["co-b"], groups
    finally:
        srv.stop()


@pytest.mark.serving
def test_tail_sampler_keeps_deadline_shed_under_ok_flood(servers):
    # 4-slot recent window: the ok flood evicts ok traces, never the shed
    srv = _server(servers["module"], servers["params"],
                  kv_pool_pages=64, trace_ring=4)
    port = srv.start(port=0)
    try:
        st, p, _ = _post(port, {**_body(), "deadlineMs": 1e-6},
                         headers={"X-Request-Id": "shed-keep"})
        assert st == 503 and p["reason"] == "deadline"
        for i in range(10):
            st, _, _ = _post(port, _body(seed=i))
            assert st == 200
        st, tr = _get(port, "/tracez?id=shed-keep")
        assert st == 200, "tail sampler evicted the shed trace"
        assert tr["status"] == "shed:deadline"
        st, data = _get(port, "/tracez?sort=errors")
        assert st == 200
        assert "shed-keep" in [t["id"] for t in data["traces"]]
    finally:
        srv.stop()


@pytest.mark.serving
def test_slo_breach_flips_sloz_and_writes_flight_recorder(
    servers, tmp_path
):
    slos = [{"name": "availability", "kind": "availability",
             "objective": 0.999, "windows": [5.0, 30.0]}]
    srv = _server(servers["module"], servers["params"],
                  kv_pool_pages=64, slos=slos, debug_dir=str(tmp_path))
    port = srv.start(port=0)
    try:
        st, sloz = _get(port, "/sloz")  # baseline sample, nothing burning
        assert st == 200 and sloz["enabled"] and not sloz["breached"]
        st, _, _ = _post(port, _body())
        assert st == 200
        for _ in range(4):  # seeded overload: 4/5 requests shed
            st, p, _ = _post(port, {**_body(), "deadlineMs": 1e-6})
            assert st == 503 and p["reason"] == "deadline"
        st, sloz = _get(port, "/sloz")
        assert st == 200 and sloz["breached"] is True
        (s,) = sloz["slos"]
        assert s["name"] == "availability" and s["breached"]
        assert s["burn_rate"] > 1.0 and s["bad"] >= 4
        assert set(s["burn_rates"]) == {"5s", "30s"}
        # the gauges reach /metricsz for the canary + alerting
        st, text = _get(port, "/metricsz")
        text = text.decode()
        assert "slo_burn_rate" in text and "slo_breached 1" in text
        # the breach edge dumped a post-mortem bundle under debug/
        bundles = sorted(tmp_path.glob("slo-*-availability"))
        assert bundles, list(tmp_path.iterdir())
        assert (bundles[0] / "breach.json").exists()
        assert (bundles[0] / "traces.jsonl").read_text().strip()
        assert (bundles[0] / "metrics.json").exists()
        state = json.loads((bundles[0] / "state.json").read_text())
        assert "queue" in state or "kv" in state, state
        st, stats = _get(port, "/statsz")
        assert stats["slo"]["flight_recorder_dumps"] == [str(bundles[0])]
    finally:
        srv.stop()


@pytest.mark.serving
def test_cli_stats_and_trace_read_live_surfaces(servers):
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    st, _, _ = _post(servers["paged"], _body(seed=3),
                     headers={"X-Request-Id": "cli-req-1"})
    assert st == 200
    url = f"http://127.0.0.1:{servers['paged']}"
    runner = CliRunner()

    res = runner.invoke(
        cli, ["stats", "--url", url, "--slo", "--traces", "3"]
    )
    assert res.exit_code == 0, res.output
    assert "tracing: on" in res.output
    assert "cli-req-1" in res.output

    res = runner.invoke(cli, ["trace", "--url", url, "-n", "5"])
    assert res.exit_code == 0, res.output
    assert "cli-req-1" in res.output

    res = runner.invoke(cli, ["trace", "cli-req-1", "--url", url])
    assert res.exit_code == 0, res.output
    assert "trace cli-req-1" in res.output
    for name in ("queue_wait", "prefill", "decode"):
        assert name in res.output

    res = runner.invoke(cli, ["trace", "no-such-id", "--url", url])
    assert res.exit_code != 0  # 404 -> clean CLI error, not a traceback

    # --slo/--traces are live-surface flags: without --url they error
    res = runner.invoke(cli, ["stats", "--slo"])
    assert res.exit_code != 0
