"""Autoregressive decode: the KV-cache path must reproduce full-reforward
greedy decoding exactly, across layer-stacking modes and GQA."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polyaxon_tpu.models import build_model
from polyaxon_tpu.models.generate import generate


def _setup(**cfg_overrides):
    cfg = {
        "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
        "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
    }
    cfg.update(cfg_overrides)
    b = build_model("transformer_lm", cfg)
    rng = jax.random.PRNGKey(0)
    prompt = jax.random.randint(rng, (2, 5), 0, 128, dtype=jnp.int32)
    params = b.module.init(
        {"params": rng}, jnp.zeros((2, 64), jnp.int32), train=False
    )["params"]
    return b.module, params, prompt


def _naive_greedy(module, params, prompt, n):
    toks = np.asarray(prompt)
    for _ in range(n):
        logits = module.apply({"params": params}, jnp.asarray(toks), train=False)
        nxt = np.argmax(np.asarray(logits[:, -1], np.float32), -1)
        toks = np.concatenate([toks, nxt[:, None].astype(np.int32)], 1)
    return toks


def test_cached_decode_matches_full_reforward_fast():
    """Fast-tier cache-correctness signal: prefill + 1 cached step against
    the independent full-reforward reference (the naive reference
    recompiles per length — 2 tokens keeps this cheap)."""
    module, params, prompt = _setup()
    out = generate(module, params, prompt, max_new_tokens=2, temperature=0.0)
    ref = _naive_greedy(module, params, prompt, 2)
    np.testing.assert_array_equal(np.asarray(out), ref)


@pytest.mark.parametrize(
    "mode",
    [pytest.param("layers", marks=pytest.mark.slow),
     pytest.param("scan", marks=pytest.mark.slow)],
)
def test_cached_decode_matches_full_reforward(mode):
    # 5 tokens exercise prefill + 4 cached steps; the naive reference
    # recompiles per length, so keep the tail short in the fast tier
    module, params, prompt = _setup(scan_layers=(mode == "scan"))
    out = generate(module, params, prompt, max_new_tokens=5, temperature=0.0)
    ref = _naive_greedy(module, params, prompt, 5)
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_sampling_reproducible_and_bounded():
    module, params, prompt = _setup()
    a = generate(module, params, prompt, max_new_tokens=6,
                 temperature=0.8, top_k=10, seed=7)
    b = generate(module, params, prompt, max_new_tokens=6,
                 temperature=0.8, top_k=10, seed=7)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = generate(module, params, prompt, max_new_tokens=6,
                 temperature=0.8, top_k=10, seed=8)
    assert (np.asarray(a) != np.asarray(c)).any()
    assert np.asarray(a).min() >= 0 and np.asarray(a).max() < 128
    # prompt is preserved verbatim
    np.testing.assert_array_equal(np.asarray(a)[:, :5], np.asarray(prompt))


def test_eos_freezes_finished_rows():
    module, params, prompt = _setup()
    eos = 3
    out = np.asarray(
        generate(module, params, prompt, max_new_tokens=12,
                 temperature=0.9, eos_id=eos, seed=1)
    )
    for row in out:
        gen = row[5:]
        hits = np.where(gen == eos)[0]
        if hits.size:  # everything after the first eos is eos
            assert (gen[hits[0]:] == eos).all()


@pytest.mark.slow
def test_eos_in_prompt_does_not_freeze_generation():
    """Prompts legitimately contain eos as separators (chat templates,
    packed documents); only a GENERATED eos may finish a row."""
    module, params, prompt = _setup()
    eos = int(np.asarray(prompt)[0, 2])  # an eos that occurs mid-prompt
    out = np.asarray(
        generate(module, params, prompt, max_new_tokens=8,
                 temperature=0.0, eos_id=eos, seed=0)
    )
    ref = _naive_greedy(module, params, prompt, 8)
    # greedy continuation of row 0 must match eos-free decoding up to the
    # first GENERATED eos (if any) — not be frozen to eos from position P
    gen, ref_gen = out[0, 5:], ref[0, 5:]
    first = np.where(ref_gen == eos)[0]
    upto = first[0] + 1 if first.size else len(ref_gen)
    np.testing.assert_array_equal(gen[:upto], ref_gen[:upto])
    assert not (gen == eos).all(), "row frozen by prompt eos"


def test_generate_overflow_errors():
    module, params, prompt = _setup()
    with pytest.raises(ValueError, match="exceeds the model's seq_len"):
        generate(module, params, prompt, max_new_tokens=100)


@pytest.mark.slow
def test_generate_pipeline_error():
    mod2, params2, prompt2 = _setup(
        pipeline_stages=2, pipeline_microbatches=2
    )
    with pytest.raises(ValueError, match="pipeline"):
        generate(mod2, params2, prompt2, max_new_tokens=4)


@pytest.mark.slow
def test_mesh_sharded_params_decode_matches_single_device(tmp_home):
    """Multi-chip decode: generation with TP/FSDP-sharded params on an
    8-device mesh produces exactly the single-device tokens — XLA inserts
    the collectives from the param shardings, generate() is unchanged."""
    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    def prog():
        return V1Program(
            model=V1ModelSpec(
                name="transformer_lm",
                config={"preset": "tiny", "seq_len": 64, "n_layers": 2,
                        "dim": 64, "vocab_size": 256},
            ),
            data=V1DataSpec(
                name="synthetic_text", batch_size=8,
                config={"seq_len": 64, "vocab_size": 256},
            ),
            optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
            train=V1TrainSpec(steps=2, log_every=2, precision="float32", seed=0),
        )

    prompt = jnp.arange(6, dtype=jnp.int32).reshape(2, 3) + 1
    t_mesh = Trainer(prog(), mesh_axes={"data": 2, "model": 2, "fsdp": 2})
    t_mesh.run()
    out_mesh = np.asarray(
        generate(t_mesh.bundle.module, t_mesh.state.params, prompt,
                 max_new_tokens=6, temperature=0.0)
    )
    t_one = Trainer(prog(), devices=jax.devices()[:1])
    t_one.run()
    out_one = np.asarray(
        generate(t_one.bundle.module, t_one.state.params, prompt,
                 max_new_tokens=6, temperature=0.0)
    )
    np.testing.assert_array_equal(out_mesh, out_one)


@pytest.mark.parametrize(
    "mode",
    ["layers", pytest.param("scan", marks=pytest.mark.slow)],
)
def test_beam_search_one_beam_equals_greedy(mode):
    module, params, prompt = _setup(scan_layers=(mode == "scan"))
    from polyaxon_tpu.models.generate import beam_search

    g = np.asarray(generate(module, params, prompt, max_new_tokens=5,
                            temperature=0.0))
    b1 = np.asarray(beam_search(module, params, prompt, max_new_tokens=5,
                                num_beams=1))
    np.testing.assert_array_equal(g, b1)


@pytest.mark.slow
def test_beam_search_beats_or_ties_greedy_logprob():
    """The point of beam search: the returned sequence's accumulated
    log-prob (scored independently by full re-forward) is >= greedy's."""
    from polyaxon_tpu.models.generate import beam_search

    module, params, prompt = _setup()
    n = 6
    g = np.asarray(generate(module, params, prompt, max_new_tokens=n,
                            temperature=0.0))
    b4 = np.asarray(beam_search(module, params, prompt, max_new_tokens=n,
                                num_beams=4))

    def seq_logprob(toks):
        lp = 0.0
        for i in range(5, toks.shape[0]):
            logits = module.apply(
                {"params": params}, jnp.asarray(toks[None, :i]), train=False
            )
            lsm = np.asarray(
                jax.nn.log_softmax(logits[0, -1].astype(jnp.float32))
            )
            lp += lsm[toks[i]]
        return lp

    for r in range(g.shape[0]):
        assert seq_logprob(b4[r]) >= seq_logprob(g[r]) - 1e-4


def test_beam_search_keeps_finished_hypotheses():
    """A hypothesis that ends in eos must survive in the finished buffer
    even when every live beam out-ranks it on RAW score mid-scan: here the
    eos continuation is never in the raw top-nb at its step, but the live
    paths decay steeply afterwards, so the final length-penalized ranking
    prefers the short finished sequence. The old freeze-in-live scheme
    evicted it at creation and returned a much worse sequence."""
    from types import SimpleNamespace

    from polyaxon_tpu.models.generate import beam_search

    V, EOS = 6, 5
    # rows are true distributions (log_softmax leaves them unchanged up to
    # the tiny -20 mass). Raw scores: the eos continuation [1, eos] lands
    # at -1.45, below BOTH live candidates at its step (-1.25, -1.43), so
    # raw pruning would drop it — but every live path then pays ~0.69 per
    # extra token and finishes near -4, so the finished hyp must win.
    t = np.full((V, V), -20.0, np.float32)
    t[0, 1], t[0, 2] = np.log(0.52), np.log(0.48)
    t[1, EOS], t[1, 2] = np.log(0.45), np.log(0.55)
    t[2, 3], t[2, 4] = np.log(0.5), np.log(0.5)
    t[3, 3], t[3, 4] = np.log(0.5), np.log(0.5)
    t[4, 3], t[4, 4] = np.log(0.5), np.log(0.5)
    table = jnp.asarray(t)

    class TableLM:
        cfg = SimpleNamespace(vocab_size=V, seq_len=16, scan_layers=False)

        def apply(self, variables, tokens, train=False, decode=False,
                  mutable=None):
            logits = table[tokens]
            cache = {"cached_key": jnp.zeros((tokens.shape[0], 1, 1, 1))}
            return (logits, {"cache": cache}) if mutable else logits

    prompt = jnp.zeros((1, 1), jnp.int32)
    out = np.asarray(
        beam_search(TableLM(), {}, prompt, max_new_tokens=6, num_beams=2,
                    length_penalty=0.0, eos_id=EOS)
    )
    assert out[0, 1] == 1 and out[0, 2] == EOS, out


# ---------- ISSUE 8: speculative decoding ----------


def test_spec_generate_byte_identical_greedy():
    """The speculation contract: spec_generate is a drop-in for
    generate() — same tokens, byte for byte, regardless of how many
    drafts were accepted or rolled back along the way."""
    from polyaxon_tpu.models.spec_decode import spec_generate

    module, params, prompt = _setup()
    base = generate(module, params, prompt, max_new_tokens=10,
                    temperature=0.0)
    stats = {}
    out = spec_generate(module, params, prompt, max_new_tokens=10,
                        draft_tokens=4, temperature=0.0, stats=stats)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))
    assert stats["windows"] >= 1 and stats["proposed"] > 0


def test_spec_generate_byte_identical_sampled_bucketed_eos():
    """The serving shape: per-row seeds, LEFT-padded rows of different
    true lengths, eos cutoff — rows accept different window lengths and
    still replay the exact baseline sample stream."""
    from polyaxon_tpu.models.spec_decode import spec_generate

    module, params, prompt = _setup()
    seeds = jnp.asarray([3, 11], jnp.int32)
    lengths = jnp.asarray([5, 3], jnp.int32)
    base = generate(module, params, prompt, max_new_tokens=12,
                    temperature=0.9, top_k=20, eos_id=5, seed=seeds,
                    prompt_lengths=lengths)
    out = spec_generate(module, params, prompt, max_new_tokens=12,
                        draft_tokens=4, temperature=0.9, top_k=20,
                        eos_id=5, seeds=seeds, prompt_lengths=lengths)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))


def test_spec_sampled_requires_per_row_seeds():
    """The scalar-seed stream keys on absolute position and draws one
    batch-wide categorical — not replayable once rows accept different
    lengths, so spec_generate must refuse rather than silently diverge."""
    from polyaxon_tpu.models.spec_decode import spec_generate

    module, params, prompt = _setup()
    with pytest.raises(ValueError, match="per-row seeds"):
        spec_generate(module, params, prompt, max_new_tokens=6,
                      temperature=0.8)


def test_spec_accepts_drafts_on_repetitive_prompt():
    """The n-gram drafter earns its keep on repetitive input: greedy
    decode of a cyclic prompt must accept at least one draft token
    (accept rate strictly positive, not just progress-by-fallback)."""
    from polyaxon_tpu.models.spec_decode import spec_generate

    module, params, _ = _setup()
    prompt = jnp.asarray(
        np.tile(np.arange(1, 9, dtype=np.int32), (2, 4))
    )
    base = generate(module, params, prompt, max_new_tokens=24,
                    temperature=0.0)
    stats = {}
    out = spec_generate(module, params, prompt, max_new_tokens=24,
                        draft_tokens=4, temperature=0.0, stats=stats)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))
    assert stats["accepted"] > 0, stats
    assert stats["rollback"] + stats["accepted"] <= stats["proposed"]


# ---------- ISSUE 8: int8 weight-only quantization ----------


def test_int8_quantize_bytes_and_greedy_agreement():
    """Quantize-on-load must cut the decode-weight footprint by >= 40%
    (int8 kernel + f32 per-channel scale vs the f32 original is ~74%;
    40% is the floor that still holds for bf16 checkpoints) and greedy
    decode through the int8 projections must track the fp model."""
    from polyaxon_tpu.models.quant import decode_weight_bytes, quantize_module

    module, params, prompt = _setup()
    target_fp, total = decode_weight_bytes(params)
    assert 0 < target_fp <= total
    qmodule, qparams, saved = quantize_module(module, params)
    assert saved / target_fp >= 0.40, (saved, target_fp)
    base = np.asarray(
        generate(module, params, prompt, max_new_tokens=8, temperature=0.0)
    )
    q = np.asarray(
        generate(qmodule, qparams, prompt, max_new_tokens=8, temperature=0.0)
    )
    agree = (base[:, 5:] == q[:, 5:]).mean()
    assert agree >= 0.75, f"int8 greedy agreement {agree}"
    # int8 params really are int8 on the wire
    leaves = jax.tree_util.tree_leaves_with_path(qparams)
    kinds = {
        str(p[-1].key): l.dtype
        for p, l in leaves
        if "q_proj" in str(p)
    }
    assert kinds["kernel"] == jnp.int8 and kinds["scale"] == jnp.float32


@pytest.mark.slow
def test_int8_scan_layers_and_spec_compose():
    """scan_layers stacks kernels with a leading layer axis — the
    per-output-channel amax must ignore it; and the quantized module
    must still satisfy the speculative byte-identity contract (verify
    windows run through Int8Dense like any other forward)."""
    from polyaxon_tpu.models.quant import quantize_module
    from polyaxon_tpu.models.spec_decode import spec_generate

    module, params, prompt = _setup(scan_layers=True)
    qmodule, qparams, saved = quantize_module(module, params)
    assert saved > 0
    base = generate(qmodule, qparams, prompt, max_new_tokens=8,
                    temperature=0.0)
    out = spec_generate(qmodule, qparams, prompt, max_new_tokens=8,
                        draft_tokens=4, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))


def test_int8_quantizes_lora_base_keeps_adapters_fp():
    """ISSUE 15 lifted the old reject-LoRA restriction: a LoRA checkpoint
    quantizes its FROZEN base kernel to int8 while the adapter deltas
    (`lora_a`/`lora_b`) stay at checkpoint precision — multi-tenant
    serving can now stack fp adapters on an int8 base. Double-quantize
    must still refuse."""
    from polyaxon_tpu.models.quant import quantize_module

    module, params, prompt = _setup(lora_rank=2, lora_targets=("q_proj",))
    qmodule, qparams, saved = quantize_module(module, params)
    assert saved > 0
    base = np.asarray(
        generate(module, params, prompt, max_new_tokens=8, temperature=0.0)
    )
    q = np.asarray(
        generate(qmodule, qparams, prompt, max_new_tokens=8, temperature=0.0)
    )
    agree = (base[:, 5:] == q[:, 5:]).mean()
    assert agree >= 0.75, f"int8+LoRA greedy agreement {agree}"
    leaves = jax.tree_util.tree_leaves_with_path(qparams)
    kinds = {
        str(p[-1].key): l.dtype
        for p, l in leaves
        if "q_proj" in str(p)
    }
    # base kernel int8 + scale, adapters untouched fp
    assert kinds["kernel"] == jnp.int8 and kinds["scale"] == jnp.float32
    assert kinds["lora_a"] == jnp.float32
    assert kinds["lora_b"] == jnp.float32

    module, params, _ = _setup()
    qmodule, qparams, _ = quantize_module(module, params)
    with pytest.raises(ValueError, match="quant"):
        quantize_module(qmodule, qparams)


# ---------- ISSUE 15: draft-model speculation ----------


def test_draft_model_spec_byte_identity_greedy():
    """A layer-truncated draft model proposes, the target verifies:
    spec_generate stays a byte-identical drop-in for generate() with the
    model drafter exactly as it is with the n-gram drafter."""
    from polyaxon_tpu.models.draft import ModelDrafter, build_draft
    from polyaxon_tpu.models.spec_decode import spec_generate

    module, params, prompt = _setup()
    dmodule, dparams, derived = build_draft(
        module, params, overrides={"n_layers": 1}
    )
    assert derived is True  # same widths → params sliced, not random
    assert dmodule.cfg.n_layers == 1
    base = generate(module, params, prompt, max_new_tokens=12,
                    temperature=0.0)
    drafter = ModelDrafter(
        dmodule, dparams, prompt, [5, 5], seeds=[0, 0],
    )
    stats = {}
    out = spec_generate(module, params, prompt, max_new_tokens=12,
                        draft_tokens=3, temperature=0.0, stats=stats,
                        drafter=drafter)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))
    assert stats["proposed"] > 0 and stats["windows"] >= 1


@pytest.mark.slow
def test_draft_model_spec_byte_identity_sampled_bucketed_eos():
    """The serving shape through the model drafter: per-row seeds,
    LEFT-padded rows, eos cutoff. The drafter replays the target's own
    fold_in(key, g) sample schedule, so sampled streams stay exact."""
    from polyaxon_tpu.models.draft import ModelDrafter, build_draft
    from polyaxon_tpu.models.spec_decode import spec_generate

    module, params, prompt = _setup()
    seeds = jnp.asarray([3, 11], jnp.int32)
    lengths = jnp.asarray([5, 3], jnp.int32)
    base = generate(module, params, prompt, max_new_tokens=12,
                    temperature=0.9, top_k=20, eos_id=5, seed=seeds,
                    prompt_lengths=lengths)
    dmodule, dparams, _ = build_draft(module, params,
                                      overrides={"n_layers": 1})
    drafter = ModelDrafter(
        dmodule, dparams, prompt, [5, 3], seeds=[3, 11],
        temperature=0.9, top_k=20,
    )
    out = spec_generate(module, params, prompt, max_new_tokens=12,
                        draft_tokens=4, temperature=0.9, top_k=20,
                        eos_id=5, seeds=seeds, prompt_lengths=lengths,
                        drafter=drafter)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))


def test_draft_model_random_init_never_changes_bytes():
    """A randomly initialized draft (the no-trained-checkpoint fallback)
    is merely slow — acceptance is exact-match, so outputs cannot
    diverge no matter how bad the proposals are."""
    from polyaxon_tpu.models.draft import (
        ModelDrafter, build_draft, init_draft_params,
    )
    from polyaxon_tpu.models.spec_decode import spec_generate

    module, params, prompt = _setup()
    dmodule, _, _ = build_draft(module, params, overrides={"n_layers": 1})
    dparams = init_draft_params(dmodule, seed=42)
    base = generate(module, params, prompt, max_new_tokens=10,
                    temperature=0.0)
    drafter = ModelDrafter(dmodule, dparams, prompt, [5, 5], seeds=[0, 0])
    out = spec_generate(module, params, prompt, max_new_tokens=10,
                        draft_tokens=3, temperature=0.0, drafter=drafter)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))


def test_draft_config_pins_vocab_and_defaults_depth():
    from polyaxon_tpu.models.draft import build_draft

    module, params, _ = _setup()
    # default draft depth = half the target's layers
    dmodule, _, derived = build_draft(module, params)
    assert dmodule.cfg.n_layers == 1 and derived is True
    with pytest.raises(ValueError, match="tokenizer"):
        build_draft(module, params, overrides={"vocab_size": 64})
    with pytest.raises(ValueError, match="unknown draft config"):
        build_draft(module, params, overrides={"n_lyers": 1})


def test_spec_truncation_corrected_accept_rate():
    """ISSUE 15 satellite: near maxNewTokens the remaining-budget clamp
    truncates an accepted run — the raw committed count deflates while
    accepted_judged keeps counting what the verify forward really
    matched. The two diverge ONLY at that boundary."""
    from polyaxon_tpu.models.spec_decode import commit_window

    # row 0: all 4 drafts judged correct but only 2 tokens of budget
    # left → commits 2, raw accepted 1, judged 4, truncated 3.
    # row 1: plenty of budget, 2 drafts accepted → no truncation.
    fed = np.tile(np.arange(10, 15, dtype=np.int32), (2, 1))
    targets = fed.copy()
    committed, done, remaining, eos_hit, stats = commit_window(
        fed, targets, accept=np.asarray([4, 2]),
        remaining=np.asarray([2, 8]), done=[False, False], eos_id=None,
    )
    assert [len(c) for c in committed] == [2, 3]
    assert stats["proposed"] == 8
    assert stats["accepted"] == 1 + 2
    assert stats["accepted_judged"] == 4 + 2
    assert stats["truncated"] == 3, stats
    assert stats["accepted_judged"] == stats["accepted"] + stats["truncated"]
    raw = stats["accepted"] / stats["proposed"]
    corrected = stats["accepted_judged"] / stats["proposed"]
    assert corrected > raw
    # away from the budget boundary the two rates are THE SAME figure
    _, _, _, _, mid = commit_window(
        fed, targets, accept=np.asarray([4, 2]),
        remaining=np.asarray([8, 8]), done=[False, False], eos_id=None,
    )
    assert mid["truncated"] == 0
    assert mid["accepted_judged"] == mid["accepted"]
