"""Serving resilience (ISSUE 5): bounded queues, deadlines, breaker, drain.

Tested at three levels:
  * pure units — CircuitBreaker state machine and the DecodeCoalescer's
    admission/shedding/watchdog behavior with fake executors (no jax);
  * chaos scenarios — seeded FaultPlans driving the serving.decode /
    serving.worker points through the REAL coalescer + server paths;
  * live HTTP — shed responses (503 + Retry-After), deadline drops (504),
    /readyz flipping during a graceful drain, and queued requests failed
    terminally when the drain budget runs out.

Plus the store durability satellites: fsync'd atomic JSON writes and
quarantine of undecodable files.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from polyaxon_tpu.serving.batching import (
    CircuitBreaker,
    DeadlineExceededError,
    DecodeCoalescer,
    GroupKey,
    PendingRequest,
    ServerClosingError,
    ServingConfig,
    ShedError,
    WorkerCrashError,
)

pytestmark = pytest.mark.serving

REPO = Path(__file__).resolve().parent.parent

KEY = GroupKey(32, 16, 0.8, 40, None)


def _req(key=KEY, plen=3, seed=0, deadline_ms=None):
    deadline = (
        time.monotonic() + deadline_ms / 1e3 if deadline_ms is not None else None
    )
    return PendingRequest(
        tokens=[1] * plen, prompt_len=plen, max_new=4, seed=seed, key=key,
        deadline=deadline,
    )


def _ok_executor(batches=None):
    def execute(batch):
        if batches is not None:
            batches.append(batch)
        for r in batch:
            r.finish(result=list(r.tokens))

    return execute


def _blocking_executor(release: threading.Event, started=None):
    """Holds every batch until `release` is set — a decode in molasses."""

    def execute(batch):
        if started is not None:
            started.set()
        release.wait(10)
        for r in batch:
            r.finish(result=list(r.tokens))

    return execute


# ------------------------------------------------------- circuit breaker
def test_breaker_trips_after_consecutive_failures():
    b = CircuitBreaker(threshold=3, cooldown_s=60)
    for _ in range(2):
        b.record_failure()
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "open" and not b.allow()


def test_breaker_success_resets_the_streak():
    b = CircuitBreaker(threshold=2, cooldown_s=60)
    b.record_failure()
    b.record_success()
    b.record_failure()
    assert b.state == "closed"  # failures were not consecutive


def test_breaker_half_open_probe_and_recovery():
    b = CircuitBreaker(threshold=1, cooldown_s=0.05)
    b.record_failure()
    assert b.state == "open" and not b.allow()
    time.sleep(0.06)
    assert b.allow()  # cooldown elapsed: ONE probe admitted
    assert b.state == "half_open"
    assert not b.allow()  # second caller inside the window: still shed
    b.record_success()
    assert b.state == "closed" and b.allow()


def test_breaker_failed_probe_reopens():
    b = CircuitBreaker(threshold=1, cooldown_s=0.05)
    b.record_failure()
    time.sleep(0.06)
    assert b.allow()
    b.record_failure()  # the probe failed
    assert b.state == "open" and not b.allow()


def test_breaker_unreported_probe_self_heals():
    # a probe that never reports (shed downstream, dropped on deadline)
    # must not wedge the breaker half-open forever
    b = CircuitBreaker(threshold=1, cooldown_s=0.05)
    b.record_failure()
    time.sleep(0.06)
    assert b.allow()  # probe 1 — never reports an outcome
    time.sleep(0.06)
    assert b.allow()  # one cooldown later another probe is admitted


def test_breaker_disabled_by_nonpositive_threshold():
    b = CircuitBreaker(threshold=0)
    for _ in range(10):
        b.record_failure()
    assert b.state == "closed" and b.allow()


def test_breaker_reports_state_changes():
    codes = []
    b = CircuitBreaker(threshold=1, cooldown_s=0.05, on_change=codes.append)
    b.record_failure()
    time.sleep(0.06)
    b.allow()
    b.record_success()
    assert codes == [1, 2, 0]  # open, half_open, closed


# --------------------------------------------------- admission / shedding
def test_coalescer_sheds_at_max_queue():
    release = threading.Event()
    c = DecodeCoalescer(
        _blocking_executor(release), max_batch=1, max_wait_ms=0, max_queue=2
    )
    r1, r2 = _req(seed=1), _req(seed=2)
    c.submit(r1)
    c.submit(r2)
    with pytest.raises(ShedError) as ei:
        c.submit(_req(seed=3))
    assert ei.value.reason == "queue_full"
    assert c.shed_total == 1 and c.depth == 2
    release.set()
    c.start()
    assert r1.done.wait(10) and r2.done.wait(10)
    c.stop()


def test_coalescer_sheds_expired_at_admission():
    c = DecodeCoalescer(_ok_executor(), max_batch=4, max_wait_ms=0)
    with pytest.raises(ShedError) as ei:
        c.submit(_req(deadline_ms=-1.0))  # already past
    assert ei.value.reason == "deadline"
    assert c.depth == 0  # never admitted


def test_coalescer_drops_expired_before_dispatch():
    # worker is wedged on group 1; a short-deadline request queued behind
    # it must be dropped WITHOUT spending a decode slot
    release = threading.Event()
    started = threading.Event()
    batches = []

    def execute(batch):
        batches.append([r.seed for r in batch])
        started.set()
        release.wait(10)
        for r in batch:
            r.finish(result=list(r.tokens))

    c = DecodeCoalescer(execute, max_batch=1, max_wait_ms=0)
    c.start()
    r1 = _req(seed=1)
    c.submit(r1)
    assert started.wait(10)
    r2 = _req(seed=2, deadline_ms=30.0)
    c.submit(r2)
    time.sleep(0.08)  # r2's deadline passes while the worker is wedged
    release.set()
    assert r1.done.wait(10) and r2.done.wait(10)
    c.stop()
    assert r1.result is not None
    assert isinstance(r2.error, DeadlineExceededError)
    assert batches == [[1]]  # r2 never reached the executor
    assert c.deadline_dropped == 1


def test_coalescer_evicts_expired_nonhead_while_coalescing():
    # ISSUE 14 regression: the coalesce wait used to be computed from the
    # HEAD row only, so a short-deadline row queued behind a deadline-less
    # head sat out the head's whole max_wait before its 504. The wait must
    # be capped at the earliest pending deadline: the non-head row fails
    # fast, spends no step tokens, and the head is NOT dispatched early.
    batches = []

    def execute(batch):
        batches.append([r.seed for r in batch])
        for r in batch:
            r.finish(result=list(r.tokens))

    c = DecodeCoalescer(execute, max_batch=4, max_wait_ms=1500.0)
    c.start()
    r1 = _req(seed=1)  # head: no deadline, coalescing for up to 1.5s
    c.submit(r1)
    time.sleep(0.02)
    r2 = _req(seed=2, deadline_ms=40.0)  # non-head, expires mid-coalesce
    c.submit(r2)
    assert r2.done.wait(0.75), "non-head row waited out the head's max_wait"
    assert isinstance(r2.error, DeadlineExceededError)
    assert c.deadline_dropped == 1
    # eviction must not have flushed the head before ITS max_wait
    assert not r1.done.is_set() and batches == []
    c.stop(drain_s=5.0)  # drain wakes the coalesce wait and flushes the head
    assert r1.done.is_set() and r1.result is not None
    assert batches == [[1]]  # r2 never reached the executor


def test_coalescer_breaker_opens_then_recovers():
    fail = {"n": 3}

    def execute(batch):
        if fail["n"] > 0:
            fail["n"] -= 1
            raise RuntimeError("decode outage")
        for r in batch:
            r.finish(result=list(r.tokens))

    breaker = CircuitBreaker(threshold=3, cooldown_s=0.05)
    c = DecodeCoalescer(execute, max_batch=1, max_wait_ms=0, breaker=breaker)
    c.start()
    for i in range(3):
        r = _req(seed=i)
        c.submit(r)
        assert r.done.wait(10)
        assert "outage" in str(r.error)
    assert breaker.state == "open"
    with pytest.raises(ShedError) as ei:
        c.submit(_req(seed=99))
    assert ei.value.reason == "breaker_open"
    assert ei.value.retry_after_s >= 1.0
    time.sleep(0.06)  # cooldown: next submit is the half-open probe
    probe = _req(seed=100)
    c.submit(probe)
    assert probe.done.wait(10)
    assert probe.result is not None
    assert breaker.state == "closed"
    c.stop()


def test_coalescer_watchdog_restarts_crashed_worker():
    from polyaxon_tpu.chaos.injector import active
    from polyaxon_tpu.chaos.plan import FaultPlan

    plan = FaultPlan.serving_worker_crash(seed=11, window=1)
    assert plan.params["crash_hit"] == 0
    c = DecodeCoalescer(_ok_executor(), max_batch=1, max_wait_ms=0)
    c.start()
    with active(plan):
        r1 = _req(seed=1)
        c.submit(r1)
        assert r1.done.wait(10)
        # the in-flight group failed FAST, not via request_timeout_s
        assert isinstance(r1.error, WorkerCrashError)
        # the restarted worker serves the next request normally
        r2 = _req(seed=2)
        c.submit(r2)
        assert r2.done.wait(10)
    c.stop()
    assert r2.result is not None
    assert c.worker_restarts == 1


def test_coalescer_drain_flushes_then_stop_fails_leftovers():
    release = threading.Event()
    started = threading.Event()
    c = DecodeCoalescer(
        _blocking_executor(release, started), max_batch=1, max_wait_ms=0
    )
    c.start()
    r1, r2 = _req(seed=1), _req(seed=2)
    c.submit(r1)
    assert started.wait(10)
    c.submit(r2)  # queued behind the wedged group
    t = threading.Thread(target=c.stop, kwargs={"drain_s": 0.15}, daemon=True)
    t.start()
    time.sleep(0.02)
    with pytest.raises(ServerClosingError):
        c.submit(_req(seed=3))  # admission closed the moment drain began
    time.sleep(0.2)  # let the drain budget lapse
    release.set()
    t.join(10)
    assert r1.done.is_set() and r2.done.is_set()
    assert r1.result is not None  # in-flight work finished
    # r2 missed the budget: terminal close, NOT a request_timeout_s hang
    assert isinstance(r2.error, ServerClosingError)
    assert c.idle


def test_coalescer_drain_with_budget_completes_everything():
    # 3 same-key rows in a max_batch=4 coalescer: a PARTIAL batch, which
    # normally sits out the 1s straggler window — draining flushes it
    c = DecodeCoalescer(_ok_executor(), max_batch=4, max_wait_ms=1000)
    c.start()
    rows = [_req(seed=i) for i in range(3)]
    for r in rows:
        c.submit(r)
    t0 = time.monotonic()
    c.stop(drain_s=5.0)
    assert time.monotonic() - t0 < 2.0
    assert all(r.result is not None for r in rows)


# ----------------------------------------------------------- chaos plans
@pytest.mark.chaos
def test_serving_fault_plans_are_seed_deterministic():
    from polyaxon_tpu.chaos.plan import FaultPlan

    for ctor, kwargs in (
        (FaultPlan.serving_flaky_decode, {"window": 20, "fails": 3}),
        (FaultPlan.serving_decode_outage, {"window": 20, "fails": 5}),
        (FaultPlan.serving_worker_crash, {"window": 20}),
        (FaultPlan.serving_brownout, {"window": 20, "slow": 2}),
    ):
        a = ctor(seed=7, **kwargs)
        b = ctor(seed=7, **kwargs)
        other = ctor(seed=8, **kwargs)
        assert a.params == b.params, ctor.__name__
        assert [vars(f) for f in a.faults] == [vars(f) for f in b.faults]
        assert a.params != other.params or a.seed != other.seed


@pytest.mark.chaos
def test_brownout_plan_sleeps_at_the_injection_site():
    from polyaxon_tpu.chaos.injector import active, inject
    from polyaxon_tpu.chaos.plan import FaultPlan

    plan = FaultPlan.serving_brownout(seed=3, window=4, slow=1, delay_ms=60.0)
    hit = plan.params["slow_start"]
    with active(plan):
        for i in range(4):
            t0 = time.monotonic()
            inject("serving.slow", rows=1)
            dt = time.monotonic() - t0
            if i == hit:
                assert dt >= 0.05, f"hit {i} did not stall ({dt * 1e3:.1f}ms)"
            else:
                assert dt < 0.05, f"hit {i} stalled unexpectedly"


# ------------------------------------------------------------- live HTTP
def _tiny_server(**cfg_overrides):
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.serving.server import ModelServer

    model_cfg = {
        "preset": "tiny", "seq_len": 64, "n_layers": 1, "dim": 32,
        "n_heads": 2, "n_kv_heads": 2, "vocab_size": 128,
    }
    bundle = build_model("transformer_lm", model_cfg)
    params = bundle.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    cfg = dict(max_batch=2, max_wait_ms=2.0, request_timeout_s=30.0)
    cfg.update(cfg_overrides)
    return ModelServer(
        bundle.module, params, model_name="resilience-test",
        config=ServingConfig(**cfg),
    )


def _post(port, body, timeout=30.0):
    """(status, payload, headers) — HTTP errors returned, not raised."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _get(port, path, timeout=10.0):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


BODY = {"tokens": [[1, 2, 3]], "maxNewTokens": 4, "temperature": 0.8,
        "topK": 10, "seed": 0}


def test_http_shed_maps_to_503_with_retry_after():
    server = _tiny_server(max_queue=1, max_batch=1, max_wait_ms=0)
    release = threading.Event()
    started = threading.Event()
    server._coalescer._execute = _blocking_executor(release, started)
    port = server.start(port=0)
    try:
        bg = threading.Thread(
            target=_post, args=(port, BODY), daemon=True
        )
        bg.start()
        assert started.wait(10)  # group 1 occupies the single slot...
        # ...but depth is 0 again once in-flight resolves, so wedge depth
        # by submitting while blocked: in-flight counts toward max_queue
        code, payload, headers = _post(port, BODY)
        assert code == 503
        assert payload["reason"] == "queue_full"
        assert int(headers["Retry-After"]) >= 1
        # the shed surfaced on /metricsz through the one pipeline
        _, text = _get(port, "/metricsz")
        assert "serving_shed_total 1" in text
        release.set()
        bg.join(10)
    finally:
        release.set()
        server.stop(drain_grace_s=0.5)


def test_http_expired_deadline_maps_to_504():
    server = _tiny_server(max_batch=1, max_wait_ms=0, max_queue=8)
    release = threading.Event()
    started = threading.Event()
    server._coalescer._execute = _blocking_executor(release, started)
    port = server.start(port=0)
    try:
        bg = threading.Thread(target=_post, args=(port, BODY), daemon=True)
        bg.start()
        assert started.wait(10)
        # queued behind the wedge with a 50ms budget: dropped, not decoded
        results = []
        t = threading.Thread(target=lambda: results.append(
            _post(port, {**BODY, "deadlineMs": 50.0})), daemon=True)
        t.start()
        time.sleep(0.15)
        release.set()
        t.join(10)
        bg.join(10)
        code, payload, _ = results[0]
        assert code == 504
        assert payload["reason"] == "deadline_exceeded"
        _, text = _get(port, "/metricsz")
        assert "serving_deadline_exceeded_total 1" in text
    finally:
        release.set()
        server.stop(drain_grace_s=0.5)


def test_http_already_expired_deadline_sheds_503():
    server = _tiny_server()
    port = server.start(port=0)
    try:
        code, payload, headers = _post(port, {**BODY, "deadlineMs": 1e-6})
        assert code == 503
        assert payload["reason"] == "deadline"
        assert "Retry-After" in headers
    finally:
        server.stop(drain_grace_s=0.5)


def test_http_invalid_deadline_is_400():
    server = _tiny_server()
    port = server.start(port=0)
    try:
        code, payload, _ = _post(port, {**BODY, "deadlineMs": -5})
        assert code == 400
        assert "deadlineMs" in payload["error"]
    finally:
        server.stop(drain_grace_s=0.5)


@pytest.mark.chaos
def test_http_decode_outage_trips_breaker_then_recovers():
    from polyaxon_tpu.chaos.injector import active
    from polyaxon_tpu.chaos.plan import FaultPlan

    # cooldown generous enough that HTTP roundtrip jitter cannot flip the
    # breaker half-open before the shed assertion runs
    server = _tiny_server(
        max_batch=1, max_wait_ms=0,
        breaker_threshold=2, breaker_cooldown_s=0.5,
    )
    port = server.start(port=0)
    try:
        # warm the compile OUTSIDE the outage so chaos hits decode, not XLA
        code, _, _ = _post(port, BODY, timeout=120.0)
        assert code == 200
        plan = FaultPlan.serving_decode_outage(seed=5, window=2, fails=2)
        assert plan.params == {"outage_start": 0, "outage_len": 2}
        with active(plan):
            for _ in range(2):  # the outage: chaos raises inside decode
                code, _, _ = _post(port, BODY)
                assert code == 500
            # 2 consecutive failures tripped the threshold-2 breaker
            code, payload, _ = _post(port, BODY)
            assert code == 503 and payload["reason"] == "breaker_open"
            _, text = _get(port, "/metricsz")
            assert "serving_breaker_state 1" in text
            time.sleep(0.6)  # cooldown: the next request is the probe
            code, _, _ = _post(port, BODY)
            assert code == 200  # outage spent; probe succeeds
        _, text = _get(port, "/metricsz")
        assert "serving_breaker_state 0" in text
        stats = json.loads(_get(port, "/statsz")[1])
        assert stats["breaker"] == "closed"
    finally:
        server.stop(drain_grace_s=0.5)


def test_http_graceful_drain_readyz_and_inflight():
    server = _tiny_server(max_batch=1, max_wait_ms=0, drain_grace_s=5.0)
    release = threading.Event()
    started = threading.Event()
    server._coalescer._execute = _blocking_executor(release, started)
    port = server.start(port=0)
    code, body = _get(port, "/readyz")
    assert code == 200 and json.loads(body)["ready"] is True

    results = []
    bg = threading.Thread(
        target=lambda: results.append(_post(port, BODY)), daemon=True
    )
    bg.start()
    assert started.wait(10)
    stopper = threading.Thread(target=server.stop, daemon=True)
    stopper.start()
    time.sleep(0.1)  # stop() has begun draining; httpd still answers
    code, body = _get(port, "/readyz")
    assert code == 503 and json.loads(body)["ready"] is False
    code, payload, _ = _post(port, BODY)
    # shed at admission mid-drain: "draining" (never queued, retryable
    # elsewhere) — "closing" is reserved for queued requests failed
    # terminally when the drain budget expires
    assert code == 503 and payload["reason"] == "draining"
    release.set()  # let the in-flight request finish inside the budget
    bg.join(10)
    stopper.join(10)
    code, payload, _ = results[0]
    assert code == 200 and payload["tokens"]


def test_http_drain_budget_fails_queued_terminally():
    server = _tiny_server(max_batch=1, max_wait_ms=0, drain_grace_s=0.05)
    release = threading.Event()
    started = threading.Event()
    server._coalescer._execute = _blocking_executor(release, started)
    port = server.start(port=0)
    results = []

    def fire():
        results.append(_post(port, BODY))

    t1 = threading.Thread(target=fire, daemon=True)
    t1.start()
    assert started.wait(10)
    t2 = threading.Thread(target=fire, daemon=True)  # queued behind wedge
    t2.start()
    time.sleep(0.1)
    stopper = threading.Thread(target=server.stop, daemon=True)
    stopper.start()
    time.sleep(0.2)  # budget (50ms) lapses with the worker still wedged
    release.set()
    t1.join(10)
    t2.join(10)
    stopper.join(10)
    codes = sorted(r[0] for r in results)
    # the wedged group finishes (200); the queued one is failed with a
    # terminal 503, NOT left to hang out request_timeout_s
    assert codes == [200, 503], results


def test_readiness_reflects_device_regression():
    server = _tiny_server()
    server.expected_devices = 9999  # conftest pins 8 fake CPU devices
    port = server.start(port=0)
    try:
        code, body = _get(port, "/readyz")
        assert code == 503
        assert "degraded slice" in json.loads(body)["reason"]
        _, text = _get(port, "/metricsz")
        assert "serving_ready 0" in text
    finally:
        server.stop(drain_grace_s=0.5)
    server2 = _tiny_server()
    server2.expected_devices = 8
    port = server2.start(port=0)
    try:
        code, body = _get(port, "/readyz")
        assert code == 200 and json.loads(body)["ready"] is True
    finally:
        server2.stop(drain_grace_s=0.5)


# ------------------------------------------------------------ spec schema
def test_serving_spec_resilience_fields_roundtrip():
    from polyaxon_tpu.schemas.run_kinds import V1ServingSpec

    spec = V1ServingSpec.model_validate({
        "maxQueue": 16, "defaultDeadlineMs": 250.0,
        "drainGraceS": 2.0, "breakerThreshold": 3,
    })
    cfg = spec.to_config()
    assert cfg.max_queue == 16
    assert cfg.default_deadline_ms == 250.0
    assert cfg.drain_grace_s == 2.0
    assert cfg.breaker_threshold == 3
    # defaults flow through untouched
    assert V1ServingSpec().to_config().max_queue == 64


@pytest.mark.parametrize("field,value", [
    ("maxQueue", 0),
    ("breakerThreshold", 0),
    ("defaultDeadlineMs", -1.0),
    ("drainGraceS", -0.5),
])
def test_serving_spec_rejects_bad_resilience_values(field, value):
    from pydantic import ValidationError

    from polyaxon_tpu.schemas.run_kinds import V1ServingSpec

    with pytest.raises(ValidationError):
        V1ServingSpec.model_validate({field: value})


def test_from_run_overrides_layer_over_spec_pins(tmp_home, tmp_path):
    # `polyaxon serve --max-queue 2` against a run whose spec pins
    # maxBatch must override ONLY max_queue — resetting the spec's other
    # pins to library defaults is the bug this guards against
    import jax
    import yaml

    from polyaxon_tpu.compiler import compile_operation
    from polyaxon_tpu.polyaxonfile import read_polyaxonfile
    from polyaxon_tpu.runtime import Executor
    from polyaxon_tpu.runtime.checkpoint import close_all
    from polyaxon_tpu.serving import ModelServer
    from polyaxon_tpu.store import RunStore

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "pinned-serving",
        "component": {
            "kind": "component",
            "name": "pinned-serving",
            "run": {
                "kind": "jaxjob",
                "program": {
                    "model": {
                        "name": "transformer_lm",
                        "config": {
                            "preset": "tiny", "seq_len": 32, "n_layers": 1,
                            "dim": 32, "n_heads": 4, "n_kv_heads": 2,
                            "vocab_size": 64,
                        },
                    },
                    "data": {
                        "name": "synthetic_lm", "batchSize": 4,
                        "config": {"seq_len": 32, "vocab_size": 64},
                    },
                    "optimizer": {"name": "adamw", "learningRate": 0.001},
                    "train": {
                        "steps": 1, "logEvery": 1, "precision": "float32",
                        "checkpointEvery": 1,
                    },
                    "serving": {
                        "maxBatch": 3, "maxWaitMs": 7.0, "maxQueue": 11,
                        "breakerThreshold": 4,
                    },
                },
            },
        },
    }
    p = tmp_path / "pinned.yaml"
    p.write_text(yaml.safe_dump(spec))
    store = RunStore()
    compiled = compile_operation(read_polyaxonfile(str(p)))
    assert Executor(store, devices=jax.devices()[:1]).execute(compiled) == (
        "succeeded"
    )
    close_all()

    server = ModelServer.from_run(
        compiled.run_uuid, store=store,
        config_overrides={"max_queue": 2, "default_deadline_ms": 123.0},
    )
    assert server.config.max_queue == 2            # overridden
    assert server.config.default_deadline_ms == 123.0
    assert server.config.max_batch == 3            # spec pins survive
    assert server.config.max_wait_ms == 7.0
    assert server.config.breaker_threshold == 4

    # and with no overrides the spec config is used verbatim
    server2 = ModelServer.from_run(compiled.run_uuid, store=store)
    assert server2.config.max_queue == 11


# -------------------------------------------------------- store satellites
def test_write_json_survives_and_is_atomic(tmp_path):
    from polyaxon_tpu.store.local import _read_json, _write_json

    p = tmp_path / "status.json"
    _write_json(p, {"status": "running", "n": 1})
    assert _read_json(p) == {"status": "running", "n": 1}
    assert not p.with_suffix(".tmp").exists()  # no droppings
    _write_json(p, {"status": "succeeded", "n": 2})
    assert _read_json(p)["status"] == "succeeded"


def test_read_json_quarantines_corrupt_file(tmp_path, caplog):
    import logging

    from polyaxon_tpu.store.local import _read_json

    p = tmp_path / "status.json"
    p.write_text('{"status": "runni')  # torn write
    with caplog.at_level(logging.WARNING, logger="polyaxon_tpu.store.local"):
        assert _read_json(p) is None
    assert not p.exists()
    quarantined = tmp_path / "status.json.corrupt"
    assert quarantined.exists()
    assert quarantined.read_text() == '{"status": "runni'  # bytes preserved
    assert any("quarantined" in r.getMessage() for r in caplog.records)
    # a fresh status can now be written over the vacated name
    assert _read_json(p) is None


def test_read_json_quarantine_shields_run_status(tmp_home):
    # end to end: a torn status.json must not wedge get_status
    from polyaxon_tpu.store.local import RunStore

    store = RunStore()
    store.create_run("u1" * 16, "torn", "proj", {"component": {"name": "x"}})
    uuid = "u1" * 16
    (store.run_dir(uuid) / "status.json").write_text("\x00garbage\x00")
    status = store.get_status(uuid)  # would raise before the quarantine
    assert status == {}
    assert (store.run_dir(uuid) / "status.json.corrupt").exists()


# ------------------------------------------------------- bench smoke (CI)
def test_overload_bench_smoke(tmp_path):
    import subprocess
    import sys

    out = tmp_path / "metricsz.txt"
    proc = subprocess.run(
        [sys.executable, str(REPO / "benchmarks/serving_overload_bench.py"),
         "--smoke", "--requests", "24", "--metricsz-out", str(out)],
        capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "serving_overload_goodput"
    assert rec["pass"] is True
    assert rec["hung"] == 0
    assert rec["shed_503"] + rec["deadline_504"] > 0
    text = out.read_text()
    for series in ("serving_shed_total", "serving_deadline_exceeded_total",
                   "serving_breaker_state", "serving_ready"):
        assert series in text, f"missing {series} on /metricsz"
