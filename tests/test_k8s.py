"""K8s converter golden tests — render manifests for each run kind and
assert structure, exactly the reference's no-cluster multi-node test
strategy (SURVEY.md §4: assert the rendered job has N replicas and the
right env, not that training runs)."""

import pytest
import yaml

from polyaxon_tpu.compiler.resolver import compile_operation
from polyaxon_tpu.connections.schemas import ConnectionCatalog
from polyaxon_tpu.k8s import ConversionError, convert_operation
from polyaxon_tpu.polyaxonfile.reader import read_polyaxonfile


def _compile(tmp_path, spec, params=None):
    p = tmp_path / "op.yaml"
    p.write_text(yaml.safe_dump(spec))
    return compile_operation(read_polyaxonfile(str(p), params=params))


JAXJOB_SPEC = {
    "version": 1.1,
    "kind": "operation",
    "name": "bert-pretrain",
    "component": {
        "kind": "component",
        "name": "bert",
        "run": {
            "kind": "jaxjob",
            "replicas": 8,
            "mesh": {"data": -1},
            "program": {
                "model": {"name": "bert", "config": {"preset": "tiny-test"}},
                "data": {"name": "synthetic_mlm", "batchSize": 32},
                "train": {"steps": 10},
            },
            "environment": {
                "resources": {"tpu": {"type": "v5e", "topology": "4x8"}},
                "labels": {"team": "ml"},
            },
        },
        "termination": {"maxRetries": 2, "timeout": 3600},
    },
}


def test_jaxjob_renders_tpu_topology(tmp_path, tmp_home):
    compiled = _compile(tmp_path, JAXJOB_SPEC)
    service, job = convert_operation(compiled)

    assert service["kind"] == "Service"
    assert service["spec"]["clusterIP"] == "None"  # headless rendezvous

    assert job["kind"] == "Job"
    spec = job["spec"]
    # v5e 4x8 = 32 chips / 4 per host = 8 indexed pods
    assert spec["completionMode"] == "Indexed"
    assert spec["completions"] == 8
    assert spec["parallelism"] == 8
    assert spec["backoffLimit"] == 2
    assert spec["activeDeadlineSeconds"] == 3600

    pod = spec["template"]["spec"]
    sel = pod["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
    assert sel["cloud.google.com/gke-tpu-topology"] == "4x8"

    main = pod["containers"][0]
    assert main["resources"]["limits"]["google.com/tpu"] == "4"
    env = {e["name"]: e for e in main["env"]}
    assert env["JAX_NUM_PROCESSES"]["value"] == "8"
    assert "job-completion-index" in str(env["JOB_COMPLETION_INDEX"]["valueFrom"])
    assert env["POLYAXON_RUN_UUID"]["value"] == compiled.run_uuid
    # gang launcher drives the worker, deriving each worker's global rank
    # from the pod's completion index
    assert main["command"] == ["polyaxon-launcher"]
    assert "--process-id-offset" in main["args"]
    assert main["args"][main["args"].index("--total-processes") + 1] == "8"

    names = [c["name"] for c in pod["containers"]]
    assert "polyaxon-sidecar" in names
    assert job["metadata"]["labels"]["team"] == "ml"


def test_job_kind_renders_batch_job(tmp_path, tmp_home):
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "prep",
        "component": {
            "kind": "component",
            "name": "prep",
            "run": {
                "kind": "job",
                "container": {
                    "image": "python:3.11",
                    "command": ["python", "prep.py"],
                    "env": {"MODE": "full"},
                },
            },
        },
    }
    compiled = _compile(tmp_path, spec)
    (job,) = convert_operation(compiled)
    main = job["spec"]["template"]["spec"]["containers"][0]
    assert main["image"] == "python:3.11"
    assert main["command"] == ["python", "prep.py"]
    assert {"name": "MODE", "value": "full"} in main["env"]
    assert "nodeSelector" not in job["spec"]["template"]["spec"]


def test_service_kind_renders_deployment_and_service(tmp_path, tmp_home):
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "tboard",
        "component": {
            "kind": "component",
            "name": "tboard",
            "run": {
                "kind": "service",
                "replicas": 2,
                "ports": [6006],
                "container": {
                    "image": "tensorflow/tensorflow",
                    "command": ["tensorboard"],
                },
            },
        },
    }
    compiled = _compile(tmp_path, spec)
    deployment, service = convert_operation(compiled)
    assert deployment["kind"] == "Deployment"
    assert deployment["spec"]["replicas"] == 2
    assert service["spec"]["ports"] == [{"port": 6006}]


def test_connections_mount(tmp_path, tmp_home):
    spec = yaml.safe_load(yaml.safe_dump(JAXJOB_SPEC))
    spec["component"]["run"]["connections"] = ["datasets"]
    compiled = _compile(tmp_path, spec)
    catalog = ConnectionCatalog.from_config(
        [
            {
                "name": "datasets",
                "spec": {
                    "kind": "host_path",
                    "hostPath": "/mnt/data",
                    "mountPath": "/data",
                    "readOnly": True,
                },
            }
        ]
    )
    _, job = convert_operation(compiled, catalog)
    pod = job["spec"]["template"]["spec"]
    vols = {v["name"]: v for v in pod["volumes"]}
    assert vols["conn-datasets"]["hostPath"]["path"] == "/mnt/data"
    mounts = {m["name"]: m for m in pod["containers"][0]["volumeMounts"]}
    assert mounts["conn-datasets"]["mountPath"] == "/data"
    assert mounts["conn-datasets"]["readOnly"] is True


def test_unknown_connection_raises(tmp_path, tmp_home):
    spec = yaml.safe_load(yaml.safe_dump(JAXJOB_SPEC))
    spec["component"]["run"]["connections"] = ["ghost"]
    compiled = _compile(tmp_path, spec)
    with pytest.raises((ConversionError, KeyError)):
        convert_operation(compiled, ConnectionCatalog())


def test_dag_kind_not_convertible(tmp_path, tmp_home):
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "d",
        "component": {
            "kind": "component",
            "name": "d",
            "run": {"kind": "dag", "operations": []},
        },
    }
    compiled = _compile(tmp_path, spec)
    with pytest.raises(ConversionError):
        convert_operation(compiled)


def test_jaxjob_multislice_renders_one_job_per_slice(tmp_path, tmp_home):
    """tpu slices: 2 -> one gang Job per slice sharing the headless service,
    slice-offset ranks, gang size across all slices, megascale env."""
    import copy

    spec = copy.deepcopy(JAXJOB_SPEC)
    spec["component"]["run"]["environment"]["resources"]["tpu"]["slices"] = 2
    spec["component"]["run"]["mesh"] = {"data": -1, "model": 2}
    compiled = _compile(tmp_path, spec)
    service, *jobs = convert_operation(compiled)

    assert service["kind"] == "Service"
    assert len(jobs) == 2
    assert [j["metadata"]["name"] for j in jobs] == [
        "bert-pretrain-s0",
        "bert-pretrain-s1",
    ]
    for slice_id, job in enumerate(jobs):
        spec_ = job["spec"]
        assert spec_["completions"] == 8  # hosts PER SLICE
        assert job["metadata"]["labels"]["polyaxon/slice"] == str(slice_id)
        main = spec_["template"]["spec"]["containers"][0]
        env = {e["name"]: e.get("value") for e in main["env"]}
        # gang spans both slices; ranks offset by slice base
        assert env["JAX_NUM_PROCESSES"] == "16"
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env["MEGASCALE_SLICE_ID"] == str(slice_id)
        # megascale gets an explicit pinned port (coordinator+1), exposed
        # on the container and the headless service — libtpu's built-in
        # default is not contractual across versions
        assert env["MEGASCALE_COORDINATOR_ADDRESS"].endswith(":12356")
        port_names = {p["name"]: p["containerPort"] for p in main["ports"]}
        assert port_names["megascale"] == 12356
        svc_ports = {p["name"]: p["port"] for p in service["spec"]["ports"]}
        assert svc_ports["megascale"] == 12356
        args = main["args"]
        assert "--total-processes" in args
        assert args[args.index("--total-processes") + 1] == "16"
        if slice_id:
            assert args[args.index("--process-id-base") + 1] == "8"
        # every slice rendezvouses at slice 0's pod 0
        assert env["JAX_COORDINATOR_ADDRESS"].startswith("bert-pretrain-s0-0.")
