"""Cluster observability plane (ISSUE 13): the shared Prometheus parser,
the federation renderer, event-log run timelines, and the surfaces that
expose them (streams `/metricsz` + `/runs/<uuid>/timeline`,
`polyaxon timeline`, `polyaxon top --once`).

The live router-side pieces (trace stitching, federated router
/metricsz on a real 2-replica rig) live in tests/test_router.py — this
file covers the pure transforms and the store/streams/CLI surfaces,
none of which need a model.
"""

import io
import json
import math
import urllib.error
import urllib.request

import pytest

from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.store.local import RunStore
from polyaxon_tpu.store.timeline import fold_timeline
from polyaxon_tpu.telemetry.federate import (
    federate,
    parse_prometheus_text,
    queue_wait_delta_ms,
    render_sample,
    sum_values,
)

RUN = "feedfacefeedface"


# ------------------------------------------------------------- parser


def test_parse_basic_names_values_and_flat():
    snap = parse_prometheus_text(
        "# HELP serving_queue_depth rows waiting\n"
        "# TYPE serving_queue_depth gauge\n"
        "serving_queue_depth 3\n"
        "serving_requests_total 120\n"
        "serving_latency_seconds_sum 1.5 1712345678\n"  # timestamp ignored\n
        "not a metric line at all\n"
    )
    assert snap.flat() == {
        "serving_queue_depth": 3.0,
        "serving_requests_total": 120.0,
        "serving_latency_seconds_sum": 1.5,
    }
    assert snap.names() == [
        "serving_queue_depth",
        "serving_requests_total",
        "serving_latency_seconds_sum",
    ]
    assert snap.types["serving_queue_depth"] == "gauge"
    assert len(snap) == 3


def test_parse_labels_histogram_components_and_special_values():
    snap = parse_prometheus_text(
        'serving_latency_seconds_bucket{le="0.1"} 4\n'
        'serving_latency_seconds_bucket{le="+Inf"} 9\n'
        "serving_latency_seconds_sum 0.42\n"
        "serving_latency_seconds_count 9\n"
        "weird_gauge NaN\n"
        "hot_gauge +Inf\n"
    )
    assert snap.value("serving_latency_seconds_bucket", le="0.1") == 4.0
    assert snap.value("serving_latency_seconds_bucket", le="+Inf") == 9.0
    assert math.isnan(snap.value("weird_gauge"))
    assert snap.value("hot_gauge") == float("inf")
    # labeled series never leak into the legacy flat view
    assert "serving_latency_seconds_bucket" not in snap.flat()


def test_parse_label_escapes_roundtrip():
    labels = {"path": 'a\\b"c\nd', "slug": "r0"}
    line = render_sample("fs_ops_total", labels, 7)
    snap = parse_prometheus_text(line + "\n")
    assert snap.get("fs_ops_total", path='a\\b"c\nd', slug="r0") == 7.0
    # superset match: fewer constraints still hit the same sample
    assert snap.get("fs_ops_total", slug="r0") == 7.0
    # mismatched label value misses -> default
    assert snap.get("fs_ops_total", 42.0, slug="r1") == 42.0


def test_render_sample_int_and_sorted_labels():
    assert render_sample("x_total", {}, 5.0) == "x_total 5"
    assert (
        render_sample("x", {"b": "2", "a": "1"}, 0.5)
        == 'x{a="1",b="2"} 0.5'
    )


def test_queue_wait_delta_ms():
    snap = parse_prometheus_text(
        "serving_queue_wait_seconds_sum 0.9\n"
        "serving_queue_wait_seconds_count 30\n"
    )
    # 10 new observations, 0.5s of new wait -> 50 ms mean
    delta, wsum, wcount = queue_wait_delta_ms(snap, 0.4, 20.0)
    assert (delta, wsum, wcount) == (50.0, 0.9, 30.0)
    # no new observation: None, caller keeps its EWMA
    delta, _, _ = queue_wait_delta_ms(snap, 0.9, 30.0)
    assert delta is None


# ----------------------------------------------------------- federate


def _replica_text(depth, requests):
    return (
        "# TYPE serving_queue_depth gauge\n"
        "# TYPE serving_requests_total counter\n"
        f"serving_queue_depth {depth}\n"
        f"serving_requests_total {requests}\n"
    )


def test_federate_relabels_and_aggregates():
    text = federate(
        [("r0", _replica_text(2, 10)), ("r1", _replica_text(3, 5))],
        label="replica",
        local_text="router_requests_total 15\n",
    )
    snap = parse_prometheus_text(text)
    # local series pass through verbatim (no replica label)
    assert snap.get("router_requests_total") == 15.0
    # every replica series carries its identity label
    assert snap.get("serving_queue_depth", replica="r0") == 2.0
    assert snap.get("serving_queue_depth", replica="r1") == 3.0
    assert snap.get("federation_source_up", replica="r0") == 1.0
    # cluster rollups: sum for everything, max only for gauge-shaped
    assert snap.get("cluster:serving_queue_depth:sum") == 5.0
    assert snap.get("cluster:serving_queue_depth:max") == 3.0
    assert snap.get("cluster:serving_requests_total:sum") == 15.0
    assert snap.get("cluster:serving_requests_total:max") is None


def test_federate_dead_source_is_visible_not_silent():
    text = federate(
        [("r0", _replica_text(1, 1)), ("r1", None)], label="replica"
    )
    snap = parse_prometheus_text(text)
    assert snap.get("federation_source_up", replica="r0") == 1.0
    assert snap.get("federation_source_up", replica="r1") == 0.0
    assert snap.get("serving_queue_depth", replica="r1") is None
    # aggregates cover only the live source
    assert snap.get("cluster:serving_queue_depth:sum") == 1.0


def test_federate_groups_histogram_buckets_per_le():
    t = 'lat_bucket{le="0.1"} 2\nlat_bucket{le="+Inf"} 4\n'
    snap = parse_prometheus_text(
        federate([("r0", t), ("r1", t)], label="replica")
    )
    assert snap.get("cluster:lat_bucket:sum", le="0.1") == 4.0
    assert snap.get("cluster:lat_bucket:sum", le="+Inf") == 8.0
    # _bucket is counter-shaped: no max
    assert snap.get("cluster:lat_bucket:max", le="0.1") is None


def test_federate_identity_label_wins_over_preexisting():
    snap = parse_prometheus_text(
        federate([("r7", 'up{replica="stale"} 1\n')], label="replica")
    )
    assert snap.get("up", replica="r7") == 1.0
    assert snap.get("up", replica="stale") is None


def test_sum_values_tolerates_missing():
    snaps = [
        parse_prometheus_text("serving_shed_total 2\n"),
        None,
        parse_prometheus_text("other 9\n"),
    ]
    assert sum_values(snaps, "serving_shed_total") == 2.0


# ------------------------------------------------------ run timelines


def _drive_preempted_run(store, run=RUN):
    """A run that gets preempted mid-flight and resumes — the ISSUE's
    acceptance scenario for `polyaxon timeline`."""
    store.create_run(run, "trainer-1", "proj", {"op": 1})
    for s in (
        V1Statuses.COMPILED,
        V1Statuses.QUEUED,
        V1Statuses.SCHEDULED,
        V1Statuses.STARTING,
        V1Statuses.RUNNING,
    ):
        store.set_status(run, s)
    store.log_event(run, "preempted", {"step": 120, "resume_step": 100})
    store.set_status(run, V1Statuses.RETRYING, reason="Preempted")
    store.set_meta(run, preempt_restarts=1)
    store.set_status(run, V1Statuses.QUEUED)
    store.set_status(run, V1Statuses.SCHEDULED)
    store.set_status(run, V1Statuses.RUNNING)
    store.log_event(run, "resumed", {"step": 100, "tier": "local"})
    store.set_status(run, V1Statuses.SUCCEEDED)
    return run


def test_fold_timeline_pure_categories_and_labels():
    history = [
        {"kind": "create", "seq": 1, "ts": 10.0, "name": "n", "project": "p"},
        {
            "kind": "status", "seq": 2, "ts": 11.0, "status": "running",
            "cond": {"reason": "PodStarted", "message": "ok"},
        },
        {
            "kind": "event", "seq": 3, "ts": 12.0,
            "event": {"kind": "preempted", "step": 7, "resume_step": 5},
        },
        {
            "kind": "event", "seq": 4, "ts": 13.0,
            "event": {"kind": "elastic_shrink", "granted": 4, "requested": 8},
        },
        {"kind": "meta", "seq": 5, "ts": 14.0,
         "entries": {"preempt_restarts": 2}},
        {
            "kind": "event", "seq": 6, "ts": 15.0,
            "event": {"kind": "never_seen_before", "x": 1},
        },
    ]
    entries = fold_timeline(history)
    assert [e["kind"] for e in entries] == [
        "created", "transition", "preemption", "elastic", "meta", "event",
    ]
    assert entries[0]["label"] == "created p/n"
    assert entries[1]["label"] == "-> running (PodStarted)"
    assert entries[2]["label"] == "preempted (step 7, resume at 5)"
    assert entries[3]["label"] == "elastic shrink: granted 4 of 8 chips"
    assert entries[4]["label"] == "preemption restarts: 2"
    # unknown inner kinds degrade to readable words, never drop
    assert entries[5]["label"] == "never seen before"
    assert [e["seq"] for e in entries] == [1, 2, 3, 4, 5, 6]


def test_store_timeline_preempt_resume_zero_scans(tmp_path):
    store = RunStore(tmp_path / "store")
    _drive_preempted_run(store)
    before = store.scans
    entries = store.timeline(RUN)
    assert store.scans == before == 0  # one log read, no directory scans

    kinds = [e["kind"] for e in entries]
    assert kinds.count("preemption") == 1
    assert kinds.count("resumed") == 1
    assert kinds[0] == "created"
    # commit order IS causal order: seq strictly increasing
    seqs = [e["seq"] for e in entries]
    assert seqs == sorted(seqs)
    retry = next(e for e in entries if e["label"].startswith("-> retrying"))
    assert "Preempted" in retry["label"]
    assert entries[-1]["label"] == "-> succeeded"
    resumed = next(e for e in entries if e["kind"] == "resumed")
    assert resumed["label"] == "resumed at step 100 from local tier"


# --------------------------------------------- streams server surfaces


def test_streams_timeline_endpoint(tmp_path):
    from polyaxon_tpu.streams.server import BackgroundServer

    store = RunStore(tmp_path / "store")
    _drive_preempted_run(store)
    with BackgroundServer(store) as srv:
        url = f"http://127.0.0.1:{srv.port}/runs/{RUN}/timeline"
        with urllib.request.urlopen(url) as r:
            body = json.loads(r.read())
    assert body["uuid"] == RUN
    assert [e["kind"] for e in body["timeline"]].count("preemption") == 1


def test_streams_metricsz_federates_siblings(tmp_path):
    from polyaxon_tpu.streams.server import BackgroundServer

    store = RunStore(tmp_path / "store")
    with BackgroundServer(store) as sibling:
        sources = {
            "agent": f"http://127.0.0.1:{sibling.port}",
            "ghost": "http://127.0.0.1:9",  # discard port: always down
        }
        with BackgroundServer(store, federate=sources) as srv:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metricsz"
            ) as r:
                snap = parse_prometheus_text(r.read().decode())
    assert snap.get("federation_source_up", source="agent") == 1.0
    assert snap.get("federation_source_up", source="ghost") == 0.0
    # the sibling's series carry their source identity
    assert any(s.labels.get("source") == "agent" for s in snap)


# ----------------------------------------------------------- CLI views


def test_cli_timeline_renders_story(tmp_home):
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    _drive_preempted_run(RunStore())
    res = CliRunner().invoke(cli, ["timeline", RUN])
    assert res.exit_code == 0, res.output
    assert "preempted (step 120, resume at 100)" in res.output
    assert "resumed at step 100 from local tier" in res.output
    assert "-> succeeded" in res.output

    res = CliRunner().invoke(cli, ["timeline", RUN, "--json"])
    assert res.exit_code == 0, res.output
    rows = [json.loads(line) for line in res.output.splitlines() if line]
    assert [r["kind"] for r in rows].count("preemption") == 1


def test_cli_timeline_unknown_run_is_clean_error(tmp_home):
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    res = CliRunner().invoke(cli, ["timeline", "nope"])
    assert res.exit_code != 0
    assert "Traceback" not in res.output


def test_top_once_frame_offline_router(tmp_path):
    """One --once frame over a dead router URL: the store pane still
    renders (runs seeded from the event log, zero scans), the router
    pane degrades to 'unreachable'."""
    from polyaxon_tpu.cli.top import run_top

    store = RunStore(tmp_path / "store")
    _drive_preempted_run(store)
    store.create_run("bb" * 8, "live-run", "proj", {"op": 1})
    before = store.scans
    out = io.StringIO()
    run_top(store, "http://127.0.0.1:9", once=True, out=out)
    frame = out.getvalue()
    assert store.scans == before
    assert "router   unreachable" in frame
    assert "succeeded:1" in frame
    assert "created:1" in frame
    assert "live-run" in frame  # active run listed; finished one is not
    assert "trainer-1" not in frame
    assert "\x1b[" not in frame  # --once is pipe-friendly: no ANSI


def test_top_frame_renders_cluster_and_slo_blocks():
    from polyaxon_tpu.cli.top import _RunTable, render_frame

    stats = {
        "requests": 40, "retries": 2, "upstream_shed": 1, "errors": 0,
        "routable": 2,
        "latency_ms": {"p95": 12.5},
        "cluster": {
            "federation": True, "queue_depth": 3.0, "inflight": 2,
            "queue_wait_ms_max": 8.0, "serving_requests": 44.0,
            "serving_shed": 1.0,
        },
        "replicas": [
            {"slug": "r0", "healthy": True, "draining": False,
             "queue_depth": 1, "queue_wait_ms": 4.0, "inflight": 1,
             "requests": 22},
            {"slug": "r1", "healthy": False, "draining": False,
             "queue_depth": None, "queue_wait_ms": None, "inflight": 0,
             "requests": 18},
        ],
    }
    slo = {"slos": [
        {"name": "p95-latency", "burn_rate": 2.41, "breached": True},
    ]}
    frame = render_frame(
        url="http://x", fleet=None, stats=stats, slo=slo, runs=_RunTable()
    )
    assert "cluster  queue 3" in frame
    assert "r0" in frame and "r1" in frame and "down" in frame
    assert "p95-latency burn 2.41 BREACHED" in frame
