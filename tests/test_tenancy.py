"""Multi-tenant serving (ISSUE 19): adapter multiplexing + per-tenant
admission.

Four layers, tested at four levels:
  * admission units — TenantSpec contracts, normalize_* validation, the
    TenantAdmission counters (caps shed `tenant_quota`, releases are
    exactly-once, fair share = tokens/weight);
  * registry units (numpy only, no jax) — refcounted slot residency, LRU
    eviction of idle adapters through the spill tier, restore-on-acquire
    byte round-trip, `adapter_capacity` shed when every slot is pinned,
    and a chaos kill mid-restore leaving ZERO leaked state;
  * server level over live HTTP — a mixed-tenant batch must be
    byte-identical per tenant to a solo single-adapter server on every
    decode path (dense in the default tier; paged/chunked/speculative
    ride the slow lane), a capped tenant's flood sheds that tenant alone
    while the victim's requests all complete, and unknown tenants are a
    400 client error (quota isolation is meaningless if anyone can mint
    a tenant);
  * config surface — V1ServingSpec tenants/adapters validation and the
    `polyaxon serve` flag plumbing down to replica child argv.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from polyaxon_tpu.serving.batching import ShedError
from polyaxon_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    TenantAdmission,
    TenantSpec,
    normalize_adapters,
    normalize_tenants,
)

pytestmark = pytest.mark.serving


# ------------------------------------------------------- admission units
class TestTenantSpec:
    def test_validation(self):
        with pytest.raises(ValueError):
            TenantSpec("")
        with pytest.raises(ValueError):
            TenantSpec("  ")
        with pytest.raises(ValueError):
            TenantSpec("a", max_outstanding=-1)
        with pytest.raises(ValueError):
            TenantSpec("a", max_tokens=-5)
        with pytest.raises(ValueError):
            TenantSpec("a", weight=0.0)

    def test_pairs_round_trip(self):
        spec = TenantSpec("acme", max_outstanding=4, weight=2.0,
                          adapter="acme")
        assert TenantSpec.from_pairs(spec.to_pairs()) == spec
        # defaults stay out of the pairs so configs compare canonically
        assert TenantSpec("a").to_pairs() == (("name", "a"),)

    def test_normalize_rejects_duplicates(self):
        with pytest.raises(ValueError, match="duplicate tenant"):
            normalize_tenants([{"name": "a"}, {"name": "a"}])
        with pytest.raises(ValueError, match="duplicate adapter"):
            normalize_adapters([("a", "seed:1"), ("a", "seed:2")])
        with pytest.raises(ValueError):
            normalize_adapters({"": "seed:1"})
        with pytest.raises(ValueError):
            normalize_adapters({"a": "  "})

    def test_normalize_sorts_canonically(self):
        t = normalize_tenants([{"name": "z"}, {"name": "a"}])
        assert [dict(p)["name"] for p in t] == ["a", "z"]
        assert normalize_adapters({"b": "s2", "a": "s1"}) == (
            ("a", "s1"), ("b", "s2"),
        )


class TestTenantAdmission:
    def test_outstanding_cap_sheds_tenant_quota(self):
        adm = TenantAdmission([{"name": "t", "max_outstanding": 2}])
        r1 = adm.admit("t", 10)
        adm.admit("t", 10)
        with pytest.raises(ShedError) as e:
            adm.admit("t", 10)
        assert e.value.reason == "tenant_quota"
        r1()
        adm.admit("t", 10)  # released capacity admits again
        # release is idempotent: the double call must not free a stranger
        r1()
        with pytest.raises(ShedError):
            adm.admit("t", 10)

    def test_token_budget(self):
        adm = TenantAdmission([{"name": "t", "max_tokens": 100}])
        rel = adm.admit("t", 80)
        with pytest.raises(ShedError) as e:
            adm.admit("t", 30)
        assert e.value.reason == "tenant_quota"
        adm.admit("t", 20)  # exactly to the cap admits
        rel()
        adm.admit("t", 80)

    def test_default_tenant_uncapped_and_unknown_rejected(self):
        adm = TenantAdmission([{"name": "t", "max_outstanding": 1}])
        for _ in range(50):
            adm.admit("", 1)  # tenant-less requests ride "default"
        assert adm.resolve(None).name == DEFAULT_TENANT
        with pytest.raises(KeyError):
            adm.admit("stranger", 1)
        with pytest.raises(KeyError):
            adm.resolve("stranger")

    def test_share_is_tokens_over_weight(self):
        adm = TenantAdmission([
            {"name": "light", "weight": 1.0},
            {"name": "heavy", "weight": 4.0},
        ])
        adm.admit("light", 100)
        adm.admit("heavy", 100)
        # the heavier tenant's share is smaller → it is picked next
        assert adm.share("heavy") == pytest.approx(25.0)
        assert adm.share("light") == pytest.approx(100.0)
        assert adm.share("heavy") < adm.share("light")

    def test_snapshot_counters(self):
        adm = TenantAdmission([{"name": "t", "max_outstanding": 1}])
        adm.admit("t", 7)
        with pytest.raises(ShedError):
            adm.admit("t", 7)
        snap = adm.snapshot()
        assert snap["t"]["admitted"] == 1 and snap["t"]["shed"] == 1
        assert snap["t"]["outstanding"] == 1 and snap["t"]["tokens"] == 7
        assert DEFAULT_TENANT in snap


# -------------------------------------------------------- registry units
TEMPLATE = {
    "layer/attn/lora_a": ((8, 2), np.dtype("float32")),
    "layer/attn/lora_b": ((2, 8), np.dtype("float32")),
}


def _registry(slots=1, sources=None, spill=True):
    """AdapterRegistry over an in-memory slot store — the unit under test
    without a model attached."""
    from polyaxon_tpu.serving.adapters import AdapterRegistry
    from polyaxon_tpu.serving.spill import SpillManager

    store = {}

    def read_slot(slot):
        return [store[slot][p] for p in sorted(TEMPLATE)]

    def write_slot(slot, adapter):
        store[slot] = {p: np.array(v) for p, v in adapter.items()}

    reg = AdapterRegistry(
        slots=slots,
        sources=sources or {"a": "seed:1", "b": "seed:2"},
        template=TEMPLATE,
        read_slot=read_slot,
        write_slot=write_slot,
        spill=SpillManager(ram_bytes=1 << 20) if spill else None,
    )
    return reg, store


class TestAdapterRegistry:
    def test_acquire_pins_release_unpins(self):
        reg, store = _registry(slots=2)
        slot, loaded = reg.acquire("a")
        assert loaded is True and slot in (1, 2)
        assert reg.refcount("a") == 1
        slot2, loaded2 = reg.acquire("a")
        assert (slot2, loaded2) == (slot, False)  # resident: no reload
        reg.release("a")
        reg.release("a")
        assert reg.refcount("a") == 0
        reg.release("a")  # over-release must not go negative
        assert reg.refcount("a") == 0
        assert store[slot]  # the weights really landed in the slot
        reg.check_invariants()

    def test_lru_evict_spill_restore_round_trips_bytes(self):
        from polyaxon_tpu.serving.adapters import synth_adapter

        reg, store = _registry(slots=1)
        slot, _ = reg.acquire("a")
        reg.release("a")
        want = synth_adapter(TEMPLATE, 1)
        for p in sorted(TEMPLATE):
            np.testing.assert_array_equal(store[slot][p], want[p])
        # "b" needs the only slot: idle "a" demotes to the spill tier
        reg.acquire("b")
        assert reg.evictions == 1 and reg.resident() == {"b": slot}
        reg.release("b")
        # "a" comes back from spill — the EXACT bytes, not a re-synth
        reg.acquire("a")
        assert reg.restores == 1
        for p in sorted(TEMPLATE):
            np.testing.assert_array_equal(store[slot][p], want[p])
        assert reg.stats()["adapters"]["b"]["state"] == "spilled"
        reg.check_invariants()

    def test_all_slots_pinned_sheds_adapter_capacity(self):
        reg, _ = _registry(slots=1)
        reg.acquire("a")  # held: refs=1, not evictable
        with pytest.raises(ShedError) as e:
            reg.acquire("b")
        assert e.value.reason == "adapter_capacity"
        reg.release("a")
        reg.acquire("b")  # idle now → evictable → admits
        reg.check_invariants()

    def test_unknown_adapter_raises_keyerror(self):
        reg, _ = _registry()
        with pytest.raises(KeyError):
            reg.acquire("stranger")

    def test_chaos_kill_mid_restore_leaks_nothing(self):
        """A process death between the spill take and the slot write must
        cost a retry, never a leak: the payload returns to the spill
        tier, the slot stays free, no refcount moves, and the next
        acquire restores the same bytes."""
        from polyaxon_tpu import chaos
        from polyaxon_tpu.chaos.plan import Fault, FaultPlan
        from polyaxon_tpu.serving.adapters import synth_adapter

        reg, store = _registry(slots=1)
        slot, _ = reg.acquire("a")
        reg.release("a")
        reg.acquire("b")  # evicts idle "a" → spilled
        reg.release("b")
        plan = FaultPlan([Fault("serving.adapter_restore", "kill", at=0)])
        with chaos.active(plan):
            with pytest.raises(chaos.SimulatedKill):
                reg.acquire("a")
        reg.check_invariants()
        assert reg.refcount("a") == 0
        assert reg.stats()["adapters"]["a"]["state"] == "spilled"
        assert reg.restores == 0
        # disarmed retry: the restore completes with the exact bytes
        s2, loaded = reg.acquire("a")
        assert loaded and reg.restores == 1
        want = synth_adapter(TEMPLATE, 1)
        for p in sorted(TEMPLATE):
            np.testing.assert_array_equal(store[s2][p], want[p])
        reg.check_invariants()

    def test_load_rejects_wrong_shape_adapter(self, tmp_path):
        from polyaxon_tpu.serving.adapters import load_adapter, save_adapter

        bad = {p: np.zeros((3, 3), np.float32) for p in TEMPLATE}
        save_adapter(tmp_path / "bad.npz", bad)
        with pytest.raises(ValueError, match="shape"):
            load_adapter(str(tmp_path / "bad.npz"), TEMPLATE)
        good = {
            p: np.ones(shape, dtype) for p, (shape, dtype) in TEMPLATE.items()
        }
        save_adapter(tmp_path / "good.npz", good)
        loaded = load_adapter(str(tmp_path / "good.npz"), TEMPLATE)
        for p in TEMPLATE:
            np.testing.assert_array_equal(loaded[p], good[p])


# ------------------------------------------------- server level over HTTP
CFG = {
    "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128, "lora_rank": 4,
}
ADAPTERS = {"acme": "seed:1", "globex": "seed:2"}


@pytest.fixture(scope="module")
def lora_model():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    b = build_model("transformer_lm", CFG)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


def _server(lora_model, **cfg):
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    module, params = lora_model
    cfg.setdefault("max_batch", 4)
    cfg.setdefault("max_wait_ms", 30.0)
    if "adapters" in cfg:
        cfg["adapters"] = normalize_adapters(cfg["adapters"])
    if "tenants" in cfg:
        cfg["tenants"] = normalize_tenants(cfg["tenants"])
    return ModelServer(
        module, params, model_name="tenancy-test",
        config=ServingConfig(**cfg),
    )


def _post(port, body, timeout=300):
    """POST /generate, returning (status, payload) without raising."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:  # noqa: BLE001 — an error body is best-effort
            return e.code, {}


PATH_CONFIGS = {
    "dense": {},
    "paged": {"kv_pool_pages": 64, "kv_page_tokens": 8},
    "chunked": {
        "kv_pool_pages": 64, "kv_page_tokens": 8, "chunked_prefill": True,
        "prefill_chunk_tokens": 8, "max_step_tokens": 64,
    },
    "speculative": {
        "kv_pool_pages": 64, "kv_page_tokens": 8, "speculate": True,
        "draft_tokens": 4,
    },
}


@pytest.mark.parametrize(
    "path",
    [
        "dense",
        pytest.param("paged", marks=pytest.mark.slow),
        pytest.param("chunked", marks=pytest.mark.slow),
        pytest.param("speculative", marks=pytest.mark.slow),
    ],
)
def test_mixed_tenant_batch_byte_identical_to_solo(lora_model, path,
                                                   tmp_home):
    """The multiplexing contract over live HTTP: a coalesced batch
    mixing both tenants (greedy AND seeded-sampled rows) produces, per
    tenant, EXACTLY what a solo server configured with only that
    tenant's adapter produces — on every decode path."""
    extra = PATH_CONFIGS[path]
    bodies = {}
    for tenant in ADAPTERS:
        for label, sampling in (
            ("greedy", {"temperature": 0.0}),
            ("sampled", {"temperature": 0.8, "topK": 20, "seed": 11}),
        ):
            bodies[(tenant, label)] = {
                "tokens": [[1, 2, 3, 4, 5]], "maxNewTokens": 6,
                "tenant": tenant, **sampling,
            }

    mixed = _server(
        lora_model, adapters=ADAPTERS,
        tenants=[{"name": n, "adapter": n} for n in ADAPTERS],
        **extra,
    )
    port = mixed.start(port=0)
    got = {}
    errors = []
    try:
        def fire(key):
            try:
                status, payload = _post(port, dict(bodies[key]))
                assert status == 200, (status, payload)
                got[key] = payload["tokens"]
            except Exception as e:  # noqa: BLE001
                errors.append((key, e))

        threads = [
            threading.Thread(target=fire, args=(k,), daemon=True)
            for k in bodies
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert not errors, errors
    finally:
        mixed.stop()

    for tenant in ADAPTERS:
        solo = _server(
            lora_model, adapters={tenant: ADAPTERS[tenant]},
            tenants=[{"name": tenant, "adapter": tenant}],
            **extra,
        )
        sport = solo.start(port=0)
        try:
            for label in ("greedy", "sampled"):
                status, payload = _post(sport, dict(bodies[(tenant, label)]))
                assert status == 200, (status, payload)
                assert payload["tokens"] == got[(tenant, label)], (
                    path, tenant, label,
                )
        finally:
            solo.stop()
    # the adapters genuinely diverge — identity above wasn't vacuous
    assert got[("acme", "greedy")] != got[("globex", "greedy")]


def test_capped_tenant_flood_sheds_alone_victim_completes(lora_model,
                                                          tmp_home):
    """Per-tenant admission over live HTTP: a noisy tenant's concurrent
    burst over its outstanding cap sheds with reason `tenant_quota`
    (503 + Retry-After), the victim tenant's requests ALL complete, and
    the per-tenant ledgers + metrics series say exactly that."""
    server = _server(
        lora_model,
        tenants=[{"name": "noisy", "max_outstanding": 1},
                 {"name": "victim"}],
        max_batch=2, max_wait_ms=50.0,
    )
    port = server.start(port=0)
    try:
        # warm the compile so the flood below really overlaps in-flight
        assert _post(port, {"tokens": [[1, 2]], "maxNewTokens": 2,
                            "tenant": "noisy"})[0] == 200
        results = []
        lock = threading.Lock()

        def noisy(i):
            status, payload = _post(port, {
                "tokens": [[1, 2]], "maxNewTokens": 16,
                "tenant": "noisy", "seed": i,
            })
            with lock:
                results.append((status, payload.get("reason")))

        threads = [
            threading.Thread(target=noisy, args=(i,), daemon=True)
            for i in range(5)
        ]
        for t in threads:
            t.start()
        for i in range(3):
            status, payload = _post(port, {
                "tokens": [[3, 4, 5]], "maxNewTokens": 4,
                "tenant": "victim", "seed": i,
            })
            assert status == 200, (status, payload)  # victim untouched
        for t in threads:
            t.join(300)
        sheds = [r for r in results if r[0] == 503]
        assert sheds, results  # the burst really overran the cap
        assert all(r[1] == "tenant_quota" for r in sheds), results
        assert any(r[0] == 200 for r in results), results

        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statsz", timeout=30).read())
        ten = stats["tenancy"]
        assert ten["enabled"] is True
        assert ten["tenants"]["noisy"]["shed"] == len(sheds)
        assert ten["tenants"]["victim"]["shed"] == 0
        assert ten["tenants"]["victim"]["admitted"] == 3
        assert ten["tenants"]["noisy"]["max_outstanding"] == 1

        text = server.telemetry.render_prometheus()
        for needle in (
            "serving_shed_by_tenant_noisy_total",
            "serving_queue_wait_by_tenant_victim",
            "serving_request_seconds_by_tenant_victim",
            "serving_tenant_queue_wait_seconds",
        ):
            assert needle in text, needle
    finally:
        server.stop()


def test_unknown_tenant_is_400_over_http(lora_model, tmp_home):
    """Unknown tenants are a client error, not a shed: quota isolation
    is meaningless if anyone can mint a fresh tenant."""
    server = _server(lora_model, tenants=[{"name": "acme"}])
    port = server.start(port=0)
    try:
        status, payload = _post(
            port, {"tokens": [[1]], "maxNewTokens": 2, "tenant": "stranger"},
        )
        assert status == 400, (status, payload)
        assert "stranger" in payload.get("error", ""), payload
        # a tenant-less request still rides "default" untouched
        status, _ = _post(port, {"tokens": [[1]], "maxNewTokens": 2})
        assert status == 200
    finally:
        server.stop()


def test_named_tenant_without_tenancy_is_client_error(lora_model):
    from polyaxon_tpu.serving.batching import ServingError

    server = _server(lora_model)
    with pytest.raises(ServingError, match="no.*tenants configured"):
        server.handle_request(
            {"tokens": [[1]], "maxNewTokens": 2, "tenant": "acme"}
        )


# --------------------------------------------------------- config surface
class TestServingSpecTenancy:
    def test_tenant_adapter_must_be_configured(self):
        from polyaxon_tpu.schemas.run_kinds import V1ServingSpec, V1TenantSpec

        with pytest.raises(ValueError, match="adapter"):
            V1ServingSpec(
                adapters={"acme": "seed:1"},
                tenants=[V1TenantSpec(name="t", adapter="stranger")],
            )
        with pytest.raises(ValueError, match="[Dd]uplicate"):
            V1ServingSpec(
                tenants=[V1TenantSpec(name="t"), V1TenantSpec(name="t")]
            )
        with pytest.raises(ValueError):
            V1ServingSpec(adapters={"": "seed:1"})
        with pytest.raises(ValueError):
            V1ServingSpec(adapter_slots=-1)
        with pytest.raises(ValueError):
            V1TenantSpec(name="t", weight=0.0)

    def test_to_config_normalizes(self):
        from polyaxon_tpu.schemas.run_kinds import V1ServingSpec, V1TenantSpec

        spec = V1ServingSpec(
            adapters={"b": "seed:2", "a": "seed:1"},
            tenants=[
                V1TenantSpec(name="t", adapter="a", maxOutstanding=4,
                             weight=2.0),
            ],
            adapterSlots=1,
        )
        cfg = spec.to_config()
        assert cfg.adapters == (("a", "seed:1"), ("b", "seed:2"))
        assert cfg.adapter_slots == 1
        t = dict(cfg.tenants[0])
        assert t == {"name": "t", "adapter": "a", "max_outstanding": 4,
                     "weight": 2.0}


class TestCliPlumbing:
    def test_serve_child_argv_round_trips_tenancy_flags(self):
        from polyaxon_tpu.cli.main import _serve_child_argv

        overrides = {
            "adapters": normalize_adapters({"acme": "seed:1"}),
            "tenants": normalize_tenants(
                [{"name": "acme", "max_outstanding": 4, "adapter": "acme"}]
            ),
            "adapter_slots": 1,
        }
        argv = _serve_child_argv("uid", 8000, None, overrides, None)
        joined = " ".join(argv)
        assert "--adapter acme=seed:1" in joined
        assert "--adapter-slots 1" in joined
        assert "--tenant-quota acme=4::1.0:acme" in joined

    def test_bad_tenant_quota_flag_is_clean_cli_error(self):
        from click.testing import CliRunner

        from polyaxon_tpu.cli.main import cli

        runner = CliRunner()
        res = runner.invoke(
            cli, ["serve", "-uid", "some-uid", "--tenant-quota", "=4::1.0:"],
        )
        assert res.exit_code != 0
        assert "tenant-quota" in res.output
        res = runner.invoke(
            cli, ["serve", "-uid", "some-uid", "--adapter", "noequals"],
        )
        assert res.exit_code != 0
        assert "NAME=SOURCE" in res.output
