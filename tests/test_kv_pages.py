"""Block-paged KV cache (ISSUE 6), tested at three levels:

  * pure units — page math, the rolling content hash, PagePool
    refcount/reservation invariants, and PrefixCache lookup/insert/LRU
    eviction/collision handling (no jax);
  * manager accounting — KVCacheManager admission (reserve → shed with
    reason kv_pages), lazy allocation, idempotent release, harvest
    indexing, and the occupancy win over dense worst-case reservation;
  * model level — paged decode through page tables must be byte-identical
    to the dense bucketed generate() path, including shared-prefix rows,
    eos latching, and chunked decode with traced positions.
"""

import numpy as np
import pytest

from polyaxon_tpu.models.kv_pages import (
    DEFAULT_PAGE_TOKENS,
    PagedKVLayout,
    PagePool,
    PagePoolExhausted,
    PrefixCache,
    page_hashes,
)

pytestmark = pytest.mark.serving


# ------------------------------------------------------------ page math
def test_layout_pages_for():
    lay = PagedKVLayout(page_tokens=8, pool_pages=4)
    assert lay.pages_for(0) == 0
    assert lay.pages_for(1) == 1
    assert lay.pages_for(8) == 1
    assert lay.pages_for(9) == 2
    assert DEFAULT_PAGE_TOKENS == 128
    with pytest.raises(ValueError):
        PagedKVLayout(page_tokens=0)


def test_page_hashes_chain():
    toks = list(range(20))
    h = page_hashes(toks, 8)
    assert len(h) == 2  # only FULL pages are addressable
    # chaining: entry k commits to the whole prefix, so a change in page
    # 0 changes page 1's hash too
    toks2 = [99] + toks[1:]
    h2 = page_hashes(toks2, 8)
    assert h[0] != h2[0] and h[1] != h2[1]
    # and identical prefixes agree regardless of the tail
    assert page_hashes(toks[:16], 8) == h


# ------------------------------------------------------------- page pool
def test_pool_refcount_lifecycle():
    pool = PagePool(4, 8)
    a = pool.alloc(2)
    assert pool.used == 2 and pool.free_pages == 2
    pool.ref(a)  # second holder
    pool.unref(a)
    assert pool.used == 2  # first holder still live
    pool.unref(a)
    assert pool.used == 0
    with pytest.raises(ValueError):
        pool.unref(a)  # unref of unallocated page


def test_pool_reservation_invariant():
    pool = PagePool(4, 8)
    pool.reserve(3)
    assert pool.available == 1
    with pytest.raises(PagePoolExhausted):
        pool.reserve(2)
    # unreserved alloc must not eat the reservation
    with pytest.raises(PagePoolExhausted):
        pool.alloc(2)
    got = pool.alloc(3, reserved=True)
    assert len(got) == 3 and pool.reserved == 0
    pool.unreserve(0)
    with pytest.raises(ValueError):
        pool.unreserve(1)  # nothing left reserved


# ---------------------------------------------------------- prefix cache
def _cache(pool_pages=16, pt=4, **kw):
    pool = PagePool(pool_pages, pt)
    return pool, PrefixCache(pool, **kw)


def test_prefix_insert_lookup_release():
    pool, pc = _cache()
    toks = list(range(8))  # 2 full pages of 4
    pages = pool.alloc(2)
    # index every chain link, as the manager's harvest does, so partial
    # overlaps can hit
    assert pc.insert(toks[:4], pages[:1])
    assert pc.insert(toks, pages)
    assert pc.insert(toks, pages) is False  # already indexed
    pool.unref(pages)  # the entries hold their own refs
    assert pool.used == 2
    plen, got, entry = pc.lookup(toks + [77, 78])
    assert plen == 8 and list(got) == pages and entry is not None
    pc.release(entry, got)
    # cap: a lookup may not consume the whole prompt (prefill needs >= 1
    # suffix token to produce logits) — the shorter chain link hits
    plen, got, entry = pc.lookup(toks, max_tokens=len(toks) - 1)
    assert plen == 4 and list(got) == pages[:1]
    pc.release(entry, got)
    assert pc.hits == 2 and pool.used == 2


def test_prefix_lru_eviction_skips_active():
    pool, pc = _cache(pool_pages=8, pt=4)
    a, b = list(range(4)), list(range(10, 14))
    pa, pb = pool.alloc(1), pool.alloc(1)
    assert pc.insert(a, pa) and pc.insert(b, pb)
    pool.unref(pa), pool.unref(pb)
    # a is older (LRU victim) — but an active lookup pins it
    plen, got, ea = pc.lookup(a + [99])
    assert plen == 4
    assert pc.evict_for(8) is False  # only b evictable: 7 of 8 available
    assert pc.contains(a) and not pc.contains(b)
    pc.release(ea, got)
    assert pc.evict_for(8)
    assert len(pc) == 0 and pool.used == 0
    assert pc.evictions == 2


def test_prefix_hash_collision_first_writer_wins():
    # adversarial hash: everything collides
    pool, pc = _cache(hash_fn=lambda prev, chunk: "same")
    a, b = list(range(4)), list(range(20, 24))
    pa = pool.alloc(1)
    assert pc.insert(a, pa)
    pb = pool.alloc(1)
    assert pc.insert(b, pb) is False  # slot taken by different content
    pool.unref(pa), pool.unref(pb)
    # lookup verifies token content: b degrades to a miss, not a wrong hit
    plen, _, entry = pc.lookup(b + [1])
    assert plen == 0 and entry is None
    assert pc.collisions >= 1
    plen, got, entry = pc.lookup(a + [1])
    assert plen == 4
    pc.release(entry, got)


# ------------------------------------------------------ manager accounting
def _tiny(scan_layers=False):
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    cfg = {
        "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
        "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
        "scan_layers": scan_layers,
    }
    b = build_model("transformer_lm", cfg)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


@pytest.fixture(scope="module")
def tiny_model():
    return _tiny()


def _mgr(tiny_model, pool_pages=16, pt=8, **kw):
    from polyaxon_tpu.serving.kv import KVCacheManager

    module, params = tiny_model
    return KVCacheManager(
        module, params, pool_pages=pool_pages, page_tokens=pt, **kw
    )


PL, NL = (8, 16, 32), (4, 8)


def test_manager_reserve_alloc_release(tiny_model):
    kv = _mgr(tiny_model)
    plan = kv.plan_row(list(range(1, 13)), 4, PL, NL, 64)
    # 12 tokens -> suffix bucket 16, new bucket 4 -> pages_for(19) = 3
    assert (plan.suffix_bucket, plan.new_bucket, plan.n_pages) == (16, 4, 3)
    assert kv.pool.reserved == 3
    kv.ensure_pages([plan], upto_slot=16)
    assert len(plan.own_pages) == 2 and plan.reserved == 1
    t = kv.tables([plan, None], 2, 3)
    assert t.shape == (2, 3)
    assert t[0, 2] == kv.scratch and (t[1] == kv.scratch).all()
    kv.release(plan)
    kv.release(plan)  # idempotent
    assert kv.pool.reserved == 0 and kv.pool.used == 1  # scratch only
    assert kv.active_rows == 0


def test_manager_exhaustion_sheds_with_reason(tiny_model):
    from polyaxon_tpu.serving.batching import ShedError

    kv = _mgr(tiny_model, pool_pages=6)  # scratch + 5 usable
    p1 = kv.plan_row(list(range(1, 9)), 4, PL, NL, 64)  # 2 pages
    p2 = kv.plan_row(list(range(20, 28)), 4, PL, NL, 64)  # 2 pages
    with pytest.raises(ShedError) as ei:
        kv.plan_row(list(range(40, 48)), 4, PL, NL, 64)
    assert ei.value.reason == "kv_pages"
    kv.release(p1)
    p3 = kv.plan_row(list(range(40, 48)), 4, PL, NL, 64)  # fits again
    kv.release(p2), kv.release(p3)
    assert kv.active_rows == 0 and kv.pool.reserved == 0


def test_manager_never_fits_is_client_error(tiny_model):
    from polyaxon_tpu.serving.batching import ServingError, ShedError

    kv = _mgr(tiny_model, pool_pages=3)
    with pytest.raises(ServingError) as ei:
        kv.plan_row(list(range(1, 40)), 8, PL, NL, 64)
    assert not isinstance(ei.value, ShedError)  # 400, not 503
    assert kv.active_rows == 0


def test_paged_occupancy_beats_dense_reservation(tiny_model):
    """The acceptance claim: at the same memory budget, page-grained
    admission holds strictly more concurrent rows than dense worst-case
    reservation (seq_len slots per row)."""
    kv = _mgr(tiny_model, pool_pages=16, pt=8)  # 128 slots
    assert kv.dense_equivalent_rows == 2  # 128 // seq_len 64
    plans = []
    for i in range(7):  # 8-token prompts + 4 new -> 2 pages per row
        plans.append(
            kv.plan_row([1 + i] * 8, 4, PL, NL, 64)
        )
    assert kv.active_rows == 7 > kv.dense_equivalent_rows
    assert kv.stats()["active_rows_hwm"] == 7
    for p in plans:
        kv.release(p)


def test_manager_harvest_indexes_prefix(tiny_model):
    kv = _mgr(tiny_model, pool_pages=32, pt=8)
    toks = list(range(1, 23))  # 22 tokens = 2 full pages + tail
    plan = kv.plan_row(toks, 4, PL, NL, 64)
    kv.ensure_pages([plan], upto_slot=plan.suffix_bucket + plan.new_bucket - 1)
    pad = plan.suffix_bucket - len(toks)
    assert kv.harvest([(toks, plan, pad)]) == 2  # both chain links indexed
    kv.release(plan)
    # a second request sharing the 16-token prefix hits; pages survive the
    # releasing row because the entries hold their own refs
    p2 = kv.plan_row(toks[:16] + [99, 98], 4, PL, NL, 64)
    assert p2.prefix_len == 16 and p2.prefix_pages_n == 2
    assert kv.prefix.hits == 1
    kv.release(p2)
    assert kv.active_rows == 0 and kv.pool.reserved == 0


# ------------------------------------------------- model-level byte identity
def _identity_case(scan_layers, pb, nb, pt, chunk, prefix_len, temp, eos):
    """Dense bucketed generate() vs paged prefill+chunks: every generated
    token must match bit for bit, including a shared prefix prefilled in a
    SEPARATE pass (the cross-request reuse shape)."""
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models.generate import (
        generate,
        jit_paged_chunk,
        jit_paged_prefill,
        make_paged_cache,
    )

    module, params = _tiny(scan_layers)
    B = 3
    rng = np.random.RandomState(1)
    shared = rng.randint(1, 100, size=prefix_len).tolist()
    sfx_lens = [max(1, pb - 3), pb, max(1, pb // 2)]
    prompts = [shared + rng.randint(1, 100, size=s).tolist() for s in sfx_lens]
    seeds = np.array([7, 11, 13], np.int32)

    # dense reference: full prompts left-padded to prefix_len + pb
    P = prefix_len + pb
    arr = np.zeros((B, P), np.int32)
    lens = np.array([len(p) for p in prompts], np.int32)
    for i, p in enumerate(prompts):
        arr[i, P - len(p):] = p
    dense = np.asarray(generate(
        module, params, jnp.asarray(arr), max_new_tokens=nb,
        temperature=temp, top_k=40, eos_id=eos, seed=jnp.asarray(seeds),
        prompt_lengths=jnp.asarray(lens),
    ))
    dense_gen = [
        dense[i, P - lens[i]:][lens[i]:lens[i] + nb] for i in range(B)
    ]

    # paged: shared prefix prefilled ONCE, rows alias its pages read-only
    layout = PagedKVLayout(page_tokens=pt, pool_pages=64)
    cache = make_paged_cache(module, params, layout)
    n_pages = -(-(prefix_len + pb + nb) // pt)
    L_pages = prefix_len // pt
    prefix_ids = list(range(1, 1 + L_pages))
    nxt = 1 + L_pages
    tables = np.zeros((B, n_pages), np.int32)
    for i in range(B):
        own = list(range(nxt, nxt + n_pages - L_pages))
        nxt += len(own)
        tables[i] = prefix_ids + own
    if prefix_len:
        pf0 = jit_paged_prefill(module, kv_layout=layout, prefix_len=0,
                                temperature=temp, top_k=40)
        cache, _ = pf0(
            params, cache, jnp.asarray(np.array([shared], np.int32)),
            jnp.zeros((1,), jnp.int32),
            jnp.asarray(np.array([prefix_ids], np.int32)),
            jnp.zeros((1,), jnp.int32),
        )
    sfx = np.zeros((B, pb), np.int32)
    pads = np.zeros((B,), np.int32)
    for i, p in enumerate(prompts):
        s = p[prefix_len:]
        sfx[i, pb - len(s):] = s
        pads[i] = pb - len(s)
    pf = jit_paged_prefill(module, kv_layout=layout, prefix_len=prefix_len,
                           temperature=temp, top_k=40)
    cache, first = pf(params, cache, jnp.asarray(sfx), jnp.asarray(pads),
                      jnp.asarray(tables), jnp.asarray(seeds))
    out = [np.asarray(first).reshape(B, 1)]
    tok, done = first, jnp.zeros((B,), bool)
    pos, g, left = prefix_len + pb, 1, nb - 1
    while left > 0:
        C = min(chunk, left)
        cf = jit_paged_chunk(module, steps=C, kv_layout=layout,
                             prefix_len=prefix_len, temperature=temp,
                             top_k=40, eos_id=eos)
        cache, toks, done = cf(
            params, cache, tok, done, jnp.asarray(pads),
            jnp.asarray(tables), jnp.asarray(seeds),
            jnp.asarray(pos, jnp.int32), jnp.asarray(g, jnp.int32),
        )
        toks = np.asarray(toks)
        out.append(toks)
        tok = jnp.asarray(toks[:, -1])
        pos, g, left = pos + C, g + C, left - C
    paged_gen = np.concatenate(out, axis=1)
    for i in range(B):
        assert np.array_equal(dense_gen[i], paged_gen[i]), (
            i, dense_gen[i].tolist(), paged_gen[i].tolist()
        )


def test_paged_decode_identity_with_shared_prefix():
    # the load-bearing shape: shared prefix from a separate prefill pass,
    # odd chunking, eos latching, sampled (not greedy) rows
    _identity_case(
        scan_layers=False, pb=8, nb=8, pt=4, chunk=3, prefix_len=8,
        temp=0.8, eos=5,
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "scan_layers,pb,nb,pt,chunk,prefix_len,temp,eos",
    [
        (False, 8, 8, 4, 3, 0, 0.8, 5),
        (True, 8, 8, 4, 3, 0, 0.8, 5),
        (True, 16, 8, 8, 8, 8, 0.8, 5),
        (False, 8, 5, 16, 2, 0, 0.0, None),  # greedy, page > window
        (False, 8, 8, 4, 4, 12, 0.8, 2),  # aggressive eos
    ],
)
def test_paged_decode_identity_ladder(
    scan_layers, pb, nb, pt, chunk, prefix_len, temp, eos
):
    _identity_case(scan_layers, pb, nb, pt, chunk, prefix_len, temp, eos)


# ----------------------------------------------- ISSUE 15: int8 KV pool
def test_quantize_kv_roundtrip_and_purity():
    """quantize_kv is a pure per-(slot, head) transform: scales amax over
    the head dim only, so the quantized bytes of a vector never depend on
    which batch/chunk wrote it — the property that keeps chunked prefill,
    COW and one-shot prefill byte-identical on a quantized pool."""
    import jax.numpy as jnp

    from polyaxon_tpu.models.quant import dequantize_kv, quantize_kv

    rng = np.random.RandomState(3)
    x = rng.randn(4, 8, 2, 16).astype(np.float32)  # [B, T, nkv, hd]
    q, s = quantize_kv(jnp.asarray(x))
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    assert q.shape == x.shape and s.shape == x.shape[:-1]
    back = np.asarray(dequantize_kv(q, s))
    # symmetric 127-level quant: error bounded by half a step per element
    step = np.maximum(np.abs(x).max(-1), 1e-8) / 127.0
    assert (np.abs(back - x) <= step[..., None] * 0.5 + 1e-7).all()
    # purity: a slice quantizes to exactly the bytes it got in the batch
    q1, s1 = quantize_kv(jnp.asarray(x[1:2, 3:5]))
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q)[1:2, 3:5])
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s)[1:2, 3:5])
    # the zero vector quantizes to zeros, not NaNs
    q0, s0 = quantize_kv(jnp.zeros((2, 16)))
    assert np.asarray(q0).sum() == 0 and np.isfinite(np.asarray(s0)).all()


def test_int8_pool_structure_and_bytes(tiny_model):
    """The quantized pool really is int8 on the wire, carries one f32
    scale per (slot, kv head), and its byte footprint matches the
    kv_pool_bytes formula the server budgets admission with."""
    import jax

    from polyaxon_tpu.models.generate import make_paged_cache
    from polyaxon_tpu.models.quant import kv_pool_bytes

    module, params = tiny_model
    lay_q = PagedKVLayout(page_tokens=8, pool_pages=16, kv_quant="int8")
    lay_fp = PagedKVLayout(page_tokens=8, pool_pages=16)
    cache_q = make_paged_cache(module, params, lay_q)
    cache_fp = make_paged_cache(module, params, lay_fp)

    leaves_q = jax.tree_util.tree_leaves_with_path(cache_q)
    kinds = {str(p[-1].key): l.dtype for p, l in leaves_q}
    import jax.numpy as jnp

    assert kinds["cached_key"] == jnp.int8
    assert kinds["cached_value"] == jnp.int8
    assert kinds["cached_key_scale"] == jnp.float32
    assert kinds["cached_value_scale"] == jnp.float32
    # scale leaves drop the head_dim axis: one scale per slot per head
    shapes = {str(p[-1].key): l.shape for p, l in leaves_q}
    assert shapes["cached_key_scale"] == shapes["cached_key"][:-1]

    def nbytes(c):
        return sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(c))

    cfg = module.cfg
    hd = cfg.dim // cfg.n_heads
    assert nbytes(cache_q) == kv_pool_bytes(
        lay_q, cfg.n_layers, cfg.n_kv_heads, hd
    )
    fp_itemsize = jax.tree.leaves(cache_fp)[0].dtype.itemsize
    assert nbytes(cache_fp) == kv_pool_bytes(
        lay_fp, cfg.n_layers, cfg.n_kv_heads, hd,
        kv_dtype_bytes=fp_itemsize,
    )
    # the capacity claim at this geometry: >= 1.9x rows per byte
    assert nbytes(cache_fp) / nbytes(cache_q) >= 1.9


def test_int8_pool_chunked_prefill_matches_one_shot(tiny_model):
    """Write-order independence on the QUANTIZED pool: prefill delivered
    in two slices must leave decode byte-identical to one-shot prefill —
    the same contract the fp pool honors, now with quantize-on-write."""
    import jax.numpy as jnp

    from polyaxon_tpu.models.generate import (
        jit_paged_chunk,
        jit_paged_prefill,
        jit_paged_prefill_chunk,
        make_paged_cache,
    )

    module, params = tiny_model
    lay = PagedKVLayout(page_tokens=4, pool_pages=32, kv_quant="int8")
    B, P, nb = 2, 8, 6
    rng = np.random.RandomState(5)
    prompt = rng.randint(1, 128, size=(B, P)).astype(np.int32)
    seeds = jnp.asarray([7, 11], jnp.int32)
    pads = jnp.zeros((B,), jnp.int32)
    n_pages = -(-(P + nb) // 4)
    tables = jnp.asarray(
        1 + np.arange(B * n_pages, dtype=np.int32).reshape(B, n_pages)
    )

    def decode(cache, first):
        cf = jit_paged_chunk(module, steps=nb - 1, kv_layout=lay,
                             prefix_len=0, temperature=0.8, top_k=40,
                             eos_id=None)
        cache, toks, _ = cf(
            params, cache, first, jnp.zeros((B,), bool), pads, tables,
            seeds, jnp.asarray(P, jnp.int32), jnp.asarray(1, jnp.int32),
        )
        return np.concatenate(
            [np.asarray(first).reshape(B, 1), np.asarray(toks)], axis=1
        )

    # one-shot
    cache = make_paged_cache(module, params, lay)
    pf = jit_paged_prefill(module, kv_layout=lay, prefix_len=0,
                           temperature=0.8, top_k=40)
    cache, first = pf(params, cache, jnp.asarray(prompt), pads, tables,
                      seeds)
    one = decode(cache, first)

    # two slices: 5 tokens then the ragged 3-token final
    cache = make_paged_cache(module, params, lay)
    zero_prefix = jnp.zeros((B,), jnp.int32)
    c1 = jit_paged_prefill_chunk(module, kv_layout=lay, temperature=0.8,
                                 top_k=40, final=False)
    cache = c1(params, cache, jnp.asarray(prompt[:, :5]), pads,
               zero_prefix, tables, seeds, jnp.asarray(0, jnp.int32))
    c2 = jit_paged_prefill_chunk(module, kv_layout=lay, temperature=0.8,
                                 top_k=40, final=True)
    cache, first2 = c2(params, cache, jnp.asarray(prompt[:, 5:]), pads,
                       zero_prefix, tables, seeds,
                       jnp.asarray(5, jnp.int32))
    two = decode(cache, first2)

    np.testing.assert_array_equal(one, two)
