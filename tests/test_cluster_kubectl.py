"""KubectlCluster: the concrete ClusterClient over `kubectl` (k8s/cluster.py)
driven against a recording stub binary (the sandbox has no apiserver), plus
reconciler fault isolation when the client misbehaves mid-tick."""

import json
import os
import stat

import pytest
import yaml

from polyaxon_tpu.k8s.cluster import ClusterError, KubectlCluster
from polyaxon_tpu.scheduler.reconciler import Reconciler
from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.store.local import RunStore

from tests.test_reconciler import SPEC, FakeCluster, _submit


STUB = """#!/bin/bash
# recording kubectl stub: logs argv + stdin, replays canned output
dir="$(dirname "$0")"
printf '%s\\n' "$@" > "$dir/last_args"
cat > "$dir/last_stdin"
if [ -f "$dir/exit_code" ]; then rc=$(cat "$dir/exit_code"); else rc=0; fi
if [ "$rc" != 0 ]; then echo "stub error text" >&2; exit "$rc"; fi
if [ -f "$dir/stdout" ]; then cat "$dir/stdout"; fi
"""


@pytest.fixture
def stub_kubectl(tmp_path):
    path = tmp_path / "kubectl"
    path.write_text(STUB)
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return path


def _args(stub):
    return (stub.parent / "last_args").read_text().splitlines()


def test_submit_applies_manifest_list(stub_kubectl):
    c = KubectlCluster(namespace="ns1", kubectl=str(stub_kubectl))
    c.submit("u1", [{"kind": "Job"}, {"kind": "Service"}])
    args = _args(stub_kubectl)
    assert args[:2] == ["-n", "ns1"]
    assert "apply" in args and "-f" in args and "--dry-run=client" not in args
    sent = json.loads((stub_kubectl.parent / "last_stdin").read_text())
    assert sent["kind"] == "List" and len(sent["items"]) == 2


def test_submit_dry_run_flag(stub_kubectl):
    c = KubectlCluster(kubectl=str(stub_kubectl), dry_run=True)
    c.submit("u1", [{"kind": "Job"}])
    assert "--dry-run=client" in _args(stub_kubectl)


def test_status_parses_pod_list(stub_kubectl):
    (stub_kubectl.parent / "stdout").write_text(
        json.dumps(
            {
                "items": [
                    {
                        "metadata": {"name": "w-0"},
                        "status": {"phase": "Running"},
                    },
                    {
                        "metadata": {"name": "w-1"},
                        "status": {
                            "phase": "Failed",
                            "reason": "Evicted",
                            "containerStatuses": [
                                {
                                    "state": {
                                        "terminated": {
                                            "exitCode": 137,
                                            "reason": "OOMKilled",
                                        }
                                    }
                                }
                            ],
                        },
                    },
                    {"metadata": {"name": "w-2"}, "status": {}},  # partial
                ]
            }
        )
    )
    c = KubectlCluster(kubectl=str(stub_kubectl))
    st = c.status("u1")
    args = _args(stub_kubectl)
    assert "polyaxon/run-uuid=u1" in args
    assert st["pods"][0] == {"name": "w-0", "phase": "Running"}
    # pod-level reason (Evicted) wins over the container's OOMKilled —
    # preemption classification depends on it
    assert st["pods"][1]["reason"] == "Evicted"
    assert st["pods"][1]["exit_code"] == 137
    assert st["pods"][2]["phase"] == "Unknown"  # partial status, no crash


def test_status_empty_output_means_no_pods(stub_kubectl):
    c = KubectlCluster(kubectl=str(stub_kubectl))
    assert c.status("nope") == {"pods": []}


def test_delete_is_label_scoped_and_nonblocking(stub_kubectl):
    c = KubectlCluster(kubectl=str(stub_kubectl))
    c.delete("u9")
    args = _args(stub_kubectl)
    assert "job,service" in args
    assert "polyaxon/run-uuid=u9" in args
    assert "--wait=false" in args


def test_kubectl_failure_raises_cluster_error(stub_kubectl):
    (stub_kubectl.parent / "exit_code").write_text("1")
    c = KubectlCluster(kubectl=str(stub_kubectl))
    with pytest.raises(ClusterError, match="stub error text"):
        c.submit("u1", [])


def test_missing_binary_raises_cluster_error():
    c = KubectlCluster(kubectl="/nonexistent/kubectl")
    with pytest.raises(ClusterError, match="not found"):
        c.status("u1")


# ---------------------------------------------------- reconciler hardening
class FlakyCluster(FakeCluster):
    """status() raises for selected runs — an apiserver flap mid-drain."""

    def __init__(self):
        super().__init__()
        self.broken: set[str] = set()

    def status(self, run_uuid):
        if run_uuid in self.broken:
            raise ClusterError("apiserver 503")
        return super().status(run_uuid)


def test_reconciler_isolates_client_faults(tmp_home, tmp_path):
    """One run's client exception must not stop other gangs from draining."""
    store = RunStore()
    cluster = FlakyCluster()
    u_bad = _submit(tmp_path, store, cluster)
    u_good = _submit(tmp_path, store, cluster)
    cluster.broken.add(u_bad)
    cluster.set_all(u_good, "Running")

    rec = Reconciler(store, cluster)
    changes = rec.tick()
    assert (u_good, V1Statuses.RUNNING) in changes
    # the broken run kept its pre-fault status and logged the error
    assert store.get_status(u_bad)["status"] == V1Statuses.SCHEDULED
    assert "reconcile error" in store.read_logs(u_bad)

    # flap heals -> next tick picks the run back up
    cluster.broken.clear()
    cluster.set_all(u_bad, "Running")
    changes = rec.tick()
    assert (u_bad, V1Statuses.RUNNING) in changes


def test_reconciler_tolerates_malformed_status(tmp_home, tmp_path):
    """None / pod dicts with missing keys must not crash the tick."""
    store = RunStore()
    cluster = FakeCluster()
    uuid = _submit(tmp_path, store, cluster)

    class WeirdCluster(FakeCluster):
        def status(self, run_uuid):
            return None  # a client returning nothing at all

    rec = Reconciler(store, WeirdCluster())
    assert rec.tick() == []  # no crash, no change

    cluster.pods[uuid] = [{"no_phase_key": True}, {"phase": "Running"}]
    rec = Reconciler(store, cluster)
    changes = rec.tick()
    assert (uuid, V1Statuses.RUNNING) in changes


class AsyncDeleteCluster(FakeCluster):
    """delete returns immediately while pods linger Terminating — the real
    `kubectl delete --wait=false` behavior a gang restart must survive."""

    def __init__(self):
        super().__init__()
        self.submit_calls = 0

    def submit(self, run_uuid, manifests):
        self.submit_calls += 1
        super().submit(run_uuid, manifests)

    def delete(self, run_uuid):
        self.deleted.append(run_uuid)  # pods NOT removed yet

    def drain(self, run_uuid):
        self.pods.pop(run_uuid, None)


def test_gang_restart_waits_for_async_delete(tmp_home, tmp_path):
    """Resubmit must be deferred until the old gang's pods are gone;
    resubmitting into a terminating gang silently loses the restart."""
    store = RunStore()
    cluster = AsyncDeleteCluster()
    uuid = _submit(tmp_path, store, cluster)
    rec = Reconciler(store, cluster)

    cluster.set_all(uuid, "Running")
    rec.tick()
    cluster.pods[uuid][0]["phase"] = "Failed"
    assert rec.tick() == [(uuid, V1Statuses.QUEUED)]
    assert cluster.deleted == [uuid]
    submits_before = cluster.submit_calls

    # old pods still draining: no resubmit, no double-delete, no re-count
    assert rec.tick() == []
    assert rec.tick() == []
    assert cluster.submit_calls == submits_before

    cluster.drain(uuid)  # k8s finishes the delete
    assert rec.tick() == [(uuid, V1Statuses.SCHEDULED)]
    assert cluster.submit_calls == submits_before + 1
    assert all(p["phase"] == "Pending" for p in cluster.pods[uuid])
