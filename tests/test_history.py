"""ISSUE 18 coverage: the embedded metrics-history store, rate/trend
queries, and the perf-regression sentinel.

Unit layer: CRC-framed tiered store semantics under a fake clock
(append/samples/de-dup, last-per-bucket downsampling that loses no rate
information, byte-bounded tiered retention), the PR 11 heal contract
under the seeded `history.append` chaos sweep (kill / scramble_tail /
corrupt_segment), query aggregation math pinned against hand-computed
references (avg windows, counter-reset-aware rate, bucket-interpolated
percentiles), every BadQuery shape, the federated `cluster:*:sum`
reset clamp promised by telemetry/federate.py's docstring, sentinel
rule kinds + edge-triggering (one event per inactive→active transition,
`rule_kind` in the body — the run event log flattens bodies, so a
`kind` key would clobber the event kind), flight-recorder bundles,
scenario trend/floor predicates, and the `polyaxon top` sparkline.

Live-HTTP layer (pytest.mark.serving, tiny models): /queryz on all
three surfaces (serving server, router with federated series, streams
server), the history health series on /metricsz, and the CLI round
trips — `polyaxon query`, `polyaxon trace --export`, and
`polyaxon perf diff` gating against a bench record.
"""

import json
import http.client
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from polyaxon_tpu.chaos import injector
from polyaxon_tpu.chaos.injector import SimulatedKill
from polyaxon_tpu.chaos.plan import Fault, FaultPlan
from polyaxon_tpu.telemetry import MetricsRegistry
from polyaxon_tpu.telemetry.federate import parse_prometheus_text
from polyaxon_tpu.telemetry.history import (
    AGGS,
    BadQuery,
    HistorySampler,
    HistoryStore,
    TIERS,
    percentile_from_counts,
    queryz_payload,
    rate_over,
    sample_from_snapshots,
    sample_registry,
)
from polyaxon_tpu.telemetry.detect import (
    DEFAULT_SERVING_RULES,
    RegressionRule,
    RegressionSentinel,
    build_rules,
)

REPO = Path(__file__).resolve().parents[1]


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt
        return self.t


def _scalar(t, **series):
    return {"t": t, "s": {k: float(v) for k, v in series.items()}}


# ----------------------------------------------------------- store unit


def test_append_samples_roundtrip_and_window_filter(tmp_path):
    store = HistoryStore(tmp_path)
    for i in range(10):
        store.append(_scalar(float(i), m=i))
    recs = store.samples()
    assert [r["t"] for r in recs] == [float(i) for i in range(10)]
    assert store.series_names() == ["m"]
    window = store.samples(since=3.0, until=6.0)
    assert [r["s"]["m"] for r in window] == [3.0, 4.0, 5.0, 6.0]
    assert store.total_bytes() > 0
    assert store.heal_stats == {"clean": 0, "torn": 0, "corrupt": 0}


def test_raw_tier_shadows_coarse_on_duplicate_timestamp(tmp_path):
    store = HistoryStore(tmp_path)
    store.append(_scalar(100.0, m=999.0), tier="1m")
    store.append(_scalar(100.0, m=1.0))  # raw copy of the same instant
    recs = store.samples()
    assert len(recs) == 1
    assert recs[0]["s"]["m"] == 1.0  # finer tier wins


def test_tiered_retention_bounds_bytes_and_preserves_rate(tmp_path):
    store = HistoryStore(tmp_path, max_bytes=4096, segment_bytes=1024)
    assert store.max_bytes == 4096 and store.segment_bytes == 1024
    # a monotone 1/sec counter: downsampling keeps the last cumulative
    # state per bucket, so the full-span rate must survive eviction
    for i in range(600):
        store.append(_scalar(float(i), c=i))
    assert store.total_bytes() <= store.max_bytes
    assert store._segments("10s"), "raw overflow must downsample, not drop"
    res = store.query("c", agg="rate")
    assert res["points"][0][1] == pytest.approx(1.0)
    assert res["resets"] == 0
    # only a fraction of the raw samples survive, all time-ordered
    recs = store.samples()
    assert 2 <= len(recs) < 600
    ts = [r["t"] for r in recs]
    assert ts == sorted(ts)


def test_heal_truncates_torn_tail_and_keeps_committed(tmp_path):
    store = HistoryStore(tmp_path)
    for i in range(3):
        store.append(_scalar(float(i), m=i))
    seg = store._segments("raw")[-1]
    with seg.open("ab") as f:
        f.write(b"\x13garbage-torn-tail")
    reopened = HistoryStore(tmp_path)
    assert reopened.heal_stats["torn"] == 1
    assert [r["s"]["m"] for r in reopened.samples()] == [0.0, 1.0, 2.0]
    # the healed store accepts new appends on the truncated segment
    reopened.append(_scalar(3.0, m=3.0))
    assert len(reopened.samples()) == 4


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [1, 7, 23])
def test_chaos_kill_mid_append_commits_prefix(tmp_path, seed):
    at = 2 + seed % 4
    store = HistoryStore(tmp_path)
    plan = FaultPlan([Fault("history.append", "kill", at=at)], seed=seed)
    appended = 0
    with injector.active(plan):
        with pytest.raises(SimulatedKill):
            for i in range(12):
                store.append(_scalar(float(i), m=i))
                appended += 1
    assert appended == at  # the injection fires before the write lands
    reopened = HistoryStore(tmp_path)
    assert [r["s"]["m"] for r in reopened.samples()] == [
        float(i) for i in range(at)
    ]


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [3, 11, 41])
def test_chaos_scramble_tail_heals_to_last_frame(tmp_path, seed):
    at = 1 + seed % 5
    store = HistoryStore(tmp_path)
    plan = FaultPlan(
        [Fault("history.append", "scramble_tail", at=at)], seed=seed
    )
    with injector.active(plan):
        with pytest.raises(SimulatedKill):
            for i in range(12):
                store.append(_scalar(float(i), m=i))
    reopened = HistoryStore(tmp_path)
    assert reopened.heal_stats["torn"] == 1
    assert [r["s"]["m"] for r in reopened.samples()] == [
        float(i) for i in range(at)
    ]


@pytest.mark.chaos
def test_chaos_corrupt_segment_quarantines_never_wedges(tmp_path):
    store = HistoryStore(tmp_path)
    for i in range(3):
        store.append(_scalar(float(i), m=i))
    plan = FaultPlan(
        [Fault("history.append", "corrupt_segment", at=0)], seed=5
    )
    with injector.active(plan):
        store.append(_scalar(3.0, m=3.0))  # bit rot lands, append proceeds
    reopened = HistoryStore(tmp_path)
    assert reopened.heal_stats["corrupt"] == 1
    assert list(tmp_path.glob("*.corrupt")), "forensics copy must exist"
    # the store boots, queries answer, and new appends land
    reopened.append(_scalar(4.0, m=4.0))
    assert reopened.samples()[-1]["s"]["m"] == 4.0


# ----------------------------------------------------------- query math


def test_query_avg_windows_exact(tmp_path):
    store = HistoryStore(tmp_path)
    for i in range(10):
        store.append(_scalar(float(i), m=i))
    res = store.query("m", since=0, until=9, step=3, agg="avg")
    assert res["points"] == [
        [0.0, pytest.approx(1.5)],  # 0,1,2,3 (window ends inclusive)
        [3.0, pytest.approx(4.5)],  # 3,4,5,6
        [6.0, pytest.approx(7.5)],  # 6,7,8,9
    ]
    assert res["samples"] == 10
    assert store.query("m", agg="min")["points"][0][1] == 0.0
    assert store.query("m", agg="max")["points"][0][1] == 9.0
    # empty window aggregates to None, not zero
    sparse = store.query("m", since=0, until=100, step=50, agg="avg")
    assert sparse["points"][1][1] is None


def test_query_rate_simple_counter(tmp_path):
    store = HistoryStore(tmp_path)
    for i in range(11):
        store.append(_scalar(float(i), c=5 * i))
    res = store.query("c", agg="rate")
    assert res["points"][0][1] == pytest.approx(5.0)
    assert res["resets"] == 0


def test_query_rate_counter_reset_clamped(tmp_path):
    store = HistoryStore(tmp_path)
    for t, v in enumerate([0, 10, 20, 5, 15]):
        store.append(_scalar(float(t), c=v))
    res = store.query("c", agg="rate")
    # 10+10 before the restart, 5 counted from zero, 10 after: never
    # a negative delta, and the restart is annotated
    assert res["points"][0][1] == pytest.approx(35 / 4)
    assert res["resets"] == 1


def test_rate_over_reference_pins():
    # the last sample BEFORE the window is the rate base
    assert rate_over([(0.0, 0.0), (10.0, 50.0)], 5.0, 10.0) == (
        pytest.approx(5.0),
        0,
    )
    assert rate_over([(0.0, 0.0)], 0.0, 10.0) == (None, 0)
    assert rate_over([], 0.0, 10.0) == (None, 0)
    v, resets = rate_over(
        [(0.0, 0.0), (1.0, 10.0), (3.0, 4.0)], 0.0, 3.0
    )
    assert v == pytest.approx(14 / 3) and resets == 1


def test_percentile_from_counts_interpolation():
    bounds = [1.0, 2.0, 4.0]
    assert percentile_from_counts([0, 10, 0, 0], bounds, 0.5) == (
        pytest.approx(1.5)
    )
    assert percentile_from_counts([0, 10, 0, 0], bounds, 0.95) == (
        pytest.approx(1.95)
    )
    # overflow bucket clamps to the top bound
    assert percentile_from_counts([0, 0, 0, 10], bounds, 0.5) == 4.0
    assert percentile_from_counts([0, 0, 0, 0], bounds, 0.5) is None
    assert percentile_from_counts([], [], 0.5) is None


def test_query_percentiles_from_histogram_window_delta(tmp_path):
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=(1.0, 2.0, 4.0))
    store = HistoryStore(tmp_path)
    for _ in range(4):
        h.observe(0.5)
    store.append(sample_registry(reg, 0.0))
    for _ in range(4):
        h.observe(3.0)
    store.append(sample_registry(reg, 10.0))
    # window [5, 10]: start = t0 state, end = t10 state, delta = the
    # four 3.0 observations → interpolated inside the (2, 4] bucket
    res = store.query("lat", since=5, until=10, agg="p50")
    assert res["points"][0][1] == pytest.approx(3.0)
    res95 = store.query("lat", since=5, until=10, agg="p95")
    assert res95["points"][0][1] == pytest.approx(2 + 2 * 0.95)
    # whole-span window has no start state: end counts alone, mixed —
    # rank 4 of 8 sits exactly at the top of the (0, 1] bucket
    both = store.query("lat", agg="p50")
    assert both["points"][0][1] == pytest.approx(1.0)


def test_query_histogram_reset_falls_back_to_end_counts(tmp_path):
    store = HistoryStore(tmp_path)
    store.append(
        {"t": 0.0, "h": {"lat": [[5, 5], 10.0, 10]}, "hb": {"lat": [1.0]}}
    )
    store.append(  # a bucket decreased: the process restarted
        {"t": 10.0, "h": {"lat": [[2, 0], 1.0, 2]}, "hb": {"lat": [1.0]}}
    )
    res = store.query("lat", since=5, until=10, agg="p50")
    assert res["resets"] == 1
    assert res["points"][0][1] is not None


def test_query_bad_query_shapes(tmp_path):
    store = HistoryStore(tmp_path)
    with pytest.raises(BadQuery):
        store.query("anything", agg="avg")  # empty store
    store.append(_scalar(0.0, m=1.0))
    store.append(
        {"t": 0.0, "h": {"lat": [[1, 0], 0.5, 1]}, "hb": {"lat": [1.0]}}
    )
    store.append({"t": 1.0, "h": {"nb": [[1, 0], 0.5, 1]}})  # no bounds
    with pytest.raises(BadQuery):
        store.query("m", agg="median")
    with pytest.raises(BadQuery):
        store.query("nope")
    with pytest.raises(BadQuery):
        store.query("m", since=10, until=0)
    with pytest.raises(BadQuery):
        store.query("m", since=0, until=100_000, step=1)
    with pytest.raises(BadQuery):
        store.query("lat", agg="avg")  # scalar agg on a histogram
    with pytest.raises(BadQuery):
        store.query("m", agg="p95")  # percentile on a scalar
    with pytest.raises(BadQuery):
        store.query("nb", agg="p50")  # histogram without bounds
    assert "median" not in AGGS


# ------------------------------------------------- sampling / federation


def test_sample_registry_shape():
    reg = MetricsRegistry()
    reg.counter("c").inc(3)
    reg.gauge("g").set(7.5)
    reg.histogram("h", buckets=(1.0,)).observe(0.5)
    rec = sample_registry(reg, 42.0)
    assert rec["t"] == 42.0
    assert rec["s"]["c"] == 3.0 and rec["s"]["g"] == 7.5
    counts, hsum, hcount = rec["h"]["h"]
    assert counts == [1, 0] and hsum == 0.5 and hcount == 1
    assert rec["hb"]["h"] == [1.0]


def test_sample_from_snapshots_federates_and_skips_buckets():
    snap = parse_prometheus_text(
        "foo_total 100\nbar 5\nqux_bucket 3\n"
    )
    rec = sample_from_snapshots([("r0", snap), ("r1", None)], 9.0)
    s = rec["s"]
    assert s['federation_source_up{replica="r0"}'] == 1.0
    assert s['federation_source_up{replica="r1"}'] == 0.0
    assert s['foo_total{replica="r0"}'] == 100.0
    assert s["cluster:foo_total:sum"] == 100.0
    assert s["cluster:bar:sum"] == 5.0
    assert not any("qux_bucket" in k for k in s)


def test_federated_cluster_sum_reset_clamp(tmp_path):
    """The hazard pinned in telemetry/federate.py's docstring: one
    source restarting drops the instantaneous `cluster:*:sum`, and
    rate() must read that as a reset — never a negative rate."""
    store = HistoryStore(tmp_path)

    def snaps(va, vb):
        return [
            ("a", parse_prometheus_text(f"req_total {va}\n")),
            ("b", parse_prometheus_text(f"req_total {vb}\n")),
        ]

    store.append(sample_from_snapshots(snaps(100, 50), 0.0))  # sum 150
    store.append(sample_from_snapshots(snaps(110, 60), 10.0))  # 170
    store.append(sample_from_snapshots(snaps(120, 0), 20.0))  # b restarted
    store.append(sample_from_snapshots(snaps(130, 10), 30.0))  # 140
    res = store.query("cluster:req_total:sum", agg="rate")
    rate = res["points"][0][1]
    assert rate is not None and rate >= 0
    assert rate == pytest.approx((20 + 120 + 20) / 30)
    assert res["resets"] == 1
    per = store.query('req_total{replica="b"}', agg="rate")
    assert per["points"][0][1] == pytest.approx(20 / 30)
    assert per["resets"] == 1


def test_history_sampler_fake_clock_and_health_metrics(tmp_path):
    reg = MetricsRegistry()
    reg.counter("c").inc(3)
    clk = FakeClock(t=50.0)
    store = HistoryStore(tmp_path / "h")
    sampler = HistorySampler(reg, store, interval_s=1.0, clock=clk)
    rec = sampler.sample_once()
    assert rec["t"] == 50.0 and rec["s"]["c"] == 3.0
    clk.tick(10.0)
    sampler.sample_once()
    assert reg.counter("history.samples").value == 2
    assert reg.gauge("history.bytes").value == store.total_bytes() > 0
    assert reg.gauge("history.healed_segments").value == 0
    assert [r["t"] for r in store.samples()] == [50.0, 60.0]
    # a reopened-after-torn-tail store surfaces on the healed gauge
    seg = store._segments("raw")[-1]
    with seg.open("ab") as f:
        f.write(b"\x09torn")
    reg2 = MetricsRegistry()
    HistorySampler(reg2, HistoryStore(tmp_path / "h"), clock=clk)
    assert reg2.gauge("history.healed_segments").value == 1


def test_queryz_payload_contract(tmp_path):
    assert queryz_payload(None, "") == (503, {"error": "history disabled"})
    store = HistoryStore(tmp_path)
    code, listing = queryz_payload(store, "")
    assert code == 200 and listing["series"] == []
    assert set(listing["tiers"]) == set(TIERS)
    store.append(_scalar(0.0, m=1.0))
    store.append(_scalar(1.0, m=3.0))
    code, listing = queryz_payload(store, None)
    assert code == 200 and listing["series"] == ["m"]
    code, res = queryz_payload(store, "series=m&agg=avg")
    assert code == 200 and res["points"][0][1] == pytest.approx(2.0)
    assert queryz_payload(store, "series=m&agg=bogus")[0] == 400
    assert queryz_payload(store, "series=zzz")[0] == 400
    assert queryz_payload(store, "series=m&last=abc")[0] == 400


# -------------------------------------------------------------- sentinel


def _fill(store, t0, t1, value, step=30.0):
    t = t0
    while t <= t1:
        store.append(_scalar(t, m=value))
        t += step


def test_sentinel_edge_fires_once_and_rearms(tmp_path):
    reg = MetricsRegistry()
    store = HistoryStore(tmp_path)
    events = []
    sentinel = RegressionSentinel(
        store,
        reg,
        build_rules(
            [{"name": "m-high", "series": "m", "kind": "ceiling",
              "threshold": 5.0, "window_s": 60.0}]
        ),
        on_event=lambda kind, body: events.append((kind, body)),
        clock=FakeClock(0.0),
    )
    _fill(store, 0, 120, 1.0)
    res = sentinel.evaluate()
    assert not res[0]["active"] and not events
    assert reg.gauge("regression.active").value == 0.0
    # spike: the last 60s window's avg crosses the ceiling
    _fill(store, 150, 180, 10.0)
    res = sentinel.evaluate()
    assert res[0]["active"] and res[0]["edge"]
    assert len(events) == 1
    kind, body = events[0]
    assert kind == "perf_regression"
    # the run event log flattens bodies: the rule's kind must travel
    # under its own name so it cannot clobber the event kind
    assert "kind" not in body
    assert body["rule_kind"] == "ceiling"
    assert body["name"] == "m-high" and body["value"] == pytest.approx(7.0)
    assert body["history_window"] and "window" not in body
    assert reg.gauge("regression.active").value == 1.0
    assert reg.gauge("regression.active.m_high").value == 1.0
    # still active: level-triggered gauges, no second event
    sentinel.evaluate()
    assert len(events) == 1
    # recovery re-arms the edge
    _fill(store, 210, 330, 1.0)
    assert not sentinel.evaluate()[0]["active"]
    assert reg.gauge("regression.active.m_high").value == 0.0
    _fill(store, 360, 390, 10.0)
    assert sentinel.evaluate()[0]["edge"]
    assert len(events) == 2
    assert sentinel.last[0]["active"]
    assert sentinel.to_dict()["active"] == ["m-high"]


def test_sentinel_rule_kinds_reference_verdicts(tmp_path):
    store = HistoryStore(tmp_path)
    # ratio series: [0,60] avg 10, [60,120] avg (10+30+30)/3
    _fill(store, 0, 60, 10.0)
    _fill(store, 90, 120, 30.0)
    ratio = RegressionRule(
        {"name": "r", "series": "m", "kind": "window_ratio",
         "threshold": 2.0, "window_s": 60.0}
    )
    res = ratio.evaluate(store, 120.0)
    assert res["active"] and res["ratio"] == pytest.approx(70 / 30)
    assert res["baseline"] == pytest.approx(10.0)
    below = RegressionRule(
        {"name": "b", "series": "m", "kind": "ceiling",
         "threshold": 5.0, "direction": "below", "window_s": 60.0}
    )
    assert not below.evaluate(store, 120.0)["active"]
    # ewma drift over three 10s windows: baseline 10, last window 17.5
    store2 = HistoryStore(tmp_path / "d")
    for t, v in [(0, 10), (10, 10), (20, 10), (30, 25)]:
        store2.append(_scalar(float(t), m=v))
    drift = RegressionRule(
        {"name": "d", "series": "m", "kind": "ewma_drift",
         "threshold": 0.5, "window_s": 10.0, "lookback_windows": 3}
    )
    res = drift.evaluate(store2, 30.0)
    assert res["baseline"] == pytest.approx(10.0)
    assert res["value"] == pytest.approx(17.5)
    assert res["active"]  # 17.5 > 10 * 1.5
    # an unqueryable series is an inactive rule, never a raise
    ghost = RegressionRule(
        {"name": "g", "series": "ghost", "kind": "ceiling", "threshold": 1}
    )
    assert not ghost.evaluate(store, 120.0)["active"]
    # min_samples guards thin histories
    thin = RegressionRule(
        {"name": "t", "series": "m", "kind": "ceiling",
         "threshold": 0.1, "window_s": 60.0, "min_samples": 100}
    )
    assert not thin.evaluate(store, 120.0)["active"]


def test_sentinel_flight_recorder_bundle(tmp_path):
    from polyaxon_tpu.telemetry import FlightRecorder

    reg = MetricsRegistry()
    store = HistoryStore(tmp_path / "h")
    recorder = FlightRecorder(tmp_path / "dbg")
    sentinel = RegressionSentinel(
        store,
        reg,
        build_rules(
            [{"name": "m-high", "series": "m", "kind": "ceiling",
              "threshold": 5.0, "window_s": 60.0}]
        ),
        recorder=recorder,
        clock=FakeClock(0.0),
    )
    _fill(store, 0, 120, 1.0)
    _fill(store, 150, 180, 10.0)
    sentinel.evaluate()
    bundles = sorted((tmp_path / "dbg").glob("slo-*-m_high/breach.json"))
    assert len(bundles) == 1
    breach = json.loads(bundles[0].read_text())
    assert breach["name"] == "m-high"
    assert breach["rule_kind"] == "ceiling"
    assert breach["history_window"]


def test_sentinel_event_kind_survives_run_store_flattening(tmp_path):
    """End-to-end pin of the flattening hazard: a sentinel edge logged
    through RunStore.log_event must still read back as a
    `perf_regression` event (not as the rule's kind)."""
    from polyaxon_tpu.store.local import RunStore

    store = RunStore(tmp_path / "runs")
    uid = "histsent0001aaaa"
    store.create_run(uid, "hist-sentinel", "default", {"kind": "test"})
    hist = HistoryStore(tmp_path / "h")
    sentinel = RegressionSentinel(
        hist,
        MetricsRegistry(),
        build_rules(
            [{"name": "surge", "series": "m", "kind": "window_ratio",
              "threshold": 2.0, "window_s": 60.0}]
        ),
        on_event=lambda kind, body: store.log_event(uid, kind, body),
        clock=FakeClock(0.0),
    )
    _fill(hist, 0, 60, 10.0)
    _fill(hist, 90, 120, 30.0)
    sentinel.evaluate()
    events = [
        e for e in store.read_events(uid) if e["kind"] == "perf_regression"
    ]
    assert len(events) == 1
    assert events[0]["rule_kind"] == "window_ratio"
    assert events[0]["name"] == "surge"
    assert events[0]["history_window"]


def test_build_rules_validation():
    rules = build_rules(DEFAULT_SERVING_RULES)
    assert [r.name for r in rules] == [
        "ttft-creep", "queue-wait-trend", "accept-rate-collapse",
        "kv-spill-surge", "tenant-queue-wait-trend", "adapter-thrash-surge",
        "handoff-latency-trend",
    ]
    with pytest.raises(ValueError, match="duplicate"):
        build_rules(
            [{"name": "x", "series": "m", "threshold": 1}] * 2
        )
    with pytest.raises(ValueError, match="kind"):
        RegressionRule(
            {"name": "x", "series": "m", "kind": "nope", "threshold": 1}
        )
    with pytest.raises(ValueError, match="direction"):
        RegressionRule(
            {"name": "x", "series": "m", "threshold": 1,
             "direction": "sideways"}
        )
    with pytest.raises(ValueError, match="window_s"):
        RegressionRule(
            {"name": "x", "series": "m", "threshold": 1, "window_s": 0}
        )
    clamped = RegressionRule(
        {"name": "x", "series": "m", "threshold": 1,
         "lookback_windows": 1, "min_samples": 0}
    )
    assert clamped.lookback_windows == 2 and clamped.min_samples == 1


# --------------------------------------------- scenario trend predicates


def test_half_means_and_trend_floor_predicates():
    from polyaxon_tpu.scenarios.registry import (
        Assertions,
        evaluate,
        half_means,
    )

    assert half_means([1, 2, 3]) == (None, None)  # too thin
    assert half_means([1, 1, 2, 2]) == (1.0, 2.0)
    assert half_means([1, None, 1, 2, 2]) == (1.0, 2.0)  # Nones dropped

    a = Assertions(
        max_metric_trend={"latency_ms": 3.0},
        min_metric_floor={"ok": 0.5},
    )
    summary = {"hung": 0, "shed_rate": 0.0, "ok": 8, "disconnected": 0}

    def verdict(history, name):
        out = evaluate(a, summary, {}, history)
        return next(v for v in out if v["assertion"] == name)

    good = {"latency_ms": [1, 1, 1, 1, 2, 2, 2, 2], "ok": [1, 1, 1, 1]}
    assert verdict(good, "max_metric_trend:latency_ms")["ok"]
    assert verdict(good, "min_metric_floor:ok")["ok"]
    drifting = {"latency_ms": [1, 1, 1, 1, 10, 10, 10, 10], "ok": good["ok"]}
    assert not verdict(drifting, "max_metric_trend:latency_ms")["ok"]
    sagging = {"latency_ms": good["latency_ms"], "ok": [1, 1, 0, 0]}
    assert not verdict(sagging, "min_metric_floor:ok")["ok"]
    # thin history: trend is vacuous-pass, a floor with no samples fails
    thin = {"latency_ms": [1, 2], "ok": []}
    v = verdict(thin, "max_metric_trend:latency_ms")
    assert v["ok"] and "vacuous" in v["detail"]
    assert not verdict(thin, "min_metric_floor:ok")["ok"]


def test_trend_tape_stride_doubling_keeps_halves():
    from polyaxon_tpu.scenarios.twin import TrendTape

    tape = TrendTape(cap=8)
    for i in range(32):
        tape.add(float(i))
    assert len(tape.points) <= 8
    assert tape.points[0] == 0.0
    diffs = {
        b - a for a, b in zip(tape.points, tape.points[1:])
    }
    assert len(diffs) == 1  # uniform stride: halves stay halves
    assert tape.points == [0.0, 8.0, 16.0, 24.0]


def test_scenarios_carry_history_assertions():
    from polyaxon_tpu.scenarios.registry import SCENARIOS

    for name in ("diurnal_soak", "prefix_storm"):
        a = SCENARIOS[name].assertions
        assert a.max_metric_trend == {"latency_ms": 3.0}
        assert a.min_metric_floor == {"ok": 0.5}


# -------------------------------------------------------------- sparkline


def test_sparkline_pure_pins():
    from polyaxon_tpu.cli.top import sparkline

    assert sparkline([]) == ""
    assert sparkline([None, None]) == ""
    assert sparkline([1.0, 1.0, 1.0]) == "▁▁▁"  # flat renders low, not empty
    assert sparkline([0.0, 7.0]) == "▁█"
    assert sparkline([0.0, None, 7.0]) == "▁ █"
    assert sparkline(list(range(100)), width=4) == "▁▃▅█"


def test_lint_rule_15_clock_free_history_layer():
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_telemetry.py")],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr


# ------------------------------------------------------------- live HTTP

CFG = {
    "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
}


def _build():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    b = build_model("transformer_lm", CFG)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


def _server(module, params, **kw):
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    server_kw = {
        k: kw.pop(k)
        for k in (
            "slos", "debug_dir", "registry", "history",
            "regression_rules", "event_sink",
        )
        if k in kw
    }
    cfg = ServingConfig(**{
        "max_batch": 4, "max_wait_ms": 2.0, "kv_page_tokens": 8,
        "stream_chunk_tokens": 3, **kw,
    })
    return ModelServer(
        module, params, model_name="tiny", config=cfg, **server_kw
    )


@pytest.fixture(scope="module")
def hist_server(tmp_path_factory):
    module, params = _build()
    hist_dir = tmp_path_factory.mktemp("history")
    srv = _server(
        module, params, kv_pool_pages=64,
        history={"dir": str(hist_dir), "interval_s": 0.05},
        regression_rules=[
            {"name": "latency-surge", "series": "serving.request_seconds",
             "kind": "window_ratio", "agg": "p95", "window_s": 2.0,
             "threshold": 2.0, "min_samples": 4}
        ],
    )
    port = srv.start(port=0)
    yield {"port": port, "srv": srv}
    srv.stop()


def _post(port, body, headers=None, path="/generate", timeout=120):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request("POST", path, json.dumps(body), headers=headers or {})
    r = c.getresponse()
    raw = r.read()
    c.close()
    return r.status, json.loads(raw)


def _get(port, path, timeout=60):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request("GET", path)
    r = c.getresponse()
    raw = r.read()
    c.close()
    try:
        return r.status, json.loads(raw)
    except (ValueError, UnicodeDecodeError):
        return r.status, raw


def _body(n_rows=1, max_new=6, seed=123):
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(1, 100, size=12).tolist() for _ in range(n_rows)]
    return {
        "tokens": prompts, "maxNewTokens": max_new, "temperature": 0.0,
        "seed": seed,
    }


@pytest.mark.serving
def test_serving_queryz_and_health_series(hist_server):
    port, srv = hist_server["port"], hist_server["srv"]
    st, _ = _post(port, _body())
    assert st == 200
    srv.history_sampler.sample_once()
    time.sleep(0.02)
    srv.history_sampler.sample_once()
    st, listing = _get(port, "/queryz")
    assert st == 200
    assert "serving.requests" in listing["series"]
    assert "serving.ttft_ms" in listing["series"]
    assert listing["bytes"] > 0
    st, res = _get(
        port, "/queryz?series=serving.requests&agg=rate&last=60&step=60"
    )
    assert st == 200 and res["agg"] == "rate" and res["points"]
    st, res = _get(port, "/queryz?series=serving.ttft_ms&agg=p95&last=60")
    assert st == 200
    assert _get(port, "/queryz?series=serving.requests&agg=bogus")[0] == 400
    st, text = _get(port, "/metricsz")
    text = text.decode() if isinstance(text, bytes) else str(text)
    for needle in (
        "history_samples_total", "history_bytes", "regression_active",
        "regression_active_latency_surge",
    ):
        assert needle in text
    assert srv.sentinel is not None


@pytest.mark.serving
def test_router_federated_queryz(hist_server, tmp_path):
    from polyaxon_tpu.serving.router import Router

    r = Router(
        [f"http://127.0.0.1:{hist_server['port']}"],
        history={"dir": str(tmp_path / "rh")},
        poll_interval_s=30.0,
    )
    r.poll_once()
    time.sleep(0.05)
    r.poll_once()
    rport = r.start("127.0.0.1", 0)
    try:
        st, listing = _get(rport, "/queryz")
        assert st == 200
        names = set(listing["series"])
        assert 'federation_source_up{replica="r0"}' in names
        assert any(n.startswith("cluster:") for n in names)
        assert 'serving_requests_total{replica="r0"}' in names
        st, res = _get(
            rport,
            "/queryz?series=cluster:serving_requests_total:sum&agg=rate",
        )
        assert st == 200 and res["resets"] == 0
        # the top dashboard's sparkline fetch rides the same surface
        from polyaxon_tpu.cli.top import fetch_sparks

        sparks = fetch_sparks(f"http://127.0.0.1:{rport}")
        assert sparks and any(label == "req/s" for label, _ in sparks)
    finally:
        r.stop()


@pytest.mark.serving
def test_fetch_sparks_none_when_series_dark(hist_server):
    # the serving surface has history but no router.* series: every
    # spark query 400s and the pane disappears rather than rendering
    from polyaxon_tpu.cli.top import fetch_sparks

    assert fetch_sparks(f"http://127.0.0.1:{hist_server['port']}") is None


def test_streams_server_queryz(tmp_path):
    from polyaxon_tpu.store.local import RunStore
    from polyaxon_tpu.streams.server import BackgroundServer

    store = RunStore(tmp_path / "runs")
    with BackgroundServer(store, history_dir=str(tmp_path / "sh")) as srv:
        srv.server.history_sampler.sample_once()
        time.sleep(0.02)
        srv.server.history_sampler.sample_once()
        st, listing = _get(srv.port, "/queryz")
        assert st == 200 and listing["series"]
        series = listing["series"][0]
        from urllib.parse import quote

        st, res = _get(
            srv.port, f"/queryz?series={quote(series)}&agg=avg"
        )
        assert st == 200 and res["points"]
    # history disabled → 503, the shared contract
    with BackgroundServer(store) as srv:
        st, err = _get(srv.port, "/queryz")
        assert st == 503 and err["error"] == "history disabled"


# ------------------------------------------------------------------- CLI


@pytest.mark.serving
def test_cli_query_listing_and_series(hist_server):
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    url = f"http://127.0.0.1:{hist_server['port']}"
    hist_server["srv"].history_sampler.sample_once()
    res = CliRunner().invoke(cli, ["query", "--url", url])
    assert res.exit_code == 0, res.output
    assert res.output.startswith("history:")
    assert "serving.requests" in res.output
    res = CliRunner().invoke(
        cli,
        ["query", "serving.requests", "--url", url, "--agg", "rate",
         "--last", "60", "--step", "60"],
    )
    assert res.exit_code == 0, res.output
    assert "agg=rate" in res.output
    res = CliRunner().invoke(
        cli, ["query", "serving.requests", "--url", url, "--json"]
    )
    assert res.exit_code == 0
    assert json.loads(res.output)["series"] == "serving.requests"


@pytest.mark.serving
def test_cli_trace_export_jsonl(hist_server, tmp_path):
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    st, _ = _post(hist_server["port"], _body(seed=77))
    assert st == 200
    out = tmp_path / "traces.jsonl"
    res = CliRunner().invoke(
        cli,
        ["trace", "--url", f"http://127.0.0.1:{hist_server['port']}",
         "--export", str(out), "-n", "5"],
    )
    assert res.exit_code == 0, res.output
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert lines and all("id" in t for t in lines)
    assert f"exported {len(lines)} traces" in res.output


@pytest.mark.serving
def test_cli_perf_diff_pass_and_gate(hist_server, tmp_path):
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    url = f"http://127.0.0.1:{hist_server['port']}"
    st, _ = _post(hist_server["port"], _body(seed=88))
    assert st == 200
    hist_server["srv"].history_sampler.sample_once()
    generous = tmp_path / "bench_ok.json"
    generous.write_text(json.dumps({"tail": '{"ttft_ms": 1e9}'}))
    res = CliRunner().invoke(
        cli,
        ["perf", "diff", str(generous), "--url", url,
         "--tolerance", "0.1"],
    )
    assert res.exit_code == 0, res.output
    assert "compared 1 field(s): ok" in res.output
    tight = tmp_path / "bench_tight.json"
    tight.write_text(json.dumps({"tail": '{"ttft_ms": 1e-6}'}))
    res = CliRunner().invoke(
        cli,
        ["perf", "diff", str(tight), "--url", url, "--tolerance", "0.0"],
    )
    assert res.exit_code != 0
    assert "REGRESSED" in res.output
    empty = tmp_path / "bench_empty.json"
    empty.write_text(json.dumps({"tail": '{"other": 1.0}'}))
    res = CliRunner().invoke(
        cli, ["perf", "diff", str(empty), "--url", url]
    )
    assert res.exit_code != 0
    assert "nothing compared" in res.output
