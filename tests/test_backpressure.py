"""Regression: long runs on a multi-device mesh must not outrun the device.

Unbounded async dispatch used to exhaust XLA's collective thread pool on the
8-device CPU mesh and abort at an all-reduce rendezvous ("Expected 8 threads
to join... only 7 arrived") after ~100 queued steps; Trainer.run now blocks
on step N-K so at most K steps are in flight.
"""

from polyaxon_tpu.runtime.trainer import Trainer
from polyaxon_tpu.schemas.run_kinds import (
    V1DataSpec,
    V1ModelSpec,
    V1OptimizerSpec,
    V1Program,
    V1TrainSpec,
)


def test_long_run_on_8_device_mesh_does_not_deadlock():
    program = V1Program(
        model=V1ModelSpec(
            name="mlp", config={"input_dim": 16, "num_classes": 4, "hidden": [8]}
        ),
        data=V1DataSpec(
            name="synthetic",
            batch_size=16,
            config={"shape": [16], "num_classes": 4},
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-2),
        # 200 steps with sparse logging: the exact shape that deadlocked —
        # log_every=50 leaves long stretches with no host sync at all
        train=V1TrainSpec(steps=200, log_every=50, precision="float32"),
    )
    trainer = Trainer(program, mesh_axes={"data": -1})
    result = trainer.run()
    assert result.history and result.history[-1]["step"] == 200
    assert result.history[-1]["loss"] == result.history[-1]["loss"]  # not NaN
