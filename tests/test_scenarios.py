"""Scenario engine coverage (ISSUE 16).

  * trace format: seeded generator determinism (same (generator, seed,
    params) → byte-identical records, PYTHONHASHSEED-independent),
    JSONL round-trip with version validation, shared-prefix cohorts
    that share real bytes;
  * discrete-event twin: deterministic reports, structural invariants
    (zero hung, zero leaked pages) under overload / chaos / disconnect
    ingredients, PhaseCosts fitting from /metricsz text;
  * registry: every real+twin scenario passes its declarative
    assertions in twin mode; `polyaxon scenario run --smoke` pins the
    million-user soak through the CLI; scenario_bench --smoke
    --twin-only pins the record schema in the default tier;
  * satellite 1 end to end: a streamed client that vanishes mid-stream
    is detected (serving_client_disconnects_total), its rows cancelled,
    its KV pages released promptly, and the server keeps serving;
  * slow tier: disconnect storm + replica-kill chaos scenarios against
    a live 2-replica router rig (zero hung, zero leaked), and the full
    scenario_bench --smoke twin-vs-real calibration pin.
"""

import http.client
import json
import os
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from polyaxon_tpu.scenarios import traces as tr
from polyaxon_tpu.scenarios.twin import PhaseCosts, ServingTwin, TwinConfig

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.serving


# ------------------------------------------------------------------ traces
SMALL = {
    "diurnal": dict(n=24, duration_s=4.0),
    "bursts": dict(n=24, duration_s=4.0),
    "flood": dict(n=24),
    "shared_prefix": dict(n=24),
    "disconnect_storm": dict(n=24),
    "bench_mix": dict(n=24),
    "single_shape": dict(n=24, rps=10.0),
}


def test_every_generator_is_deterministic_per_seed():
    for name, params in SMALL.items():
        a = list(tr.generate(name, 3, **params))
        b = list(tr.generate(name, 3, **params))
        c = list(tr.generate(name, 4, **params))
        assert a == b, f"{name}: same seed must reproduce byte-identically"
        assert a != c, f"{name}: a different seed must change the trace"
        # structural invariants every generator keeps
        assert [r.i for r in a] == list(range(len(a)))
        assert all(r.at >= 0 for r in a)
        assert all(x.at <= y.at for x, y in zip(a, a[1:])), name
        assert all(r.prompt_len >= 1 and r.max_new >= 1 for r in a)


def test_prompt_tokens_deterministic_and_cohorts_share_bytes():
    recs = list(tr.generate("shared_prefix", 5, n=40, cohorts=2))
    by_cohort = {}
    for r in recs:
        by_cohort.setdefault(r.prefix_group, []).append(r)
    assert len(by_cohort) == 2
    for group, members in by_cohort.items():
        toks = [tr.prompt_tokens(r, 256) for r in members[:4]]
        plen = max(1, (3 * members[0].prompt_len) // 4)
        for t in toks[1:]:
            assert t[:plen] == toks[0][:plen], "cohort must share its prefix"
    # derivation is pure: same record, same tokens
    r0 = recs[0]
    assert tr.prompt_tokens(r0, 256) == tr.prompt_tokens(r0, 256)
    # low-entropy prompts are cyclic (speculation-friendly by design)
    low = tr.TraceRequest(i=0, at=0.0, prompt_len=8, max_new=4,
                          prompt_seed=10, entropy="low")
    toks = tr.prompt_tokens(low, 128)
    assert toks == [(10 + j) % 128 for j in range(8)]


def test_trace_jsonl_roundtrip(tmp_path):
    path = tmp_path / "t.jsonl"
    recs = list(tr.generate("disconnect_storm", 7, n=12))
    n = tr.write_trace(path, {"name": "dc", "seed": 7,
                              "generator": "disconnect_storm"}, recs)
    assert n == 12
    head, back = tr.read_trace(path)
    assert head["trace_version"] == tr.TRACE_VERSION
    assert head["count"] == 12 and head["name"] == "dc"
    assert back == recs  # None-field omission must round-trip losslessly

    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"trace_version": 999}\n')
    with pytest.raises(ValueError, match="version"):
        tr.read_trace(bad)


def test_body_for_carries_request_contract():
    rec = tr.TraceRequest(i=1, at=0.0, prompt_len=6, max_new=4, seed=9,
                          prompt_seed=2, deadline_ms=250.0)
    body = tr.body_for(rec, 64)
    assert len(body["tokens"][0]) == 6
    assert all(0 <= t < 64 for t in body["tokens"][0])
    assert body["maxNewTokens"] == 4 and body["seed"] == 9
    assert body["topK"] == 40 and body["deadlineMs"] == 250.0
    no_dl = tr.body_for(tr.TraceRequest(i=0, at=0.0, prompt_len=4,
                                        max_new=2, top_k=None), 64)
    assert "deadlineMs" not in no_dl and "topK" not in no_dl

    with pytest.raises(ValueError, match="unknown trace generator"):
        tr.generate("nope", 0)


# -------------------------------------------------------------------- twin
def _twin(cfg=None, **kw):
    return ServingTwin(cfg or TwinConfig(), PhaseCosts(), **kw)


def test_twin_is_deterministic_and_structurally_sound():
    recs = lambda: tr.generate("diurnal", 11, n=2000, duration_s=30.0,  # noqa: E731
                               base_rps=80.0)
    a = _twin().run(recs())
    b = _twin().run(recs())
    assert a == b, "same trace + config must reproduce the same report"
    assert a["hung"] == 0 and a["kv_pages_leaked"] == 0
    assert a["offered"] == 2000
    assert a["ok"] + a["shed"] + a["deadline_504"] + a["disconnected"] \
        + a["error"] == 2000


def test_twin_sheds_queue_and_kv_pressure():
    cfg = TwinConfig(replicas=1, max_batch=2, max_queue=4,
                     kv_pool_pages=12, kv_page_tokens=8)
    out = ServingTwin(cfg, PhaseCosts(decode_step_ms=5.0)).run(
        tr.generate("flood", 2, n=300, rps=5000.0)
    )
    assert out["shed"] > 0
    assert set(out["shed_reasons"]) <= {"queue_full", "kv_pages"}
    assert out["hung"] == 0 and out["kv_pages_leaked"] == 0


def test_twin_replica_down_fails_over_without_hangs():
    out = ServingTwin(
        TwinConfig(replicas=2, kv_pool_pages=64),
        PhaseCosts(),
        faults=[{"kind": "replica_down", "replica": 0, "at_s": 1.0,
                 "duration_s": 2.0}],
    ).run(tr.generate("diurnal", 3, n=500, duration_s=10.0, base_rps=60.0))
    assert out["hung"] == 0 and out["kv_pages_leaked"] == 0
    assert out["ok"] > 0

    with pytest.raises(ValueError, match="unknown twin fault"):
        ServingTwin(TwinConfig(), PhaseCosts(),
                    faults=[{"kind": "meteor_strike"}])


def test_twin_prefix_directory_models_affinity_and_hit_rate():
    # ISSUE 17: each twin replica keeps a prefix directory; affinity
    # steers cohort repeats to the replica that already prefilled the
    # shared prefix, so only a handful of cold prefills happen
    # near-simultaneous arrivals: queues build, so JSQ genuinely spreads
    # rows across both replicas and affinity has a decision to make
    recs = lambda: tr.generate("shared_prefix", 5, n=60, rps=2000.0,  # noqa: E731
                               cohorts=3)
    on = _twin(TwinConfig(replicas=2, prefix_cache=True,
                          kv_pool_pages=64)).run(recs())
    p = on["prefix"]
    assert p["lookups"] > 0 and p["hits"] > 0
    assert p["hit_rate"] >= 0.5, p
    assert on["hung"] == 0 and on["kv_pages_leaked"] == 0
    # without affinity, JSQ spreads each cohort across BOTH replicas —
    # every replica pays its own cold prefill, so strictly fewer hits
    off = _twin(TwinConfig(replicas=2, prefix_cache=True,
                           kv_pool_pages=64, prefix_affinity=False)).run(
        recs())
    assert off["prefix"]["hits"] < p["hits"], (off["prefix"], p)
    # a replica death empties its directory with its pages
    dead = _twin(
        TwinConfig(replicas=2, prefix_cache=True, kv_pool_pages=64),
        faults=[{"kind": "replica_down", "replica": 0, "at_s": 0.5,
                 "duration_s": 0.5}],
    ).run(recs())
    assert dead["hung"] == 0 and dead["kv_pages_leaked"] == 0
    # prefix off: the ledger stays empty and hit_rate is None
    plain = _twin(TwinConfig(replicas=2, kv_pool_pages=64)).run(recs())
    assert plain["prefix"] == {"lookups": 0, "hits": 0, "hit_rate": None}


def test_twin_counts_disconnects_and_truncates_their_latency():
    out = _twin().run(tr.generate("disconnect_storm", 6, n=60, rps=30.0))
    assert out["disconnected"] > 0
    assert out["hung"] == 0 and out["kv_pages_leaked"] == 0


def test_phase_costs_fit_from_metricsz_text():
    # 10 requests: TTFT 40ms each (5ms of it queue wait), total 100ms
    text = "\n".join([
        "serving_ttft_ms_sum 400.0",
        "serving_ttft_ms_count 10",
        "serving_request_seconds_sum 1.0",
        "serving_request_seconds_count 10",
        "serving_queue_wait_seconds_sum 0.05",
        "serving_queue_wait_seconds_count 10",
    ])
    c = PhaseCosts.fit(text, mean_prompt_tokens=20.0, mean_new_tokens=7.0)
    # prefill region = 40 - 5 = 35ms → 80/20 split over 20 tokens
    assert c.prefill_ms_per_token == pytest.approx(0.8 * 35.0 / 20.0)
    assert c.batch_overhead_ms == pytest.approx(0.2 * 35.0)
    # decode region = 100 - 40 = 60ms over 6 steps
    assert c.decode_step_ms == pytest.approx(10.0)

    # a warmup baseline is subtracted sum-and-count-wise
    base = "\n".join([
        "serving_ttft_ms_sum 200.0",
        "serving_ttft_ms_count 2",
        "serving_request_seconds_sum 0.5",
        "serving_request_seconds_count 2",
    ])
    text2 = "\n".join([
        "serving_ttft_ms_sum 520.0",
        "serving_ttft_ms_count 10",
        "serving_request_seconds_sum 1.3",
        "serving_request_seconds_count 10",
    ])
    c2 = PhaseCosts.fit(text2, 20.0, 7.0, baseline_texts=base)
    assert c2.prefill_ms_per_token == pytest.approx(0.8 * 40.0 / 20.0)

    with pytest.raises(ValueError, match="no serving_ttft_ms"):
        PhaseCosts.fit("", 10.0, 5.0)


# ---------------------------------------------------------------- registry
def test_registry_twin_mode_passes_every_scenario():
    from polyaxon_tpu.scenarios.registry import SCENARIOS, run_twin

    for name, scn in SCENARIOS.items():
        if scn.twin_only:
            continue  # the 1M soak is pinned via the CLI test below
        res = run_twin(scn, smoke=True)
        assert res["pass"], (name, res["assertions"])
        assert res["summary"]["hung"] == 0
        assert res["summary"]["kv_pages_leaked"] == 0
        # twin runs are deterministic per (scenario, seed)
        assert run_twin(scn, smoke=True)["summary"] == res["summary"]


def test_registry_rejects_unknowns():
    from polyaxon_tpu.scenarios.registry import SCENARIOS, run_scenario

    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("nope", mode="twin")
    with pytest.raises(ValueError, match="twin-only"):
        run_scenario("million_user_soak", mode="real")
    assert len(SCENARIOS) >= 6


def test_cli_scenario_ls_and_million_user_twin_soak_pin():
    """`polyaxon scenario run million_user_soak --smoke` IS the CI pin:
    a million-request diurnal soak through the twin, zero hung requests,
    zero leaked pages, inside the per-test watchdog budget."""
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    runner = CliRunner()
    ls = runner.invoke(cli, ["scenario", "ls"])
    assert ls.exit_code == 0, ls.output
    rows = [json.loads(l) for l in ls.output.splitlines() if l.strip()]
    assert {r["name"] for r in rows} >= {
        "diurnal_soak", "burst_overload", "high_entropy_flood",
        "replica_kill_midsoak", "disconnect_storm", "million_user_soak",
    }

    run = runner.invoke(
        cli, ["scenario", "run", "million_user_soak", "--smoke"]
    )
    assert run.exit_code == 0, run.output
    head = json.loads(run.output.splitlines()[0])
    assert head["pass"] is True and head["mode"] == "twin"
    assert head["offered"] == 1_000_000 and head["hung"] == 0


def test_scenario_bench_twin_only_smoke_schema(tmp_home):
    """The default-tier wiring for scenario_bench: --twin-only emits the
    per-scenario records and the <60s million-user soak pin without
    touching jax (the full --smoke calibration is in the slow tier)."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "benchmarks/scenario_bench.py"),
         "--smoke", "--twin-only"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    twin = {r["scenario"]: r for r in recs if r["metric"] == "scenario_twin"}
    assert len(twin) >= 5
    for r in twin.values():
        assert {"value", "unit", "p99_ms", "slo_burn", "hung",
                "kv_pages_leaked", "trace_seed", "pass"} <= r.keys(), r
        assert r["hung"] == 0 and r["kv_pages_leaked"] == 0
        assert r["pass"], r
    soak = [r for r in recs if r["metric"] == "scenario_twin_soak_wall_s"]
    assert len(soak) == 1
    assert soak[0]["pass"] and soak[0]["value"] < 60.0, soak[0]
    assert soak[0]["requests"] == 1_000_000 and soak[0]["hung"] == 0


# --------------------------------------------- satellite 1: disconnect e2e
CFG = {
    "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
}


def _mini_server():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    b = build_model("transformer_lm", CFG)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return ModelServer(
        b.module, params, model_name="dc-e2e",
        config=ServingConfig(
            max_batch=2, max_wait_ms=2.0, kv_page_tokens=8,
            kv_pool_pages=32, stream_chunk_tokens=3,
            prefix_cache=False, request_timeout_s=60.0,
        ),
    )


def _metric(port: int, name: str) -> float:
    import urllib.request

    from polyaxon_tpu.telemetry import parse_prometheus_text

    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metricsz", timeout=30
    ).read().decode()
    return parse_prometheus_text(text).value(name, 0.0)


def test_midstream_disconnect_cancels_rows_and_releases_pages():
    """A streamed client that closes its socket after the first chunk
    must be counted on serving_client_disconnects_total, its rows
    cancelled (decode ends early), its KV pages released promptly — and
    the server must keep serving afterwards."""
    server = _mini_server()
    port = server.start(port=0)
    body = {"tokens": [[7] * 8], "maxNewTokens": 40, "temperature": 0.8,
            "topK": 40, "seed": 1}
    try:
        # warm the compile so the stream below is steady-state
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
        c.request("POST", "/generate", json.dumps(body),
                  {"Content-Type": "application/json"})
        assert c.getresponse().status == 200
        c.close()

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/generate?stream=1", json.dumps(body),
                     {"Content-Type": "application/json",
                      "X-Request-Id": "dc-e2e-1"})
        resp = conn.getresponse()
        assert resp.status == 200
        got = 0
        for raw in resp:
            if raw.startswith(b"data: "):
                got += 1
                break  # first token frame seen: vanish mid-stream
        assert got, "stream produced no frames"
        # abrupt close — what a vanished client looks like to the server
        # (the connection handed its socket to the response: Connection:
        # close, so conn.sock is already None — close the response's fp)
        resp.close()
        conn.close()

        # the server notices at its next write, cancels, releases
        waiter = threading.Event()
        for _ in range(200):
            if (
                _metric(port, "serving_client_disconnects_total") >= 1.0
                and _metric(port, "serving_kv_pages_used") <= 1.0
            ):
                break
            waiter.wait(0.1)
        assert _metric(port, "serving_client_disconnects_total") >= 1.0
        # <= 1: only the KV manager's permanent scratch page may remain
        assert _metric(port, "serving_kv_pages_used") <= 1.0

        # and the server still serves: no leaked decode slot or queue depth
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        c.request("POST", "/generate", json.dumps({**body, "seed": 2}),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        assert r.status == 200
        out = json.loads(r.read())
        assert len(out["tokens"][0]) == 8 + 40
        c.close()
    finally:
        server.stop()


def test_cancel_marks_only_unfinished_rows():
    from polyaxon_tpu.serving.batching import PendingRequest

    r = PendingRequest(tokens=[1], prompt_len=1, max_new=1, seed=0, key=None)
    r.cancel()
    assert r.cancelled
    done = PendingRequest(tokens=[1], prompt_len=1, max_new=1, seed=0,
                          key=None)
    done.finish(result=[1, 2])
    done.cancel()
    assert not done.cancelled, "a resolved row must not flip to cancelled"


# ------------------------------------------------- slow tier: live 2-replica
@pytest.fixture(scope="module")
def rig():
    from polyaxon_tpu.scenarios.registry import build_rig

    r = build_rig(replicas=2)
    yield r
    r.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_real_disconnect_storm_scenario(rig):
    from polyaxon_tpu.scenarios.registry import SCENARIOS, run_real

    res = run_real(SCENARIOS["disconnect_storm"], smoke=True, rig=rig)
    assert res["pass"], res["assertions"]
    assert res["summary"]["hung"] == 0
    assert res["metrics"]["kv_pages_leaked"] == 0
    assert res["metrics"]["client_disconnects"] >= 1


@pytest.mark.slow
@pytest.mark.chaos
def test_real_replica_kill_midsoak_scenario(rig):
    from polyaxon_tpu.scenarios.registry import SCENARIOS, run_real

    res = run_real(SCENARIOS["replica_kill_midsoak"], smoke=True, rig=rig)
    assert res["pass"], res["assertions"]
    assert res["chaos"] and "kill_tick" in res["chaos"]
    assert res["summary"]["hung"] == 0
    assert res["metrics"]["kv_pages_leaked"] == 0


@pytest.mark.slow
def test_real_prefix_storm_scenario():
    # own rig: prefix_storm needs prefix_cache + spill overrides the
    # shared fixture rig does not carry
    from polyaxon_tpu.scenarios.registry import SCENARIOS, run_real

    res = run_real(SCENARIOS["prefix_storm"], smoke=True)
    assert res["pass"], res["assertions"]
    assert res["summary"]["hung"] == 0
    # warm pages are NOT leaks: the prefix_held gauge discounts them
    assert res["metrics"]["kv_pages_leaked"] == 0
    assert res["metrics"]["prefix_hit_rate"] >= 0.25


@pytest.mark.slow
def test_scenario_bench_full_smoke_calibration_pin(tmp_home):
    proc = subprocess.run(
        [sys.executable, str(REPO / "benchmarks/scenario_bench.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=420,
        env=dict(os.environ, POLYAXON_JAX_PLATFORM="cpu",
                 POLYAXON_NUM_CPU_DEVICES="1"),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    cal = [r for r in recs
           if r["metric"] == "sim_vs_real_calibration_error"]
    assert len(cal) == 1
    assert cal[0]["pass"] and cal[0]["value"] <= 0.25, cal[0]
    real = [r for r in recs if r["metric"] == "scenario_real"]
    assert real and real[0]["hung"] == 0
