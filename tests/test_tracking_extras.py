"""Tracking extras: event kinds (image/histogram/html), framework
callbacks, deploy rendering."""

import numpy as np

from polyaxon_tpu import tracking
from polyaxon_tpu.k8s.deploy import render_deploy, write_deploy
from polyaxon_tpu.store.local import RunStore
from polyaxon_tpu.tracking.callbacks import (
    PolyaxonHFCallback,
    PolyaxonKerasCallback,
    polyaxon_log_fn,
)


def _fresh_run(monkeypatch):
    monkeypatch.delenv("POLYAXON_RUN_UUID", raising=False)
    monkeypatch.delenv("POLYAXON_RUN_OUTPUTS_PATH", raising=False)
    return tracking.init(name="extras")


def test_event_kinds(tmp_home, monkeypatch):
    run = _fresh_run(monkeypatch)
    run.log_image(np.zeros((4, 4, 3)), name="sample")
    run.log_histogram("weights", np.random.default_rng(0).normal(size=256))
    run.log_html("report", "<h1>hi</h1>")
    run.end()
    store = RunStore()
    kinds = [e["kind"] for e in store.read_events(run.uuid)]
    assert {"image", "histogram", "html"} <= set(kinds)
    hist = next(e for e in store.read_events(run.uuid) if e["kind"] == "histogram")
    assert sum(hist["counts"]) == 256
    files = list((store.outputs_dir(run.uuid)).rglob("*"))
    assert any(p.suffix == ".npy" for p in files)
    assert any(p.suffix == ".html" for p in files)


def test_keras_style_callback(tmp_home, monkeypatch):
    run = _fresh_run(monkeypatch)
    cb = PolyaxonKerasCallback(run)
    cb.set_params({"epochs": 2})
    cb.on_epoch_end(0, {"loss": 1.5, "acc": 0.5, "name": "skipme"})
    cb.on_epoch_end(1, {"loss": 1.0, "acc": 0.7})
    cb.on_train_end({"loss": 1.0})
    run.end()
    metrics = RunStore().read_metrics(run.uuid)
    assert [m["loss"] for m in metrics] == [1.5, 1.0]


def test_hf_callback_logs(tmp_home, monkeypatch):
    run = _fresh_run(monkeypatch)
    cb = PolyaxonHFCallback(run)

    class State:
        global_step = 7
        epoch = 1.0

    cb.on_log(None, State(), None, logs={"loss": 0.3, "lr": 1e-4, "txt": "no"})
    cb.on_train_end(None, State(), None)
    run.end()
    store = RunStore()
    metrics = store.read_metrics(run.uuid)
    assert metrics[0]["step"] == 7 and metrics[0]["loss"] == 0.3
    assert any(e["kind"] == "outputs" for e in store.read_events(run.uuid))


def test_generic_log_fn(tmp_home, monkeypatch):
    run = _fresh_run(monkeypatch)
    fn = polyaxon_log_fn(run)
    fn(3, {"loss": 0.9})
    run.end()
    assert RunStore().read_metrics(run.uuid)[0]["step"] == 3


def test_deploy_rendering(tmp_path):
    manifests = render_deploy(namespace="mlops", streams_port=9000)
    kinds = [m["kind"] for m in manifests]
    assert kinds.count("Deployment") == 2
    assert "PersistentVolumeClaim" in kinds and "Role" in kinds
    agent = next(
        m for m in manifests if m["metadata"]["name"] == "polyaxon-agent" and m["kind"] == "Deployment"
    )
    assert agent["metadata"]["namespace"] == "mlops"
    cmd = agent["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "agent" in cmd
    paths = write_deploy(manifests, str(tmp_path / "deploy"))
    assert len(paths) == len(manifests)
