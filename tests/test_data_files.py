"""File-backed data pipelines: memory-mapped token corpora and .npy array
datasets, end-to-end through the trainer."""

import numpy as np
import pytest

from polyaxon_tpu.data import build_data


def test_token_file_bin_and_npy(tmp_path):
    rng = np.random.default_rng(0)
    corpus = rng.integers(0, 512, size=4096).astype(np.uint16)
    bin_path = tmp_path / "corpus.bin"
    corpus.tofile(bin_path)
    npy_path = tmp_path / "corpus.npy"
    np.save(npy_path, corpus.astype(np.int32))

    for path, dtype in ((bin_path, "uint16"), (npy_path, None)):
        spec = build_data(
            "token_file",
            8,
            {"path": str(path), "seq_len": 64, "dtype": dtype},
            seed=1,
        )
        batch = next(spec.iterator)
        assert batch["inputs"].shape == (8, 64)
        assert batch["labels"].shape == (8, 64)
        # next-token alignment: labels are inputs shifted by one
        b2 = next(spec.iterator)
        assert (b2["inputs"][:, 1:] == b2["labels"][:, :-1]).all()
        assert spec.meta["corpus_tokens"] == 4096


def test_token_file_host_sharding_disjoint_streams(tmp_path):
    # token value == its offset, so a window's first token IS its start
    corpus = np.arange(8192, dtype=np.uint16)
    path = tmp_path / "c.bin"
    corpus.tofile(path)
    a = build_data("token_file", 4, {"path": str(path), "seq_len": 32},
                   seed=5, process_index=0, process_count=2)
    b = build_data("token_file", 4, {"path": str(path), "seq_len": 32},
                   seed=5, process_index=1, process_count=2)
    # disjoint by construction: host 0 draws even starts, host 1 odd —
    # no window can ever appear on both hosts in any step
    seen_a, seen_b = set(), set()
    for _ in range(8):
        seen_a.update(int(x) for x in next(a.iterator)["inputs"][:, 0])
        seen_b.update(int(x) for x in next(b.iterator)["inputs"][:, 0])
    assert not (seen_a & seen_b), "hosts sampled overlapping windows"


def test_token_file_errors(tmp_path):
    with pytest.raises(FileNotFoundError):
        build_data("token_file", 4, {"path": str(tmp_path / "nope.bin")})
    tiny = tmp_path / "tiny.bin"
    np.arange(4, dtype=np.uint16).tofile(tiny)
    with pytest.raises(ValueError, match="need at least"):
        next(build_data("token_file", 4, {"path": str(tiny), "seq_len": 64}).iterator)


def test_array_file_classification_end_to_end(tmp_home, tmp_path):
    """array_file feeds the trainer: a linearly-separable .npy dataset
    trains an MLP to near-zero loss through the full runtime."""
    rng = np.random.default_rng(3)
    protos = rng.normal(size=(4, 16)).astype(np.float32)
    labels = rng.integers(0, 4, size=512)
    inputs = protos[labels] + 0.1 * rng.normal(size=(512, 16)).astype(np.float32)
    np.save(tmp_path / "x.npy", inputs.astype(np.float32))
    np.save(tmp_path / "y.npy", labels.astype(np.int64))

    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    program = V1Program(
        model=V1ModelSpec(
            name="mlp", config={"input_dim": 16, "num_classes": 4, "hidden": [32]}
        ),
        data=V1DataSpec(
            name="array_file",
            batch_size=32,
            config={"inputs": str(tmp_path / "x.npy"), "labels": str(tmp_path / "y.npy")},
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=0.01),
        train=V1TrainSpec(steps=40, log_every=40, precision="float32"),
    )
    result = Trainer(program, mesh_axes={"data": -1}).run()
    assert result.history[-1]["loss"] < 0.3
    assert result.history[-1]["accuracy"] > 0.9


def test_dataspec_close_releases_native_loader(tmp_path):
    """DataSpec.shutdown() closes the native loader deterministically —
    the executor/trainer teardown path, not GC-time __del__ (ADVICE r3)."""
    import numpy as np

    from polyaxon_tpu.data import build_data

    corpus = np.arange(4096, dtype=np.uint16)
    p = tmp_path / "corpus.bin"
    corpus.tofile(p)
    spec = build_data(
        "token_file", 4,
        {"path": str(p), "seq_len": 16, "loader": "native"},
    )
    assert spec.meta["loader"] == "native"
    assert spec.close is not None
    batch = next(spec.iterator)
    assert batch["inputs"].shape == (4, 16)
    spec.shutdown()
    spec.shutdown()  # idempotent
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="closed"):
        next(spec.iterator)
