"""Deterministic fault-injection scenarios over the run lifecycle.

Every scenario is seeded: the fault schedule (which step the kill lands
on, which poll the preemption strikes) derives from the seed, and the
test asserts the EXACT recovery point the plan's params predict — not
just "it eventually succeeded"."""

import time

import jax
import pytest
import yaml

from polyaxon_tpu import chaos
from polyaxon_tpu.chaos import (
    Fault,
    FaultPlan,
    FlakyCluster,
    PartitionedCluster,
    PreemptingCluster,
    ScriptedCluster,
)
from polyaxon_tpu.compiler import compile_operation
from polyaxon_tpu.connections.schemas import ConnectionCatalog
from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.retry import PermanentError, RetryPolicy, TransientError, classify
from polyaxon_tpu.runtime import Executor
from polyaxon_tpu.scheduler.agent import Agent
from polyaxon_tpu.scheduler.reconciler import ClusterSubmitter, Reconciler
from polyaxon_tpu.schemas import V1Operation
from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.store import RunStore

pytestmark = pytest.mark.chaos


def _train_op(name: str, *, steps: int, max_retries: int, checkpoint_every: int = 2):
    return V1Operation.model_validate(
        {
            "kind": "operation",
            "name": name,
            "component": {
                "kind": "component",
                "termination": {"maxRetries": max_retries},
                "run": {
                    "kind": "jaxjob",
                    "program": {
                        "model": {
                            "name": "mlp",
                            "config": {"hidden": [16], "input_dim": 8, "num_classes": 4},
                        },
                        "data": {
                            "name": "synthetic",
                            "batchSize": 16,
                            "config": {"shape": [8], "num_classes": 4},
                        },
                        "optimizer": {"name": "adamw", "learningRate": 0.01},
                        "train": {
                            "steps": steps,
                            "logEvery": 2,
                            "precision": "float32",
                            "checkpointEvery": checkpoint_every,
                        },
                    },
                },
            },
        }
    )


def _events(store, uuid, kind):
    return [e for e in store.read_events(uuid) if e["kind"] == kind]


def _conditions(store, uuid, type_=None):
    conds = store.get_status(uuid)["conditions"]
    return [c for c in conds if type_ is None or c["type"] == type_]


# --------------------------------------------------------------- unit layer
class TestRetryPolicy:
    def test_delays_deterministic_and_capped(self):
        p = RetryPolicy(max_retries=5, backoff=0.5, backoff_factor=2.0,
                        backoff_max=2.0, jitter=0.0)
        assert [p.delay(i) for i in range(4)] == [0.5, 1.0, 2.0, 2.0]
        pj = RetryPolicy(max_retries=5, backoff=1.0, jitter=0.2)
        d1 = pj.delay(0, seed="run-a")
        assert d1 == pj.delay(0, seed="run-a")  # same seed → same jitter
        assert 0.8 <= d1 <= 1.0  # jitter only shrinks

    def test_classification(self):
        from polyaxon_tpu.k8s.cluster import ClusterError, _is_transient_stderr

        assert classify(TransientError("flap")) == "transient"
        assert classify(PermanentError("bad spec")) == "permanent"
        assert classify(ValueError("unknown")) == "transient"  # safe default
        assert classify(ClusterError("x", transient=False)) == "permanent"
        assert _is_transient_stderr(
            "Unable to connect to the server: connection refused"
        )
        assert not _is_transient_stderr('error validating "STDIN": unknown field')

    def test_permanent_error_not_retried_by_call(self):
        calls = []

        def fn():
            calls.append(1)
            raise PermanentError("never works")

        p = RetryPolicy(max_retries=3, backoff=0.0)
        with pytest.raises(PermanentError):
            p.call(fn)
        assert len(calls) == 1  # zero retries burned on a permanent failure


class TestFaultPlan:
    def test_scenarios_reproducible_from_seed(self):
        p1 = FaultPlan.corrupt_then_kill(42, steps=20, checkpoint_every=2)
        p2 = FaultPlan.corrupt_then_kill(42, steps=20, checkpoint_every=2)
        assert p1.params == p2.params
        # the seed actually varies the scenario
        kills = {
            FaultPlan.kill_mid_run(s, steps=100).params["kill_step"]
            for s in range(10)
        }
        assert len(kills) > 1

    def test_fault_fires_once_then_spent(self):
        plan = FaultPlan([Fault("p", "raise", at=1, count=1)])
        with chaos.active(plan):
            chaos.inject("p")  # hit 0: not due
            with pytest.raises(chaos.ChaosError):
                chaos.inject("p")  # hit 1: fires
            chaos.inject("p")  # spent: the retry must not be re-killed
        chaos.inject("p")  # disarmed: no-op


# ---------------------------------------------------------- executor layer
class TestChaosExecutor:
    def test_kill_mid_run_resumes_at_checkpointed_step(self, tmp_home):
        plan = FaultPlan.kill_mid_run(seed=3, steps=6, min_step=3)
        kill_step = plan.params["kill_step"]
        expected_resume = (kill_step // 2) * 2  # newest save before the kill
        store = RunStore()
        compiled = compile_operation(_train_op("chaos-kill", steps=6, max_retries=1))
        with chaos.active(plan):
            status = Executor(store, devices=jax.devices()[:1]).execute(compiled)
        assert status == V1Statuses.SUCCEEDED
        resumed = _events(store, compiled.run_uuid, "resumed")
        assert resumed and resumed[0]["step"] == expected_resume
        assert len(_conditions(store, compiled.run_uuid, "retrying")) == 1
        assert store.read_metrics(compiled.run_uuid)[-1]["step"] == 6

    def test_corrupt_latest_checkpoint_falls_back_to_intact(self, tmp_home):
        plan = FaultPlan.corrupt_then_kill(seed=5, steps=8, checkpoint_every=2)
        fallback = plan.params["fallback_step"]
        corrupt_step = plan.params["corrupt_step"]
        store = RunStore()
        compiled = compile_operation(
            _train_op("chaos-corrupt", steps=8, max_retries=1)
        )
        with chaos.active(plan):
            status = Executor(store, devices=jax.devices()[:1]).execute(compiled)
        assert status == V1Statuses.SUCCEEDED
        fb = _events(store, compiled.run_uuid, "checkpoint_fallback")
        assert fb, "corrupt checkpoint must be detected at restore"
        assert fb[0]["restored_step"] == fallback
        assert corrupt_step in fb[0]["corrupt_steps"]
        resumed = _events(store, compiled.run_uuid, "resumed")
        assert resumed and resumed[0]["step"] == fallback
        assert store.read_metrics(compiled.run_uuid)[-1]["step"] == 8

    def test_sigterm_preemption_checkpoints_and_restarts_free(self, tmp_home):
        # maxRetries=0: ONLY the free preemption restart can finish this run
        plan = FaultPlan.preempt_mid_run(seed=9, steps=6, min_step=2)
        store = RunStore()
        compiled = compile_operation(
            _train_op("chaos-preempt", steps=6, max_retries=0)
        )
        with chaos.active(plan):
            status = Executor(store, devices=jax.devices()[:1]).execute(compiled)
        assert status == V1Statuses.SUCCEEDED
        preempted = _events(store, compiled.run_uuid, "preempted")
        assert preempted, "trainer must emit the preempted event"
        retrying = _conditions(store, compiled.run_uuid, "retrying")
        assert len(retrying) == 1 and retrying[0]["reason"] == "preempted"
        assert store.read_metrics(compiled.run_uuid)[-1]["step"] == 6

    def test_permanent_error_fails_fast_no_retries(self, tmp_home):
        plan = FaultPlan(
            [Fault("trainer.step", "raise_permanent", at=0,
                   message="chaos: unfixable config")]
        )
        store = RunStore()
        compiled = compile_operation(
            _train_op("chaos-permanent", steps=6, max_retries=3)
        )
        with chaos.active(plan):
            status = Executor(store, devices=jax.devices()[:1]).execute(compiled)
        assert status == V1Statuses.FAILED
        assert _conditions(store, compiled.run_uuid, "retrying") == []
        last = _conditions(store, compiled.run_uuid)[-1]
        assert last["reason"] == "PermanentError"

    def test_backoff_spaced_retries_recorded(self, tmp_home):
        op = V1Operation.model_validate(
            {
                "kind": "operation",
                "name": "chaos-backoff",
                "component": {
                    "kind": "component",
                    "termination": {
                        "maxRetries": 2,
                        "backoff": 0.05,
                        "backoffFactor": 2,
                        "jitter": 0,
                    },
                    "run": {"kind": "job", "container": {"command": ["false"]}},
                },
            }
        )
        store = RunStore()
        compiled = compile_operation(op)
        t0 = time.monotonic()
        assert Executor(store).execute(compiled) == V1Statuses.FAILED
        elapsed = time.monotonic() - t0
        retries = _events(store, compiled.run_uuid, "retry")
        assert [e["delay"] for e in retries] == [0.05, 0.1]
        assert elapsed >= 0.15  # the sleeps actually happened
        reasons = [c["reason"] for c in _conditions(store, compiled.run_uuid, "retrying")]
        assert reasons == ["retry 1/2 after 0.05s", "retry 2/2 after 0.1s"]


# --------------------------------------------------------- reconciler layer
GANG_SPEC = {
    "version": 1.1,
    "kind": "operation",
    "name": "chaosgang",
    "component": {
        "kind": "component",
        "name": "chaosgang",
        "termination": {"maxRetries": 0},
        "run": {
            "kind": "jaxjob",
            "replicas": 2,
            "container": {"image": "img", "command": ["train"]},
        },
    },
}


def _submit_gang(tmp_path, store, cluster):
    p = tmp_path / "op.yaml"
    p.write_text(yaml.safe_dump(GANG_SPEC))
    op = read_polyaxonfile(str(p))
    agent = Agent(
        store=store,
        submit_fn=ClusterSubmitter(store, cluster, ConnectionCatalog()),
    )
    uuid = agent.submit(op)
    agent.drain()
    return uuid


def _drive(rec, store, uuid, ticks=30):
    for _ in range(ticks):
        rec.tick()
        if store.get_status(uuid)["status"] == V1Statuses.SUCCEEDED:
            break
    return store.get_status(uuid)


class TestChaosCluster:
    def test_flaky_cluster_completes_within_error_budget(self, tmp_home, tmp_path):
        inner = ScriptedCluster(pending_polls=1, running_polls=2)
        store = RunStore()
        uuid = _submit_gang(tmp_path, store, inner)
        flaky = FlakyCluster(inner, seed=13, rate=0.5, max_consecutive=2)
        rec = Reconciler(store, flaky, error_budget=3)
        st = _drive(rec, store, uuid)
        assert st["status"] == V1Statuses.SUCCEEDED
        assert flaky.injected > 0, "the flake schedule must actually fire"
        # flakes stayed inside the budget: never parked, no budget burned
        types = [c["type"] for c in st["conditions"]]
        assert "unknown" not in types
        assert int((st.get("meta") or {}).get("cluster_attempts") or 0) == 0

    def test_partition_parks_unknown_then_recovers(self, tmp_home, tmp_path):
        inner = ScriptedCluster(pending_polls=1, running_polls=2)
        store = RunStore()
        # submit (global call 0) lands before the window; polls 1-3 black out
        cluster = PartitionedCluster(inner, start=1, length=3)
        uuid = _submit_gang(tmp_path, store, cluster)
        rec = Reconciler(store, cluster, error_budget=3)
        rec.tick()
        rec.tick()
        # two failed polls: budget not yet spent, status untouched
        assert store.get_status(uuid)["status"] == V1Statuses.SCHEDULED
        changes = rec.tick()  # third consecutive failure exhausts the budget
        assert (uuid, V1Statuses.UNKNOWN) in changes
        assert store.get_status(uuid)["status"] == V1Statuses.UNKNOWN
        # partition heals: the run recovers through the normal ladder
        st = _drive(rec, store, uuid, ticks=10)
        assert st["status"] == V1Statuses.SUCCEEDED
        types = [c["type"] for c in st["conditions"]]
        assert "unknown" in types and types[-1] == "succeeded"

    def test_gang_preemption_restarts_without_burning_budget(
        self, tmp_home, tmp_path
    ):
        inner = ScriptedCluster(pending_polls=1, running_polls=2)
        store = RunStore()
        # seed=1 over window=3 draws poll index 2: the reconciler observes
        # RUNNING (poll 1) before the reclaim lands, so the restart walks
        # the full RUNNING→RETRYING→QUEUED ladder
        cluster = PreemptingCluster(inner, seed=1, n_preemptions=1, window=3)
        uuid = _submit_gang(tmp_path, store, cluster)
        rec = Reconciler(store, cluster)
        st = _drive(rec, store, uuid)
        assert st["status"] == V1Statuses.SUCCEEDED
        assert cluster.preempted == 1
        # maxRetries is 0: only the budget-free preemption path can restart
        assert int((st.get("meta") or {}).get("cluster_attempts") or 0) == 0
        retrying = [c for c in st["conditions"] if c["type"] == "retrying"]
        assert retrying and "preempted" in retrying[0]["reason"]
