"""Artifact-store data plane + init/sidecar execution semantics
(SURVEY.md §2 "Connections/fs", §1/§3 aux containers)."""

import subprocess

import pytest
import yaml

from polyaxon_tpu.compiler.resolver import compile_operation
from polyaxon_tpu.connections.fs import (
    ArtifactStore,
    ArtifactStoreError,
    build_artifact_store,
)
from polyaxon_tpu.connections.schemas import ConnectionCatalog, V1Connection
from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.runtime.executor import Executor
from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.store.local import RunStore


# ------------------------------------------------------------------ data plane
def test_artifact_store_round_trip(tmp_path):
    store = ArtifactStore(tmp_path / "root")
    src = tmp_path / "a.txt"
    src.write_text("hello")
    store.put(src, "exp/a.txt")
    assert store.exists("exp/a.txt")
    assert store.list("exp") == ["exp/a.txt"]
    out = store.get("exp/a.txt", tmp_path / "back.txt")
    assert out.read_text() == "hello"
    with store.open("exp/b.bin", "wb") as f:
        f.write(b"\x01\x02")
    assert store.open("exp/b.bin").read() == b"\x01\x02"
    store.delete("exp/a.txt")
    assert not store.exists("exp/a.txt")


def test_artifact_store_trees_and_escape(tmp_path):
    store = ArtifactStore(tmp_path / "root")
    d = tmp_path / "tree"
    (d / "sub").mkdir(parents=True)
    (d / "x.txt").write_text("x")
    (d / "sub" / "y.txt").write_text("y")
    keys = store.put_tree(d, "runs/u1/outputs")
    assert sorted(keys) == ["runs/u1/outputs/sub/y.txt", "runs/u1/outputs/x.txt"]
    got = store.get_tree("runs/u1/outputs", tmp_path / "out")
    assert sorted(p.name for p in got) == ["x.txt", "y.txt"]
    with pytest.raises(ArtifactStoreError):
        store.put(d / "x.txt", "../../escape.txt")


def test_bucket_connection_maps_under_object_root(tmp_path, monkeypatch):
    monkeypatch.setenv("POLYAXON_OBJECT_STORE_ROOT", str(tmp_path / "obj"))
    conn = V1Connection.model_validate(
        {"name": "gcs", "spec": {"kind": "bucket", "bucket": "gs://my-bkt/pre"}}
    )
    store = build_artifact_store(conn)
    assert store.root == tmp_path / "obj" / "my-bkt" / "pre"
    with pytest.raises(ArtifactStoreError):
        build_artifact_store(
            V1Connection.model_validate(
                {"name": "bad", "spec": {"kind": "bucket", "bucket": "not-a-url"}}
            )
        )


# ------------------------------------------------------------- init semantics
def _compile(tmp_path, spec):
    p = tmp_path / "op.yaml"
    p.write_text(yaml.safe_dump(spec))
    return compile_operation(read_polyaxonfile(str(p)))


def test_init_git_file_paths_and_sidecar_upload(tmp_home, tmp_path, monkeypatch):
    monkeypatch.setenv("POLYAXON_OBJECT_STORE_ROOT", str(tmp_path / "obj"))
    # a local git repo to clone (no network in this image)
    repo = tmp_path / "srcrepo"
    repo.mkdir()
    (repo / "code.py").write_text("print('hi')\n")
    for cmd in (
        ["git", "init", "-q"],
        ["git", "add", "."],
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", "commit", "-qm", "init"],
    ):
        subprocess.run(cmd, cwd=repo, check=True)
    host_file = tmp_path / "datafile.bin"
    host_file.write_bytes(b"\x00\x01")

    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "aux",
        "component": {
            "kind": "component",
            "name": "aux",
            "run": {
                "kind": "job",
                "init": [
                    {"git": {"url": str(repo)}},
                    {"file": {"name": "cfg.json", "content": "{\"a\": 1}"}},
                    {"paths": [str(host_file)]},
                ],
                "connections": ["gcs"],
                "container": {
                    "command": [
                        "sh",
                        "-c",
                        'echo result > "$POLYAXON_RUN_OUTPUTS_PATH/result.txt"',
                    ]
                },
            },
        },
    }
    catalog = ConnectionCatalog.from_config(
        [{"name": "gcs", "spec": {"kind": "bucket", "bucket": "gs://bkt"}}]
    )
    store = RunStore()
    compiled = _compile(tmp_path, spec)
    status = Executor(store, catalog=catalog).execute(compiled)
    assert status == V1Statuses.SUCCEEDED

    ctx = store.run_dir(compiled.run_uuid) / "context"
    assert (ctx / "srcrepo" / "code.py").exists()  # git clone
    assert (ctx / "cfg.json").read_text() == '{"a": 1}'  # literal file
    assert (ctx / "datafile.bin").read_bytes() == b"\x00\x01"  # host path

    # sidecar semantics: outputs landed in the bucket store
    astore = build_artifact_store(catalog.get("gcs"))
    key = f"default/{compiled.run_uuid}/outputs/result.txt"
    assert astore.exists(key)
    events = store.read_events(compiled.run_uuid)
    assert any(e.get("kind") == "outputs_uploaded" for e in events)


def test_init_failure_fails_run_with_context(tmp_home, tmp_path):
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "bad-init",
        "component": {
            "kind": "component",
            "name": "bad-init",
            "run": {
                "kind": "job",
                "init": [{"paths": ["/definitely/not/a/path"]}],
                "container": {"command": ["true"]},
            },
        },
    }
    store = RunStore()
    compiled = _compile(tmp_path, spec)
    status = Executor(store).execute(compiled)
    assert status == V1Statuses.FAILED
    assert "init path not found" in store.read_logs(compiled.run_uuid)


def test_init_artifacts_from_previous_run(tmp_home, tmp_path):
    """Run B pulls run A's outputs into its context — the restart/lineage
    pattern (artifacts: {run: <uuid>})."""
    store = RunStore()
    spec_a = {
        "version": 1.1,
        "kind": "operation",
        "name": "a",
        "component": {
            "kind": "component",
            "name": "a",
            "run": {
                "kind": "job",
                "container": {
                    "command": [
                        "sh",
                        "-c",
                        'echo model-weights > "$POLYAXON_RUN_OUTPUTS_PATH/w.txt"',
                    ]
                },
            },
        },
    }
    a = _compile(tmp_path, spec_a)
    assert Executor(store).execute(a) == V1Statuses.SUCCEEDED

    spec_b = {
        "version": 1.1,
        "kind": "operation",
        "name": "b",
        "component": {
            "kind": "component",
            "name": "b",
            "run": {
                "kind": "job",
                "init": [{"artifacts": {"run": a.run_uuid, "files": ["w.txt"]}}],
                "container": {"command": ["true"]},
            },
        },
    }
    b = _compile(tmp_path, spec_b)
    assert Executor(store).execute(b) == V1Statuses.SUCCEEDED
    ctx = store.run_dir(b.run_uuid) / "context"
    assert (ctx / "w.txt").read_text().strip() == "model-weights"


def test_sidecar_container_runs_alongside(tmp_home, tmp_path):
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "sc",
        "component": {
            "kind": "component",
            "name": "sc",
            "run": {
                "kind": "job",
                "sidecars": [
                    {"command": ["sh", "-c", "echo sidecar-alive; sleep 30"]}
                ],
                "container": {"command": ["sh", "-c", "sleep 0.3; echo main-done"]},
            },
        },
    }
    store = RunStore()
    compiled = _compile(tmp_path, spec)
    assert Executor(store).execute(compiled) == V1Statuses.SUCCEEDED
    logs = store.read_logs(compiled.run_uuid)
    assert "main-done" in logs
    assert "[sidecar] sidecar-alive" in logs


# -------------------------------------------------------------- notifier
def test_webhook_notifier_hook_delivers(tmp_home, tmp_path):
    """A hook with a webhook connection POSTs the run's terminal status."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    received = []

    class Sink(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            import hashlib
            import hmac

            expect = "sha256=" + hmac.new(
                b"s3cr3t", body, hashlib.sha256
            ).hexdigest()
            assert self.headers["Authorization"] == "Bearer s3cr3t"
            assert self.headers["X-Polyaxon-Signature"] == expect
            received.append(_json.loads(body))
            self.send_response(200)
            self.end_headers()

    server = ThreadingHTTPServer(("127.0.0.1", 0), Sink)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        spec = {
            "version": 1.1,
            "kind": "operation",
            "name": "notify-me",
            "hooks": [{"trigger": "succeeded", "connection": "slack"}],
            "component": {
                "kind": "component",
                "name": "notify-me",
                "run": {"kind": "job", "container": {"command": ["true"]}},
            },
        }
        catalog = ConnectionCatalog.from_config(
            [{"name": "slack", "spec": {"kind": "webhook",
                                        "url": f"http://127.0.0.1:{port}/hook",
                                        "secret": "s3cr3t"}}]
        )
        store = RunStore()
        compiled = _compile(tmp_path, spec)
        assert Executor(store, catalog=catalog).execute(compiled) == V1Statuses.SUCCEEDED
        assert received and received[0]["status"] == "succeeded"
        assert received[0]["run_uuid"] == compiled.run_uuid
        events = [e for e in store.read_events(compiled.run_uuid)
                  if e.get("kind") == "notification"]
        assert events and events[0]["delivered"] is True
    finally:
        server.shutdown()


def test_webhook_notifier_failure_never_fails_run(tmp_home, tmp_path):
    spec = {
        "version": 1.1,
        "kind": "operation",
        "name": "notify-dead",
        "hooks": [{"connection": "dead"}],
        "component": {
            "kind": "component",
            "name": "notify-dead",
            "run": {"kind": "job", "container": {"command": ["true"]}},
        },
    }
    catalog = ConnectionCatalog.from_config(
        [{"name": "dead", "spec": {"kind": "webhook",
                                    "url": "http://127.0.0.1:1/nope"}}]
    )
    store = RunStore()
    compiled = _compile(tmp_path, spec)
    assert Executor(store, catalog=catalog).execute(compiled) == V1Statuses.SUCCEEDED
    events = [e for e in store.read_events(compiled.run_uuid)
              if e.get("kind") == "notification"]
    assert events and events[0]["delivered"] is False
