"""Router + replica-fleet coverage (ISSUE 10), over live HTTP against
tiny models and scriptable fake upstreams:

  * JSQ/P2C balancing units: deterministic seeded picks, score ordering,
    the retry ladder;
  * the Prometheus scrape parser the balancer feeds on;
  * V1ServingSpec replicas/meshAxes validators, to_config plumbing, and
    the V1JAXJob meshAxes-vs-resources.chips cross-check;
  * replica child argv translation (fleet mode reuses `polyaxon serve`);
  * shed-retry on a sibling (and the deadline shed that must NOT retry),
    connection-failure retry, and mid-stream failover with exact per-row
    token trimming — against fake upstreams, so every branch is forced;
  * 2-replica live routing: byte-identical responses vs a direct replica
    (greedy and seeded-sampled, streamed and not), SSE X-Request-Id
    pass-through, router series on /metricsz, `polyaxon stats --url`;
  * chaos worker-kill mid-request: the router replays on the sibling and
    the client never sees the crash;
  * ReplicaSetManager: crash restart under the retry taxonomy, fleet
    reservations per slot, scale up/down, rolling redeploy with zero
    failed requests under concurrent traffic;
  * tensor-parallel decode: a batch×model mesh serves byte-identical
    tokens to single-device serving.
"""

import http.client
import json
import socket
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from polyaxon_tpu.serving.router import (
    AutoscalePolicy,
    P2CBalancer,
    ReplicaState,
    Router,
    parse_prometheus,
)

pytestmark = pytest.mark.serving

CFG = {
    "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
}


def _build():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    b = build_model("transformer_lm", CFG)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


def _server(module, params, **overrides):
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    cfg = ServingConfig(**{
        "max_batch": 4, "max_wait_ms": 2.0, "kv_page_tokens": 8,
        "kv_pool_pages": 64, "stream_chunk_tokens": 3, **overrides,
    })
    return ModelServer(module, params, model_name="tiny", config=cfg)


def _post(port, body, path="/generate", rid=None, timeout=120):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    headers = {"Content-Type": "application/json"}
    if rid is not None:
        headers["X-Request-Id"] = rid
    c.request("POST", path, body if isinstance(body, (bytes, str))
              else json.dumps(body), headers)
    r = c.getresponse()
    out = r.read()
    hdrs = dict(r.getheaders())
    c.close()
    return r.status, out, hdrs


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=60
    ).read()


def _frames(raw: bytes) -> list[dict]:
    return [
        json.loads(f[len(b"data: "):])
        for f in raw.split(b"\n\n")
        if f.startswith(b"data: ")
    ]


def _row_tokens(frames: list[dict]) -> dict[int, list[int]]:
    out: dict[int, list[int]] = {}
    for ev in frames:
        if "row" in ev and "tokens" in ev:
            out.setdefault(ev["row"], []).extend(ev["tokens"])
    return out


# --------------------------------------------------------------- units
def test_parse_prometheus():
    text = (
        "# HELP serving_queue_depth depth\n"
        "# TYPE serving_queue_depth gauge\n"
        "serving_queue_depth 3\n"
        "serving_queue_wait_seconds_sum 0.25\n"
        "serving_queue_wait_seconds_count 10\n"
        "bad line with words\n"
        "router_requests_total 7\n"
    )
    m = parse_prometheus(text)
    assert m["serving_queue_depth"] == 3.0
    assert m["serving_queue_wait_seconds_sum"] == 0.25
    assert m["router_requests_total"] == 7.0
    assert "bad" not in m


def _state(url, depth=0.0, wait=0.0, inflight=0):
    s = ReplicaState(url=url, slug=url[-2:], healthy=True)
    s.queue_depth, s.queue_wait_ms, s.inflight = depth, wait, inflight
    return s


def test_p2c_pick_prefers_shorter_queue():
    a = _state("http://a/r0", depth=5.0)
    b = _state("http://b/r1", depth=0.0)
    bal = P2CBalancer(seed=0)
    # <=2 candidates: pure JSQ, no sampling
    assert bal.pick([a, b]) is b
    # in-flight counts weigh the same as scraped depth
    b.inflight = 7
    assert bal.pick([a, b]) is a
    # queue-wait breaks depth ties
    b.inflight = 5
    b.queue_wait_ms, a.queue_wait_ms = 1.0, 9.0
    assert bal.pick([a, b]) is b


def test_p2c_seeded_sampling_deterministic():
    cands = [_state(f"http://x/r{i}", depth=float(i)) for i in range(5)]
    picks1 = [P2CBalancer(seed=42).pick(cands).url for _ in range(1)]
    picks2 = [P2CBalancer(seed=42).pick(cands).url for _ in range(1)]
    assert picks1 == picks2  # same seed, same sample
    # the P2C winner always beats at least one sampled loser: it can
    # never be the strictly worst of the sampled pair
    seq = [P2CBalancer(seed=7).pick(cands) for _ in range(20)]
    assert all(s is not None for s in seq)


def test_p2c_order_is_retry_ladder():
    cands = [_state(f"http://x/r{i}", depth=float(9 - i)) for i in range(4)]
    order = P2CBalancer(seed=3).order(cands)
    assert len(order) == 4 and len(set(id(s) for s in order)) == 4
    # after the P2C head, the rest are strictly score-sorted
    tail = order[1:]
    assert tail == sorted(tail, key=ReplicaState.score)
    assert P2CBalancer().order([]) == []


def test_retryable_matrix():
    r = Router([])
    shed = json.dumps({"error": "x", "reason": "queue"}).encode()
    deadline = json.dumps({"error": "x", "reason": "deadline"}).encode()
    assert r._retryable(503, shed) is True
    assert r._retryable(503, deadline) is False  # budget spent everywhere
    assert r._retryable(500, b"{}") is True  # decode is deterministic
    assert r._retryable(599, b"{}") is True  # synthetic connect failure
    assert r._retryable(502, b"{}") is True
    assert r._retryable(504, b"{}") is False  # deadline, by status
    assert r._retryable(400, b"{}") is False  # client error
    assert r._retryable(200, b"{}") is False
    assert r.stats()["upstream_shed"] == 2  # both 503s counted
    # no replicas at all: a clean 503, not an exception
    status, payload, _ = r.forward(b"{}", "rid-x")
    assert status == 503 and json.loads(payload)["reason"] == "no_replicas"


class _Scaler:
    def __init__(self, target):
        self.target = target
        self.calls = []

    def scale_to(self, n):
        self.calls.append(n)
        self.target = n


def test_autoscale_scale_up_cooldown_and_clamp():
    sc = _Scaler(target=1)
    r = Router(
        [], scaler=sc,
        autoscale=AutoscalePolicy(max_replicas=3, cooldown_s=3600.0),
    )
    assert r.slo_engine is not None  # shed-burn objective is armed
    r._last_scale_t = 0.0
    r._scale_up({"slo": "router-upstream-shed"})
    assert sc.calls == [2]
    r._scale_up({})  # inside cooldown: ignored
    assert sc.calls == [2]
    sc.target = 3
    r._last_scale_t = -1e9  # cooldown long past
    r._scale_up({})  # already at max: clamped, no call
    assert sc.calls == [2]


def test_autoscale_calm_window_scales_down():
    sc = _Scaler(target=2)
    r = Router(
        ["http://127.0.0.1:9"], scaler=sc,
        autoscale=AutoscalePolicy(
            min_replicas=1, cooldown_s=0.0, calm_for_s=0.05,
        ),
    )
    r.states()[0].healthy = True  # idle, zero queue → calm
    r._last_scale_t = 0.0
    r._autoscale_tick()  # opens the calm window
    assert sc.calls == []
    time.sleep(0.1)
    r._autoscale_tick()  # window elapsed → scale down to min
    assert sc.calls == [1]
    r._autoscale_tick()  # at min: stays
    assert sc.calls == [1]


# ------------------------------------------------------------- schemas
def test_serving_spec_replicas_and_mesh_axes():
    import pydantic

    from polyaxon_tpu.schemas.run_kinds import V1ServingSpec

    with pytest.raises(pydantic.ValidationError, match="replicas"):
        V1ServingSpec(replicas=0)
    with pytest.raises(pydantic.ValidationError, match="meshAxes"):
        V1ServingSpec(meshAxes={})
    with pytest.raises(pydantic.ValidationError, match="batch"):
        V1ServingSpec(meshAxes={"pipeline": 2})
    with pytest.raises(pydantic.ValidationError, match="meshAxes"):
        V1ServingSpec(meshAxes={"model": 0})
    with pytest.raises(pydantic.ValidationError, match="-1"):
        V1ServingSpec(meshAxes={"batch": -1, "model": -1})

    s = V1ServingSpec(replicas=2, meshAxes={"model": 2, "batch": 2})
    assert s.chips_needed() == 4
    assert s.to_config().mesh_axes == (("batch", 2), ("model", 2))
    # legacy axes are accepted (decode_mesh folds them into batch)
    assert V1ServingSpec(meshAxes={"data": 2, "model": 2}).chips_needed() == 4
    # all-1s canonicalize to no mesh; -1 defers sizing to the host
    assert V1ServingSpec(meshAxes={"model": 1}).to_config().mesh_axes is None
    assert V1ServingSpec(meshAxes={"model": -1}).chips_needed() is None
    # unresolved {{param}} interpolations must not break parse-time checks
    assert V1ServingSpec(meshAxes={"model": "{{tp}}"}).chips_needed() is None


def test_jaxjob_mesh_axes_vs_chips_crosscheck():
    import pydantic

    from polyaxon_tpu.schemas.run_kinds import V1JAXJob

    job = {
        "kind": "jaxjob",
        "program": {
            "model": {"name": "mlp"},
            "serving": {"meshAxes": {"model": 4}},
        },
        "environment": {"resources": {"chips": 2}},
    }
    with pytest.raises(pydantic.ValidationError, match="needs 4"):
        V1JAXJob.model_validate(job)
    job["environment"]["resources"]["chips"] = 4
    assert V1JAXJob.model_validate(job).program.serving.chips_needed() == 4
    # no resources declared → nothing to cross-check against
    del job["environment"]
    V1JAXJob.model_validate(job)


def test_serve_child_argv_translation():
    from polyaxon_tpu.cli.main import _serve_child_argv

    argv = _serve_child_argv(
        "uuid1234", 8301, {"batch": 2, "model": 2},
        {"max_batch": 8, "batching": False, "speculate": True,
         "prompt_buckets": (32, 64)},
        4,
    )
    assert argv[:4] == [sys.executable, "-m", "polyaxon_tpu.cli.main",
                        "serve"]
    text = " ".join(argv)
    assert "-uid uuid1234" in text
    assert "--port 8301" in text
    assert "--mesh batch=2,model=2" in text
    assert "--expected-devices 4" in text
    assert "--max-batch 8" in text
    assert "--no-batching" in text
    assert "--speculate" in text
    assert "--buckets 32,64" in text


# ------------------------------------------------- fake-upstream forcing
def _fake_upstream(generate, tracez=None):
    """An HTTP server that looks like a healthy replica (/readyz,
    /metricsz) whose POST /generate is the scriptable `generate(handler,
    body, query)`. With `tracez` (a `rid -> trace dict or None`
    callable), GET /tracez?id= answers the stitching fetch the way a
    real replica's ring would. Returns (httpd, base_url)."""

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _json(self, code, payload):
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path.startswith("/readyz"):
                self._json(200, {"ready": True, "reason": "ok"})
            elif self.path.startswith("/metricsz"):
                data = b"serving_queue_depth 0\n"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            elif self.path.startswith("/tracez") and tracez is not None:
                rid = self.path.partition("id=")[2]
                t = tracez(rid)
                if t is None:
                    self._json(404, {"error": f"no trace {rid!r}"})
                else:
                    self._json(200, t)
            else:
                self._json(404, {"error": "no route"})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            _, _, query = self.path.partition("?")
            generate(self, body, query)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _json_reply(handler, code, payload, headers=None):
    data = json.dumps(payload).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(data)))
    for k, v in (headers or {}).items():
        handler.send_header(k, v)
    handler.end_headers()
    handler.wfile.write(data)


def _sse_reply(handler, events, terminal=True):
    handler.send_response(200)
    handler.send_header("Content-Type", "text/event-stream")
    handler.send_header("Connection", "close")
    handler.end_headers()
    for ev in events:
        handler.wfile.write(b"data: " + json.dumps(ev).encode() + b"\n\n")
        handler.wfile.flush()
    if terminal:
        handler.wfile.write(
            b"data: " + json.dumps({"done": True}).encode() + b"\n\n"
        )
        handler.wfile.flush()


class _FixedOrder(P2CBalancer):
    """Force the retry ladder for tests: candidates in the given URL
    order, so 'the shedding replica is tried first' is deterministic."""

    def __init__(self, urls):
        super().__init__()
        self._pos = {u: i for i, u in enumerate(urls)}

    def order(self, candidates):
        return sorted(candidates, key=lambda s: self._pos.get(s.url, 99))


def _dead_url():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return f"http://127.0.0.1:{s.getsockname()[1]}"


def test_shed_retries_on_sibling():
    shedder, surl = _fake_upstream(
        lambda h, b, q: _json_reply(
            h, 503, {"error": "queue full", "reason": "queue"},
            headers={"Retry-After": "1"},
        )
    )
    ok, ourl = _fake_upstream(
        lambda h, b, q: _json_reply(h, 200, {"ok": True})
    )
    try:
        r = Router([surl, ourl], balancer=_FixedOrder([surl, ourl]))
        r.poll_once()
        status, payload, _ = r.forward(b"{}", "rid-1")
        assert status == 200 and json.loads(payload) == {"ok": True}
        st = r.stats()
        assert st["retries"] == 1 and st["upstream_shed"] == 1
        assert st["errors"] == 0
    finally:
        shedder.shutdown()
        ok.shutdown()


def test_deadline_shed_is_not_retried():
    shedder, surl = _fake_upstream(
        lambda h, b, q: _json_reply(
            h, 503, {"error": "budget spent", "reason": "deadline"}
        )
    )
    ok, ourl = _fake_upstream(
        lambda h, b, q: _json_reply(h, 200, {"ok": True})
    )
    try:
        r = Router([surl, ourl], balancer=_FixedOrder([surl, ourl]))
        r.poll_once()
        status, payload, _ = r.forward(b"{}", "rid-2")
        # the deadline is just as expired on the sibling: relay the 503
        assert status == 503
        assert json.loads(payload)["reason"] == "deadline"
        assert r.stats()["retries"] == 0
    finally:
        shedder.shutdown()
        ok.shutdown()


def test_connection_failure_retries_on_sibling():
    dead = _dead_url()
    ok, ourl = _fake_upstream(
        lambda h, b, q: _json_reply(h, 200, {"ok": True})
    )
    try:
        # no poll: cold-start fallback must try all candidates rather
        # than bounce the request
        r = Router([dead, ourl], balancer=_FixedOrder([dead, ourl]))
        status, payload, _ = r.forward(b"{}", "rid-3")
        assert status == 200 and json.loads(payload) == {"ok": True}
        assert r.stats()["retries"] == 1
    finally:
        ok.shutdown()


def test_midstream_failover_trims_delivered_tokens():
    # upstream A dies after delivering [1,2] then [3] for row 0 (no
    # terminal done); sibling B replays the full sequence — the client
    # must see each token exactly once, [4] arriving in a trimmed frame
    dying, durl = _fake_upstream(
        lambda h, b, q: _sse_reply(
            h,
            [{"row": 0, "tokens": [1, 2]}, {"row": 0, "tokens": [3]}],
            terminal=False,
        )
    )
    full, furl = _fake_upstream(
        lambda h, b, q: _sse_reply(
            h,
            [
                {"row": 0, "tokens": [1, 2]},
                {"row": 0, "tokens": [3, 4]},
                {"row": 0, "tokens": [5]},
                {"row": 0, "done": True},
            ],
        )
    )
    try:
        r = Router([durl, furl], balancer=_FixedOrder([durl, furl]))
        r.poll_once()
        frames = [
            _frames(f)[0] for f in r.forward_stream(b"{}", "rid-4")
        ]
        assert _row_tokens(frames) == {0: [1, 2, 3, 4, 5]}
        # the overlap frame was re-serialized down to the fresh suffix
        assert {"row": 0, "tokens": [4]} in frames
        assert frames[-1] == {"done": True}
        assert sum(1 for f in frames if f.get("row") == 0 and f.get("done")) == 1
        assert not any("error" in f for f in frames)
        assert r.stats()["retries"] == 1
    finally:
        dying.shutdown()
        full.shutdown()


def test_row_error_frame_triggers_failover():
    # a worker crash scatters {"row": i, "error": ...} to every row —
    # the router must fail over, not relay the error to the client
    crashing, curl = _fake_upstream(
        lambda h, b, q: _sse_reply(
            h, [{"row": 0, "error": "decode worker crashed"}]
        )
    )
    full, furl = _fake_upstream(
        lambda h, b, q: _sse_reply(
            h, [{"row": 0, "tokens": [7, 8]}, {"row": 0, "done": True}]
        )
    )
    try:
        r = Router([curl, furl], balancer=_FixedOrder([curl, furl]))
        r.poll_once()
        frames = [
            _frames(f)[0] for f in r.forward_stream(b"{}", "rid-5")
        ]
        assert _row_tokens(frames) == {0: [7, 8]}
        assert not any("error" in f for f in frames)
        assert r.stats()["retries"] == 1
    finally:
        crashing.shutdown()
        full.shutdown()


# ---------------------------------------------------- live 2-replica rig
@pytest.fixture(scope="module")
def model():
    return _build()


@pytest.fixture(scope="module")
def rig(model):
    from polyaxon_tpu.retry import RetryPolicy
    from polyaxon_tpu.serving.replicas import (
        InProcessReplica,
        ReplicaSetManager,
    )

    module, params = model
    mgr = ReplicaSetManager(
        lambda i: InProcessReplica(lambda: _server(module, params)),
        replicas=2,
        retry=RetryPolicy(max_retries=3, backoff=0.05),
        monitor_interval_s=0.1,
    )
    router = Router(
        mgr.endpoints, balancer=P2CBalancer(seed=7), poll_interval_s=0.2
    )
    mgr.attach_router(router)
    mgr.start()
    rport = router.start("127.0.0.1", 0)
    direct = _server(module, params)
    dport = direct.start(port=0)
    yield {
        "mgr": mgr, "router": router, "rport": rport,
        "direct": direct, "dport": dport,
    }
    router.stop()
    mgr.stop()
    direct.stop()


def _bodies():
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 100, size=12).tolist() for _ in range(2)]
    greedy = {"tokens": prompts, "maxNewTokens": 8}
    sampled = {
        "tokens": prompts, "maxNewTokens": 8, "temperature": 0.8,
        "topK": 40, "seed": 123,
    }
    return greedy, sampled


def test_router_byte_identity_nonstream(rig):
    for i, body in enumerate(_bodies()):
        rid = f"rid-identity-{i}"
        raw = json.dumps(body)
        s1, o1, _ = _post(rig["dport"], raw, rid=rid)
        s2, o2, h2 = _post(rig["rport"], raw, rid=rid)
        assert s1 == 200 and s2 == 200, (s1, s2, o1, o2)
        assert o1 == o2  # bytes, not just tokens
        assert h2.get("X-Request-Id") == rid


def test_router_stream_byte_identity_and_rid(rig):
    _, sampled = _bodies()
    rid = "rid-stream-1"
    raw = json.dumps(sampled)
    s1, o1, h1 = _post(rig["dport"], raw, path="/generate?stream=1", rid=rid)
    s2, o2, h2 = _post(rig["rport"], raw, path="/generate?stream=1", rid=rid)
    assert s1 == 200 and s2 == 200
    assert o1 == o2  # frames relayed verbatim
    assert h1.get("X-Request-Id") == rid and h2.get("X-Request-Id") == rid
    frames = _frames(o2)
    assert frames and frames[-1]["done"] is True
    assert all(f["requestId"] == rid for f in frames)
    # stream suffix equals the non-stream result's new tokens
    s3, o3, _ = _post(rig["rport"], raw, rid=rid)
    assert s3 == 200
    whole = json.loads(o3)["tokens"]
    got = _row_tokens(frames)
    for i, row in enumerate(whole):
        assert got[i] == row[len(sampled["tokens"][i]):]


def test_router_observability_surfaces(rig):
    rig["router"].poll_once()
    metrics = parse_prometheus(_get(rig["rport"], "/metricsz").decode())
    for name in (
        "router_requests_total", "router_retries_total",
        "router_upstream_shed_total", "router_errors_total",
        "router_replicas_routable", "router_replica_healthy_r0",
        "router_replica_healthy_r1", "router_replica_queue_wait_ms_r0",
        "router_replica_queue_depth_r0", "router_request_seconds_count",
    ):
        assert name in metrics, name
    assert metrics["router_replicas_routable"] == 2.0
    assert metrics["router_replica_healthy_r0"] == 1.0
    st = json.loads(_get(rig["rport"], "/statsz"))
    assert st["role"] == "router" and st["routable"] == 2
    assert len(st["replicas"]) == 2
    assert st["replicas"][0]["slug"] == "r0"
    assert st["autoscale"]["enabled"] is False
    ready = json.loads(_get(rig["rport"], "/readyz"))
    assert ready["ready"] is True
    health = json.loads(_get(rig["rport"], "/healthz"))
    assert health["role"] == "router" and health["replicas"] == 2
    slo = json.loads(_get(rig["rport"], "/sloz"))
    assert slo["enabled"] is False


def test_cli_stats_against_router(rig):
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    res = CliRunner().invoke(
        cli, ["stats", "--url", f"http://127.0.0.1:{rig['rport']}"]
    )
    assert res.exit_code == 0, res.output
    assert '"role": "router"' in res.output
    assert '"routable"' in res.output


def test_chaos_worker_kill_fails_over_midstream(rig):
    from polyaxon_tpu.chaos.injector import active
    from polyaxon_tpu.chaos.plan import Fault, FaultPlan

    _, sampled = _bodies()
    raw = json.dumps(sampled)
    rid = "rid-chaos-1"
    # reference first, outside the armed window
    s0, o0, _ = _post(rig["dport"], raw, path="/generate?stream=1", rid=rid)
    assert s0 == 200
    want = _row_tokens(_frames(o0))
    retries_before = rig["router"].stats()["retries"]
    # the first decode batch dispatched while armed dies with the worker
    # thread (count=1: the sibling's replay must survive)
    with active(FaultPlan([Fault("serving.worker", "kill", at=0)])):
        s1, o1, _ = _post(
            rig["rport"], raw, path="/generate?stream=1", rid=rid
        )
    assert s1 == 200
    frames = _frames(o1)
    assert not any("error" in f for f in frames), frames
    assert frames[-1]["done"] is True
    assert _row_tokens(frames) == want
    assert rig["router"].stats()["retries"] >= retries_before + 1


def test_replica_crash_restart_keeps_slot(rig):
    mgr, router = rig["mgr"], rig["router"]
    before = mgr.endpoints()
    restarts0 = int(mgr._m_restarts.value)
    mgr.replica(0).kill()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and mgr.live() < 2:
        time.sleep(0.1)
    assert mgr.live() == 2
    assert int(mgr._m_restarts.value) >= restarts0 + 1
    after = mgr.endpoints()
    assert len(after) == 2
    assert after[1] == before[1]  # the sibling never moved
    router.poll_once()
    assert sum(1 for s in router.states() if s.routable) == 2
    # slugs are positional: the restarted replica keeps r0
    assert [s.slug for s in router.states()] == ["r0", "r1"]


def test_rolling_redeploy_zero_downtime(rig):
    mgr = rig["mgr"]
    results, errors = [], []
    stop = threading.Event()
    body = json.dumps({"tokens": [[5, 6, 7]], "maxNewTokens": 2})

    def client():
        while not stop.is_set():
            try:
                status, payload, _ = _post(rig["rport"], body, timeout=60)
                results.append((status, payload))
            except Exception as e:  # noqa: BLE001 — any failure is the bug
                errors.append(repr(e))

    t = threading.Thread(target=client)
    t.start()
    try:
        before = set(mgr.endpoints())
        mgr.rolling_redeploy()
        after = set(mgr.endpoints())
    finally:
        stop.set()
        t.join(timeout=60)
    assert not errors, errors[:3]
    assert results, "no traffic flowed during the redeploy"
    bad = [(s, p) for s, p in results if s != 200]
    assert not bad, bad[:3]
    assert before.isdisjoint(after)  # every replica was replaced
    rig["router"].poll_once()
    assert rig["router"].readiness() == (True, "ok")


# ----------------------------------------------- manager + fleet ledger
class _FakeFleet:
    configured = True

    def __init__(self, capacity):
        self.capacity = capacity
        self.reserved = {}

    def reserve(self, uuid, *, chips, queue=None):
        if sum(self.reserved.values()) + chips > self.capacity:
            return None
        self.reserved[uuid] = chips
        return {"uuid": uuid, "chips": chips, "queue": queue}

    def release(self, uuid):
        self.reserved.pop(uuid, None)


class _NullReplica:
    _n = 0

    def __init__(self):
        self._alive = False
        _NullReplica._n += 1
        self.url = f"http://127.0.0.1:{10000 + _NullReplica._n}"

    def start(self):
        self._alive = True
        return self.url

    def alive(self):
        return self._alive

    def stop(self, drain_grace_s=None):
        self._alive = False

    def kill(self):
        self._alive = False


def test_manager_fleet_reservations_and_scale():
    from polyaxon_tpu.retry import RetryPolicy
    from polyaxon_tpu.serving.replicas import ReplicaSetManager

    fleet = _FakeFleet(capacity=4)
    mgr = ReplicaSetManager(
        lambda i: _NullReplica(), replicas=2, fleet=fleet,
        chips_per_replica=2, name="t",
        retry=RetryPolicy(max_retries=2, backoff=0.01),
        monitor_interval_s=999.0,  # supervise manually via monitor_once
    )
    try:
        urls = mgr.start()
        assert len(urls) == 2 and mgr.live() == 2
        assert fleet.reserved == {"t-r0": 2, "t-r1": 2}
        # no capacity for a third: the grow is absorbed, not fatal
        mgr.scale_to(3)
        assert mgr.live() == 2 and mgr.target == 3
        assert len(mgr.endpoints()) == 2
        # shrink releases the highest slot's reservation
        mgr.scale_to(1)
        assert mgr.live() == 1
        assert fleet.reserved == {"t-r0": 2}
        assert len(mgr.endpoints()) == 1
        # crash restart rides the retry taxonomy and re-reserves
        mgr.replica(0).kill()
        assert mgr.live() == 0
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and mgr.live() < 1:
            mgr.monitor_once()
            time.sleep(0.02)
        assert mgr.live() == 1
        assert "t-r0" in fleet.reserved
    finally:
        mgr.stop()
    assert fleet.reserved == {}  # every slot released on stop


def test_manager_gives_up_after_retry_budget():
    from polyaxon_tpu.retry import RetryPolicy
    from polyaxon_tpu.serving.replicas import ReplicaSetManager

    class _Crasher(_NullReplica):
        def start(self):
            raise RuntimeError("boom")

    mgr = ReplicaSetManager(
        lambda i: _Crasher(), replicas=1,
        retry=RetryPolicy(max_retries=2, backoff=0.0, jitter=0.0),
        monitor_interval_s=999.0,
    )
    with pytest.raises(RuntimeError, match="boom"):
        mgr.start()
    for _ in range(10):
        mgr.monitor_once()
        time.sleep(0.01)
    # attempts are capped: the slot stays down instead of crash-looping
    assert mgr._attempts[0] > mgr.retry.max_retries
    assert mgr.live() == 0
    mgr.stop()


# ------------------------------------------------ tensor-parallel decode
def test_mesh_sharded_decode_byte_identity(model):
    import jax

    if jax.device_count() < 4:
        pytest.skip("needs 4 virtual devices (conftest forces 8)")
    from polyaxon_tpu.models.transformer import TRANSFORMER_RULES
    from polyaxon_tpu.serving.batching import (
        ServingConfig,
        normalize_mesh_axes,
    )
    from polyaxon_tpu.serving.server import ModelServer

    module, params = model
    ref = ModelServer(
        module, params, model_name="tiny",
        config=ServingConfig(max_batch=4, max_wait_ms=1.0),
    )
    tp = ModelServer(
        module, params, model_name="tiny",
        config=ServingConfig(
            max_batch=4, max_wait_ms=1.0,
            mesh_axes=normalize_mesh_axes({"batch": 2, "model": 2}),
        ),
        sharding_rules=TRANSFORMER_RULES,
    )
    st = tp.stats()["mesh"]
    assert st["enabled"] and st["devices"] == 4
    assert st["axes"] == {"batch": 2, "model": 2}
    assert tp.stats()["mesh"] != ref.stats()["mesh"]
    assert ref.stats()["mesh"]["enabled"] is False
    greedy, sampled = _bodies()
    for body in (greedy, sampled):
        assert tp.generate(body)["tokens"] == ref.generate(body)["tokens"]
    # single-row prefill-only path through the sharded kernels
    one = dict(greedy, tokens=greedy["tokens"][:1], maxNewTokens=1)
    assert tp.generate(one)["tokens"] == ref.generate(one)["tokens"]


# --------------------------- cluster observability plane (ISSUE 13):
# cross-process trace stitching + metrics federation on the router
def _get_trace(rport, rid, timeout=8.0):
    """Poll router /tracez?id= until the trace lands in the ring (it is
    recorded in the handler's finally, a beat after the response)."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return json.loads(_get(rport, f"/tracez?id={rid}"))
        except urllib.error.HTTPError as e:
            if e.code != 404 or time.monotonic() > deadline:
                raise
            time.sleep(0.05)


def _attempt_spans(t):
    return [s for s in t["spans"] if s["name"] == "upstream_attempt"]


def _local_span_ms(t):
    """Router-side (non-grafted) span durations, ms. Spans are
    sequential, so their sum must reconcile with the trace duration."""
    return 1000.0 * sum(
        s["dur_s"] for s in t["spans"] if not s["attrs"].get("remote")
    )


def _remote_trace(rid, status, spans, dur_ms=1.0):
    """What a replica's /tracez?id= would answer: offsets relative to
    the REMOTE trace start — stitching must re-anchor them."""
    return {
        "id": rid, "status": status, "dur_ms": dur_ms, "error": None,
        "attrs": {}, "spans": spans,
    }


def test_router_tracez_contract(rig):
    rid = "rid-contract-1"
    body = json.dumps({"tokens": [[5, 6, 7]], "maxNewTokens": 2})
    s, _, _ = _post(rig["rport"], body, rid=rid)
    assert s == 200
    t = _get_trace(rig["rport"], rid)
    assert t["id"] == rid and t["status"] == "ok"

    for sort in ("recent", "slowest", "errors"):
        page = json.loads(_get(rig["rport"], f"/tracez?sort={sort}"))
        assert "traces" in page and page["capacity"] > 0
    assert any(
        tr["id"] == rid
        for tr in json.loads(_get(rig["rport"], "/tracez"))["traces"]
    )

    with pytest.raises(urllib.error.HTTPError) as err:
        _get(rig["rport"], "/tracez?sort=bogus")
    assert err.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(rig["rport"], "/tracez?id=never-seen")
    assert err.value.code == 404

    st = json.loads(_get(rig["rport"], "/statsz"))
    assert st["tracing"]["enabled"] and st["tracing"]["stitch"]
    assert st["tracing"]["recorded"] >= 1


def test_stitched_shed_retry_fake_upstreams():
    """A shed-retry crosses two replicas: the router trace must carry
    BOTH upstream_attempt subtrees, each grafted with that replica's own
    timeline, under one request id."""
    rid = "rid-stitch-fake"
    shedder, surl = _fake_upstream(
        lambda h, b, q: _json_reply(
            h, 503, {"error": "queue full", "reason": "queue_full"}
        ),
        tracez=lambda r: _remote_trace(
            r, "shed",
            [{"name": "admission", "start_s": 0.0, "dur_s": 0.001,
              "attrs": {}}],
        ),
    )
    ok, ourl = _fake_upstream(
        lambda h, b, q: _json_reply(h, 200, {"ok": True}),
        tracez=lambda r: _remote_trace(
            r, "ok",
            [{"name": "admission", "start_s": 0.0, "dur_s": 0.001,
              "attrs": {}},
             {"name": "decode", "start_s": 0.001, "dur_s": 0.02,
              "attrs": {"group": 1}}],
            dur_ms=21.0,
        ),
    )
    r = Router([surl, ourl], balancer=_FixedOrder([surl, ourl]))
    rport = r.start("127.0.0.1", 0)
    try:
        r.poll_once()
        s, out, _ = _post(rport, "{}", rid=rid)
        assert s == 200 and json.loads(out) == {"ok": True}

        t = _get_trace(rport, rid)
        assert t["id"] == rid
        assert t["attrs"]["attempts"] == 2 and t["attrs"]["stitched"] == 2
        att = _attempt_spans(t)
        assert [a["attrs"]["attempt"] for a in att] == [0, 1]
        assert att[0]["attrs"]["remote_status"] == "shed"
        assert att[1]["attrs"]["remote_status"] == "ok"
        assert all(a["attrs"]["stitched"] for a in att)

        # grafted spans are re-anchored at their attempt's start and
        # carry the replica/attempt identity plus remote: True
        remote = [s_ for s_ in t["spans"] if s_["attrs"].get("remote")]
        assert sorted(s_["name"] for s_ in remote) == [
            "admission", "admission", "decode",
        ]
        decode = next(s_ for s_ in remote if s_["name"] == "decode")
        assert decode["attrs"]["attempt"] == 1
        assert decode["attrs"]["replica"] == att[1]["attrs"]["replica"]
        assert decode["start_s"] >= att[1]["start_s"]

        # the graft is cached in the ring: a second read re-stitches
        # nothing (the stitched counter holds still)
        stitched0 = json.loads(
            _get(rport, "/statsz")
        )["tracing"]["stitched"]
        again = _get_trace(rport, rid)
        assert len(again["spans"]) == len(t["spans"])
        assert json.loads(
            _get(rport, "/statsz")
        )["tracing"]["stitched"] == stitched0
    finally:
        r.stop()
        shedder.shutdown()
        ok.shutdown()


def test_stitch_miss_is_counted_not_fatal():
    """A replica that cannot answer the trace fetch (sampler dropped it,
    or it died) must leave a visible miss, not a broken trace."""
    rid = "rid-stitch-miss"
    ok, ourl = _fake_upstream(
        lambda h, b, q: _json_reply(h, 200, {"ok": True}),
        tracez=lambda r: None,  # 404 every time
    )
    r = Router([ourl])
    rport = r.start("127.0.0.1", 0)
    try:
        r.poll_once()
        s, _, _ = _post(rport, "{}", rid=rid)
        assert s == 200
        t = _get_trace(rport, rid)
        assert t["attrs"]["attempts"] == 1 and t["attrs"]["stitched"] == 0
        assert _attempt_spans(t)[0]["attrs"]["stitched"] is False
        assert not any(s_["attrs"].get("remote") for s_ in t["spans"])
        assert json.loads(
            _get(rport, "/statsz")
        )["tracing"]["stitch_misses"] >= 1
    finally:
        r.stop()
        ok.shutdown()


def test_live_shed_retry_one_stitched_trace(model):
    """Acceptance (ISSUE 13): a real shed-retry across two live replicas
    produces ONE router trace whose two upstream_attempt subtrees share
    the request id, with the replicas' own spans grafted in and span
    sums reconciling with the trace duration within 10%."""
    module, params = model
    # replica A admits exactly one request at a time: while a slow
    # request is in its custody, the next one sheds queue_full
    a = _server(module, params, max_queue=1)
    b = _server(module, params)
    aport = a.start(port=0)
    bport = b.start(port=0)
    urls = [f"http://127.0.0.1:{aport}", f"http://127.0.0.1:{bport}"]
    r = Router(urls, balancer=_FixedOrder(urls))
    rport = r.start("127.0.0.1", 0)
    rid = "rid-stitch-live"
    slow = json.dumps({
        "tokens": [list(range(1, 13))], "maxNewTokens": 48,
    })
    body = json.dumps({
        "tokens": [list(range(1, 13))], "maxNewTokens": 16,
    })
    try:
        r.poll_once()
        shed = False
        for _ in range(5):  # saturation is timing-based: retry the setup
            hog = threading.Thread(
                target=lambda: _post(aport, slow, timeout=120)
            )
            hog.start()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                depth = json.loads(_get(aport, "/statsz"))["queue_depth"]
                if depth >= 1:
                    break
                time.sleep(0.01)
            s, _, _ = _post(rport, body, rid=rid)
            hog.join(timeout=120)
            assert s == 200
            t = _get_trace(rport, rid)
            if t["attrs"]["attempts"] == 2:
                shed = True
                break
        assert shed, "replica A never shed: trace shows one attempt"

        att = _attempt_spans(t)
        assert att[0]["attrs"]["status"] == 503
        assert att[1]["attrs"]["status"] == 200
        assert t["attrs"]["stitched"] == 2, t["attrs"]
        assert att[0]["attrs"]["remote_status"].startswith("shed")
        assert att[1]["attrs"]["remote_status"] == "ok"
        # the replica-side decode really happened inside attempt 2
        decode = [
            s_ for s_ in t["spans"]
            if s_["name"] == "decode" and s_["attrs"].get("remote")
        ]
        assert decode and all(
            s_["attrs"]["attempt"] == 1 for s_ in decode
        )
        # router-side spans are sequential and cover the request: their
        # sum reconciles with the end-to-end duration within 10%
        assert _local_span_ms(t) >= 0.9 * t["dur_ms"], (
            _local_span_ms(t), t["dur_ms"],
        )
        assert _local_span_ms(t) <= 1.1 * t["dur_ms"]
        assert r.stats()["retries"] >= 1
    finally:
        r.stop()
        a.stop()
        b.stop()


def test_chaos_failover_one_stitched_trace(rig):
    """Acceptance (ISSUE 13): a mid-stream worker kill fails over to the
    sibling and still yields ONE router trace with both attempts under
    the same request id."""
    from polyaxon_tpu.chaos.injector import active
    from polyaxon_tpu.chaos.plan import Fault, FaultPlan

    _, sampled = _bodies()
    raw = json.dumps(sampled)
    rid = "rid-chaos-trace"
    with active(FaultPlan([Fault("serving.worker", "kill", at=0)])):
        s1, o1, _ = _post(
            rig["rport"], raw, path="/generate?stream=1", rid=rid
        )
    assert s1 == 200
    assert _frames(o1)[-1]["done"] is True

    t = _get_trace(rig["rport"], rid)
    assert t["id"] == rid and t["status"] == "ok"
    att = _attempt_spans(t)
    assert len(att) == 2, [a["attrs"] for a in att]
    assert att[0]["attrs"]["status"] in (500, 502)
    assert att[1]["attrs"]["status"] == 200
    assert all(a["attrs"]["streamed"] for a in att)
    # the replay on the sibling is annotated (failover if frames had
    # already flowed, retry when the worker died pre-stream)
    assert any(s_["name"] in ("failover", "retry") for s_ in t["spans"])
    # the surviving attempt carries the sibling's own decode spans
    assert any(
        s_["name"] == "decode"
        and s_["attrs"].get("remote")
        and s_["attrs"]["attempt"] == 1
        for s_ in t["spans"]
    ), [s_["name"] for s_ in t["spans"]]
    assert _local_span_ms(t) >= 0.9 * t["dur_ms"]
    assert _local_span_ms(t) <= 1.1 * t["dur_ms"]


def test_router_metricsz_federates_replicas(rig):
    """One router scrape answers for the fleet: every replica's series
    re-labeled replica="r<N>", plus cluster:...:sum/:max rollups."""
    from polyaxon_tpu.telemetry.federate import parse_prometheus_text

    body = json.dumps({"tokens": [[5, 6, 7]], "maxNewTokens": 2})
    s, _, _ = _post(rig["rport"], body, rid="rid-fed-1")
    assert s == 200
    rig["router"].poll_once()  # capture fresh /metricsz texts
    snap = parse_prometheus_text(_get(rig["rport"], "/metricsz").decode())

    assert snap.get("federation_source_up", replica="r0") == 1.0
    assert snap.get("federation_source_up", replica="r1") == 1.0
    for slug in ("r0", "r1"):
        assert snap.get("serving_requests_total", replica=slug) is not None
        assert snap.get("serving_queue_depth", replica=slug) is not None
    # cluster rollups: sums for counters, max only for gauge-shaped
    assert snap.get("cluster:serving_requests_total:sum") >= 1.0
    assert snap.get("cluster:serving_queue_depth:sum") is not None
    assert snap.get("cluster:serving_queue_depth:max") is not None
    assert snap.get("cluster:serving_requests_total:max") is None
    # the router's own series stay label-less (local, not federated)
    assert snap.get("router_requests_total") is not None
    st = json.loads(_get(rig["rport"], "/statsz"))
    assert st["cluster"]["federation"] is True
    assert st["cluster"]["scraped"] == 2
    assert st["cluster"]["serving_requests"] >= 1.0


def test_cli_trace_and_stats_against_router(rig):
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli

    rid = "rid-cli-trace"
    body = json.dumps({"tokens": [[5, 6, 7]], "maxNewTokens": 2})
    s, _, _ = _post(rig["rport"], body, rid=rid)
    assert s == 200
    _get_trace(rig["rport"], rid)  # wait for the ring to catch up
    url = f"http://127.0.0.1:{rig['rport']}"

    res = CliRunner().invoke(cli, ["trace", "--url", url])
    assert res.exit_code == 0, res.output
    assert "traces:" in res.output and rid in res.output

    res = CliRunner().invoke(cli, ["trace", rid, "--url", url])
    assert res.exit_code == 0, res.output
    assert f"trace {rid}" in res.output
    assert "upstream_attempt" in res.output
    assert "admission" in res.output

    res = CliRunner().invoke(
        cli, ["stats", "--url", url, "--traces", "5"]
    )
    assert res.exit_code == 0, res.output
    assert "traces:" in res.output
