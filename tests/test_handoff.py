"""Disaggregated prefill/decode pools with crash-honest live KV handoff
(ISSUE 20):

  * LeaseTable units: monotonic epochs, same/lower-epoch rejection,
    preemption (complete-after-preempt is disowned), release, the id
    bound;
  * wire codec: SpillPayload → CRC-framed bytes round-trip, torn and
    corrupt inputs rejected whole;
  * HandoffClient against scripted upstreams: connection-level failures
    retry on the RetryPolicy curve with strictly increasing epochs,
    protocol refusals (409/400/503) are final, exhaustion reports
    "connect";
  * router `_handoff_for` unit: only a prefill replica with a
    decode-capable sibling gets a target;
  * seeded chaos plans: `FaultPlan.kv_handoff_crash` determinism;
  * live two-pool rig (prefill + decode replicas behind the router,
    speculative decode on, vs a monolithic direct server): byte-identity
    for greedy/sampled/streamed paths through a REAL export→import→adopt
    handoff, mid-flight stream continuation, stale-exporter double-adopt
    rejected over HTTP, and a chaos kill in every handoff window
    (export-capture, export-send, import, adopt) — each completes the
    request by clean retry or monolithic fallback with zero leaked pages
    on either side.
"""

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from polyaxon_tpu.serving.handoff import (
    HandoffClient,
    HandoffError,
    LeaseTable,
    StaleLeaseError,
    payload_from_wire,
    payload_to_wire,
)
from polyaxon_tpu.serving.router import (
    P2CBalancer,
    ReplicaState,
    Router,
    parse_prometheus,
)
from polyaxon_tpu.serving.spill import SpillPayload

pytestmark = pytest.mark.serving

CFG = {
    "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
}


# ------------------------------------------------------------ lease units
def test_lease_table_monotonic_epochs():
    t = LeaseTable()
    lease = t.acquire("r1", 5)
    assert t.active == 1
    assert t.complete(lease) is True
    assert t.active == 0
    # the id remembers its high-water mark after completion
    with pytest.raises(StaleLeaseError):
        t.acquire("r1", 5)
    with pytest.raises(StaleLeaseError):
        t.acquire("r1", 4)
    higher = t.acquire("r1", 6)
    assert t.complete(higher) is True
    st = t.stats()
    assert st["granted"] == 2 and st["completed"] == 2
    assert st["stale_rejections"] == 2


def test_lease_table_preemption_disowns_the_loser():
    t = LeaseTable()
    old = t.acquire("r2", 1)
    new = t.acquire("r2", 2)  # preempts mid-adopt
    assert old.state == "preempted"
    # the stale owner's completion is disowned — it must stand down
    assert t.complete(old) is False
    assert t.complete(new) is True
    assert t.stats()["preempted"] == 1


def test_lease_table_release_allows_higher_retry():
    t = LeaseTable()
    lease = t.acquire("r3", 7)
    t.release(lease)  # abort path: shed mid-adopt
    assert t.active == 0
    # same epoch stays burned (monotonicity survives the abort)...
    with pytest.raises(StaleLeaseError):
        t.acquire("r3", 7)
    # ...but the retry's higher epoch proceeds
    assert t.acquire("r3", 8).epoch == 8


def test_lease_table_id_bound_evicts_oldest():
    t = LeaseTable(max_ids=2)
    for i in range(3):
        t.complete(t.acquire(f"id{i}", 1))
    # id0 was forgotten by the bound: its epoch history reset
    assert t.acquire("id0", 1).epoch == 1
    with pytest.raises(StaleLeaseError):
        t.acquire("id2", 1)


# ------------------------------------------------------------- wire codec
def _payload(n_pages=2, leaves=2):
    pages = [
        [np.full((2, 3), 10 * p + l, dtype=np.float32)
         for l in range(leaves)]
        for p in range(n_pages)
    ]
    tokens = tuple(range(8 * n_pages))
    hashes = tuple(f"h{p}" for p in range(n_pages))
    return SpillPayload(tokens, hashes, pages)


def test_wire_roundtrip():
    p = _payload()
    data = payload_to_wire(p)
    q = payload_from_wire(data)
    assert q.tokens == p.tokens and q.hashes == p.hashes
    assert len(q.pages) == len(p.pages)
    for a, b in zip(p.pages, q.pages):
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


def test_wire_rejects_torn_and_corrupt():
    data = payload_to_wire(_payload())
    with pytest.raises(HandoffError, match="torn"):
        payload_from_wire(data[:-7])  # truncated mid-frame
    flipped = bytearray(data)
    flipped[len(data) // 2] ^= 0xFF
    with pytest.raises(HandoffError):
        payload_from_wire(bytes(flipped))
    # a structurally-clean but shape-less frame set is also refused
    from polyaxon_tpu.store.eventlog import frame

    with pytest.raises(HandoffError, match="malformed"):
        payload_from_wire(frame(b'{"not": "a segment"}'))


# ------------------------------------------------------- scripted client
class _ScriptedImport(BaseHTTPRequestHandler):
    """POST /kv_import upstream answering from a scripted status list."""

    script: list = []
    seen: list = []

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        self.rfile.read(n)
        type(self).seen.append((
            self.headers.get("X-Handoff-Id"),
            int(self.headers.get("X-Handoff-Epoch")),
        ))
        status, body = type(self).script.pop(0)
        data = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *a):  # noqa: ARG002 — quiet test logs
        pass


def _scripted(script):
    handler = type("H", (_ScriptedImport,), {"script": list(script),
                                             "seen": []})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, handler, f"http://127.0.0.1:{srv.server_port}"


def test_client_retries_502_then_adopts():
    srv, handler, url = _scripted([
        (502, {"error": "upstream sneeze"}),
        (200, {"adopted_pages": 3}),
    ])
    try:
        res = HandoffClient().send(url, "rid-a", b"x", base_epoch=2)
        assert res.ok and res.adopted_pages == 3 and res.attempts == 2
        # epochs strictly increase across attempts, offset by the
        # router-attempt base so a failed-over exporter always outranks
        assert [e for _, e in handler.seen] == [512, 513]
        assert res.epoch == 513
    finally:
        srv.shutdown()


def test_client_protocol_refusals_are_final():
    for status, body, want in (
        (409, {"reason": "stale_epoch"}, "stale_epoch"),
        (400, {"error": "bad hash chain"}, "rejected"),
        (503, {"reason": "kv_handoff"}, "kv_handoff"),
    ):
        srv, handler, url = _scripted([(status, body)])
        try:
            res = HandoffClient().send(url, "rid-b", b"x")
            assert not res.ok and res.reason == want
            assert res.attempts == 1  # refusals never burn retries
            assert len(handler.seen) == 1
        finally:
            srv.shutdown()


def test_client_connect_exhaustion():
    from polyaxon_tpu.retry import RetryPolicy

    # a dead port: every attempt is a connection-level failure
    client = HandoffClient(
        retry=RetryPolicy(max_retries=1, backoff=0.01, backoff_max=0.02),
        attempt_timeout_s=0.5,
    )
    res = client.send("http://127.0.0.1:9", "rid-c", b"x")
    assert not res.ok and res.reason == "connect" and res.attempts == 2


# ---------------------------------------------------------- router units
def _state(url, role="both"):
    s = ReplicaState(url=url, slug=url[-2:], healthy=True)
    s.role = role
    return s


def test_handoff_for_targets_decode_siblings_only():
    r = Router([], balancer=P2CBalancer(seed=1))
    pre = _state("http://p/r0", "prefill")
    dec = _state("http://d/r1", "decode")
    both = _state("http://b/r2", "both")
    # prefill + decode sibling: target is the first non-prefill sink
    assert r._handoff_for(pre, [pre, dec, both], 0) == ("http://d/r1", 0)
    assert r._handoff_for(pre, [pre, both], 2) == ("http://b/r2", 2)
    # a decode/both replica never gets a target
    assert r._handoff_for(dec, [pre, dec], 0) is None
    assert r._handoff_for(both, [both, dec], 0) is None
    # a prefill-only fleet degrades to monolithic (no header)
    assert r._handoff_for(pre, [pre], 0) is None
    assert r._handoff_for(
        pre, [pre, _state("http://q/r3", "prefill")], 0
    ) is None


def test_kv_handoff_crash_plan_is_seed_deterministic():
    from polyaxon_tpu.chaos.plan import FaultPlan

    a = FaultPlan.kv_handoff_crash(seed=5, window=4)
    b = FaultPlan.kv_handoff_crash(seed=5, window=4)
    assert a.params == b.params
    assert [vars(f) for f in a.faults] == [vars(f) for f in b.faults]
    assert a.params["fault_point"] in (
        "serving.kv_export", "serving.kv_import", "serving.kv_adopt"
    )
    assert 0 <= a.params["fault_hit"] < 4
    assert any(
        FaultPlan.kv_handoff_crash(seed=s).params != a.params
        for s in range(6, 16)
    )


# ------------------------------------------------------- live two-pool rig
def _build():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    b = build_model("transformer_lm", CFG)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


def _server(module, params, **overrides):
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    cfg = ServingConfig(**{
        "max_batch": 4, "max_wait_ms": 2.0, "kv_page_tokens": 8,
        "kv_pool_pages": 64, "stream_chunk_tokens": 3,
        "chunked_prefill": True, "prefix_cache": True,
        # the speculative path rides every request in this rig: identical
        # configs on both sides keep byte-identity meaningful
        "speculate": True, "draft_tokens": 3,
        **overrides,
    })
    return ModelServer(module, params, model_name="tiny", config=cfg)


@pytest.fixture(scope="module")
def pools():
    module, params = _build()
    pre = _server(module, params, role="prefill")
    dec = _server(module, params, role="decode")
    direct = _server(module, params)
    pp, dp, xp = pre.start(port=0), dec.start(port=0), direct.start(port=0)
    router = Router(
        [f"http://127.0.0.1:{pp}", f"http://127.0.0.1:{dp}"],
        balancer=P2CBalancer(seed=7), poll_interval_s=0.1,
    )
    rp = router.start("127.0.0.1", 0)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        router.poll_once()
        reps = router.stats()["replicas"]
        if len(reps) == 2 and all(r["healthy"] for r in reps):
            break
        time.sleep(0.1)
    yield {
        "pre": pre, "dec": dec, "direct": direct, "router": router,
        "pp": pp, "dp": dp, "xp": xp, "rp": rp,
    }
    router.stop()
    pre.stop()
    dec.stop()
    direct.stop()


def _post(port, body, path="/generate", rid=None, timeout=120):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    headers = {"Content-Type": "application/json"}
    if rid is not None:
        headers["X-Request-Id"] = rid
    c.request("POST", path, body if isinstance(body, (bytes, str))
              else json.dumps(body), headers)
    r = c.getresponse()
    out = r.read()
    c.close()
    return r.status, out


def _get(port, path):
    import urllib.request

    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=60
    ).read()


def _stream_tokens(raw: bytes) -> dict[int, list[int]]:
    rows: dict[int, list[int]] = {}
    for line in raw.decode().splitlines():
        if line.startswith("data: "):
            ev = json.loads(line[6:])
            if "tokens" in ev and "row" in ev:
                rows.setdefault(ev["row"], []).extend(ev["tokens"])
    return rows


def _drained(port, budget_s=15.0):
    """Zero-leak gate: used pages back to scratch + prefix-held, no
    export in flight. Polls because restores/fallbacks finish async."""
    deadline = time.monotonic() + budget_s
    last = {}
    while time.monotonic() < deadline:
        last = parse_prometheus(_get(port, "/metricsz").decode())
        used = last.get("serving_kv_pages_used", 0.0)
        held = last.get("serving_kv_pages_prefix_held", 0.0)
        inflight = last.get("serving_kv_handoff_inflight", 0.0)
        if used <= 1 + held and inflight == 0:
            return True
        time.sleep(0.1)
    raise AssertionError(f"pages leaked or export stuck: {last}")


def _prompt(seed, n=14):
    rng = np.random.RandomState(seed)
    return rng.randint(1, CFG["vocab_size"] - 1, size=n).tolist()


def test_pooled_byte_identity_nonstream(pools):
    exports0 = pools["pre"].stats()["handoff"]["exports"]
    bodies = [
        {"tokens": [_prompt(1)], "maxNewTokens": 8},
        {"tokens": [_prompt(2)], "maxNewTokens": 8, "temperature": 0.8,
         "topK": 40, "seed": 123},
    ]
    for i, body in enumerate(bodies):
        rid = f"rid-pool-{i}"
        raw = json.dumps(body)
        s1, o1 = _post(pools["xp"], raw, rid=rid)
        s2, o2 = _post(pools["rp"], raw, rid=rid)
        assert s1 == 200 and s2 == 200, (s1, s2, o1, o2)
        assert o1 == o2  # bytes, not just tokens
    # the identity rode REAL handoffs, not a silent fallback
    h = pools["pre"].stats()["handoff"]
    assert h["exports"] >= exports0 + 2, h
    d = pools["dec"].stats()["handoff"]
    assert d["imports"] >= 2 and d["leases"]["completed"] >= 2
    _drained(pools["pp"])
    _drained(pools["dp"])


def test_pooled_stream_continues_midflight(pools):
    body = {"tokens": [_prompt(3)], "maxNewTokens": 8, "temperature": 0.7,
            "topK": 30, "seed": 99}
    rid = "rid-pool-stream"
    raw = json.dumps(body)
    s1, o1 = _post(pools["xp"], raw, rid=rid)
    s2, o2 = _post(pools["rp"], raw, path="/generate?stream=1", rid=rid)
    assert s1 == 200 and s2 == 200, (o1, o2)
    whole = json.loads(o1)["tokens"][0]
    rows = _stream_tokens(o2)
    # first token came from the prefill replica, the rest streamed from
    # the decode replica mid-flight — trimmed to exactly the suffix
    assert rows[0] == whole[len(body["tokens"][0]):]
    frames = [json.loads(l[6:]) for l in o2.decode().splitlines()
              if l.startswith("data: ")]
    assert frames[-1].get("done") is True
    assert not any("error" in f for f in frames)
    _drained(pools["pp"])
    _drained(pools["dp"])


def test_role_advertised_on_surfaces(pools):
    for port, role in ((pools["pp"], "prefill"), (pools["dp"], "decode"),
                       (pools["xp"], "both")):
        ready = json.loads(_get(port, "/readyz"))
        assert ready["role"] == role
        kvz = json.loads(_get(port, "/kvz"))
        assert kvz["role"] == role
    st = json.loads(_get(pools["rp"], "/statsz"))
    assert {r["replica_role"] for r in st["replicas"]} == \
        {"prefill", "decode"}
    # the handoff series flow on /metricsz
    pre_m = _get(pools["pp"], "/metricsz").decode()
    assert "serving_kv_handoff_ms_bucket" in pre_m
    assert "serving_kv_handoff_exports_total" in pre_m
    dec_m = parse_prometheus(_get(pools["dp"], "/metricsz").decode())
    assert dec_m.get("serving_kv_handoff_imports_total", 0.0) >= 1.0
    # and the /statsz kv block counts adoption, not leakage
    kv = pools["dec"].stats()["kv"]["handoff"]
    assert kv["adopted_pages"] >= 1 and kv["pending_pages"] == 0


def test_stale_exporter_double_adopt_rejected_over_http(pools):
    # harvest a real page set on the prefill replica, then replay the
    # SAME bytes with non-increasing epochs: a stale exporter that lost
    # the router's failover race can never double-adopt
    prompt = _prompt(4, n=16)
    s, _ = _post(pools["pp"], {"tokens": [prompt], "maxNewTokens": 4})
    assert s == 200
    payload = pools["pre"]._kv.export_prefix(prompt)
    assert payload is not None
    data = payload_to_wire(payload)

    def imp(epoch, rid="rid-stale"):
        c = http.client.HTTPConnection("127.0.0.1", pools["dp"], timeout=60)
        c.request("POST", "/kv_import", data, {
            "Content-Type": "application/octet-stream",
            "X-Handoff-Id": rid,
            "X-Handoff-Epoch": str(epoch),
        })
        r = c.getresponse()
        out = json.loads(r.read())
        c.close()
        return r.status, out

    st0 = pools["dec"].stats()["handoff"]["leases"]["stale_rejections"]
    code, body = imp(100)
    assert code == 200 and body["adopted_pages"] >= 1
    for stale in (100, 99):
        code, body = imp(stale)
        assert code == 409 and body["reason"] == "stale_epoch", body
    # a higher epoch is honored — and idempotent (chain already resident)
    code, body = imp(101)
    assert code == 200 and body["adopted_pages"] == 0
    assert pools["dec"].stats()["handoff"]["leases"]["stale_rejections"] \
        == st0 + 2
    # corrupt bytes never adopt
    c = http.client.HTTPConnection("127.0.0.1", pools["dp"], timeout=60)
    c.request("POST", "/kv_import", data[:-9], {
        "Content-Type": "application/octet-stream",
        "X-Handoff-Id": "rid-torn", "X-Handoff-Epoch": "1",
    })
    r = c.getresponse()
    assert r.status == 400 and json.loads(r.read())["reason"] == "rejected"
    c.close()
    _drained(pools["dp"])


@pytest.mark.chaos
@pytest.mark.parametrize("point,at", [
    ("serving.kv_export", 0),   # capture window: harvest/export dies
    ("serving.kv_import", 0),   # import window: decode side 500s
    ("serving.kv_adopt", 0),    # adopt window: dies holding fresh pages
])
def test_chaos_kill_in_handoff_window_falls_back_clean(pools, point, at):
    from polyaxon_tpu.chaos.injector import active
    from polyaxon_tpu.chaos.plan import Fault, FaultPlan

    body = {"tokens": [_prompt(50 + at * 7 + len(point))],
            "maxNewTokens": 6}
    rid = f"rid-chaos-{point.split('.')[-1]}"
    raw = json.dumps(body)
    s1, o1 = _post(pools["xp"], raw, rid=rid)
    assert s1 == 200
    fb0 = pools["pre"].stats()["handoff"]["fallbacks"]
    plan = FaultPlan(
        [Fault(point, "raise", at=at,
               message=f"chaos: killed in {point} window")], seed=1,
    )
    with active(plan):
        s2, o2 = _post(pools["rp"], raw, rid=rid)
    # the client NEVER sees the crash: completed via monolithic fallback,
    # byte-identical to the direct server
    assert s2 == 200, o2
    assert o1 == o2
    assert pools["pre"].stats()["handoff"]["fallbacks"] == fb0 + 1
    # zero leaked pages on either side, no export stuck in flight
    _drained(pools["pp"])
    _drained(pools["dp"])


@pytest.mark.chaos
def test_chaos_send_crash_retries_then_adopts(pools):
    from polyaxon_tpu.chaos.injector import active
    from polyaxon_tpu.chaos.plan import Fault, FaultPlan

    body = {"tokens": [_prompt(77)], "maxNewTokens": 6}
    rid = "rid-chaos-send"
    raw = json.dumps(body)
    s1, o1 = _post(pools["xp"], raw, rid=rid)
    assert s1 == 200
    before = pools["pre"].stats()["handoff"]
    granted0 = pools["dec"].stats()["handoff"]["leases"]["granted"]
    # hit 0 is the capture window; hit 1 is send attempt 0 — the retry
    # (attempt 1, next epoch) goes through: a CLEAN RETRY, not a fallback
    plan = FaultPlan(
        [Fault("serving.kv_export", "raise", at=1,
               message="chaos: exporter died mid-send")], seed=2,
    )
    with active(plan):
        s2, o2 = _post(pools["rp"], raw, rid=rid)
    assert s2 == 200 and o1 == o2
    after = pools["pre"].stats()["handoff"]
    assert after["exports"] == before["exports"] + 1
    assert after["fallbacks"] == before["fallbacks"]
    assert pools["dec"].stats()["handoff"]["leases"]["granted"] == \
        granted0 + 1
    _drained(pools["pp"])
    _drained(pools["dp"])


@pytest.mark.chaos
def test_chaos_import_crash_midstream_falls_back(pools):
    from polyaxon_tpu.chaos.injector import active
    from polyaxon_tpu.chaos.plan import Fault, FaultPlan

    body = {"tokens": [_prompt(88)], "maxNewTokens": 8,
            "temperature": 0.9, "topK": 25, "seed": 7}
    rid = "rid-chaos-stream"
    raw = json.dumps(body)
    s1, o1 = _post(pools["xp"], raw, rid=rid)
    assert s1 == 200
    whole = json.loads(o1)["tokens"][0]
    plan = FaultPlan(
        [Fault("serving.kv_import", "raise", at=0,
               message="chaos: import window death")], seed=3,
    )
    with active(plan):
        s2, o2 = _post(pools["rp"], raw, path="/generate?stream=1",
                       rid=rid)
    assert s2 == 200
    rows = _stream_tokens(o2)
    # the stream resolved through the LOCAL fallback decode mid-flight:
    # same bytes, no client-visible error, done frame present
    assert rows[0] == whole[len(body["tokens"][0]):]
    frames = [json.loads(l[6:]) for l in o2.decode().splitlines()
              if l.startswith("data: ")]
    assert frames[-1].get("done") is True
    assert not any("error" in f for f in frames)
    _drained(pools["pp"])
    _drained(pools["dp"])
