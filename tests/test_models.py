"""Model-zoo tests: every registered model builds, trains a few steps on a
sharded virtual mesh, and its loss is finite/descending where cheap to check.
Mirrors the reference's strategy of testing distributed paths without a
cluster (SURVEY.md §4) — but here we actually execute on a fake 8-dev slice.
"""

import jax
import numpy as np
import pytest

from polyaxon_tpu.models import build_model, registered_models
from polyaxon_tpu.runtime.trainer import Trainer
from polyaxon_tpu.schemas.run_kinds import (
    V1DataSpec,
    V1ModelSpec,
    V1OptimizerSpec,
    V1Program,
    V1TrainSpec,
)


def _train(model_name, model_cfg, data_name, data_cfg, mesh, steps=4, batch=8):
    prog = V1Program(
        model=V1ModelSpec(name=model_name, config=model_cfg),
        data=V1DataSpec(name=data_name, batch_size=batch, config=data_cfg),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
        train=V1TrainSpec(steps=steps, log_every=steps, precision="float32"),
    )
    trainer = Trainer(prog, mesh_axes=mesh)
    return trainer, trainer.run()


def test_registry_contents():
    names = registered_models()
    for required in ("mlp", "transformer_lm", "llama", "resnet", "vit", "bert"):
        assert required in names


@pytest.mark.slow
def test_transformer_trains_tp_fsdp_dp():
    trainer, result = _train(
        "transformer_lm",
        {"preset": "tiny", "seq_len": 64},
        "synthetic_text",
        {"seq_len": 64, "vocab_size": 4096},
        {"data": 2, "fsdp": 2, "model": 2},
    )
    assert np.isfinite(result.history[-1]["loss"])
    # TP rule actually sharded the ffn kernel over `model`
    flat = jax.tree_util.tree_leaves_with_path(trainer.p_shard)
    specs = {
        "/".join(str(getattr(k, "key", k)) for k in path): s.spec
        for path, s in flat
    }
    gate = [v for k, v in specs.items() if "gate_proj" in k and "kernel" in k]
    assert gate and gate[0] == ("fsdp", "model")


@pytest.mark.slow
def test_transformer_scan_layers_matches_param_count():
    plain = build_model("transformer_lm", {"preset": "tiny"})
    scanned = build_model("transformer_lm", {"preset": "tiny", "scan_layers": True})
    x = plain.example_inputs(2)
    p1 = plain.module.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    p2 = scanned.module.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    n1 = sum(a.size for a in jax.tree.leaves(p1))
    n2 = sum(a.size for a in jax.tree.leaves(p2))
    assert n1 == n2


@pytest.mark.slow
def test_lora_freezes_base_params():
    trainer, result = _train(
        "transformer_lm",
        {"preset": "tiny", "seq_len": 64, "lora_rank": 4},
        "synthetic_text",
        {"seq_len": 64, "vocab_size": 4096},
        {"data": 8},
        steps=3,
    )
    params = jax.device_get(trainer.state.params)

    fresh = build_model(
        "transformer_lm", {"preset": "tiny", "seq_len": 64, "lora_rank": 4}
    )
    init = jax.device_get(
        fresh.module.init(
            {"params": jax.random.PRNGKey(0)},
            fresh.example_inputs(8),
            train=False,
        )["params"]
    )

    def find(tree, *keys):
        for k in keys:
            tree = tree[k]
        return tree

    # base kernel unchanged, lora_a/b moved (b starts at zero)
    base_before = find(init, "layer_0", "attention", "q_proj", "kernel")
    base_after = find(params, "layer_0", "attention", "q_proj", "kernel")
    np.testing.assert_array_equal(base_before, base_after)
    lora_b = find(params, "layer_0", "attention", "q_proj", "lora_b")
    assert np.abs(lora_b).max() > 0


@pytest.mark.slow
def test_resnet_batchnorm_stats_update():
    trainer, result = _train(
        "resnet",
        {"depth": 18, "num_classes": 10, "image_size": 32, "width": 16},
        "synthetic",
        {"shape": (32, 32, 3), "num_classes": 10},
        {"data": 8},
        steps=3,
        batch=16,
    )
    assert np.isfinite(result.history[-1]["loss"])
    stats = jax.device_get(trainer.state.extra["batch_stats"])
    stem_mean = stats["stem_bn"]["mean"]
    assert np.abs(stem_mean).max() > 0  # moved off the zero init


@pytest.mark.slow
def test_vit_trains_and_descends():
    _, result = _train(
        "vit",
        {"preset": "tiny-test", "num_classes": 10},
        "synthetic",
        {"shape": (32, 32, 3), "num_classes": 10},
        {"data": 2, "model": 4},
        steps=8,
        batch=16,
    )
    assert result.history[-1]["loss"] < 2.5  # well below ln(10)+slack


@pytest.mark.slow
def test_bert_mlm_loss_finite():
    _, result = _train(
        "bert",
        {"preset": "tiny-test"},
        "synthetic_mlm",
        {"seq_len": 64, "vocab_size": 1024},
        {"data": 2, "fsdp": 2, "model": 2},
    )
    assert np.isfinite(result.history[-1]["loss"])


def test_bad_preset_raises():
    with pytest.raises(ValueError):
        build_model("vit", {"preset": "nope"})
    with pytest.raises(ValueError):
        build_model("transformer_lm", {"preset": "nope"})
    with pytest.raises(ValueError):
        build_model("resnet", {"depth": 42})


@pytest.mark.slow
def test_graft_entry():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 128, 4096)
    g.dryrun_multichip(8)


def test_seq2seq_forward_shapes(tmp_home):
    """Fast tier: decoder-only logits, packed input stream."""
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    b = build_model("seq2seq", {"preset": "tiny-test", "src_len": 16, "tgt_len": 8})
    toks = jnp.zeros((2, 24), jnp.int32)
    params = b.module.init({"params": jax.random.PRNGKey(0)}, toks, train=False)[
        "params"
    ]
    logits = b.module.apply({"params": params}, toks, train=False)
    assert logits.shape == (2, 8, 1024)  # decoder span only


@pytest.mark.slow
def test_seq2seq_trains_reversal_task(tmp_home):
    """Encoder-decoder learns the reversal task: loss descends well below
    uniform (log 1024 ≈ 6.93) and the decoder actually uses cross-attention
    (source-position logits are zeroed and ignored via -100)."""
    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    program = V1Program(
        model=V1ModelSpec(
            name="seq2seq",
            config={"preset": "tiny-test", "src_len": 16, "tgt_len": 16},
        ),
        data=V1DataSpec(
            name="synthetic_seq2seq",
            batch_size=32,
            config={"src_len": 16, "tgt_len": 16, "vocab_size": 1024},
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=3e-3),
        # curve (verified on CPU): ~6.9 uniform → ~6.4 @50 → ~4.0 @75 →
        # ~1.2 @100; 80 steps with margin distinguishes learning from noise
        train=V1TrainSpec(steps=80, log_every=80, precision="float32"),
    )
    result = Trainer(program, mesh_axes={"data": -1}).run()
    last = result.history[-1]
    assert last["loss"] == last["loss"]
    assert last["loss"] < 6.0, f"no learning signal: {last['loss']}"


@pytest.mark.slow
def test_seq2seq_trains_tp_mesh(tmp_home):
    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    program = V1Program(
        model=V1ModelSpec(
            name="seq2seq",
            config={"preset": "tiny-test", "src_len": 16, "tgt_len": 16},
        ),
        data=V1DataSpec(
            name="synthetic_seq2seq",
            batch_size=16,
            config={"src_len": 16, "tgt_len": 16, "vocab_size": 1024},
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
        train=V1TrainSpec(steps=4, log_every=4, precision="float32"),
    )
    result = Trainer(
        program, mesh_axes={"data": 2, "fsdp": 2, "model": 2}
    ).run()
    assert result.history[-1]["loss"] == result.history[-1]["loss"]


@pytest.mark.slow
def test_fused_lm_loss_matches_regular_training():
    """fused_lm_loss=True (chunked head+CE, no [B,S,V] logits) trains to
    the same losses as the regular path — same seed, same data."""
    import numpy as np

    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    def prog(fused):
        return V1Program(
            model=V1ModelSpec(
                name="transformer_lm",
                config={
                    "preset": "tiny", "seq_len": 64, "n_layers": 2,
                    "dim": 64, "vocab_size": 300,  # ragged vs chunk 128
                    "fused_lm_loss": fused, "fused_loss_chunk": 128,
                },
            ),
            data=V1DataSpec(
                name="synthetic_text", batch_size=8,
                config={"seq_len": 64, "vocab_size": 300},
            ),
            optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
            train=V1TrainSpec(steps=3, log_every=1, precision="float32",
                              seed=0),
        )

    import jax

    r_reg = Trainer(prog(False), devices=jax.devices()[:1]).run()
    r_fused = Trainer(prog(True), devices=jax.devices()[:1]).run()
    for a, b in zip(r_reg.history, r_fused.history):
        np.testing.assert_allclose(a["loss"], b["loss"], rtol=2e-5,
                                   err_msg=str((a, b)))


def test_fused_linear_masked_lm_matches_reference():
    """ops-level parity: chunked fused head+CE == materialized logits path,
    forward and grads, with masked rows and a ragged final chunk."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.ops.losses import fused_linear_masked_lm, masked_lm

    rng = jax.random.PRNGKey(0)
    B, S, D, V = 2, 8, 16, 50
    f = jax.random.normal(rng, (B, S, D), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(rng, 1), (D, V)) * 0.1
    labels = jax.random.randint(jax.random.fold_in(rng, 2), (B, S), 0, V)
    labels = labels.at[0, :3].set(-100)

    def ref(f, k):
        logits = (f.reshape(B * S, D) @ k).reshape(B, S, V)
        return masked_lm(logits, {"labels": labels})

    def fused(f, k):
        return fused_linear_masked_lm(f, k, labels, chunk_size=16)

    np.testing.assert_allclose(ref(f, k), fused(f, k), rtol=1e-6)
    g1 = jax.grad(ref, argnums=(0, 1))(f, k)
    g2 = jax.grad(fused, argnums=(0, 1))(f, k)
    for a, b, n in zip(g1, g2, ("dfeatures", "dkernel")):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6, err_msg=n)


@pytest.mark.slow
def test_fused_lm_loss_tied_embeddings_matches_regular():
    """fused_lm_loss with tie_embeddings: kernel = embedding.T — same
    trajectories as the regular tied path."""
    import numpy as np

    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    def prog(fused):
        return V1Program(
            model=V1ModelSpec(
                name="transformer_lm",
                config={
                    "preset": "tiny", "seq_len": 64, "n_layers": 2,
                    "dim": 64, "vocab_size": 300, "tie_embeddings": True,
                    "fused_lm_loss": fused, "fused_loss_chunk": 128,
                },
            ),
            data=V1DataSpec(
                name="synthetic_text", batch_size=8,
                config={"seq_len": 64, "vocab_size": 300},
            ),
            optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
            train=V1TrainSpec(steps=3, log_every=1, precision="float32",
                              seed=0),
        )

    import jax

    r_reg = Trainer(prog(False), devices=jax.devices()[:1]).run()
    r_fused = Trainer(prog(True), devices=jax.devices()[:1]).run()
    for a, b in zip(r_reg.history, r_fused.history):
        np.testing.assert_allclose(a["loss"], b["loss"], rtol=2e-5,
                                   err_msg=str((a, b)))
