"""Expert-parallel MoE and pipeline-parallel tests on the virtual 8-device
mesh — executing real shardings, not just rendering them (SURVEY.md §4)."""

import pytest
import jax
import numpy as np

from polyaxon_tpu.models import build_model
from polyaxon_tpu.parallel.mesh import build_mesh
from polyaxon_tpu.parallel.ring import set_current_mesh
from polyaxon_tpu.runtime.trainer import Trainer
from polyaxon_tpu.schemas.run_kinds import (
    V1DataSpec,
    V1ModelSpec,
    V1OptimizerSpec,
    V1Program,
    V1TrainSpec,
)


def _prog(model_cfg, batch=8, steps=4, seq=64):
    return V1Program(
        model=V1ModelSpec(
            name="transformer_lm",
            config={"preset": "tiny", "seq_len": seq, **model_cfg},
        ),
        data=V1DataSpec(
            name="synthetic_text",
            batch_size=batch,
            config={"seq_len": seq, "vocab_size": 4096},
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
        train=V1TrainSpec(steps=steps, log_every=steps, precision="float32"),
    )


def _spec_of(shard_tree, fragment):
    for path, s in jax.tree_util.tree_leaves_with_path(shard_tree):
        p = "/".join(str(getattr(k, "key", k)) for k in path)
        if fragment in p:
            return s.spec
    raise AssertionError(f"no param matching {fragment!r}")


@pytest.mark.slow
def test_moe_trains_with_expert_axis():
    trainer = Trainer(_prog({"n_experts": 4}), mesh_axes={"data": 2, "expert": 4})
    result = trainer.run()
    assert np.isfinite(result.history[-1]["loss"])
    assert _spec_of(trainer.p_shard, "gate_kernel")[0] == "expert"


@pytest.mark.slow
def test_moe_aux_loss_enters_total():
    """With a huge aux weight the loss must visibly exceed the pure-CE
    ceiling (ln 4096 ≈ 8.3), proving sown losses reach the objective."""
    bundle = build_model("transformer_lm", {"preset": "tiny", "n_experts": 4})
    assert bundle.aux_losses
    t1 = Trainer(_prog({"n_experts": 4}), mesh_axes={"data": 8})
    r1 = t1.run()
    assert np.isfinite(r1.history[-1]["loss"])
    # aux term is small but present: loss > plain CE of an untrained model
    # would be flaky; instead check the sown collection exists structurally
    tokens = bundle.example_inputs(4)
    params = bundle.module.init({"params": jax.random.PRNGKey(0)}, tokens, train=False)[
        "params"
    ]
    _, aux = bundle.module.apply(
        {"params": params}, tokens, train=False, mutable=["losses"]
    )
    leaves = jax.tree.leaves(aux["losses"])
    assert leaves and all(np.isfinite(v) for v in leaves)


@pytest.mark.slow
def test_pipeline_forward_matches_sequential():
    cfg = {
        "preset": "tiny",
        "seq_len": 64,
        "pipeline_stages": 4,
        "pipeline_microbatches": 4,
    }
    bundle = build_model("transformer_lm", dict(cfg))
    tokens = np.random.default_rng(0).integers(0, 4096, (8, 64)).astype("int32")
    set_current_mesh(None)
    params = bundle.module.init(
        {"params": jax.random.PRNGKey(0)}, tokens, train=False
    )["params"]
    ref = bundle.module.apply({"params": params}, tokens, train=False)
    mesh = build_mesh({"data": 2, "pipeline": 4})
    set_current_mesh(mesh)
    try:
        out = jax.jit(
            lambda p, t: bundle.module.apply({"params": p}, t, train=False)
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )
    finally:
        set_current_mesh(None)


@pytest.mark.slow
def test_pipeline_trains_with_stage_sharding():
    trainer = Trainer(
        _prog({"pipeline_stages": 4, "pipeline_microbatches": 4}),
        mesh_axes={"data": 2, "pipeline": 4},
    )
    result = trainer.run()
    assert np.isfinite(result.history[-1]["loss"])
    assert _spec_of(trainer.p_shard, "gate_proj/kernel")[0] == "pipeline"


@pytest.mark.slow
def test_pipeline_gradients_match_sequential():
    """GPipe backward (autodiff through ppermute) == sequential backward."""
    cfg = {
        "preset": "tiny",
        "seq_len": 64,
        "n_layers": 2,
        "pipeline_stages": 2,
        "pipeline_microbatches": 2,
    }
    bundle = build_model("transformer_lm", dict(cfg))
    tokens = np.random.default_rng(1).integers(0, 4096, (8, 64)).astype("int32")
    set_current_mesh(None)
    params = bundle.module.init(
        {"params": jax.random.PRNGKey(0)}, tokens, train=False
    )["params"]

    def loss(p):
        return bundle.module.apply({"params": p}, tokens, train=False).mean()

    g_ref = jax.grad(loss)(params)
    mesh = build_mesh({"pipeline": 2, "data": 4})
    set_current_mesh(mesh)
    try:
        g_pp = jax.jit(jax.grad(loss))(params)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_pp)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
            )
    finally:
        set_current_mesh(None)


# ------------------------------------------------------- composed meshes
# Strategies must COMPOSE, not just coexist (SURVEY.md §2 parallelism
# census) — mirrors __graft_entry__.dryrun_multichip's composed modes.
@pytest.mark.slow
@pytest.mark.parametrize(
    "axes,cfg",
    [
        ({"model": 2, "context": 2, "data": 2}, {"attention": "ring"}),
        (
            {"model": 2, "pipeline": 2, "data": 2},
            {"pipeline_stages": 2, "pipeline_microbatches": 2},
        ),
        ({"fsdp": 2, "expert": 2, "data": 2}, {"n_experts": 2}),
    ],
    ids=["tp+context+dp", "tp+pipeline+dp", "fsdp+expert+dp"],
)
def test_composed_mesh_trains(axes, cfg):
    trainer = Trainer(_prog(cfg, steps=2), mesh_axes=axes)
    result = trainer.run()
    assert np.isfinite(result.history[-1]["loss"])


# ------------------------------------------------------- multi-slice mesh
def test_hybrid_mesh_data_axis_is_slice_major():
    """slices=2 on 8 virtual devices: the data axis's outer half lives in
    slice 0 (first device block), the inner structure inside a slice —
    create_hybrid_device_mesh semantics on virtual slices."""
    from polyaxon_tpu.parallel.mesh import build_mesh

    mesh = build_mesh({"data": 4, "model": 2}, slices=2)
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    devs = mesh.devices  # [data=4, model=2]
    ids = [[d.id for d in row] for row in devs]
    first_half = {i for row in ids[:2] for i in row}
    second_half = {i for row in ids[2:] for i in row}
    assert first_half == {0, 1, 2, 3}, ids  # slice 0 block
    assert second_half == {4, 5, 6, 7}, ids  # slice 1 block


def test_hybrid_mesh_requires_divisible_data_axis():
    from polyaxon_tpu.parallel.mesh import build_mesh

    with pytest.raises(ValueError, match="divisible by slices"):
        build_mesh({"model": 8}, slices=2)  # no data axis to span DCN


@pytest.mark.slow
def test_multislice_trainer_end_to_end():
    trainer = Trainer(
        _prog({}, steps=2), mesh_axes={"data": 4, "model": 2}, slices=2
    )
    result = trainer.run()
    assert np.isfinite(result.history[-1]["loss"])


def test_trainer_rejects_indivisible_slices():
    with pytest.raises(ValueError, match="divisible by slices"):
        Trainer(_prog({}), mesh_axes={"data": 2, "model": 4}, slices=4)
