"""Schema pins for the ISSUE 6 benchmark surfaces. decode_bench and
serving_bench JSON is consumed unattended (TPU canary, driver scorecard),
so the paged-KV / TTFT / prefix-reuse fields added there are contract:
renaming one silently voids the perf evidence. Each test runs the real
script in a subprocess on CPU smoke settings and pins the record keys."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.slow  # each drives real compiles in a subprocess


def _run(script, *args, timeout=420):
    import os

    env = dict(
        os.environ,
        POLYAXON_JAX_PLATFORM="cpu",
        POLYAXON_NUM_CPU_DEVICES="1",
    )
    return subprocess.run(
        [sys.executable, str(REPO / script), *args],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def _records(proc):
    return [
        json.loads(l)
        for l in proc.stdout.splitlines()
        if l.strip().startswith("{")
    ]


def test_decode_bench_schema(tmp_home):
    proc = _run("benchmarks/decode_bench.py", "--smoke")
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = _records(proc)
    for r in recs:
        assert "error" not in r, r

    dense = [r for r in recs if r["metric"] == "decode_tokens_per_sec"]
    assert dense
    for r in dense:
        # TTFT is first-class on every dense record (= prefill time:
        # dense decode emits nothing until the whole batch completes)
        assert r["ttft_ms"] > 0, r
        assert r["ttft_ms"] == r["prefill_ms"]

    paged = [r for r in recs if r["metric"] == "paged_decode_tokens_per_sec"]
    assert len(paged) == 1, recs
    p = paged[0]
    assert {
        "value", "unit", "page_tokens", "pool_pages", "kv_pool_bytes",
        "ttft_ms", "per_token_ms", "cache_donated", "batch", "prompt_len",
        "max_new",
    } <= p.keys(), p
    assert p["value"] > 0 and p["unit"] == "tok/s"
    assert p["page_tokens"] >= 8 and p["pool_pages"] > p["batch"]
    assert p["kv_pool_bytes"] > 0 and p["ttft_ms"] > 0
    # report-only on CPU (XLA ignores donation there), asserted on TPU
    assert isinstance(p["cache_donated"], bool)

    # ISSUE 8: the speculation record — byte-identity is asserted inside
    # the bench itself; here the contract keys and the acceptance claim
    # (>= 1.3x on the copy-friendly workload) are pinned
    spec = [
        r for r in recs if r["metric"] == "speculative_decode_tokens_per_sec"
    ]
    assert len(spec) == 1, recs
    s = spec[0]
    assert {
        "value", "unit", "draft_tokens", "accept_rate", "tokens_per_step",
        "baseline_tokens_per_sec", "speedup_vs_baseline",
        "compiled_programs", "identical_to_baseline",
    } <= s.keys(), s
    assert s["identical_to_baseline"] is True
    assert s["accept_rate"] > 0.5, s  # the drafter really tracked the cycle
    assert s["tokens_per_step"] > 1.0, s
    assert s["speedup_vs_baseline"] >= 1.3, s
    # the whole run compiles exactly one prefill + one verify program —
    # the ladder the serving compile cache keys on stays flat
    assert s["compiled_programs"] == 2

    # ISSUE 8: the int8 record — >= 40% decode-weight HBM reduction with
    # the greedy top-1 agreement bound
    q = [r for r in recs if r["metric"] == "int8_decode_tokens_per_sec"]
    assert len(q) == 1, recs
    q = q[0]
    assert {
        "value", "unit", "decode_weight_bytes_fp", "decode_weight_bytes_int8",
        "hbm_reduction", "top1_agreement", "logit_max_abs_delta",
        "baseline_tokens_per_sec",
    } <= q.keys(), q
    assert q["decode_weight_bytes_int8"] < q["decode_weight_bytes_fp"]
    assert q["hbm_reduction"] >= 0.40, q
    assert q["top1_agreement"] >= 0.75, q
    assert q["logit_max_abs_delta"] < 1.0, q

    # ISSUE 15: the draft-model record — a real small model proposes,
    # the target verifies, outputs stay byte-identical, and the draft
    # weights were derived from the target (no separate training run)
    d = [r for r in recs if r["metric"] == "draft_model_decode_tokens_per_sec"]
    assert len(d) == 1, recs
    d = d[0]
    assert {
        "value", "unit", "draft_tokens", "draft_layers", "target_layers",
        "draft_params_derived", "accept_rate", "windows",
        "baseline_tokens_per_sec", "speedup_vs_baseline",
        "identical_to_baseline",
    } <= d.keys(), d
    assert d["identical_to_baseline"] is True
    assert d["draft_params_derived"] is True
    assert d["draft_layers"] < d["target_layers"], d
    assert d["accept_rate"] > 0.5, d  # the truncated draft tracked the cycle
    assert d["speedup_vs_baseline"] >= 1.3, d

    # ISSUE 15: the adaptive record — high-entropy traffic where n-gram
    # speculation loses; the controller must detect the low accept rate,
    # disable speculation, and land within 5% of plain decode while
    # beating the always-on n-gram path
    a = [
        r for r in recs
        if r["metric"] == "adaptive_spec_decode_tokens_per_sec"
    ]
    assert len(a) == 1, recs
    a = a[0]
    assert {
        "value", "unit", "plain_tokens_per_sec", "ngram_tokens_per_sec",
        "ngram_accept_rate", "adaptive_vs_plain",
        "adaptive_vs_ngram_speedup", "auto_disable_engaged",
        "effective_k_final", "spec_windows", "identical_to_baseline",
    } <= a.keys(), a
    assert a["identical_to_baseline"] is True
    assert a["auto_disable_engaged"] is True, a
    assert a["adaptive_vs_plain"] >= 0.95, a
    assert a["adaptive_vs_ngram_speedup"] > 1.0, a

    # ISSUE 15: the int8-KV record — ~2x+ decode rows per HBM byte vs
    # the f32 pool, with chunked prefill and prefix reuse byte-identical
    # on the quantized pool
    k = [r for r in recs if r["metric"] == "int8_kv_decode_tokens_per_sec"]
    assert len(k) == 1, recs
    k = k[0]
    assert {
        "value", "unit", "kv_quant", "page_tokens", "pool_pages",
        "kv_pool_bytes", "kv_pool_bytes_fp", "bytes_ratio", "rows_fp",
        "dense_equivalent_rows", "rows_per_byte_vs_fp",
        "chunked_prefill_identical", "prefix_reuse_identical",
    } <= k.keys(), k
    assert k["kv_quant"] == "int8"
    assert k["kv_pool_bytes"] < k["kv_pool_bytes_fp"], k
    assert k["chunked_prefill_identical"] is True
    assert k["prefix_reuse_identical"] is True
    assert k["rows_per_byte_vs_fp"] >= 1.9, k


def test_serving_bench_paged_schema(tmp_home):
    proc = _run(
        "benchmarks/serving_bench.py", "--smoke", "--mode", "paged",
        "--kv-pool-pages", "96",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = _records(proc)
    assert len(recs) == 1, recs
    r = recs[0]
    assert r["metric"] == "serving_requests_per_sec"
    assert r["mode"] == "paged" and not r.get("errors")
    assert {
        "ttft_p50_ms", "ttft_p95_ms", "kv_pages_total", "kv_pages_used_hwm",
        "prefix_hit_rate",
    } <= r.keys(), r
    assert r["value"] > 0
    assert r["ttft_p50_ms"] > 0 and r["ttft_p95_ms"] >= r["ttft_p50_ms"]
    assert r["kv_pages_total"] == 96
    # occupancy accounting really ran: scratch + at least one data page
    assert 1 < r["kv_pages_used_hwm"] <= r["kv_pages_total"]
    assert 0.0 <= r["prefix_hit_rate"] <= 1.0


def test_serving_bench_shared_prefix_demonstrates_reuse(tmp_home):
    proc = _run(
        "benchmarks/serving_bench.py", "--smoke", "--shared-prefix",
        "--kv-pool-pages", "96",
    )
    # rc=1 is the script's own "no reuse demonstrated" signal — fail loudly
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    recs = _records(proc)
    assert len(recs) == 1, recs
    r = recs[0]
    assert r["metric"] == "serving_prefix_reuse_ttft_speedup"
    assert {
        "value", "ttft_cold_ms", "ttft_warm_p50_ms", "ttft_warm_p95_ms",
        "prefix_hit_rate", "prefix_hits", "kv_pages_total",
        "kv_pages_used_hwm", "shared_prefix_tokens", "page_tokens",
    } <= r.keys(), r
    # the acceptance claim: warm requests skip the shared prefill, so
    # hit-rate is positive and warm TTFT beats cold
    assert r["prefix_hit_rate"] > 0
    assert r["ttft_warm_p50_ms"] < r["ttft_cold_ms"]
    assert r["value"] > 1.0


def test_serving_bench_speculate_schema(tmp_home):
    proc = _run(
        "benchmarks/serving_bench.py", "--smoke", "--speculate",
        "--kv-pool-pages", "96",
    )
    # rc=1 is the script's own "no drafts accepted / outputs diverged"
    # signal — fail loudly
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    recs = {r["metric"]: r for r in _records(proc)}

    s = recs["serving_speculative_speedup"]
    assert {
        "value", "unit", "tokens_per_sec", "baseline_tokens_per_sec",
        "accept_rate", "tokens_per_step", "draft_tokens", "proposed",
        "accepted", "rollbacks", "compile_count", "identical_outputs",
    } <= s.keys(), s
    assert s["identical_outputs"] is True
    assert s["accepted"] > 0 and s["accept_rate"] > 0
    assert s["tokens_per_step"] > 1.0
    # the mode adds exactly two programs (spec prefill + verify) per
    # bucket signature — one traffic shape means a flat compile ladder
    assert s["compile_count"] <= 4, s

    q = recs["serving_quant_bytes_saved"]
    assert {
        "value", "unit", "hbm_reduction", "top1_agreement_vs_fp",
        "agreement_horizon", "tokens_per_sec", "fp_tokens_per_sec",
    } <= q.keys(), q
    assert q["value"] > 0 and q["unit"] == "bytes"
    assert q["hbm_reduction"] >= 0.40
    assert 0.0 <= q["top1_agreement_vs_fp"] <= 1.0


def test_serving_bench_trace_overhead_schema(tmp_home):
    proc = _run("benchmarks/serving_bench.py", "--smoke", "--trace-overhead")
    # rc=1 is the script's own "tracing cost above 5%" gate — fail loudly
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    recs = _records(proc)
    assert len(recs) == 1, recs
    r = recs[0]
    assert r["metric"] == "serving_trace_overhead" and r["unit"] == "%"
    assert {
        "value", "req_per_sec_on", "req_per_sec_off", "p99_on_ms",
        "p99_off_ms", "repeats",
    } <= r.keys(), r
    assert r["req_per_sec_on"] > 0 and r["req_per_sec_off"] > 0
    assert r["value"] <= 5.0, r


def test_serving_bench_federation_overhead_schema(tmp_home):
    proc = _run(
        "benchmarks/serving_bench.py", "--smoke", "--federation-overhead",
        timeout=560,
    )
    # rc=1 is the script's own gate (plane cost above 5% p95, or the
    # on-router never actually federated) — fail loudly
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    recs = _records(proc)
    assert len(recs) == 1, recs
    r = recs[0]
    assert r["metric"] == "serving_federation_overhead" and r["unit"] == "%"
    assert {
        "value", "p95_on_ms", "p95_off_ms", "req_per_sec_on",
        "req_per_sec_off", "federated_series", "cluster_aggregates",
        "replicas", "repeats",
    } <= r.keys(), r
    assert r["req_per_sec_on"] > 0 and r["req_per_sec_off"] > 0
    # the on-router must have really federated and stitched, otherwise
    # the overhead number measures nothing
    assert r["federated_series"] is True
    assert r["cluster_aggregates"] is True
    assert r["value"] <= 5.0, r


def test_serving_bench_router_schema(tmp_home):
    proc = _run(
        "benchmarks/serving_bench.py", "--smoke", "--router",
        "--replicas", "2", timeout=560,
    )
    # rc=1 is the script's own gate (overhead > 10%, scaling below 1.7x
    # where enforced, or a byte-identity break) — fail loudly
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    recs = {r["metric"]: r for r in _records(proc)}

    s = recs["router_aggregate_speedup"]
    assert {
        "value", "unit", "replicas", "req_per_sec_router",
        "req_per_sec_single_direct", "host_cores", "gate_enforced",
    } <= s.keys(), s
    assert s["replicas"] == 2 and s["unit"] == "x"
    assert s["req_per_sec_router"] > 0
    assert s["req_per_sec_single_direct"] > 0
    assert not s.get("errors"), s
    # the scaling claim gates only where two processes can actually run
    # in parallel; the record says which regime it measured
    assert s["gate_enforced"] == (s["host_cores"] >= 2)
    if s["gate_enforced"]:
        assert s["value"] >= 1.7, s

    o = recs["router_latency_overhead"]
    assert {
        "value", "unit", "p50_direct_ms", "p95_direct_ms", "p50_router_ms",
        "p95_router_ms", "samples", "byte_identical",
    } <= o.keys(), o
    assert o["byte_identical"] is True
    assert o["value"] <= 10.0, o

    # ISSUE 17: every router record carries the cluster-wide prefix hit
    # rate so regressions in cache effectiveness show up in any run
    assert "cluster_prefix_hit_rate" in s, s
    assert "cluster_prefix_hit_rate" in o, o


def test_serving_bench_interference_schema(tmp_home):
    proc = _run(
        "benchmarks/serving_bench.py", "--smoke", "--interference",
        timeout=560,
    )
    # rc=1 is the script's own gate (no chunks landed, or <2x where the
    # host can express the TTFT win) — fail loudly
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    recs = _records(proc)
    assert len(recs) == 1, recs
    r = recs[0]
    assert r["metric"] == "serving_interference_ttft_speedup"
    assert {
        "value", "unit", "ttft_short_p50_unchunked_ms",
        "ttft_short_p50_chunked_ms", "ttft_short_p95_unchunked_ms",
        "ttft_short_p95_chunked_ms", "long_total_p50_unchunked_ms",
        "long_total_p50_chunked_ms", "long_prompt_tokens",
        "short_prompt_tokens", "short_requests", "prefill_chunk_tokens",
        "max_step_tokens", "steps", "prefill_chunks", "host_cores",
        "gate_enforced", "platform", "device_kind",
    } <= r.keys(), r
    assert r["unit"] == "x"
    # the step scheduler really ran: prefill arrived in slices across
    # multiple device steps, not one blocking execute
    assert r["prefill_chunks"] > 0 and r["steps"] > 0
    assert r["ttft_short_p95_chunked_ms"] > 0
    # the TTFT claim gates only where the timing clients and the step
    # loop don't fight over one core; the record says which regime
    assert r["gate_enforced"] == (r["host_cores"] >= 2)
    if r["gate_enforced"]:
        assert r["value"] >= 2.0, r


def test_serving_bench_affinity_schema(tmp_home):
    # ISSUE 17: warm TTFT survives both a forced re-route (affinity sends
    # the repeat to the replica that cached it) and an eviction→spill→
    # restore cycle (pages come back from the spill tier, no re-prefill)
    proc = _run(
        "benchmarks/serving_bench.py", "--smoke", "--affinity",
        timeout=560,
    )
    # rc=1 is the script's own gate (no affinity hits, no spill→restore
    # cycle, a byte-identity break, or — where the host can express the
    # timing — warm TTFT not preserved) — fail loudly
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    recs = _records(proc)
    assert len(recs) == 1, recs
    r = recs[0]
    assert r["metric"] == "serving_affinity_warm_ttft_speedup"
    assert {
        "value", "unit", "ttft_cold_ms", "ttft_warm_ms", "ttft_restore_ms",
        "ttft_reroute_cold_ms", "restore_speedup", "affinity_hits",
        "spills", "spill_restores", "spilled_bytes",
        "cluster_prefix_hit_rate", "byte_identical", "prompt_tokens",
        "page_tokens", "pool_pages", "host_cores", "gate_enforced",
        "platform", "device_kind",
    } <= r.keys(), r
    assert r["unit"] == "x"
    # the mechanisms really ran, independent of timing noise: the router
    # steered repeats to the holder, cold entries demoted to the spill
    # tier, and at least one spilled prefix was restored instead of
    # re-prefilled
    assert r["affinity_hits"] >= 2, r
    assert r["spills"] >= 1 and r["spill_restores"] >= 1, r
    assert r["spilled_bytes"] > 0, r
    assert (r["cluster_prefix_hit_rate"] or 0) > 0, r
    # restored pages must decode the exact same continuation
    assert r["byte_identical"] is True, r
    # the TTFT claims gate only where the replicas and the timing client
    # don't fight over one core; the record says which regime
    assert r["gate_enforced"] == (r["host_cores"] >= 2)
    if r["gate_enforced"]:
        assert r["value"] >= 1.2, r
        assert r["restore_speedup"] >= 1.0, r


def test_serving_bench_tenants_schema(tmp_home):
    # ISSUE 19: per-tenant admission isolates the victim from a noisy
    # flood, and LoRA adapter multiplexing (per-row slot gather + hot
    # evict→spill→restore swaps) stays within 10% of a plain LoRA server
    proc = _run(
        "benchmarks/serving_bench.py", "--smoke", "--tenants",
        timeout=560,
    )
    # rc=1 is the script's own gate (the flood never shed tenant_quota,
    # the victim shed, no evict→restore cycle ran, swap tax above 10%,
    # or — where the host can express it — the isolation ratio blown)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    recs = {r["metric"]: r for r in _records(proc)}

    iso = recs["serving_tenant_isolation_p95_ratio"]
    assert {
        "value", "unit", "victim_p50_alone_ms", "victim_p95_alone_ms",
        "victim_p50_contended_ms", "victim_p95_contended_ms",
        "victim_requests", "victim_shed", "victim_errors", "noisy_ok",
        "noisy_shed", "noisy_shed_reasons", "noisy_max_outstanding",
        "flood_clients", "repeats", "host_cores", "gate_enforced",
        "platform", "device_kind",
    } <= iso.keys(), iso
    assert iso["unit"] == "x"
    # the admission mechanism really ran: the flood shed, every shed was
    # attributed to the noisy tenant's own quota, and the uncapped
    # victim was never touched
    assert iso["noisy_shed"] > 0, iso
    assert set(iso["noisy_shed_reasons"]) == {"tenant_quota"}, iso
    assert iso["victim_shed"] == 0 and iso["victim_errors"] == 0, iso
    # the isolation-ratio claim gates only where the flood threads and
    # the decode worker don't fight over one core; the record says which
    assert iso["gate_enforced"] == (iso["host_cores"] >= 2)
    if iso["gate_enforced"]:
        assert iso["value"] <= 3.0, iso

    swap = recs["serving_adapter_swap_overhead"]
    assert {
        "value", "unit", "p95_multi_ms", "p95_solo_ms", "adapters",
        "adapter_slots", "adapters_resident", "swap_p50_ms",
        "resident_p50_ms", "swap_requests", "swap_loads",
        "swap_evictions", "swap_restores", "repeats",
    } <= swap.keys(), swap
    assert swap["unit"] == "%"
    assert swap["value"] <= 10.0, swap
    # the churn phase priced REAL swaps: three adapters rotated through
    # two hot slots, so weights demoted to the spill tier and came back
    assert swap["swap_evictions"] >= 1, swap
    assert swap["swap_restores"] >= 1, swap
    assert swap["swap_loads"] >= swap["swap_restores"], swap


def test_elastic_bench_schema(tmp_home):
    proc = _run("benchmarks/elastic_bench.py", "--smoke")
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    recs = {r["metric"]: r for r in _records(proc)}
    for r in recs.values():
        assert "error" not in r, r

    stall = recs["checkpoint_stall_ms"]
    # the stall numbers come from the trainer's own histogram — the same
    # series the canary greps off /metricsz, not a bench-local clock
    assert stall["status"] == "succeeded"
    assert stall["boundaries"] > 0
    assert {"stall_p50_ms", "stall_p95_ms", "stall_max_ms",
            "tier_writes"} <= stall.keys(), stall
    # two tiers: every boundary lands locally AND replicates durably
    assert stall["tier_writes"] >= 2 * stall["boundaries"]

    lost = recs["steps_lost_per_preemption"]
    assert lost["preemptions"] >= 1
    assert lost["bound_held"] is True
    assert lost["steps_lost_max"] <= lost["checkpoint_every"]
    assert lost["time_to_resume_ms_mean"] is not None

    resize = recs["elastic_resize"]
    assert resize["grants"][0] > resize["grants"][1]  # shrank under pressure
    assert resize["grants"][-1] == resize["grants"][0]  # grew back
    assert resize["elastic_wait_total_s"] == 0.0  # the ladder never parks
    assert resize["elastic_makespan_s"] < resize["rigid_makespan_s"]
