"""Elastic training: multi-tier checkpointing + gang resize on preemption.

Three layers, mirroring the implementation:

- **Tiers unit layer** — `CheckpointTiers` semantics pinned directly:
  boundary saves land on the local tier and replicate to the durable tier
  through a fsynced staging dir + atomic rename; restore prefers the
  durable copy of a step and falls back to the local copy of the SAME
  step with per-tier quarantine; a kill mid-upload surfaces at the next
  save/wait barrier while the durable tier never lists the torn step.

- **Scheduler layer** — elastic admission walks the halving ladder to the
  `minChips` floor instead of parking in WAIT; the reservation records
  the full request so `consider_expansion` can grow the run back; the
  simulator replays a seeded shrink→grow round trip with invariants
  asserted at every event.

- **Executor layer (chaos)** — seeded scenarios through the REAL run
  lifecycle: eviction at peak lost work resumes at a smaller admissible
  gang with byte-stable state versus a non-preempted reference; a kill
  during a durable upload recovers from the local tier within the
  `checkpoint_every` bound; a durable-tier outage degrades to local-only
  saves without failing the run.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polyaxon_tpu import chaos
from polyaxon_tpu.chaos import Fault, FaultPlan
from polyaxon_tpu.runtime import checkpoint as ck
from polyaxon_tpu.runtime.checkpoint import CheckpointTiers
from polyaxon_tpu.telemetry import get_registry


def _state(scale: float = 1.0):
    return {
        "w": jnp.arange(8, dtype=jnp.float32) * scale,
        "b": jnp.ones((4,), dtype=jnp.float32) * scale,
    }


def _digit_dirs(path: str) -> set[int]:
    try:
        return {int(n) for n in os.listdir(path) if n.isdigit()}
    except OSError:
        return set()


def _corrupt_copy(directory: str, step: int) -> None:
    from polyaxon_tpu.chaos.injector import corrupt_checkpoint

    corrupt_checkpoint(directory, step=step)


# ------------------------------------------------------------ tiers unit
class TestCheckpointTiers:
    def test_save_replicates_and_restore_prefers_durable(self, tmp_path):
        tiers = CheckpointTiers(
            str(tmp_path / "durable"), local=str(tmp_path / "local")
        )
        tiers.save(2, _state(1.0))
        tiers.save(4, _state(2.0), wait=True)
        by_tier = tiers.steps_by_tier()
        assert by_tier["local"] == [2, 4]
        assert by_tier["durable"] == [2, 4]
        state, step, corrupt, tier = tiers.restore_latest_intact(_state(0.0))
        assert (step, tier, corrupt) == (4, "durable", [])
        np.testing.assert_array_equal(np.asarray(state["w"]),
                                      np.arange(8, dtype=np.float32) * 2.0)

    def test_corrupt_durable_falls_back_to_local_copy_of_same_step(
        self, tmp_path
    ):
        durable, local = str(tmp_path / "durable"), str(tmp_path / "local")
        tiers = CheckpointTiers(durable, local=local)
        tiers.save(2, _state(1.0))
        tiers.save(4, _state(2.0), wait=True)
        _corrupt_copy(durable, 4)
        state, step, corrupt, tier = tiers.restore_latest_intact(_state(0.0))
        # same step, other tier — the torn durable copy costs nothing
        assert (step, tier) == (4, "local")
        assert corrupt == [("durable", 4)]
        # the poisoned copy is quarantined in ITS tier only
        assert os.path.isdir(os.path.join(durable, "4.corrupt"))
        assert os.path.isdir(os.path.join(local, "4"))

    def test_without_local_tier_degrades_to_single_directory(self, tmp_path):
        tiers = CheckpointTiers(str(tmp_path / "durable"))
        tiers.save(2, _state(), wait=True)
        assert "local" not in tiers.steps_by_tier()
        assert tiers.latest_step() == 2
        _, step, _, tier = tiers.restore_latest_intact(_state(0.0))
        assert (step, tier) == (2, "durable")

    def test_upload_failure_counts_and_step_stays_local_only(self, tmp_path):
        durable = str(tmp_path / "durable")
        tiers = CheckpointTiers(durable, local=str(tmp_path / "local"))
        failures = get_registry().counter("checkpoint.upload_failures")
        base = failures.value
        plan = FaultPlan(
            [Fault("checkpoint.upload", "raise", at=0,
                   message="chaos: durable tier unavailable")]
        )
        with chaos.active(plan):
            tiers.save(2, _state(1.0), wait=True)  # wait() must NOT raise
        assert failures.value == base + 1
        assert tiers.steps_by_tier() == {"durable": [], "local": [2]}
        # the outage over, the next boundary replicates normally
        tiers.save(4, _state(2.0), wait=True)
        assert tiers.steps_by_tier()["durable"] == [4]
        assert tiers.latest_step() == 4

    def test_kill_mid_upload_surfaces_at_barrier_durable_never_torn(
        self, tmp_path
    ):
        from polyaxon_tpu.chaos.injector import SimulatedKill

        durable = str(tmp_path / "durable")
        tiers = CheckpointTiers(durable, local=str(tmp_path / "local"))
        plan = FaultPlan([Fault("checkpoint.upload", "kill", step=2)])
        with chaos.active(plan):
            tiers.save(2, _state(1.0))
            with pytest.raises(SimulatedKill):
                tiers.wait()
        # the durable tier never lists the torn step — no dir, no staging
        assert _digit_dirs(durable) == set()
        residue = os.listdir(durable) if os.path.isdir(durable) else []
        assert not any(n.endswith(".uploading") for n in residue)
        # the local copy is intact: a restart restores step 2 from it
        state, step, corrupt, tier = tiers.restore_latest_intact(_state(0.0))
        assert (step, tier, corrupt) == (2, "local", [])

    def test_durable_retention_mirrors_keep(self, tmp_path):
        tiers = CheckpointTiers(
            str(tmp_path / "durable"), local=str(tmp_path / "local"), keep=2
        )
        for i, step in enumerate((2, 4, 6), start=1):
            tiers.save(step, _state(float(i)), wait=True)
        assert _digit_dirs(tiers.durable) == {4, 6}


# ------------------------------------------- manager cache + quarantine
class TestManagerLifecycle:
    def test_keep_mismatch_rebuilds_manager_and_retention_tracks(
        self, tmp_path
    ):
        d = str(tmp_path / "ckpt")
        first = ck._manager(d)  # pins the default keep=3
        assert ck._manager(d) is first  # keep=None reuses
        assert ck._manager(d, keep=3) is first  # agreeing keep reuses
        rebuilt = ck._manager(d, keep=2)  # disagreeing keep REBUILDS
        assert rebuilt is not first
        assert ck._manager(d, keep=2) is rebuilt
        for step in (1, 2, 3, 4):
            ck.save_checkpoint(d, step, _state(), wait=True, keep=2)
        assert ck.all_steps(d) == [3, 4]  # the later keep won

    def test_quarantine_fsyncs_parent_directory(self, tmp_path, monkeypatch):
        d = tmp_path / "ckpt"
        (d / "5").mkdir(parents=True)
        (d / "5" / "data").write_bytes(b"x")
        synced = []
        monkeypatch.setattr(ck, "_fsync_dir", lambda p: synced.append(p))
        ck._quarantine(str(d), 5)
        assert (d / "5.corrupt").is_dir() and not (d / "5").exists()
        # the rename is made durable through the PARENT directory
        assert synced == [str(d)]

    def test_restart_with_save_in_flight_never_quarantines(
        self, tmp_path, monkeypatch
    ):
        """Satellite 3: an async save still writing at restart must be
        waited for, not judged mid-write — the restore path barriers on
        `wait_until_finished` BEFORE listing steps, so an in-flight
        checkpoint is never seen half-written and quarantined."""
        d = str(tmp_path / "ckpt")
        ck.save_checkpoint(d, 2, _state(1.0))  # async, no wait
        mgr = ck._manager(d)
        order = []
        real_wait = mgr.wait_until_finished
        real_all = ck.all_steps
        monkeypatch.setattr(
            mgr, "wait_until_finished",
            lambda: (order.append("wait"), real_wait())[1],
        )
        monkeypatch.setattr(
            ck, "all_steps",
            lambda *a, **k: (order.append("list"), real_all(*a, **k))[1],
        )
        state, step, corrupt = ck.restore_latest_intact(d, _state(0.0))
        assert (step, corrupt) == (2, [])
        assert not os.path.isdir(os.path.join(d, "2.corrupt"))
        assert "wait" in order and order.index("wait") < order.index("list")


# ----------------------------------------------------- scheduler layer
@pytest.mark.scheduler
class TestElasticAdmission:
    def _entry(self, uuid, chips, min_chips=None, priority=0):
        return {
            "uuid": uuid,
            "payload": {"project": "default"},
            "priority": priority,
            "chips": chips,
            "min_chips": min_chips,
            "block": None,
        }

    def test_shrink_ladder_halves_to_floor(self):
        from polyaxon_tpu.scheduler.fleet import shrink_candidates

        assert shrink_candidates(8, None, 2) == [(4, None), (2, None)]
        assert shrink_candidates(8, (2, 4), 1) == [
            (4, (2, 2)), (2, (1, 2)), (1, (1, 1))
        ]
        assert shrink_candidates(4, None, 4) == []  # floor == full: rigid

    def test_min_chips_demand_reads_resources(self):
        from polyaxon_tpu.schemas.operation import V1Operation
        from polyaxon_tpu.scheduler.fleet import min_chips_demand

        op = V1Operation.model_validate(
            {
                "name": "el",
                "environment": {"resources": {"chips": 4, "minChips": 2}},
                "component": {
                    "name": "c",
                    "run": {"kind": "job", "container": {"command": ["true"]}},
                },
            }
        )
        assert min_chips_demand(op) == 2
        rigid = V1Operation.model_validate(
            {
                "name": "r",
                "environment": {"resources": {"chips": 4}},
                "component": {
                    "name": "c",
                    "run": {"kind": "job", "container": {"command": ["true"]}},
                },
            }
        )
        assert min_chips_demand(rigid) is None

    def test_elastic_admits_shrunk_grant_instead_of_wait(self, tmp_home):
        from polyaxon_tpu.scheduler.admission import (
            ADMIT,
            WAIT,
            AdmissionController,
        )
        from polyaxon_tpu.scheduler.fleet import Fleet
        from polyaxon_tpu.store import RunStore

        store = RunStore()
        fleet = Fleet(store)
        fleet.configure(chips=4)
        fleet.reserve("busy", chips=3)
        ac = AdmissionController(store, fleet=fleet)

        rigid = ac.try_admit(self._entry("rigid", chips=4))
        assert rigid.outcome == WAIT  # the old behavior: park until free

        decision = ac.try_admit(self._entry("el1", chips=4, min_chips=1))
        assert decision.outcome == ADMIT  # the elastic run never parks
        assert decision.reservation["chips"] == 1
        rec = fleet.ledger.get("el1")
        assert rec["requested_chips"] == 4  # full demand on the ledger

    def test_unplaceable_floor_rejects(self, tmp_home):
        from polyaxon_tpu.scheduler.admission import (
            REJECT,
            AdmissionController,
        )
        from polyaxon_tpu.scheduler.fleet import Fleet
        from polyaxon_tpu.store import RunStore

        store = RunStore()
        fleet = Fleet(store)
        fleet.configure(chips=4)
        ac = AdmissionController(store, fleet=fleet)
        decision = ac.try_admit(self._entry("huge", chips=8, min_chips=6))
        assert decision.outcome == REJECT

    def test_consider_expansion_flags_shrunk_run_when_space_frees(
        self, tmp_home
    ):
        from polyaxon_tpu.schemas.lifecycle import V1Statuses
        from polyaxon_tpu.scheduler.admission import (
            ADMIT,
            AdmissionController,
        )
        from polyaxon_tpu.scheduler.fleet import Fleet
        from polyaxon_tpu.store import RunStore

        store = RunStore()
        fleet = Fleet(store)
        fleet.configure(chips=4)
        fleet.reserve("busy", chips=3)
        ac = AdmissionController(store, fleet=fleet)
        store.create_run("el1", "el1", "default", {})
        store.set_status("el1", V1Statuses.COMPILED)
        store.set_status("el1", V1Statuses.QUEUED)
        assert ac.try_admit(
            self._entry("el1", chips=4, min_chips=1)
        ).outcome == ADMIT
        assert ac.consider_expansion() == []  # no space yet: stay shrunk

        fleet.release("busy")
        assert ac.consider_expansion() == ["el1"]
        meta = store.get_status("el1")["meta"]
        assert meta["preempt_requested"] is True
        kinds = [e["kind"] for e in store.read_events("el1")]
        assert "elastic_expand_requested" in kinds


@pytest.mark.scheduler
def test_sim_shrink_then_grow_round_trip(tmp_home):
    """Seeded round trip through the REAL admission stack under SimClock:
    a full-fleet elastic job yields to a higher-priority rigid arrival by
    shrinking (not waiting), then grows back to full size the moment the
    rigid job's chips free — grants [4, 2, 4], chip-second accounting
    exact at every rung, invariants asserted at every event."""
    from polyaxon_tpu.scheduler.sim import FleetSimulator, SimJob

    elastic = SimJob(
        "elastic", duration=8.0, arrival=0.0, chips=4, min_chips=1
    )
    rigid = SimJob("rigid", duration=4.0, arrival=2.0, chips=2, priority=1)
    sim = FleetSimulator(
        [elastic, rigid],
        chips=4,
        invariant_fn=lambda s: s.check_invariants(),
    )
    report = sim.run()
    assert report["succeeded"] == 2
    assert elastic.grants == [4, 2, 4]
    # only the shrunk grant counts as a resize: the grow-back IS the
    # requested size
    assert elastic.resizes == 1
    assert report["elastic_resizes"] == 1
    # never parked: every (re)admission happened the instant it queued
    assert all(w == 0.0 for w in elastic.waits)
    # work accounting is exact across rungs: 2s at full rate + 4s at half
    # rate + 4s at full rate = 8s of full-size work, finishing at t=10
    assert elastic.finished_at == pytest.approx(10.0)
    assert rigid.finished_at == pytest.approx(6.0)


# ---------------------------------------------------- executor layer
def _elastic_train_op(
    name: str,
    *,
    steps: int,
    checkpoint_every: int = 2,
    max_retries: int = 0,
    chips: int | None = None,
    min_chips: int | None = None,
    local_dir: str | None = None,
):
    from polyaxon_tpu.schemas.operation import V1Operation

    train = {
        "steps": steps,
        "logEvery": 1,
        "precision": "float32",
        "checkpointEvery": checkpoint_every,
    }
    if local_dir:
        train["checkpointLocalDir"] = local_dir
    spec = {
        "kind": "operation",
        "name": name,
        "component": {
            "kind": "component",
            "name": "c",
            "termination": {"maxRetries": max_retries},
            "run": {
                "kind": "jaxjob",
                "program": {
                    "model": {
                        "name": "mlp",
                        "config": {
                            "input_dim": 8, "num_classes": 2, "hidden": [4]
                        },
                    },
                    "data": {
                        "name": "synthetic",
                        "batchSize": 8,
                        "config": {"shape": [8], "num_classes": 2},
                    },
                    "optimizer": {"name": "sgd", "learningRate": 0.01},
                    "train": train,
                },
            },
        },
    }
    if chips is not None:
        resources = {"chips": chips}
        if min_chips is not None:
            resources["minChips"] = min_chips
        spec["environment"] = {"resources": resources}
    return V1Operation.model_validate(spec)


def _events(store, uuid, kind):
    return [e for e in store.read_events(uuid) if e["kind"] == kind]


@pytest.mark.chaos
class TestElasticChaos:
    def test_kill_mid_upload_recovers_from_local_tier(self, tmp_home, tmp_path):
        from polyaxon_tpu.compiler import compile_operation
        from polyaxon_tpu.runtime import Executor
        from polyaxon_tpu.schemas.lifecycle import V1Statuses
        from polyaxon_tpu.store import RunStore

        steps, every = 8, 2
        plan = FaultPlan.kill_mid_upload(seed=7, steps=steps,
                                         checkpoint_every=every)
        upload_step = plan.params["upload_step"]
        store = RunStore()
        compiled = compile_operation(
            _elastic_train_op(
                "chaos-upload", steps=steps, checkpoint_every=every,
                max_retries=1, local_dir=str(tmp_path / "fast"),
            )
        )
        with chaos.active(plan):
            status = Executor(store, devices=jax.devices()[:1]).execute(
                compiled
            )
        assert status == V1Statuses.SUCCEEDED
        # the kill surfaced as ONE transient retry and resume lost at most
        # the steps since the boundary the upload was carrying
        retrying = [
            c for c in store.get_status(compiled.run_uuid)["conditions"]
            if c["type"] == "retrying"
        ]
        assert len(retrying) == 1
        resumed = _events(store, compiled.run_uuid, "resumed")
        assert resumed
        assert resumed[0]["step"] >= upload_step
        assert steps - resumed[0]["step"] <= every
        assert store.read_metrics(compiled.run_uuid)[-1]["step"] == steps
        # the durable tier never lists a torn step — no staging residue
        durable = str(store.outputs_dir(compiled.run_uuid) / "checkpoints")
        assert not any(
            n.endswith(".uploading") for n in os.listdir(durable)
        )
        # the fast tier is per-run scoped and took the boundary saves
        local = tmp_path / "fast" / compiled.run_uuid / "checkpoints"
        assert _digit_dirs(str(local))

    def test_durable_tier_outage_degrades_to_local_only(
        self, tmp_home, tmp_path
    ):
        from polyaxon_tpu.compiler import compile_operation
        from polyaxon_tpu.runtime import Executor
        from polyaxon_tpu.schemas.lifecycle import V1Statuses
        from polyaxon_tpu.store import RunStore

        steps, every, fails = 8, 2, 2
        plan = FaultPlan.durable_tier_outage(
            seed=11, steps=steps, checkpoint_every=every, fails=fails
        )
        outage_steps = set(plan.params["outage_steps"])
        failures = get_registry().counter("checkpoint.upload_failures")
        base = failures.value
        store = RunStore()
        compiled = compile_operation(
            _elastic_train_op(
                "chaos-outage", steps=steps, checkpoint_every=every,
                local_dir=str(tmp_path / "fast"),
            )
        )
        with chaos.active(plan):
            status = Executor(store, devices=jax.devices()[:1]).execute(
                compiled
            )
        assert status == V1Statuses.SUCCEEDED
        # the outage was absorbed, not retried and not fatal
        assert failures.value == base + fails
        conds = store.get_status(compiled.run_uuid)["conditions"]
        assert all(c["type"] != "retrying" for c in conds)
        assert store.read_metrics(compiled.run_uuid)[-1]["step"] == steps
        # the refused steps stayed local-only; later boundaries replicated
        durable = str(store.outputs_dir(compiled.run_uuid) / "checkpoints")
        assert _digit_dirs(durable).isdisjoint(outage_steps)
        assert max(_digit_dirs(durable)) == steps
        # async checkpointing kept the step loop moving: the stall
        # histogram observed every boundary
        stall = get_registry().histogram("trainer.checkpoint_stall_ms")
        assert stall.count >= steps // every

    def test_preempt_at_peak_resumes_within_checkpoint_bound(self, tmp_home):
        from polyaxon_tpu.compiler import compile_operation
        from polyaxon_tpu.runtime import Executor
        from polyaxon_tpu.schemas.lifecycle import V1Statuses
        from polyaxon_tpu.store import RunStore

        steps, every = 8, 2
        plan = FaultPlan.preempt_at_peak(seed=5, steps=steps,
                                         checkpoint_every=every)
        peak = plan.params["preempt_step"]
        store = RunStore()
        compiled = compile_operation(
            _elastic_train_op("chaos-peak", steps=steps,
                              checkpoint_every=every, max_retries=0)
        )
        with chaos.active(plan):
            status = Executor(store, devices=jax.devices()[:1]).execute(
                compiled
            )
        assert status == V1Statuses.SUCCEEDED
        # the cooperative preemption flushes a save at the preempt step
        # itself, so even the worst-case notice (one step shy of the next
        # boundary) loses ZERO completed steps — well inside the
        # `<= checkpoint_every` acceptance bound
        resumed = _events(store, compiled.run_uuid, "resumed")
        assert resumed and resumed[0]["step"] == peak
        preempted = _events(store, compiled.run_uuid, "preempted")
        assert preempted and preempted[0]["resume_step"] == peak
        assert peak - plan.params["last_boundary"] <= every
        assert store.read_metrics(compiled.run_uuid)[-1]["step"] == steps


@pytest.mark.chaos
def test_eviction_shrinks_gang_and_resumes_byte_stable(tmp_home, monkeypatch):
    """The acceptance round trip: an elastic 2-chip run is evicted at
    peak, its freed chips are partially stolen (a 1-chip hog appears the
    instant they release), and re-admission grants the 1-chip rung of the
    ladder instead of parking — the trainer rebuilds the mesh at 1 device,
    doubles grad accumulation to hold the global batch, and resumes from a
    checkpoint whose parameters are byte-identical to a non-preempted
    reference run at the same step."""
    from polyaxon_tpu.compiler import compile_operation
    from polyaxon_tpu.runtime import Executor
    from polyaxon_tpu.scheduler.agent import Agent
    from polyaxon_tpu.scheduler.fleet import Fleet
    from polyaxon_tpu.schemas.lifecycle import V1Statuses
    from polyaxon_tpu.store import RunStore

    steps, every, evict_logged_step = 6, 2, 4

    class EvictAtPeak(RunStore):
        """Raise the scheduler's eviction flag when the victim logs the
        step just before a boundary save — peak uncheckpointed work."""

        target: str | None = None

        def log_metrics(self, run_uuid, step, metrics):
            super().log_metrics(run_uuid, step, metrics)
            if run_uuid == self.target and step == evict_logged_step:
                meta = (self.get_status(run_uuid) or {}).get("meta") or {}
                if not meta.get("preempt_restarts"):
                    self.set_meta(run_uuid, preempt_requested=True)

    store = EvictAtPeak()
    Fleet(store).configure(chips=2)
    agent = Agent(store=store)
    op = _elastic_train_op(
        "elastic-victim", steps=steps, checkpoint_every=every,
        max_retries=0, chips=2, min_chips=1,
    )
    uid = agent.submit(op)
    store.target = uid

    # the hog: the moment the evicted run releases its 2 chips, 1 of them
    # is reserved away — the original block can never re-place, so the
    # only way forward is the smaller rung of the ladder
    hogged = []
    real_release = Fleet.release

    def release_and_hog(self, run_uuid):
        rec = real_release(self, run_uuid)
        if run_uuid == uid and not hogged:
            hogged.append(1)
            assert self.reserve("hog", chips=1, project="hog") is not None
        return rec

    monkeypatch.setattr(Fleet, "release", release_and_hog)

    # one drain: claim(2 chips) → run → evict at peak → hog steals a chip
    # → re-admit(1 chip) → resume → done. If the elastic run ever parked
    # in WAIT the drain would leave it QUEUED.
    resizes = get_registry().counter("trainer.elastic_resizes")
    shrinks = get_registry().counter("scheduler.elastic_shrinks")
    base_resizes, base_shrinks = resizes.value, shrinks.value
    agent.drain()

    status = store.get_status(uid)
    assert status["status"] == V1Statuses.SUCCEEDED
    meta = status["meta"]
    assert meta["preempt_restarts"] == 1
    assert meta["granted_chips"] == 1 and meta["requested_chips"] == 2
    assert shrinks.value == base_shrinks + 1
    assert resizes.value == base_resizes + 1

    # the first attempt ran at the full gang; the eviction recorded it
    # (the trainer also emits its own un-flagged preempted event)
    evictions = [
        e for e in _events(store, uid, "preempted") if e.get("scheduler")
    ]
    assert len(evictions) == 1
    assert evictions[0]["granted_chips"] == 2
    shrink_ev = _events(store, uid, "elastic_shrink")
    assert shrink_ev
    assert shrink_ev[-1]["granted"] == 1 and shrink_ev[-1]["requested"] == 2
    resize_ev = _events(store, uid, "elastic_resize")
    assert resize_ev
    assert resize_ev[0]["granted"] == 1 and resize_ev[0]["requested"] == 2
    # global batch held constant: grad accumulation doubled for the
    # half-width mesh
    assert resize_ev[0]["grad_accum"] == 2
    # the flag logged at step 4 is observed at the head of step 5, where
    # the cooperative exit flushes a step-5 save: zero completed steps lost
    resumed = _events(store, uid, "resumed")
    assert resumed and resumed[0]["step"] == evict_logged_step + 1
    assert store.read_metrics(uid)[-1]["step"] == steps
    # terminal transition released the shrunk reservation; only the hog
    # remains
    assert Fleet(store).reserved_chips() == 1

    # ---- byte-stability: a never-preempted reference run's checkpoint at
    # the restore step must match the elastic run's bit for bit
    ref = compile_operation(
        _elastic_train_op("reference", steps=steps, checkpoint_every=every)
    )
    assert Executor(store, devices=jax.devices()).execute(ref) == (
        V1Statuses.SUCCEEDED
    )
    el_dir = str(store.outputs_dir(uid) / "checkpoints")
    ref_dir = str(store.outputs_dir(ref.run_uuid) / "checkpoints")
    el_tree = ck._manager(el_dir).restore(evict_logged_step)
    ref_tree = ck._manager(ref_dir).restore(evict_logged_step)
    el_leaves = jax.tree.leaves(el_tree)
    ref_leaves = jax.tree.leaves(ref_tree)
    assert len(el_leaves) == len(ref_leaves) > 0
    for a, b in zip(el_leaves, ref_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the losses logged up to the eviction are byte-equal too
    el_metrics = {
        m["step"]: m["loss"] for m in store.read_metrics(uid)
    }
    ref_metrics = {
        m["step"]: m["loss"] for m in store.read_metrics(ref.run_uuid)
    }
    for s in range(1, evict_logged_step + 1):
        assert el_metrics[s] == ref_metrics[s]


def test_grad_accum_auto_adjusts_to_mesh_width(tmp_home):
    """The divisibility contract is an automatic adjustment, not an
    error: a microbatch count the requested accumulation doesn't divide
    picks the next feasible value and announces it."""
    from polyaxon_tpu.compiler import compile_operation
    from polyaxon_tpu.runtime import Executor
    from polyaxon_tpu.schemas.lifecycle import V1Statuses
    from polyaxon_tpu.store import RunStore

    from polyaxon_tpu.schemas.operation import V1Operation

    op = _elastic_train_op("accum-adjust", steps=2)
    program = op.component.run.program
    train = program.train.model_copy(update={"grad_accum": 3})
    op = op.model_copy(
        update={
            "component": op.component.model_copy(
                update={
                    "run": op.component.run.model_copy(
                        update={
                            "program": program.model_copy(
                                update={"train": train}
                            )
                        }
                    )
                }
            )
        }
    )
    assert isinstance(op, V1Operation)
    store = RunStore()
    compiled = compile_operation(op)
    status = Executor(store, devices=jax.devices()[:1]).execute(compiled)
    assert status == V1Statuses.SUCCEEDED
    adjusted = _events(store, compiled.run_uuid, "grad_accum_adjusted")
    # batch 8 on 1 device → 8 microbatches; 3 ∤ 8 → next divisor is 4
    assert adjusted and adjusted[0]["requested"] == 3
    assert adjusted[0]["effective"] == 4


def test_min_chips_schema_validation():
    import pydantic

    from polyaxon_tpu.schemas.environment import V1Resources

    ok = V1Resources.model_validate({"chips": 4, "minChips": 2})
    assert ok.min_chips == 2
    with pytest.raises(pydantic.ValidationError):
        V1Resources.model_validate({"chips": 4, "minChips": 0})
    with pytest.raises(pydantic.ValidationError):
        V1Resources.model_validate({"chips": 4, "minChips": 8})
