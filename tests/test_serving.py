"""Model serving: a checkpointed LM run becomes an HTTP generate endpoint
(train → checkpoint → ModelServer.from_run → POST /generate over the wire)."""

import json
import urllib.error
import urllib.request

import pytest
import yaml

from polyaxon_tpu.compiler import compile_operation
from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.runtime import Executor
from polyaxon_tpu.serving import ModelServer
from polyaxon_tpu.serving.server import ServingError
from polyaxon_tpu.store import RunStore


def test_from_run_unknown_ref_fast(tmp_home):
    with pytest.raises(KeyError):
        ModelServer.from_run("nope", store=RunStore())

SPEC = {
    "version": 1.1,
    "kind": "operation",
    "name": "lm-for-serving",
    "component": {
        "kind": "component",
        "name": "lm-for-serving",
        "run": {
            "kind": "jaxjob",
            "program": {
                "model": {
                    "name": "transformer_lm",
                    "config": {
                        "preset": "tiny", "seq_len": 64, "n_layers": 2,
                        "dim": 64, "vocab_size": 256,
                    },
                },
                "data": {
                    "name": "synthetic_text", "batchSize": 8,
                    "config": {"seq_len": 64, "vocab_size": 256},
                },
                "optimizer": {"name": "adamw", "learningRate": 0.001},
                "train": {
                    "steps": 4, "logEvery": 4, "precision": "float32",
                    "checkpointEvery": 4,
                },
            },
        },
    },
}


def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _train_run(tmp_path):
    import jax

    p = tmp_path / "lm.yaml"
    p.write_text(yaml.safe_dump(SPEC))
    store = RunStore()
    compiled = compile_operation(read_polyaxonfile(str(p)))
    status = Executor(store, devices=jax.devices()[:1]).execute(compiled)
    assert status == "succeeded"
    return store, compiled.run_uuid


@pytest.mark.slow
def test_serve_checkpointed_run_end_to_end(tmp_home, tmp_path):
    from polyaxon_tpu.runtime.checkpoint import close_all

    store, uuid = _train_run(tmp_path)
    close_all()  # flush the async save before another process-alike reads it
    server = ModelServer.from_run(uuid[:8], store=store)
    assert server.step == 4
    port = server.start(port=0)
    try:
        health = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30
            ).read()
        )
        assert health == {"status": "ok", "model": "transformer_lm", "step": 4}
        out = _post(
            f"http://127.0.0.1:{port}/generate",
            {"tokens": [[1, 2, 3]], "maxNewTokens": 5, "temperature": 0.5,
             "topK": 20, "seed": 1},
        )
        assert len(out["tokens"]) == 1 and len(out["tokens"][0]) == 8
        assert all(0 <= t < 256 for t in out["tokens"][0])
        # same-shape request reuses the jitted decode program (new seed is
        # a runtime arg, not a recompile)
        out2 = _post(
            f"http://127.0.0.1:{port}/generate",
            {"tokens": [[1, 2, 3]], "maxNewTokens": 5, "temperature": 0.5,
             "topK": 20, "seed": 2},
        )
        assert len(server._compiled) == 1
        assert out2["tokens"] != out["tokens"]  # seed actually varies output
        # bad requests surface as 400 with a message, not a 500
        for bad in (
            {"tokens": []},
            {"tokens": [[]]},  # empty row
            {"tokens": [[1, 2], [3]]},  # ragged
            {"tokens": [[1, 2, 3]], "maxNewTokens": 100},  # > seq_len
            {"tokens": [[999999]]},  # out of vocab
            {"tokens": [[1, 2, 3]], "numBeams": 4096},  # beam DoS cap
        ):
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(f"http://127.0.0.1:{port}/generate", bad)
            assert err.value.code == 400, bad
    finally:
        server.stop()


@pytest.mark.slow
def test_beam_route_over_http(tmp_home, tmp_path):
    from polyaxon_tpu.runtime.checkpoint import close_all

    store, uuid = _train_run(tmp_path)
    close_all()
    server = ModelServer.from_run(uuid, store=store)
    port = server.start(port=0)
    try:
        beam = _post(
            f"http://127.0.0.1:{port}/generate",
            {"tokens": [[1, 2, 3]], "maxNewTokens": 5, "numBeams": 3},
        )
        assert len(beam["tokens"][0]) == 8
    finally:
        server.stop()


@pytest.mark.slow
def test_from_run_errors(tmp_home, tmp_path):
    store = RunStore()
    with pytest.raises(KeyError):
        ModelServer.from_run("nope", store=store)
    # a run without checkpoints is rejected with guidance
    spec = {k: v for k, v in SPEC.items()}
    spec["component"] = json.loads(json.dumps(SPEC["component"]))
    del spec["component"]["run"]["program"]["train"]["checkpointEvery"]
    p = tmp_path / "nock.yaml"
    p.write_text(yaml.safe_dump(spec))
    import jax

    compiled = compile_operation(read_polyaxonfile(str(p)))
    assert Executor(store, devices=jax.devices()[:1]).execute(compiled) == "succeeded"
    with pytest.raises(ServingError, match="checkpoint"):
        ModelServer.from_run(compiled.run_uuid, store=store)


@pytest.mark.slow
def test_mesh_sharded_serving_over_http(tmp_home, tmp_path):
    """--mesh serving: params restored sharded over an 8-device mesh serve
    the same greedy tokens as single-device serving."""
    from polyaxon_tpu.runtime.checkpoint import close_all

    store, uuid = _train_run(tmp_path)
    close_all()
    body = {"tokens": [[1, 2, 3]], "maxNewTokens": 5}

    single = ModelServer.from_run(uuid, store=store)
    port = single.start(port=0)
    try:
        ref = _post(f"http://127.0.0.1:{port}/generate", body)
    finally:
        single.stop()

    close_all()
    sharded = ModelServer.from_run(
        uuid, store=store, mesh_axes={"data": 2, "model": 2, "fsdp": 2}
    )
    port = sharded.start(port=0)
    try:
        out = _post(f"http://127.0.0.1:{port}/generate", body)
    finally:
        sharded.stop()
    assert out["tokens"] == ref["tokens"]


@pytest.mark.slow
def test_from_run_needs_no_data_pipeline(tmp_home, tmp_path, monkeypatch):
    """Serving restores params-only from the stored spec: no Trainer, no
    data pipeline (the training corpus need not exist on the serving host),
    no optimizer moments in memory."""
    from polyaxon_tpu.runtime.checkpoint import close_all

    store, uuid = _train_run(tmp_path)
    close_all()

    def boom(*a, **k):
        raise AssertionError("serving must not build the data pipeline")

    monkeypatch.setattr("polyaxon_tpu.runtime.trainer.build_data", boom)
    monkeypatch.setattr("polyaxon_tpu.data.build_data", boom)
    server = ModelServer.from_run(uuid, store=store)
    assert server.step == 4
    out = server.generate({"tokens": [[1, 2, 3]], "maxNewTokens": 2})
    assert len(out["tokens"][0]) == 5
