"""Unified telemetry: registry, spans, /metricsz, and the trainer's
span-accounting invariant.

The contract under test: ONE metrics pipeline per process. /statsz and
/metricsz render from the same Histogram/Counter objects (they cannot
disagree), the trainer's per-step data_wait + compute spans cover the
step body (they sum to the step walltime), and no module outside
polyaxon_tpu/telemetry hand-rolls a perf_counter timing loop."""

import json
import subprocess
import sys
import threading
import urllib.request
from pathlib import Path

import pytest

from polyaxon_tpu.telemetry import (
    MetricsRegistry,
    SpanTracer,
    quantile,
    summarize,
    train_step_flops,
)

pytestmark = pytest.mark.telemetry

REPO_ROOT = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------- registry
def test_counter_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("runs.retries", help="x")
    c.inc()
    c.inc(2.0)
    assert c.value == 3.0
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("train.loss")
    assert g.value is None  # unset gauge reports None, not 0
    g.set(0.25)
    assert g.value == 0.25
    # same name → same object; different kind → error, not a split series
    assert reg.counter("runs.retries") is c
    with pytest.raises(ValueError):
        reg.gauge("runs.retries")


def test_registry_concurrent_increments_exact():
    """N threads hammering one counter + one histogram lose no updates."""
    reg = MetricsRegistry()
    c = reg.counter("hits")
    h = reg.histogram("lat", buckets=(0.5, 1.0))
    threads_n, iters = 8, 500

    def work(tid):
        for i in range(iters):
            c.inc()
            h.observe((tid + i) % 2)  # alternates buckets

    threads = [
        threading.Thread(target=work, args=(t,)) for t in range(threads_n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == threads_n * iters
    assert h.count == threads_n * iters
    assert h.sum == sum((t + i) % 2 for t in range(threads_n) for i in range(iters))


def test_histogram_bucket_boundaries():
    """Values AT an upper bound land in that bucket (le semantics); above
    the last bound they land in +Inf only."""
    reg = MetricsRegistry()
    h = reg.histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 2.0, 4.0, 9.0):
        h.observe(v)
    text = reg.render_prometheus()
    assert 'h_bucket{le="1"} 2' in text  # 0.5, 1.0
    assert 'h_bucket{le="2"} 4' in text  # + 1.5, 2.0  (cumulative)
    assert 'h_bucket{le="4"} 5' in text  # + 4.0
    assert 'h_bucket{le="+Inf"} 6' in text
    assert "h_sum 18" in text
    assert "h_count 6" in text
    # mismatched re-registration is a programming error
    with pytest.raises(ValueError):
        reg.histogram("h", buckets=(1.0, 2.0))


def test_histogram_percentiles_clamped_and_sane():
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for _ in range(100):
        h.observe(0.05)
    s = h.summary()
    assert s["count"] == 100 and s["min"] == s["max"] == 0.05
    # all mass in one bucket: estimates must clamp to observed range
    for q in ("p50", "p95", "p99"):
        assert s[q] == pytest.approx(0.05)
    assert reg.histogram("empty").percentile(0.5) is None


def test_prometheus_rendering_conventions():
    reg = MetricsRegistry()
    reg.counter("serving.requests", help="Total requests").inc(3)
    reg.gauge("train.loss").set(0.5)
    reg.gauge("never.set")  # must NOT render a sample line
    text = reg.render_prometheus()
    assert "# HELP serving_requests_total Total requests" in text
    assert "# TYPE serving_requests_total counter" in text
    assert "serving_requests_total 3" in text  # dots sanitized, _total suffix
    assert "train_loss 0.5" in text
    assert "never_set" not in text.replace("# TYPE never_set gauge", "")
    assert text.endswith("\n")


def test_snapshot_matches_prometheus_view():
    """snapshot() (the /statsz side) and render_prometheus() (the
    /metricsz side) read the same objects."""
    reg = MetricsRegistry()
    reg.counter("a").inc(7)
    h = reg.histogram("b", buckets=(1.0,))
    h.observe(0.5)
    h.observe(2.0)
    snap = reg.snapshot()
    text = reg.render_prometheus()
    assert snap["a"] == 7 and "a_total 7" in text
    assert snap["b"]["count"] == 2 and "b_count 2" in text
    assert snap["b"]["sum"] == 2.5 and "b_sum 2.5" in text


# ------------------------------------------------------------ exact stats
def test_exact_quantile_type7():
    vals = [1.0, 2.0, 3.0, 4.0]
    assert quantile(vals, 0.0) == 1.0
    assert quantile(vals, 1.0) == 4.0
    assert quantile(vals, 0.5) == 2.5  # numpy-default interpolation
    assert quantile([], 0.5) is None
    with pytest.raises(ValueError):
        quantile(vals, 1.5)
    s = summarize(vals)
    assert s["count"] == 4 and s["mean"] == 2.5 and s["p50"] == 2.5


def test_train_step_flops_formula():
    assert train_step_flops(
        n_params=10, n_layers=2, dim=4, seq_len=8, tokens=3
    ) == (6 * 10 + 12 * 2 * 4 * 8) * 3


# ----------------------------------------------------------------- spans
def test_span_nesting_and_jsonl_export(tmp_path):
    path = tmp_path / "t" / "spans.jsonl"
    tr = SpanTracer(path=str(path))
    with tr.span("step", step=3) as outer:
        with tr.span("data_wait"):
            pass
        with tr.span("compute") as inner:
            inner.set(tokens=128)
        tr.event("checkpoint", step=3)
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    by_name = {r["name"]: r for r in recs}
    assert [r["name"] for r in recs] == [
        "data_wait", "compute", "checkpoint", "step",  # completion order
    ]
    assert by_name["data_wait"]["parent_id"] == outer.span_id
    assert by_name["compute"]["parent_id"] == outer.span_id
    assert by_name["compute"]["attrs"] == {"tokens": 128}
    assert by_name["checkpoint"]["kind"] == "event"
    assert by_name["checkpoint"]["parent_id"] == outer.span_id
    assert by_name["step"]["parent_id"] is None
    assert by_name["step"]["attrs"] == {"step": 3}
    assert all(r["dur_s"] >= 0 for r in recs)
    assert tr.recent(2) == recs[-2:]  # memory ring mirrors the file


def test_span_nesting_is_per_thread():
    tr = SpanTracer()
    parents = {}

    def work(name):
        with tr.span(name) as s:
            parents[name] = s.parent_id

    with tr.span("main"):
        t = threading.Thread(target=work, args=("other-thread",))
        t.start()
        t.join()
        work("same-thread")
    assert parents["other-thread"] is None  # no cross-thread adoption
    assert parents["same-thread"] is not None


def test_tracer_export_failure_is_advisory(tmp_path):
    blocked = tmp_path / "file"
    blocked.write_text("")  # a FILE where a parent dir is needed
    tr = SpanTracer(path=str(blocked / "spans.jsonl"))
    with tr.span("s"):
        pass  # must not raise
    assert tr._broken and tr.recent()  # ring still records


# ------------------------------------------------- trainer span accounting
def _mlp_program(observability=None, **train_overrides):
    from polyaxon_tpu.schemas.run_kinds import V1Program

    train = {"steps": 8, "logEvery": 4, "precision": "float32", "seed": 0}
    train.update(train_overrides)
    spec = {
        "model": {
            "name": "mlp",
            "config": {"hidden": [32], "input_dim": 16, "num_classes": 4},
        },
        "data": {
            "name": "synthetic",
            "batchSize": 32,
            "config": {"shape": [16], "num_classes": 4},
        },
        "optimizer": {"name": "adamw", "learningRate": 0.01},
        "train": train,
    }
    if observability is not None:
        spec["observability"] = observability
    return V1Program.model_validate(spec)


def test_trainer_spans_account_for_step_walltime(tmp_path):
    """The acceptance invariant: a CPU run writes spans.jsonl into the
    artifacts dir, and per step the data_wait + compute child spans sum
    to the step span's walltime (within 10% in aggregate — the only
    uncovered work in the step body is a preemption-flag check)."""
    import jax

    from polyaxon_tpu.runtime.trainer import Trainer

    t = Trainer(
        _mlp_program(),
        mesh_axes={"data": 1},
        devices=jax.devices()[:1],
        artifacts_dir=str(tmp_path),
    )
    result = t.run()
    assert result.history[-1]["loss"] < result.history[0]["loss"]

    span_file = tmp_path / "telemetry" / "spans.jsonl"
    assert span_file.exists()
    recs = [json.loads(line) for line in span_file.read_text().splitlines()]
    steps = {r["span_id"]: r for r in recs if r["name"] == "step"}
    assert len(steps) == 8
    covered = {sid: 0.0 for sid in steps}
    for r in recs:
        if r["name"] in ("data_wait", "compute"):
            covered[r["parent_id"]] += r["dur_s"]
    total_step = sum(r["dur_s"] for r in steps.values())
    total_children = sum(covered.values())
    assert total_children <= total_step + 1e-6
    assert total_children >= 0.9 * total_step, (
        f"children cover {total_children:.6f}s of {total_step:.6f}s"
    )
    # per-step: children never exceed the parent, and cover it up to a
    # small absolute slack (sub-ms steps make pure ratios noisy)
    for sid, rec in steps.items():
        assert covered[sid] <= rec["dur_s"] + 1e-6
        assert covered[sid] >= 0.9 * rec["dur_s"] - 2e-3

    # the same run fed the registry: step histogram saw every step and
    # wait+compute histogram sums bracket the step histogram sum
    snap = t.telemetry.snapshot()
    assert snap["trainer.step_seconds"]["count"] == 8
    assert snap["trainer.steps"] == 8
    assert (
        snap["trainer.data_wait_seconds"]["sum"]
        + snap["trainer.compute_seconds"]["sum"]
        <= snap["trainer.step_seconds"]["sum"] + 1e-6
    )
    # derived throughput gauges landed in history at log points
    assert "data_wait_frac" in result.history[0]
    assert 0.0 <= result.history[0]["data_wait_frac"] <= 1.0


def test_trainer_trace_opt_out(tmp_path):
    """observability.trace: false suppresses the spans file (the spans
    still exist in memory for /statsz-style surfaces)."""
    import jax

    from polyaxon_tpu.runtime.trainer import Trainer

    t = Trainer(
        _mlp_program(observability={"trace": False}, steps=2, logEvery=1),
        mesh_axes={"data": 1},
        devices=jax.devices()[:1],
        artifacts_dir=str(tmp_path),
    )
    t.run()
    assert not (tmp_path / "telemetry" / "spans.jsonl").exists()
    assert t.tracer.recent()  # memory ring still populated


# -------------------------------------------- serving /statsz ↔ /metricsz
def _tiny_server():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    cfg = {
        "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
        "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
    }
    b = build_model("transformer_lm", cfg)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((2, 64), jnp.int32),
        train=False,
    )["params"]
    return ModelServer(
        b.module, params, config=ServingConfig(max_batch=4, max_wait_ms=30.0)
    )


def _parse_prom(text: str) -> dict:
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        key, val = line.rsplit(" ", 1)
        out[key] = float(val)
    return out


@pytest.mark.serving
def test_statsz_and_metricsz_report_the_same_pipeline(tmp_home):
    """Drive real requests over HTTP, then check the JSON and Prometheus
    surfaces agree — both render from the same registry objects."""
    server = _tiny_server()
    port = server.start(port=0)
    try:
        def post(i):
            body = {
                "tokens": [[(i + j) % 128 for j in range(4)]],
                "maxNewTokens": 3, "temperature": 0.5, "topK": 10, "seed": i,
            }
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=300) as r:
                json.loads(r.read())

        threads = [
            threading.Thread(target=post, args=(i,), daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)

        stats = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statsz", timeout=30
            ).read()
        )
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metricsz", timeout=30
        ) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            prom_text = r.read().decode()
        prom = _parse_prom(prom_text)

        # required series exist (the canary scrapes these names)
        assert 'serving_request_seconds_bucket{le="+Inf"}' in prom
        assert "serving_compile_cache_misses_total" in prom
        assert "serving_compile_cache_hits_total" in prom

        # cross-surface agreement: counters
        assert prom["serving_requests_total"] == stats["requests"] == 4
        assert prom["serving_compile_cache_hits_total"] == stats["compile_cache"]["hits"]
        assert prom["serving_compile_cache_misses_total"] == stats["compile_cache"]["misses"]
        assert stats["compile_cache"]["misses"] == stats["compile_count"] >= 1
        # cross-surface agreement: the latency histogram
        assert prom["serving_request_seconds_count"] == 4
        assert prom['serving_request_seconds_bucket{le="+Inf"}'] == 4
        lat = stats["latency_ms"]
        assert lat["p50"] is not None and lat["p50"] <= lat["p95"] <= lat["p99"]
        assert lat["p99"] * 1e-3 <= prom["serving_request_seconds_sum"] + 1e-9
        # queue-wait and occupancy measured on the batched path
        assert stats["queue_wait_ms"]["p50"] is not None
        assert prom["serving_batches_total"] >= 1
        assert prom['serving_batch_occupancy_bucket{le="+Inf"}'] >= 1
    finally:
        server.stop()


# ------------------------------------------------------- cross-cutting
def test_store_transitions_and_retries_hit_global_registry(tmp_home):
    from polyaxon_tpu.retry import RetryPolicy, TransientError
    from polyaxon_tpu.store.local import RunStore
    from polyaxon_tpu.telemetry import get_registry

    reg = get_registry()
    base_t = reg.counter("runs.transitions").value
    base_r = reg.counter("retry.attempts").value

    store = RunStore()
    store.create_run("feedbeef0001", "t", "default", {"kind": "test"})
    for st in ("compiled", "scheduled", "running", "succeeded"):
        store.set_status("feedbeef0001", st)
    assert reg.counter("runs.transitions").value >= base_t + 4
    assert reg.counter("runs.transitions.succeeded").value >= 1

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientError("blip")
        return "ok"

    policy = RetryPolicy(max_retries=5)  # backoff=0 → immediate retries
    assert policy.call(flaky) == "ok"
    assert reg.counter("retry.attempts").value == base_r + 2


def test_streams_metricsz_route(tmp_home):
    from polyaxon_tpu.store.local import RunStore
    from polyaxon_tpu.streams import BackgroundServer

    store = RunStore()
    store.create_run("feedbeef0002", "t", "default", {"kind": "test"})
    for st in ("compiled", "scheduled", "running"):
        store.set_status("feedbeef0002", st)
    with BackgroundServer(store) as srv:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metricsz", timeout=30
        ) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
    assert "runs_transitions_total" in text


# ------------------------------------------------------------------ lint
def test_no_raw_perf_counter_outside_telemetry():
    """polyaxon_tpu.telemetry.now() is the one metrics clock; any other
    module timing with perf_counter is growing a second pipeline."""
    res = subprocess.run(
        [sys.executable, str(REPO_ROOT / "scripts" / "lint_telemetry.py")],
        capture_output=True,
        text=True,
    )
    assert res.returncode == 0, res.stderr


# ---------------------------------------------------------------- schema
def test_observability_schema():
    from polyaxon_tpu.schemas.run_kinds import V1ObservabilitySpec

    spec = V1ObservabilitySpec.model_validate(
        {"sampleInterval": 2.5, "histogramBuckets": [0.01, 0.1, 1.0]}
    )
    assert spec.sample_interval == 2.5 and spec.trace is True
    # templated value survives validation (resolved downstream)
    V1ObservabilitySpec.model_validate({"sampleInterval": "{{ interval }}"})
    with pytest.raises(Exception):
        V1ObservabilitySpec.model_validate({"sampleInterval": -1})
    with pytest.raises(Exception):
        V1ObservabilitySpec.model_validate({"histogramBuckets": [1.0, 1.0]})
    with pytest.raises(Exception):
        V1ObservabilitySpec.model_validate({"histogramBuckets": [2.0, 1.0]})


def test_stats_cli_renders_run(tmp_home, tmp_path):
    """`polyaxon stats <run>` prints status, latest metrics, and events."""
    from click.testing import CliRunner

    from polyaxon_tpu.cli.main import cli
    from polyaxon_tpu.store.local import RunStore

    store = RunStore()
    uuid = "feedbeef0003"
    store.create_run(uuid, "t", "default", {"kind": "test"})
    for st in ("compiled", "scheduled", "running"):
        store.set_status(uuid, st)
    store.log_metrics(uuid, 5, {"loss": 0.5, "tokens_per_sec": 1234.0})
    store.log_event(uuid, "artifact", {"kind": "profile", "path": "profile"})
    out_dir = Path(store.outputs_dir(uuid)) / "telemetry"
    out_dir.mkdir(parents=True, exist_ok=True)
    tr = SpanTracer(path=str(out_dir / "spans.jsonl"))
    with tr.span("step", step=5):
        with tr.span("compute"):
            pass
    store.set_status(uuid, "succeeded")

    res = CliRunner().invoke(cli, ["stats", uuid])
    assert res.exit_code == 0, res.output
    assert "succeeded" in res.output
    assert "tokens_per_sec" in res.output and "1234" in res.output
    assert "step" in res.output and "compute" in res.output
    assert "profile" in res.output

    res = CliRunner().invoke(cli, ["stats", "nope"])
    assert res.exit_code != 0
