"""Flash-attention kernel and ring-attention correctness vs the XLA
reference implementation, forward and backward (pallas kernels run
interpreted on the CPU test mesh; the same code compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polyaxon_tpu.ops.attention import dot_product_attention
from polyaxon_tpu.ops.flash_attention import flash_attention
from polyaxon_tpu.parallel.mesh import build_mesh
from polyaxon_tpu.parallel.ring import ring_attention, set_current_mesh


def _qkv(B=2, S=128, H=4, D=32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, S, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla_forward(causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal, backend="xla")
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_kv=64)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla_backward(causal):
    q, k, v = _qkv(S=64)

    def loss_flash(q, k, v):
        return flash_attention(
            q, k, v, causal=causal, block_q=32, block_kv=32
        ).sum()

    def loss_ref(q, k, v):
        return dot_product_attention(q, k, v, causal=causal, backend="xla").sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5, err_msg=name)


def test_flash_rejects_indivisible_seq():
    q, k, v = _qkv(S=100)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_kv=64)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_xla(causal):
    """Ring attention over a real context axis == single-device attention."""
    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(S=64)
        ref = dot_product_attention(q, k, v, causal=causal, backend="xla")
        out = ring_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


@pytest.mark.slow
def test_ring_backward_matches_xla():
    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(S=64)
        g1 = jax.grad(lambda q: ring_attention(q, k, v).sum())(q)
        g2 = jax.grad(
            lambda q: dot_product_attention(
                q, k, v, causal=True, backend="xla"
            ).sum()
        )(q)
        np.testing.assert_allclose(g1, g2, atol=5e-5, rtol=5e-5)
    finally:
        set_current_mesh(None)


def test_ring_falls_back_without_context_axis():
    set_current_mesh(None)
    q, k, v = _qkv(S=64)
    out = ring_attention(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True, backend="xla")
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_trainer_ring_attention_end_to_end():
    """Full train step with context parallelism: mesh {data:2, context:4},
    transformer with attention=ring — loss finite and sequence sharded."""
    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    prog = V1Program(
        model=V1ModelSpec(
            name="transformer_lm",
            config={"preset": "tiny", "seq_len": 128, "attention": "ring"},
        ),
        data=V1DataSpec(
            name="synthetic_text",
            batch_size=4,
            config={"seq_len": 128, "vocab_size": 4096},
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
        train=V1TrainSpec(steps=2, log_every=1, precision="float32"),
    )
    trainer = Trainer(prog, mesh_axes={"data": 2, "context": 4})
    result = trainer.run()
    assert np.isfinite(result.history[-1]["loss"])


# --------------------------------------------------------------- ulysses
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_xla(causal):
    """All-to-all sequence parallelism == single-device attention."""
    from polyaxon_tpu.parallel.ulysses import ulysses_attention

    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(S=64)  # H=8 divisible by context=4
        ref = dot_product_attention(q, k, v, causal=causal, backend="xla")
        out = ulysses_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


def test_ulysses_rejects_indivisible_heads():
    from polyaxon_tpu.parallel.ulysses import ulysses_attention

    mesh = build_mesh({"context": 8})  # H=8 heads... use S small
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(S=64)
        # H=8, context=8: divisible — force the error with a model axis? use
        # a 3-head tensor instead
        import jax.numpy as jnp

        q3, k3, v3 = (x[:, :, :6] for x in (q, k, v))  # 6 heads vs ctx 8
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q3, k3, v3)
    finally:
        set_current_mesh(None)


def test_ulysses_falls_back_without_context_axis():
    from polyaxon_tpu.parallel.ulysses import ulysses_attention

    set_current_mesh(None)
    q, k, v = _qkv(S=32)
    ref = dot_product_attention(q, k, v, causal=True, backend="flash")
    np.testing.assert_allclose(ulysses_attention(q, k, v), ref, atol=1e-6)


@pytest.mark.slow
def test_trainer_ulysses_attention_end_to_end(tmp_home):
    """Full train step with attention=ulysses on a context mesh."""
    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    program = V1Program(
        model=V1ModelSpec(
            name="transformer_lm",
            config={"preset": "tiny", "seq_len": 64, "attention": "ulysses",
                    "n_heads": 8, "n_kv_heads": 8},
        ),
        data=V1DataSpec(
            name="synthetic_text",
            batch_size=8,
            config={"seq_len": 64, "vocab_size": 4096},
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
        train=V1TrainSpec(steps=3, log_every=3, precision="float32"),
    )
    result = Trainer(program, mesh_axes={"context": 2, "data": 4}).run()
    assert result.history[-1]["loss"] == result.history[-1]["loss"]


def test_auto_backend_resolution():
    """`auto` picks flash only on a SINGLE TPU chip with long,
    block-aligned shapes — any multi-device environment (this suite's
    8-CPU virtual slice included) stays on the partitionable einsum."""
    import jax

    from polyaxon_tpu.ops.attention import resolve_auto_backend

    if jax.default_backend() == "tpu" and len(jax.devices()) == 1:
        # pragma: no cover — chip-only branch
        assert resolve_auto_backend(4096, 512) == "flash"
        assert resolve_auto_backend(1024, 512) == "xla"  # short seq
        assert resolve_auto_backend(2496, 192) == "xla"  # % block_q fails
    else:
        assert resolve_auto_backend(4096, 512) == "xla"
