"""Flash-attention kernel and ring-attention correctness vs the XLA
reference implementation, forward and backward (pallas kernels run
interpreted on the CPU test mesh; the same code compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from polyaxon_tpu.ops.attention import dot_product_attention
from polyaxon_tpu.ops.flash_attention import flash_attention
from polyaxon_tpu.parallel.mesh import build_mesh
from polyaxon_tpu.parallel.ring import ring_attention, set_current_mesh


def _qkv(B=2, S=128, H=4, D=32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, S, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla_forward(causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal, backend="xla")
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_kv=64)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla_backward(causal):
    q, k, v = _qkv(S=64)

    def loss_flash(q, k, v):
        return flash_attention(
            q, k, v, causal=causal, block_q=32, block_kv=32
        ).sum()

    def loss_ref(q, k, v):
        return dot_product_attention(q, k, v, causal=causal, backend="xla").sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5, err_msg=name)


def test_flash_rejects_indivisible_seq():
    q, k, v = _qkv(S=100)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_kv=64)


@pytest.mark.parametrize(
    "causal", [True, pytest.param(False, marks=pytest.mark.slow)]
)
def test_ring_matches_xla(causal):
    """Ring attention over a real context axis == single-device attention."""
    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(S=64)
        ref = dot_product_attention(q, k, v, causal=causal, backend="xla")
        out = ring_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


@pytest.mark.slow
def test_ring_backward_matches_xla():
    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(S=64)
        g1 = jax.grad(lambda q: ring_attention(q, k, v).sum())(q)
        g2 = jax.grad(
            lambda q: dot_product_attention(
                q, k, v, causal=True, backend="xla"
            ).sum()
        )(q)
        np.testing.assert_allclose(g1, g2, atol=5e-5, rtol=5e-5)
    finally:
        set_current_mesh(None)


@pytest.mark.slow
def test_ring_degrades_indivisible_batch():
    """B=1 (eval/decode) on a data×context mesh: the batch axis degrades to
    replication instead of a shard_map divisibility error."""
    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(B=1, S=64)
        ref = dot_product_attention(q, k, v, causal=True, backend="xla")
        out = ring_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


def test_ring_falls_back_to_xla_on_indivisible_seq():
    """S not divisible by the context degree: einsum fallback, same math."""
    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(S=66)
        ref = dot_product_attention(q, k, v, causal=True, backend="xla")
        out = ring_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


def test_ring_falls_back_without_context_axis():
    set_current_mesh(None)
    q, k, v = _qkv(S=64)
    out = ring_attention(q, k, v)
    ref = dot_product_attention(q, k, v, causal=True, backend="xla")
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_trainer_ring_attention_end_to_end():
    """Full train step with context parallelism: mesh {data:2, context:4},
    transformer with attention=ring — loss finite and sequence sharded."""
    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    prog = V1Program(
        model=V1ModelSpec(
            name="transformer_lm",
            config={"preset": "tiny", "seq_len": 128, "attention": "ring"},
        ),
        data=V1DataSpec(
            name="synthetic_text",
            batch_size=4,
            config={"seq_len": 128, "vocab_size": 4096},
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
        train=V1TrainSpec(steps=2, log_every=1, precision="float32"),
    )
    trainer = Trainer(prog, mesh_axes={"data": 2, "context": 4})
    result = trainer.run()
    assert np.isfinite(result.history[-1]["loss"])


# --------------------------------------------------------------- ulysses
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_xla(causal):
    """All-to-all sequence parallelism == single-device attention."""
    from polyaxon_tpu.parallel.ulysses import ulysses_attention

    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(S=64)  # H=8 divisible by context=4
        ref = dot_product_attention(q, k, v, causal=causal, backend="xla")
        out = ulysses_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


def test_ulysses_rejects_indivisible_heads():
    from polyaxon_tpu.parallel.ulysses import ulysses_attention

    mesh = build_mesh({"context": 8})  # H=8 heads... use S small
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(S=64)
        # H=8, context=8: divisible — force the error with a model axis? use
        # a 3-head tensor instead
        import jax.numpy as jnp

        q3, k3, v3 = (x[:, :, :6] for x in (q, k, v))  # 6 heads vs ctx 8
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q3, k3, v3)
    finally:
        set_current_mesh(None)


def test_ulysses_falls_back_without_context_axis():
    from polyaxon_tpu.parallel.ulysses import ulysses_attention

    set_current_mesh(None)
    q, k, v = _qkv(S=32)
    ref = dot_product_attention(q, k, v, causal=True, backend="flash")
    np.testing.assert_allclose(ulysses_attention(q, k, v), ref, atol=1e-6)


@pytest.mark.slow
def test_trainer_ulysses_attention_end_to_end(tmp_home):
    """Full train step with attention=ulysses on a context mesh."""
    from polyaxon_tpu.runtime.trainer import Trainer
    from polyaxon_tpu.schemas.run_kinds import (
        V1DataSpec,
        V1ModelSpec,
        V1OptimizerSpec,
        V1Program,
        V1TrainSpec,
    )

    program = V1Program(
        model=V1ModelSpec(
            name="transformer_lm",
            config={"preset": "tiny", "seq_len": 64, "attention": "ulysses",
                    "n_heads": 8, "n_kv_heads": 8},
        ),
        data=V1DataSpec(
            name="synthetic_text",
            batch_size=8,
            config={"seq_len": 64, "vocab_size": 4096},
        ),
        optimizer=V1OptimizerSpec(name="adamw", learning_rate=1e-3),
        train=V1TrainSpec(steps=3, log_every=3, precision="float32"),
    )
    result = Trainer(program, mesh_axes={"context": 2, "data": 4}).run()
    assert result.history[-1]["loss"] == result.history[-1]["loss"]


def test_auto_backend_resolution(monkeypatch):
    """`auto` picks the flash kernel on TPU whenever the sequence dim stays
    whole per device (single chip, or DP/FSDP/TP meshes via the shard_map
    dispatch); ring when the mesh shards the sequence; einsum for short or
    block-misaligned shapes and off-mesh multi-device tracing."""
    import jax

    from polyaxon_tpu.ops.attention import resolve_auto_backend

    # off-TPU (this suite's CPU slice): always the einsum
    assert resolve_auto_backend(4096, 512) == "xla"

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    set_current_mesh(None)
    assert resolve_auto_backend(1024, 512) == "xla"  # short seq
    assert resolve_auto_backend(2496, 192) == "xla"  # % block_q fails
    assert resolve_auto_backend(4096, 512, head_dim=80) == "xla"  # odd D
    assert resolve_auto_backend(4096, 512, head_dim=512) == "xla"  # huge D
    # no mesh bound: only a lone device can run the unpartitioned kernel
    expect = "flash" if len(jax.devices()) == 1 else "xla"
    assert resolve_auto_backend(4096, 512) == expect

    try:
        # seq whole per device -> flash via the shard_map dispatch
        set_current_mesh(build_mesh({"data": 2, "fsdp": 2, "model": 2}))
        assert resolve_auto_backend(4096, 512) == "flash"
        # seq sharded over context -> ring
        set_current_mesh(build_mesh({"data": 2, "context": 4}))
        assert resolve_auto_backend(4096, 512) == "ring"
    finally:
        set_current_mesh(None)

    # inside a shard_map body the per-device view is single-device
    from polyaxon_tpu.parallel.sharding import suspend_constraints

    with suspend_constraints():
        assert resolve_auto_backend(4096, 512) == "flash"


@pytest.mark.parametrize("axes", [{"data": 2, "fsdp": 2, "model": 2},
                                  {"fsdp": 4, "model": 2}])
def test_flash_sharded_matches_xla(axes):
    """backend=flash on a live multi-device mesh == the einsum reference:
    the shard_map dispatch partitions batch over data/fsdp and heads over
    model while keeping the sequence whole per device."""
    mesh = build_mesh(axes)
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(B=4, S=64, H=4, D=32)
        ref = dot_product_attention(q, k, v, causal=True, backend="xla")
        out = dot_product_attention(q, k, v, causal=True, backend="flash")
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


@pytest.mark.slow
def test_flash_sharded_backward_matches_xla():
    mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2})
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(B=4, S=64, H=4, D=32)
        g1 = jax.grad(
            lambda q: dot_product_attention(
                q, k, v, causal=True, backend="flash"
            ).sum()
        )(q)
        g2 = jax.grad(
            lambda q: dot_product_attention(
                q, k, v, causal=True, backend="xla"
            ).sum()
        )(q)
        np.testing.assert_allclose(g1, g2, atol=5e-5, rtol=5e-5)
    finally:
        set_current_mesh(None)


def test_flash_sharded_degrades_indivisible_dims():
    """Odd batch/head counts degrade those axes to replication instead of
    erroring — correctness over parallelism."""
    mesh = build_mesh({"data": 2, "model": 4})
    set_current_mesh(mesh)
    try:
        q, k, v = _qkv(B=2, S=64, H=3, D=32)  # H=3 % model=4 fails
        ref = dot_product_attention(q, k, v, causal=True, backend="xla")
        out = dot_product_attention(q, k, v, causal=True, backend="flash")
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


# ----------------------------------------------------------- GQA native
@pytest.mark.parametrize(
    "causal",
    [pytest.param(True, marks=pytest.mark.slow),
     pytest.param(False, marks=pytest.mark.slow)],
)
def test_flash_gqa_native_matches_expanded(causal):
    """Grouped-query flash: kv stays [B,S,KV,D] (no repeated K/V in HBM);
    output and ALL grads match the expand-then-attend reference."""
    B, S, H, KV, D = 2, 64, 8, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, KV, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, KV, D), jnp.float32)

    def ref(q, k, v):
        ke = jnp.repeat(k, H // KV, axis=2)
        ve = jnp.repeat(v, H // KV, axis=2)
        return dot_product_attention(q, ke, ve, causal=causal, backend="xla")

    out = flash_attention(q, k, v, causal=causal, block_q=32, block_kv=32)
    np.testing.assert_allclose(out, ref(q, k, v), atol=2e-5, rtol=2e-5)
    g1 = jax.grad(
        lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=32, block_kv=32
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(lambda q, k, v: ref(q, k, v).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5, err_msg=name)


def test_flash_sharded_gqa_on_mesh():
    """backend=flash with grouped kv on a live TP mesh: kv heads shard
    over `model` when they divide, and the result matches the expanded
    einsum reference."""
    mesh = build_mesh({"data": 4, "model": 2})
    set_current_mesh(mesh)
    try:
        B, S, H, KV, D = 4, 64, 8, 2, 16
        ks = jax.random.split(jax.random.PRNGKey(4), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, KV, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, KV, D), jnp.float32)
        ref = dot_product_attention(
            q,
            jnp.repeat(k, H // KV, axis=2),
            jnp.repeat(v, H // KV, axis=2),
            causal=True,
            backend="xla",
        )
        out = dot_product_attention(q, k, v, causal=True, backend="flash")
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


def test_flash_sharded_mqa_expands_to_keep_tp():
    """KV smaller than the model axis (MQA-ish): kv expands so head TP is
    kept rather than replicating every query head per device."""
    mesh = build_mesh({"data": 2, "model": 4})
    set_current_mesh(mesh)
    try:
        B, S, H, KV, D = 2, 64, 8, 1, 16
        ks = jax.random.split(jax.random.PRNGKey(5), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, KV, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, KV, D), jnp.float32)
        ref = dot_product_attention(
            q,
            jnp.repeat(k, H, axis=2),
            jnp.repeat(v, H, axis=2),
            causal=True,
            backend="xla",
        )
        out = dot_product_attention(q, k, v, causal=True, backend="flash")
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


def test_attention_rejects_indivisible_gqa_heads():
    q, k, v = _qkv(H=4)
    k5 = jnp.concatenate([k, k[:, :, :1] * 0 + 1.0], axis=2)[:, :, :3]
    with pytest.raises(ValueError, match="divisible"):
        dot_product_attention(q[:, :, :4], k5, k5, causal=True, backend="xla")


def test_ulysses_gqa_grouped_matches_expanded():
    """GQA ulysses: kv scatter at true kv-head width == the expanded
    reference (4x less all-to-all traffic at llama ratios)."""
    from polyaxon_tpu.parallel.ulysses import ulysses_attention

    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        B, S, H, KV, D = 2, 64, 8, 4, 16
        ks = jax.random.split(jax.random.PRNGKey(6), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, KV, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, KV, D), jnp.float32)
        ref = dot_product_attention(
            q,
            jnp.repeat(k, H // KV, axis=2),
            jnp.repeat(v, H // KV, axis=2),
            causal=True,
            backend="xla",
        )
        out = ulysses_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


def test_ulysses_gqa_expands_when_kv_indivisible():
    """KV heads that don't divide the context degree expand internally —
    correct result, not an error."""
    from polyaxon_tpu.parallel.ulysses import ulysses_attention

    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        B, S, H, KV, D = 2, 64, 8, 2, 16  # KV=2 % context=4 != 0
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, KV, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, KV, D), jnp.float32)
        ref = dot_product_attention(
            q,
            jnp.repeat(k, H // KV, axis=2),
            jnp.repeat(v, H // KV, axis=2),
            causal=True,
            backend="xla",
        )
        out = ulysses_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


@pytest.mark.parametrize("kv", [4, 2])
def test_ulysses_gqa_with_model_axis(kv):
    """Grouped kv under TP+context: model-sharded heads keep their group
    alignment through the all-to-all (kv=4 rides grouped; kv=2 expands
    because local kv 2/model 2 = 1 % context 2 != 0)."""
    from polyaxon_tpu.parallel.ulysses import ulysses_attention

    mesh = build_mesh({"data": 2, "context": 2, "model": 2})
    set_current_mesh(mesh)
    try:
        B, S, H, D = 2, 64, 8, 16
        ks = jax.random.split(jax.random.PRNGKey(8), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, kv, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, kv, D), jnp.float32)
        ref = dot_product_attention(
            q,
            jnp.repeat(k, H // kv, axis=2),
            jnp.repeat(v, H // kv, axis=2),
            causal=True,
            backend="xla",
        )
        out = ulysses_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


@pytest.mark.parametrize(
    "kv",
    [pytest.param(2, marks=pytest.mark.slow),
     pytest.param(4, marks=pytest.mark.slow)],
)
def test_ring_gqa_grouped_matches_expanded(kv):
    """GQA ring: K/V rotate the ring at true kv-head width; result matches
    the expanded reference."""
    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        B, S, H, D = 2, 64, 8, 16
        ks = jax.random.split(jax.random.PRNGKey(9), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, kv, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, kv, D), jnp.float32)
        ref = dot_product_attention(
            q,
            jnp.repeat(k, H // kv, axis=2),
            jnp.repeat(v, H // kv, axis=2),
            causal=True,
            backend="xla",
        )
        out = ring_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


@pytest.mark.slow
def test_ring_gqa_backward_matches_expanded():
    mesh = build_mesh({"data": 2, "context": 4})
    set_current_mesh(mesh)
    try:
        B, S, H, KV, D = 2, 64, 8, 2, 16
        ks = jax.random.split(jax.random.PRNGKey(10), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, KV, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, KV, D), jnp.float32)
        g1 = jax.grad(
            lambda q, k, v: ring_attention(q, k, v, causal=True).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: dot_product_attention(
                q,
                jnp.repeat(k, H // KV, axis=2),
                jnp.repeat(v, H // KV, axis=2),
                causal=True,
                backend="xla",
            ).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5, err_msg=name)
    finally:
        set_current_mesh(None)


def test_ring_gqa_grouped_with_model_axis():
    """The riskiest path: grouped KV stays unexpanded while a live model
    axis shards heads (KV % model == 0) — per-shard group alignment must
    survive the head split AND the ring rotation."""
    mesh = build_mesh({"data": 2, "context": 2, "model": 2})
    set_current_mesh(mesh)
    try:
        B, S, H, KV, D = 2, 64, 8, 2, 16  # KV=2 % model=2 == 0: grouped
        ks = jax.random.split(jax.random.PRNGKey(12), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, KV, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, KV, D), jnp.float32)
        ref = dot_product_attention(
            q,
            jnp.repeat(k, H // KV, axis=2),
            jnp.repeat(v, H // KV, axis=2),
            causal=True,
            backend="xla",
        )
        out = ring_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)


def test_ring_gqa_with_model_axis_expands_when_needed():
    """TP+context with KV % model != 0 forces the internal expansion —
    correct result either way."""
    mesh = build_mesh({"data": 2, "context": 2, "model": 2})
    set_current_mesh(mesh)
    try:
        B, S, H, KV, D = 2, 64, 8, 1, 16  # KV=1 % model=2 != 0
        ks = jax.random.split(jax.random.PRNGKey(11), 3)
        q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, KV, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, KV, D), jnp.float32)
        ref = dot_product_attention(
            q,
            jnp.repeat(k, H, axis=2),
            jnp.repeat(v, H, axis=2),
            causal=True,
            backend="xla",
        )
        out = ring_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    finally:
        set_current_mesh(None)
