"""Spec-layer unit tests: parse/validate good & bad polyaxonfiles and
round-trip serialization (mirrors the reference's spec test strategy,
SURVEY.md §4 row 1)."""

import pytest
from pydantic import ValidationError

from polyaxon_tpu.schemas import (
    V1Component,
    V1GridSearch,
    V1Hyperband,
    V1JAXJob,
    V1MeshSpec,
    V1Operation,
    V1Statuses,
    V1TpuSpec,
    can_transition,
    parse_matrix,
    parse_run,
)


def test_component_requires_run():
    with pytest.raises(ValidationError):
        V1Component.model_validate({"kind": "component", "name": "x"})


def test_component_jaxjob_parses():
    c = V1Component.model_validate(
        {
            "kind": "component",
            "name": "train",
            "inputs": [{"name": "lr", "type": "float", "value": 0.1}],
            "run": {
                "kind": "jaxjob",
                "program": {"model": {"name": "mlp"}},
                "mesh": {"data": 4, "model": 2},
            },
        }
    )
    assert isinstance(c.run, V1JAXJob)
    assert c.run.mesh.axis_sizes() == {"data": 4, "model": 2}


def test_jaxjob_needs_program_or_container():
    with pytest.raises(ValidationError):
        parse_run({"kind": "jaxjob"})


def test_unknown_run_kind_rejected():
    with pytest.raises(ValueError, match="unknown run kind"):
        parse_run({"kind": "sparkjob"})


def test_io_type_validation():
    c = V1Component.model_validate(
        {
            "kind": "component",
            "inputs": [{"name": "lr", "type": "float"}],
            "run": {"kind": "job", "container": {"command": ["true"]}},
        }
    )
    io = c.get_input("lr")
    assert io.validate_value("0.5") == 0.5
    with pytest.raises(ValueError):
        io.validate_value("abc")
    with pytest.raises(ValueError):
        io.validate_value(None)  # required, no default


def test_tpu_spec_topology():
    t = V1TpuSpec.model_validate({"type": "v5e", "topology": "4x8"})
    assert t.num_chips == 32
    assert t.num_hosts == 8
    assert t.dims == (4, 8)
    with pytest.raises(ValidationError):
        V1TpuSpec.model_validate({"type": "v5e", "topology": "4xbad"})
    with pytest.raises(ValidationError):
        V1TpuSpec.model_validate({"type": "v99", "count": 8})
    with pytest.raises(ValidationError):
        V1TpuSpec.model_validate({"type": "v5e"})  # needs topology or count


def test_mesh_spec_validation():
    m = V1MeshSpec.model_validate({"data": -1, "model": 4})
    assert m.axis_sizes() == {"data": -1, "model": 4}
    with pytest.raises(ValidationError):
        V1MeshSpec.model_validate({"data": -1, "model": -1})
    with pytest.raises(ValidationError):
        V1MeshSpec.model_validate({"data": 0})


def test_operation_param_shorthand():
    op = V1Operation.model_validate(
        {"kind": "operation", "hubRef": "x", "params": {"lr": 0.1, "full": {"value": 2}}}
    )
    assert op.params["lr"].value == 0.1
    assert op.params["full"].value == 2


def test_operation_single_ref():
    with pytest.raises(ValidationError):
        V1Operation.model_validate(
            {"kind": "operation", "hubRef": "a", "pathRef": "b"}
        )


def test_matrix_kinds_parse():
    g = parse_matrix(
        {"kind": "grid", "params": {"lr": {"kind": "choice", "value": [0.1, 0.2]}}}
    )
    assert isinstance(g, V1GridSearch)
    h = parse_matrix(
        {
            "kind": "hyperband",
            "params": {"lr": {"kind": "uniform", "value": {"low": 0.0, "high": 1.0}}},
            "maxIterations": 81,
            "eta": 3,
            "resource": {"name": "steps", "type": "int"},
            "metric": {"name": "loss", "optimization": "minimize"},
        }
    )
    assert isinstance(h, V1Hyperband)
    with pytest.raises(ValueError, match="unknown matrix kind"):
        parse_matrix({"kind": "simulated_annealing"})


def test_grid_rejects_continuous_params():
    with pytest.raises(ValidationError, match="must be discrete"):
        parse_matrix(
            {
                "kind": "grid",
                "params": {"lr": {"kind": "uniform", "value": {"low": 0, "high": 1}}},
            }
        )


def test_hp_space_helpers():
    from polyaxon_tpu.schemas import V1HpLinSpace, V1HpLogSpace
    from polyaxon_tpu.schemas.matrix import V1HpRange

    assert V1HpRange.model_validate(
        {"kind": "range", "value": {"start": 0, "stop": 6, "step": 2}}
    ).to_list() == [0, 2, 4]
    ls = V1HpLinSpace.model_validate(
        {"kind": "linspace", "value": {"start": 0.0, "stop": 1.0, "num": 3}}
    ).to_list()
    assert ls == [0.0, 0.5, 1.0]
    lg = V1HpLogSpace.model_validate(
        {"kind": "logspace", "value": {"start": 0.0, "stop": 2.0, "num": 3}}
    ).to_list()
    assert lg == pytest.approx([1.0, 10.0, 100.0])


def test_pchoice_probability_sum():
    with pytest.raises(ValidationError):
        parse_matrix(
            {
                "kind": "random",
                "numRuns": 3,
                "params": {"x": {"kind": "pchoice", "value": [["a", 0.5], ["b", 0.2]]}},
            }
        )


def test_lifecycle_transitions():
    assert can_transition(V1Statuses.CREATED, V1Statuses.COMPILED)
    assert can_transition(V1Statuses.COMPILED, V1Statuses.QUEUED)
    assert can_transition(V1Statuses.RUNNING, V1Statuses.SUCCEEDED)
    assert not can_transition(V1Statuses.SUCCEEDED, V1Statuses.RUNNING)
    assert not can_transition(V1Statuses.CREATED, V1Statuses.RUNNING)
    assert can_transition(V1Statuses.FAILED, V1Statuses.RETRYING)


def test_roundtrip_serialization():
    doc = {
        "kind": "operation",
        "name": "sweep",
        "matrix": {
            "kind": "random",
            "numRuns": 4,
            "seed": 7,
            "params": {"lr": {"kind": "loguniform", "value": {"low": -6.0, "high": -1.0}}},
        },
        "component": {
            "kind": "component",
            "run": {
                "kind": "jaxjob",
                "program": {"model": {"name": "vit"}},
                "environment": {"resources": {"tpu": {"type": "v5e", "topology": "2x4"}}},
            },
        },
    }
    op = V1Operation.model_validate(doc)
    d1 = op.to_dict()
    d2 = V1Operation.model_validate(d1).to_dict()
    assert d1 == d2
    assert d1["matrix"]["numRuns"] == 4  # camelCase surface preserved


def test_legacy_kinds_parse():
    for kind, replica in (("tfjob", "worker"), ("pytorchjob", "master"), ("mpijob", "launcher")):
        r = parse_run(
            {
                "kind": kind,
                replica: {"replicas": 2, "container": {"image": "x", "command": ["t"]}},
            }
        )
        assert r.kind == kind


def test_extra_legacy_kinds_parse_and_normalize():
    """xgboost/paddle/dask/ray jobs parse and compile down to JAXJob gangs."""
    from polyaxon_tpu.compiler.resolver import compile_operation
    from polyaxon_tpu.schemas.component import V1Component
    from polyaxon_tpu.schemas.operation import V1Operation

    for kind, groups in (
        ("xgboostjob", {"master": {"replicas": 1}, "worker": {"replicas": 3}}),
        ("paddlejob", {"worker": {"replicas": 2}}),
        ("daskjob", {"scheduler": {"replicas": 1}, "worker": {"replicas": 2}}),
        ("rayjob", {"head": {"replicas": 1}, "worker": {"replicas": 4}}),
    ):
        groups = {
            g: {**spec, "container": {"image": "x", "command": ["run"]}}
            for g, spec in groups.items()
        }
        op = V1Operation(
            name=f"legacy-{kind}",
            component=V1Component.model_validate(
                {"kind": "component", "name": kind, "run": {"kind": kind, **groups}}
            ),
        )
        compiled = compile_operation(op)
        assert compiled.run.kind == "jaxjob"
        assert compiled.run.replicas == sum(
            g["replicas"] for g in groups.values()
        )
