"""Compiler tests: interpolation, param resolution, patches, legacy-kind
normalization, mesh validation (mirrors reference compiler-test strategy,
SURVEY.md §4 row 3)."""

import pytest

from polyaxon_tpu.compiler import (
    CompilationError,
    apply_suggestion,
    compile_operation,
    interpolate,
    interpolate_str,
)
from polyaxon_tpu.polyaxonfile import read_polyaxonfile
from polyaxon_tpu.schemas import V1Operation

CTX = {"params": {"lr": 0.01, "name": "x"}, "globals": {"uuid": "abc123"}}


def _op(doc):
    return V1Operation.model_validate(doc)


def jaxjob_op(**over):
    doc = {
        "kind": "operation",
        "name": "t",
        "component": {
            "kind": "component",
            "inputs": [
                {"name": "lr", "type": "float", "value": 0.1},
                {"name": "steps", "type": "int", "value": 10},
            ],
            "run": {
                "kind": "jaxjob",
                "program": {
                    "model": {"name": "mlp"},
                    "optimizer": {"learningRate": "{{ params.lr }}"},
                    "train": {"steps": "{{ params.steps }}"},
                },
            },
        },
    }
    doc.update(over)
    return _op(doc)


class TestInterpolation:
    def test_typed_whole_string(self):
        assert interpolate_str("{{ params.lr }}", CTX) == 0.01
        assert isinstance(interpolate_str("{{ params.lr }}", CTX), float)

    def test_embedded_substitution(self):
        assert interpolate_str("run-{{ globals.uuid }}-{{ params.name }}", CTX) == "run-abc123-x"

    def test_nested_structures(self):
        out = interpolate({"a": ["{{ params.lr }}", {"b": "{{ globals.uuid }}"}]}, CTX)
        assert out == {"a": [0.01, {"b": "abc123"}]}

    def test_unknown_reference(self):
        with pytest.raises(CompilationError, match="unknown reference"):
            interpolate_str("{{ params.missing }}", CTX)
        with pytest.raises(CompilationError, match="available"):
            interpolate_str("{{ params.missing }}", CTX)


class TestCompile:
    def test_params_resolve_with_defaults(self):
        c = compile_operation(jaxjob_op())
        assert c.params == {"lr": 0.1, "steps": 10}
        assert c.run.program.optimizer.learning_rate == 0.1
        assert c.run.program.train.steps == 10

    def test_param_override_and_coercion(self):
        op = jaxjob_op(params={"lr": {"value": "0.5"}})
        c = compile_operation(op)
        assert c.params["lr"] == 0.5

    def test_bad_param_type(self):
        op = jaxjob_op(params={"lr": {"value": "abc"}})
        with pytest.raises((CompilationError, ValueError)):
            compile_operation(op)

    def test_missing_required_param(self):
        op = _op(
            {
                "kind": "operation",
                "component": {
                    "kind": "component",
                    "inputs": [{"name": "req", "type": "int"}],
                    "run": {"kind": "job", "container": {"command": ["x"]}},
                },
            }
        )
        with pytest.raises(CompilationError, match="required"):
            compile_operation(op)

    def test_globals_paths(self):
        c = compile_operation(jaxjob_op(), run_uuid="u1", artifacts_root="/tmp/a")
        g = c.contexts["globals"]
        assert g["run_artifacts_path"] == "/tmp/a/u1"
        assert g["run_outputs_path"] == "/tmp/a/u1/outputs"

    def test_run_patch(self):
        op = jaxjob_op(run_patch={"program": {"train": {"logEvery": 99}}})
        c = compile_operation(op)
        assert c.run.program.train.log_every == 99
        assert c.run.program.model.name == "mlp"  # untouched

    def test_environment_patch(self):
        op = jaxjob_op(
            environment={"resources": {"tpu": {"type": "v5e", "topology": "2x2"}}}
        )
        c = compile_operation(op)
        assert c.run.environment.resources.tpu.num_chips == 4

    def test_termination_merge(self):
        op = jaxjob_op(termination={"maxRetries": 3})
        c = compile_operation(op)
        assert c.component.termination.max_retries == 3


class TestMeshValidation:
    def _with_mesh(self, mesh, topology="2x4"):
        op = jaxjob_op()
        return _op(
            {
                **op.to_dict(),
                "runPatch": {
                    "mesh": mesh,
                    "environment": {"resources": {"tpu": {"type": "v5e", "topology": topology}}},
                },
            }
        )

    def test_autofill(self):
        c = compile_operation(self._with_mesh({"data": -1, "model": 2}))
        assert c.run.mesh.axis_sizes() == {"data": 4, "model": 2}

    def test_exact(self):
        c = compile_operation(self._with_mesh({"data": 8}))
        assert c.run.mesh.axis_sizes() == {"data": 8}

    def test_mismatch(self):
        with pytest.raises(CompilationError, match="chips"):
            compile_operation(self._with_mesh({"data": 3}))

    def test_indivisible_autofill(self):
        with pytest.raises(CompilationError, match="divide"):
            compile_operation(self._with_mesh({"data": -1, "model": 3}))

    def test_gpu_rejected(self):
        op = jaxjob_op(environment={"resources": {"gpu": 4}})
        with pytest.raises(CompilationError, match="tpu"):
            compile_operation(op)

    def _with_slices(self, mesh, slices):
        op = jaxjob_op()
        return _op(
            {
                **op.to_dict(),
                "runPatch": {
                    "mesh": mesh,
                    "environment": {
                        "resources": {
                            "tpu": {
                                "type": "v5e",
                                "topology": "2x4",
                                "slices": slices,
                            }
                        }
                    },
                },
            }
        )

    def test_multislice_mesh_spans_all_slices(self):
        # 2x4 = 8 chips per slice, 2 slices -> 16-chip mesh
        c = compile_operation(self._with_slices({"data": -1, "model": 2}, 2))
        assert c.run.mesh.axis_sizes() == {"data": 8, "model": 2}

    def test_multislice_data_axis_must_divide(self):
        # data=1 cannot span 2 slices; model never crosses DCN
        with pytest.raises(CompilationError, match="slice"):
            compile_operation(self._with_slices({"data": 1, "model": 16}, 2))


class TestLegacyKinds:
    def _legacy(self, kind, groups):
        return _op(
            {
                "kind": "operation",
                "component": {
                    "kind": "component",
                    "run": {
                        "kind": kind,
                        **groups,
                        "program": {"model": {"name": "mlp"}},
                    },
                },
            }
        )

    def test_tfjob_normalizes(self):
        op = self._legacy(
            "tfjob",
            {
                "chief": {"replicas": 1, "container": {"command": ["t"]}},
                "worker": {"replicas": 3},
            },
        )
        c = compile_operation(op)
        assert c.run.kind == "jaxjob"
        assert c.run.replicas == 4
        assert c.run.mesh.axis_sizes() == {"data": -1}

    def test_pytorchjob_normalizes(self):
        op = self._legacy(
            "pytorchjob",
            {"master": {"replicas": 1}, "worker": {"replicas": 7}},
        )
        c = compile_operation(op)
        assert c.run.kind == "jaxjob"
        assert c.run.replicas == 8

    def test_tfjob_ps_rejected(self):
        op = self._legacy(
            "tfjob", {"worker": {"replicas": 2}, "ps": {"replicas": 1}}
        )
        with pytest.raises(CompilationError, match="parameter servers"):
            compile_operation(op)


EXAMPLES = __import__("pathlib").Path(__file__).parent.parent / "examples"


class TestSuggestions:
    def test_apply_suggestion(self):
        op = read_polyaxonfile(EXAMPLES / "vit_hyperband.yaml")
        child = apply_suggestion(op, {"lr": 0.003, "batch_size": 256})
        assert child.matrix is None
        assert child.params["lr"].value == 0.003
        c = compile_operation(child)
        assert c.run.program.optimizer.learning_rate == 0.003
        assert c.run.program.data.batch_size == 256


def test_all_examples_compile():
    examples = sorted(EXAMPLES.glob("*.yaml"))
    assert examples, "no example polyaxonfiles found"
    for ex in examples:
        op = read_polyaxonfile(ex)
        if op.matrix is not None:
            op = apply_suggestion(op, {})
        c = compile_operation(op)
        assert c.run.kind == "jaxjob"
        from polyaxon_tpu.compiler import has_template

        assert not has_template(c.component.to_dict()), f"{ex} left templates"
