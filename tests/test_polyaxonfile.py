"""Polyaxonfile reader tests: file parsing, CLI params, check summaries."""

import pytest

from polyaxon_tpu.polyaxonfile import (
    PolyaxonfileError,
    check_polyaxonfile,
    read_polyaxonfile,
    read_specs,
)
from polyaxon_tpu.polyaxonfile.reader import parse_cli_param

GOOD = """
version: 1.1
kind: operation
name: mnist
params:
  lr: 0.01
component:
  kind: component
  inputs:
  - {name: lr, type: float, value: 0.1}
  run:
    kind: jaxjob
    program:
      model: {name: mlp}
"""

BARE_COMPONENT = """
kind: component
name: hello
run:
  kind: job
  container: {command: ["echo", "hi"]}
"""


def _write(tmp_path, text, name="poly.yaml"):
    p = tmp_path / name
    p.write_text(text)
    return p


def test_read_operation(tmp_path):
    op = read_polyaxonfile(_write(tmp_path, GOOD))
    assert op.name == "mnist"
    assert op.params["lr"].value == 0.01


def test_bare_component_wrapped(tmp_path):
    op = read_polyaxonfile(_write(tmp_path, BARE_COMPONENT))
    assert op.component.name == "hello"
    assert op.name == "hello"


def test_cli_params_override(tmp_path):
    op = read_polyaxonfile(_write(tmp_path, GOOD), params={"lr": 0.5, "extra": "x"})
    assert op.params["lr"].value == 0.5
    assert op.params["extra"].value == "x"


def test_parse_cli_param_types():
    assert parse_cli_param("lr=0.1") == ("lr", 0.1)
    assert parse_cli_param("n=3") == ("n", 3)
    assert parse_cli_param("flag=true") == ("flag", True)
    assert parse_cli_param("xs=[1, 2]") == ("xs", [1, 2])
    assert parse_cli_param("s=hello") == ("s", "hello")
    with pytest.raises(PolyaxonfileError):
        parse_cli_param("noequals")


def test_missing_file():
    with pytest.raises(PolyaxonfileError, match="not found"):
        read_polyaxonfile("/nonexistent/x.yaml")


def test_empty_file(tmp_path):
    with pytest.raises(PolyaxonfileError, match="empty"):
        read_polyaxonfile(_write(tmp_path, "\n"))


def test_bad_kind(tmp_path):
    with pytest.raises(PolyaxonfileError, match="kind"):
        read_polyaxonfile(_write(tmp_path, "kind: pipeline\nname: x\n"))


def test_negative_replicas_rejected(tmp_path):
    bad = GOOD.replace("kind: jaxjob", "kind: jaxjob\n    replicas: -2")
    with pytest.raises(PolyaxonfileError, match="replicas"):
        read_polyaxonfile(_write(tmp_path, bad))


def test_multidoc(tmp_path):
    ops = read_specs(_write(tmp_path, GOOD + "\n---\n" + BARE_COMPONENT))
    assert len(ops) == 2
    with pytest.raises(PolyaxonfileError, match="2 specs"):
        read_polyaxonfile(_write(tmp_path, GOOD + "\n---\n" + BARE_COMPONENT))


def test_check_summary(tmp_path):
    out = check_polyaxonfile(_write(tmp_path, GOOD))
    assert out == [
        {
            "name": "mnist",
            "kind": "operation",
            "run_kind": "jaxjob",
            "params": ["lr"],
            "matrix": None,
        }
    ]


def test_examples_all_check():
    """Every shipped example polyaxonfile must validate."""
    from pathlib import Path

    examples = sorted(Path(__file__).parent.parent.glob("examples/*.yaml"))
    assert examples, "no example polyaxonfiles found"
    for ex in examples:
        assert check_polyaxonfile(ex), ex
