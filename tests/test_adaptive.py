"""ISSUE 15 coverage: accept-rate-driven speculation control.

Two layers:

  * controller units — `AdaptiveSpecController` driven by fake counters:
    AIMD ramping (raise on high accept, halve on low), auto-disable at
    k_min, logical-step reprobe re-enabling, stale feedback while
    disabled, truncation-corrected vs raw rate accounting, and ctor
    validation;
  * live HTTP — a speculating server with `adaptive_draft` must flip
    `auto_disabled` under high-entropy traffic (accept → 0) and keep it
    false under copy-friendly cyclic traffic, while every response stays
    byte-identical to the plain server; the draft-model server is pinned
    byte-identical too.
"""

import json
import urllib.request

import numpy as np
import pytest

from polyaxon_tpu.serving.adaptive import AdaptiveSpecController

pytestmark = pytest.mark.serving

CFG = {
    "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
}


# ---------------------------------------------------- controller units
def test_controller_raises_k_on_high_accept():
    c = AdaptiveSpecController(k_init=2, k_min=1, k_max=4, window=8)
    assert c.window_k() == 2
    c.observe(8, 8)  # one full window at accept 1.0
    assert c.window_k() == 3
    c.observe(8, 8)
    assert c.window_k() == 4
    c.observe(8, 8)  # capped at k_max
    assert c.window_k() == 4
    assert c.stats()["adjustments"] == 2


def test_controller_halves_k_on_low_accept():
    c = AdaptiveSpecController(k_init=8, k_min=1, k_max=8, window=10,
                               lower_at=0.3)
    c.observe(10, 1)  # rate 0.1 < lower_at
    assert c.window_k() == 4
    c.observe(10, 1)
    assert c.window_k() == 2
    # middling rate holds K steady
    c.observe(10, 4)
    assert c.window_k() == 2


def test_controller_auto_disables_only_at_k_min():
    c = AdaptiveSpecController(k_init=2, k_min=1, k_max=4, window=4,
                               lower_at=0.3, disable_at=0.1)
    c.observe(4, 0)  # rate 0 but k=2 > k_min: halve, don't disable
    assert c.window_k() == 1 and not c.auto_disabled
    c.observe(4, 0)  # rate 0 at k_min: off
    assert c.auto_disabled and c.window_k() == 0
    assert c.stats()["disables"] == 1
    assert c.stats()["effective_k"] == 0


def test_controller_window_accumulates_before_deciding():
    c = AdaptiveSpecController(k_init=1, k_min=1, k_max=4, window=16)
    c.observe(6, 0)
    c.observe(6, 0)
    assert c.window_k() == 1 and not c.auto_disabled  # 12 < window
    c.observe(6, 0)  # crosses 16: decision fires
    assert c.auto_disabled


def test_controller_reprobe_reenables_at_k_min():
    c = AdaptiveSpecController(k_init=4, k_min=1, k_max=8, window=4,
                               reprobe=10)
    c.observe(4, 0)  # 4 -> 2
    c.observe(4, 0)  # 2 -> 1
    c.observe(4, 0)  # off
    assert c.auto_disabled
    c.tick_plain(9)
    assert c.auto_disabled  # 9 < reprobe
    c.tick_plain(1)
    assert not c.auto_disabled
    assert c.window_k() == 1  # probes at k_min, not the old K
    assert c.stats()["reprobes"] == 1
    # ticks while enabled are ignored (no spurious state)
    c.tick_plain(100)
    assert not c.auto_disabled


def test_controller_ignores_stale_feedback_while_disabled():
    """In-flight spec groups finish after the disable decision; their
    counts must not flip state or pollute the next probe window."""
    c = AdaptiveSpecController(k_init=1, k_min=1, k_max=4, window=4)
    c.observe(4, 0)
    assert c.auto_disabled
    c.observe(400, 400)  # stale: lifetime totals only
    assert c.auto_disabled and c.window_k() == 0
    s = c.stats()
    assert s["accept_rate_corrected"] > 0.9  # totals did accumulate


def test_controller_raw_vs_corrected_rates():
    """The controller decides on the truncation-CORRECTED accepts;
    the raw committed count rides along for /statsz only."""
    c = AdaptiveSpecController(k_init=1, k_min=1, k_max=4, window=8,
                               raise_at=0.6)
    # judged 8/8 but only 5 committed (budget-truncated run): the
    # corrected rate (1.0) must drive K up even though raw is 0.625
    c.observe(8, 8, accepted_raw=5)
    assert c.window_k() == 2
    s = c.stats()
    assert s["accept_rate_corrected"] == 1.0
    assert s["accept_rate_raw"] == pytest.approx(0.625)


def test_controller_ctor_validation():
    with pytest.raises(ValueError, match="k_min"):
        AdaptiveSpecController(k_init=0)
    with pytest.raises(ValueError, match="k_min"):
        AdaptiveSpecController(k_init=9, k_max=8)
    with pytest.raises(ValueError, match="disable_at"):
        AdaptiveSpecController(disable_at=0.5, lower_at=0.2)


# --------------------------------------------------------- live HTTP
@pytest.fixture(scope="module")
def built():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    b = build_model("transformer_lm", CFG)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


def _server(built, **overrides):
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    module, params = built
    cfg = ServingConfig(**{
        "max_batch": 4, "max_wait_ms": 2.0, "stream_chunk_tokens": 3,
        **overrides,
    })
    return ModelServer(module, params, model_name="tiny", config=cfg)


def _post(port, body, timeout=120):
    import http.client

    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request("POST", "/generate", json.dumps(body))
    r = c.getresponse()
    out = r.read()
    c.close()
    return r.status, out


def _spec_stats(port):
    return json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statsz", timeout=60
    ).read())["speculation"]


def _entropy_body(rows=4, plen=12, max_new=24, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "tokens": [rng.randint(1, 128, size=plen).tolist()
                   for _ in range(rows)],
        "maxNewTokens": max_new, "temperature": 0.0,
    }


def _cyclic_body(rows=4, max_new=24):
    cyc = np.tile(np.arange(1, 9, dtype=np.int32), 4).tolist()
    return {"tokens": [cyc] * rows, "maxNewTokens": max_new,
            "temperature": 0.0}


CYCLE = tuple(range(1, 9))


@pytest.fixture(scope="module")
def copy_built(built):
    """The copy-friendly regime: blocks zeroed to the residual identity,
    embed/lm_head crafted so greedy decode replays CYCLE verbatim — the
    repetitive-text workload speculation exists for (same construction
    as benchmarks/decode_bench.cyclic_copy_params)."""
    import jax.numpy as jnp

    module, params = built

    def rebuild(tree):
        out = {}
        for k, v in tree.items():
            if hasattr(v, "items"):
                if k in ("o_proj", "down_proj") and "kernel" in v:
                    out[k] = {
                        n: (jnp.zeros_like(a) if n == "kernel" else a)
                        for n, a in v.items()
                    }
                else:
                    out[k] = rebuild(v)
            else:
                out[k] = v
        return out

    params = rebuild(dict(params))
    emb = np.zeros(params["embed"]["embedding"].shape, np.float32)
    head = np.zeros(params["lm_head"]["kernel"].shape, np.float32)
    p = len(CYCLE)
    for i, t in enumerate(CYCLE):
        emb[t, i] = 1.0
        head[i, CYCLE[(i + 1) % p]] = 1.0
    params["embed"]["embedding"] = jnp.asarray(
        emb, params["embed"]["embedding"].dtype
    )
    params["lm_head"]["kernel"] = jnp.asarray(
        head, params["lm_head"]["kernel"].dtype
    )
    return module, params


def test_high_entropy_traffic_flips_auto_disabled(built):
    """Random prompts give the n-gram drafter nothing to copy: the
    accept rate collapses, K walks down to k_min and speculation turns
    itself off — while every response still matches the plain server."""
    plain = _server(built)
    pp = plain.start(port=0)
    adaptive = _server(built, speculate=True, draft_tokens=3,
                       adaptive_draft=True)
    pa = adaptive.start(port=0)
    try:
        st = _spec_stats(pa)
        assert st["adaptive"] is True
        assert st["auto_disabled"] is False
        # each request's group feeds one observe(); two decisions walk
        # K 3 -> 1 -> off (window=64 proposals per decision)
        for seed in (0, 1, 2):
            body = _entropy_body(seed=seed)
            s1, o1 = _post(pp, body)
            s2, o2 = _post(pa, body)
            assert s1 == 200 and s2 == 200, (o1, o2)
            assert json.loads(o1)["tokens"] == json.loads(o2)["tokens"]
        st = _spec_stats(pa)
        assert st["auto_disabled"] is True, st
        assert st["effective_k"] == 0
        assert st["controller"]["disables"] >= 1
        # lifetime rate, not the windowed one the decision used — random
        # prompts on a 128-vocab model still land ~10% by chance
        assert st["accept_rate_corrected"] < 0.3, st
        # disabled means later groups run plain — and still match
        body = _entropy_body(seed=9)
        _, o1 = _post(pp, body)
        _, o2 = _post(pa, body)
        assert json.loads(o1)["tokens"] == json.loads(o2)["tokens"]
    finally:
        plain.stop()
        adaptive.stop()


def test_cyclic_traffic_keeps_speculation_on(copy_built):
    """Copy-friendly traffic must NOT trip the kill switch: the accept
    rate stays high, the controller ramps K UP, auto_disabled stays
    false."""
    srv = _server(copy_built, speculate=True, draft_tokens=3,
                  adaptive_draft=True)
    port = srv.start(port=0)
    try:
        for _ in range(3):
            status, out = _post(port, _cyclic_body())
            assert status == 200, out
        st = _spec_stats(port)
        assert st["auto_disabled"] is False, st
        assert st["effective_k"] > 3, st  # additive raise engaged
        assert st["accept_rate_corrected"] > 0.5, st
        assert st["controller"]["disables"] == 0
    finally:
        srv.stop()


def test_draft_model_server_byte_identity(built):
    """The draft-model proposer over live HTTP: sampled and greedy
    responses are byte-identical to the plain server, and /statsz
    reports the draft topology."""
    plain = _server(built)
    pp = plain.start(port=0)
    srv = _server(built, speculate=True, draft_tokens=3,
                  draft_model=(("n_layers", 1),))
    pd = srv.start(port=0)
    try:
        rng = np.random.RandomState(0)
        shared = rng.randint(1, 100, size=16).tolist()
        body = {
            "tokens": [shared + rng.randint(1, 100, size=6).tolist()
                       for _ in range(3)],
            "maxNewTokens": 8, "temperature": 0.8, "topK": 40,
            "eosId": 5, "seed": 123,
        }
        for b in (body, dict(body, temperature=0.0)):
            s1, o1 = _post(pp, b)
            s2, o2 = _post(pd, b)
            assert s1 == 200 and s2 == 200, (o1, o2)
            assert json.loads(o1)["tokens"] == json.loads(o2)["tokens"]
        st = _spec_stats(pd)
        assert st["proposed"] > 0
        assert st["draft_model"] == {"n_layers": 1, "derived": True}, st
    finally:
        plain.stop()
        srv.stop()


def test_draft_model_composes_chunked_prefill_int8(built):
    """The acceptance stack in one pot: int8 WEIGHTS + int8 KV pool +
    chunked prefill + draft-model speculation must return exactly the
    bytes of a plain server on the same quantized model and pool,
    streamed and not."""
    common = {"kv_pool_pages": 64, "kv_page_tokens": 8,
              "quantize": "int8", "kv_quant": "int8"}
    plain = _server(built, **common)
    pp = plain.start(port=0)
    srv = _server(built, speculate=True, draft_tokens=3,
                  draft_model=(("n_layers", 1),), adaptive_draft=True,
                  chunked_prefill=True, prefill_chunk_tokens=8,
                  max_step_tokens=32, **common)
    pd = srv.start(port=0)
    try:
        rng = np.random.RandomState(1)
        shared = rng.randint(1, 100, size=16).tolist()
        prompts = [shared + rng.randint(1, 100, size=6).tolist()
                   for _ in range(3)]
        body = {"tokens": prompts, "maxNewTokens": 8, "temperature": 0.8,
                "topK": 40, "eosId": 5, "seed": 9}
        for b in (body, dict(body, temperature=0.0)):
            s1, o1 = _post(pp, b)
            s2, o2 = _post(pd, b)
            assert s1 == 200 and s2 == 200, (o1, o2)
            assert json.loads(o1)["tokens"] == json.loads(o2)["tokens"]
        # streamed == non-streamed through the speculative step lanes
        import http.client

        c = http.client.HTTPConnection("127.0.0.1", pd, timeout=120)
        c.request("POST", "/generate?stream=1", json.dumps(body))
        r = c.getresponse()
        raw = r.read().decode()
        c.close()
        assert r.status == 200, raw
        rows: dict[int, list[int]] = {}
        for line in raw.splitlines():
            if line.startswith("data: "):
                ev = json.loads(line[6:])
                if "tokens" in ev and "row" in ev:
                    rows.setdefault(ev["row"], []).extend(ev["tokens"])
        _, o2 = _post(pd, body)
        full = [prompts[i] + rows[i] for i in range(len(prompts))]
        assert full == json.loads(o2)["tokens"]
    finally:
        plain.stop()
        srv.stop()


# ------------------------------------------------------ config plumbing
def test_serving_spec_adaptive_fields_validate_and_plumb():
    from polyaxon_tpu.schemas.run_kinds import V1ServingSpec

    spec = V1ServingSpec(
        speculate=True, draftModel={"n_layers": 1}, adaptiveDraft=True,
        kvQuant="int8", kvPoolPages=64, kvPageTokens=8,
    )
    cfg = spec.to_config()
    assert cfg.draft_model == (("n_layers", 1),)
    assert cfg.adaptive_draft is True
    assert cfg.kv_quant == "int8"
    # {} means "auto": build the draft from the config's own defaults —
    # it must NOT collapse to None (= draft model off)
    auto = V1ServingSpec(speculate=True, draftModel={})
    assert auto.to_config().draft_model == ()
    # defaults stay off
    off = V1ServingSpec().to_config()
    assert off.draft_model is None
    assert off.adaptive_draft is False and off.kv_quant == "none"

    with pytest.raises(ValueError, match="speculate"):
        V1ServingSpec(draftModel={"n_layers": 1})
    with pytest.raises(ValueError, match="speculate"):
        V1ServingSpec(adaptiveDraft=True)
    with pytest.raises(ValueError, match="kvPoolPages"):
        V1ServingSpec(kvQuant="int8")


def test_serve_replica_argv_layers_adaptive_flags():
    """One replica flag must not drop the others: the child argv carries
    exactly the adaptive/draft/kv-quant pins the parent was given."""
    from polyaxon_tpu.cli.main import _serve_child_argv

    argv = _serve_child_argv(
        "uid", 9000, None,
        {"draft_model": (("n_layers", 1),), "adaptive_draft": True,
         "kv_quant": "int8"},
        None,
    )
    assert "--adaptive-draft" in argv
    assert argv[argv.index("--draft-model") + 1] == "n_layers=1"
    assert argv[argv.index("--kv-quant") + 1] == "int8"
    # the "auto" draft (empty overrides) serializes as --draft-model auto
    argv_auto = _serve_child_argv("uid", 9000, None,
                                  {"draft_model": ()}, None)
    assert argv_auto[argv_auto.index("--draft-model") + 1] == "auto"
    # flags not given do not appear (and so cannot reset spec pins)
    argv_off = _serve_child_argv("uid", 9000, None, {}, None)
    for flag in ("--draft-model", "--adaptive-draft", "--kv-quant"):
        assert flag not in argv_off


def test_server_rejects_combos_the_spec_would(built):
    """CLI overrides bypass V1ServingSpec, so the server itself must
    refuse the same invalid combos — a silently ignored kv_quant would
    have the operator capacity-planning on memory they don't have."""
    with pytest.raises(ValueError, match="kv_pool_pages"):
        _server(built, kv_quant="int8")
    with pytest.raises(ValueError, match="speculate"):
        _server(built, adaptive_draft=True)
    with pytest.raises(ValueError, match="speculate"):
        _server(built, draft_model=(("n_layers", 1),))
