"""Serving-path coverage for the paged KV cache + streamed decode
(ISSUE 6), over live HTTP against tiny models:

  * dense vs paged byte-identity end to end (`POST /generate`);
  * cross-request prefix reuse: a warm re-post hits the prefix cache and
    returns identical tokens;
  * `POST /generate?stream=1` SSE: prompt + concatenated chunks equals
    the non-streamed result, delivered incrementally;
  * TTFT / page-pool / prefix-cache series on /metricsz (the canary gate);
  * pool exhaustion sheds 503 with reason "kv_pages" through the PR 5
    admission path without crashing the worker, and never-fits is a 400;
  * no leaked pages or reservations once traffic drains.
"""

import http.client
import json
import threading
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.serving

CFG = {
    "preset": "tiny", "seq_len": 64, "n_layers": 2, "dim": 64,
    "n_heads": 4, "n_kv_heads": 2, "vocab_size": 128,
}


def _build():
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model

    b = build_model("transformer_lm", CFG)
    params = b.module.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 8), jnp.int32),
        train=False,
    )["params"]
    return b.module, params


def _server(module, params, **overrides):
    from polyaxon_tpu.serving.batching import ServingConfig
    from polyaxon_tpu.serving.server import ModelServer

    cfg = ServingConfig(**{
        "max_batch": 4, "max_wait_ms": 2.0, "kv_page_tokens": 8,
        "stream_chunk_tokens": 3, **overrides,
    })
    return ModelServer(module, params, model_name="tiny", config=cfg)


@pytest.fixture(scope="module")
def servers():
    module, params = _build()
    dense = _server(module, params)
    paged = _server(module, params, kv_pool_pages=64)
    pd, pp = dense.start(port=0), paged.start(port=0)
    yield {"dense": pd, "paged": pp, "module": module, "params": params}
    dense.stop()
    paged.stop()


def _post(port, body, path="/generate", timeout=120):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request("POST", path, json.dumps(body))
    r = c.getresponse()
    out = r.read()
    c.close()
    return r.status, out


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=60
    ).read()


def _body(n_rows=3, prefix=16, suffix=6, max_new=10, seed=123):
    rng = np.random.RandomState(0)
    shared = rng.randint(1, 100, size=prefix).tolist()
    prompts = [
        shared + rng.randint(1, 100, size=suffix).tolist()
        for _ in range(n_rows)
    ]
    return prompts, {
        "tokens": prompts, "maxNewTokens": max_new, "temperature": 0.8,
        "topK": 40, "eosId": 5, "seed": seed,
    }


def test_paged_matches_dense_over_http(servers):
    _, body = _body()
    s1, o1 = _post(servers["dense"], body)
    s2, o2 = _post(servers["paged"], body)
    assert s1 == 200 and s2 == 200, (s1, s2, o1, o2)
    assert json.loads(o1)["tokens"] == json.loads(o2)["tokens"]
    # single-token decode exercises the prefill-only path
    one = dict(body, tokens=body["tokens"][:1], maxNewTokens=1)
    _, oa = _post(servers["dense"], one)
    _, ob = _post(servers["paged"], one)
    assert json.loads(oa)["tokens"] == json.loads(ob)["tokens"]


def test_warm_prefix_hits_and_identical_tokens(servers):
    _, body = _body(seed=321)
    s1, o1 = _post(servers["paged"], body)
    assert s1 == 200, o1
    st0 = json.loads(_get(servers["paged"], "/statsz"))["kv"]
    s2, o2 = _post(servers["paged"], body)
    assert s2 == 200 and json.loads(o2)["tokens"] == json.loads(o1)["tokens"]
    st1 = json.loads(_get(servers["paged"], "/statsz"))["kv"]
    assert st1["enabled"]
    assert st1["prefix"]["hits"] > st0["prefix"]["hits"]


def test_streamed_equals_non_streamed(servers):
    prompts, body = _body(seed=77)
    _, o = _post(servers["paged"], body)
    full = json.loads(o)["tokens"]

    c = http.client.HTTPConnection("127.0.0.1", servers["paged"], timeout=120)
    c.request("POST", "/generate?stream=1", json.dumps(body))
    r = c.getresponse()
    assert r.status == 200
    assert r.getheader("Content-Type") == "text/event-stream"
    chunks = {i: [] for i in range(len(prompts))}
    events, buf = [], b""
    while True:
        data = r.read(64)
        if not data:
            break
        buf += data
        while b"\n\n" in buf:
            frame, buf = buf.split(b"\n\n", 1)
            ev = json.loads(frame[len(b"data: "):])
            events.append(ev)
            if "row" in ev and "tokens" in ev:
                chunks[ev["row"]].extend(ev["tokens"])
    c.close()
    # every frame carries the request id (ISSUE 9) alongside the
    # terminal done marker
    done = events[-1]
    assert done["done"] is True and done["requestId"]
    assert all(
        ev["requestId"] == done["requestId"] for ev in events
    )
    assert not any("error" in ev for ev in events), events
    for i, p in enumerate(prompts):
        assert p + chunks[i] == full[i], (i, chunks[i], full[i])
    # incremental delivery: 10 new tokens at chunk size 3 means several
    # tokens-events per row, not one terminal blob
    assert sum(1 for e in events if e.get("row") == 0 and "tokens" in e) >= 3


def test_metricsz_exports_kv_series(servers):
    _, body = _body(seed=55)
    assert _post(servers["paged"], body)[0] == 200
    m = _get(servers["paged"], "/metricsz").decode()
    for series in (
        "serving_kv_pages_total",
        "serving_kv_pages_used",
        "serving_prefix_cache_hits_total",
        "serving_prefix_cache_misses_total",
        "serving_ttft_ms",
    ):
        assert series in m, f"missing {series} on /metricsz"
    st = json.loads(_get(servers["paged"], "/statsz"))["kv"]
    assert st["pages_total"] == 64
    assert st["ttft_ms"]["p50"] is not None  # TTFT actually observed


def test_no_leaked_pages_after_traffic(servers):
    _, body = _body(seed=99)
    assert _post(servers["paged"], body)[0] == 200
    st = json.loads(_get(servers["paged"], "/statsz"))["kv"]
    assert st["active_rows"] == 0
    assert st["pages_reserved"] == 0
    # prefix entries may hold pages; only the scratch page is otherwise live
    assert st["pages_used"] >= 1


def test_speculative_servers_byte_identical_over_http(servers):
    """ISSUE 8: ServingConfig(speculate=True) must be invisible in the
    payload — dense AND paged speculative servers return exactly the
    baseline servers' tokens, streamed and not, including a warm re-post
    whose shared prefix was prefilled by the earlier request."""
    module, params = servers["module"], servers["params"]
    spec_d = _server(module, params, speculate=True, draft_tokens=4)
    spec_p = _server(module, params, kv_pool_pages=64, speculate=True,
                     draft_tokens=4)
    pd, pp = spec_d.start(port=0), spec_p.start(port=0)
    try:
        prompts, body = _body(seed=888)
        s1, o1 = _post(servers["dense"], body)
        s2, o2 = _post(pd, body)
        assert s1 == 200 and s2 == 200, (s1, s2, o1, o2)
        assert json.loads(o1)["tokens"] == json.loads(o2)["tokens"]
        s3, o3 = _post(servers["paged"], body)
        s4, o4 = _post(pp, body)
        assert s3 == 200 and s4 == 200, (s3, s4, o3, o4)
        full = json.loads(o4)["tokens"]
        assert json.loads(o3)["tokens"] == full

        # warm re-post: the shared prefix was prefilled (and harvested)
        # by the request above — hit rate grows, tokens stay identical
        st0 = json.loads(_get(pp, "/statsz"))["kv"]
        s5, o5 = _post(pp, body)
        assert s5 == 200 and json.loads(o5)["tokens"] == full
        st1 = json.loads(_get(pp, "/statsz"))["kv"]
        assert st1["prefix"]["hits"] > st0["prefix"]["hits"]

        # streamed speculative decode delivers the same tokens in chunks
        c = http.client.HTTPConnection("127.0.0.1", pp, timeout=120)
        c.request("POST", "/generate?stream=1", json.dumps(body))
        r = c.getresponse()
        assert r.status == 200
        chunks = {i: [] for i in range(len(prompts))}
        buf, events = b"", []
        while True:
            data = r.read(64)
            if not data:
                break
            buf += data
            while b"\n\n" in buf:
                frame, buf = buf.split(b"\n\n", 1)
                ev = json.loads(frame[len(b"data: "):])
                events.append(ev)
                if "row" in ev and "tokens" in ev:
                    chunks[ev["row"]].extend(ev["tokens"])
        c.close()
        done = events[-1]
        assert done["done"] is True and done["requestId"]
        assert not any("error" in ev for ev in events), events
        for i, p in enumerate(prompts):
            assert p + chunks[i] == full[i], (i, chunks[i], full[i])

        # greedy too (the high-acceptance regime)
        g = dict(body, temperature=0.0)
        _, og = _post(servers["paged"], g)
        _, ogs = _post(pp, g)
        assert json.loads(og)["tokens"] == json.loads(ogs)["tokens"]

        # the new observability surface: counters on /metricsz, the
        # speculation block (with actual proposals) on /statsz
        m = _get(pp, "/metricsz").decode()
        for series in (
            "serving_spec_proposed_total",
            "serving_spec_accepted_total",
            "serving_spec_rollback_total",
            "serving_quant_bytes_saved",
        ):
            assert series in m, f"missing {series} on /metricsz"
        sp = json.loads(_get(pp, "/statsz"))["speculation"]
        assert sp["enabled"] and sp["draft_tokens"] == 4
        assert sp["proposed"] > 0 and sp["accept_rate"] is not None

        # no leaked pages once speculative traffic drains
        st = json.loads(_get(pp, "/statsz"))["kv"]
        assert st["active_rows"] == 0 and st["pages_reserved"] == 0
    finally:
        spec_d.stop()
        spec_p.stop()


def test_quantized_server_serves_and_reports_footprint(servers):
    """ISSUE 8: quantize-on-load — the server quantizes the fp params in
    __init__, serves greedy traffic, and reports the saved bytes on both
    /statsz and /metricsz."""
    module, params = servers["module"], servers["params"]
    q = _server(module, params, quantize=True)
    port = q.start(port=0)
    try:
        _, body = _body(seed=999)
        st, o = _post(port, dict(body, temperature=0.0))
        assert st == 200, o
        toks = json.loads(o)["tokens"]
        assert all(
            len(t) == len(p) + body["maxNewTokens"]
            for t, p in zip(toks, body["tokens"])
        )
        stats = json.loads(_get(port, "/statsz"))["quant"]
        assert stats["enabled"] and stats["bytes_saved"] > 0
        m = _get(port, "/metricsz").decode()
        assert "serving_quant_bytes_saved" in m
    finally:
        q.stop()


def test_pool_exhaustion_sheds_503_without_crashing():
    module, params = _build()
    # pool 4 = scratch + 3 usable; an 8-token prompt + 4 new reserves 2
    # pages, so two concurrent requests oversubscribe the pool
    srv = _server(
        module, params, max_batch=1, max_wait_ms=150.0, kv_pool_pages=4,
        prompt_buckets=(8,), max_new_buckets=(4,), prefix_cache=False,
    )
    port = srv.start(port=0)
    try:
        ok = {
            "tokens": [list(range(1, 9))], "maxNewTokens": 4,
            "temperature": 0.0,
        }
        assert _post(port, ok)[0] == 200
        res = [None, None]

        def go(i):
            res[i] = _post(port, ok)

        ts = [threading.Thread(target=go, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sorted(r[0] for r in res) == [200, 503], res
        shed = json.loads([r for r in res if r[0] == 503][0][1])
        assert shed["reason"] == "kv_pages", shed
        # a request that could NEVER fit the pool is a client error, not
        # a shed
        big = {
            "tokens": [list(range(1, 40))], "maxNewTokens": 16,
            "temperature": 0.0,
        }
        assert _post(port, big)[0] == 400
        # worker survived both: same request serves again
        assert _post(port, ok)[0] == 200
        st = json.loads(_get(port, "/statsz"))["kv"]
        assert st["active_rows"] == 0 and st["pages_reserved"] == 0
    finally:
        srv.stop()
