"""HF Llama checkpoint import: converted weights must reproduce the HF
model's logits to float tolerance, and greedy generation must agree."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # torch + transformers import is seconds


def _tiny_hf(tie=False, seed=0):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=tie,
    )
    torch.manual_seed(seed)
    return LlamaForCausalLM(cfg).eval()


def test_logit_parity_with_hf():
    import jax.numpy as jnp
    import torch

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.models.convert_hf import from_hf_llama

    hf = _tiny_hf()
    cfg, params = from_hf_llama(hf)
    assert cfg["n_kv_heads"] == 2 and cfg["hidden_dim"] == 96
    bundle = build_model("transformer_lm", cfg)
    tokens = np.random.default_rng(0).integers(0, 128, (2, 16))
    ours = np.asarray(
        bundle.module.apply(
            {"params": params}, jnp.asarray(tokens, jnp.int32), train=False
        ),
        np.float32,
    )
    with torch.no_grad():
        theirs = hf(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-4, rtol=1e-4)


def test_greedy_generation_matches_hf():
    import jax.numpy as jnp
    import torch

    from polyaxon_tpu.models import build_model, generate
    from polyaxon_tpu.models.convert_hf import from_hf_llama

    hf = _tiny_hf(seed=1)
    cfg, params = from_hf_llama(hf)
    bundle = build_model("transformer_lm", cfg)
    prompt = np.random.default_rng(1).integers(0, 128, (1, 6))
    ours = np.asarray(
        generate(
            bundle.module, params, jnp.asarray(prompt, jnp.int32),
            max_new_tokens=8, temperature=0.0,
        )
    )
    with torch.no_grad():
        theirs = hf.generate(
            torch.tensor(prompt), max_new_tokens=8, do_sample=False,
        ).numpy()
    np.testing.assert_array_equal(ours, theirs)


def test_conversion_errors_are_clear():
    from polyaxon_tpu.models.convert_hf import HFConversionError, from_hf_llama

    class FakeCfg:
        hidden_size = 64
        num_attention_heads = 3  # 64/3 not integral via head_dim=20
        head_dim = 20
        num_hidden_layers = 1

    class FakeModel:
        config = FakeCfg()

        def state_dict(self):
            return {}

    with pytest.raises(HFConversionError, match="geometry"):
        from_hf_llama(FakeModel())


def test_round_trip_export_to_hf():
    """Export our params back to an HF state dict: loading it into a fresh
    HF model reproduces the original logits exactly."""
    import jax.numpy as jnp
    import torch
    from transformers import LlamaForCausalLM

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.models.convert_hf import (
        from_hf_llama,
        to_hf_llama_state_dict,
    )

    hf = _tiny_hf(seed=2)
    cfg, params = from_hf_llama(hf)
    sd = to_hf_llama_state_dict(cfg, params)
    fresh = LlamaForCausalLM(hf.config).eval()
    missing, unexpected = fresh.load_state_dict(
        {k: torch.tensor(v) for k, v in sd.items()}, strict=False
    )
    assert not unexpected, unexpected
    # rotary tables are buffers HF recomputes; no weights may be missing
    assert not [m for m in missing if "rotary" not in m], missing

    tokens = np.random.default_rng(2).integers(0, 128, (2, 12))
    with torch.no_grad():
        a = hf(torch.tensor(tokens)).logits.float().numpy()
        b = fresh(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)
    # and our own apply agrees with the re-imported weights
    bundle = build_model("transformer_lm", cfg)
    ours = np.asarray(
        bundle.module.apply(
            {"params": params}, jnp.asarray(tokens, jnp.int32), train=False
        ),
        np.float32,
    )
    np.testing.assert_allclose(ours, b, atol=2e-4, rtol=1e-4)


def test_merge_lora_preserves_function():
    """Merging LoRA deltas into base kernels: the merged plain model
    computes the same logits as the base+LoRA path."""
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import build_model
    from polyaxon_tpu.models.convert_hf import merge_lora

    cfg = {
        "dim": 64, "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
        "vocab_size": 128, "seq_len": 64, "hidden_dim": 96,
        "lora_rank": 4, "lora_alpha": 16.0,
    }
    lora = build_model("transformer_lm", cfg)
    rng = jax.random.PRNGKey(3)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 128, (2, 10)), jnp.int32
    )
    params = lora.module.init({"params": rng}, tokens, train=False)["params"]
    # give the zero-init lora_b real values so the delta is non-trivial
    params = jax.tree_util.tree_map_with_path(
        lambda path, x: jax.random.normal(
            jax.random.fold_in(rng, abs(hash(str(path))) % (2**31)),
            x.shape,
        ) * 0.05
        if path and getattr(path[-1], "key", "") == "lora_b"
        else x,
        params,
    )
    with_lora = np.asarray(
        lora.module.apply({"params": params}, tokens, train=False), np.float32
    )

    plain_cfg = {k: v for k, v in cfg.items() if not k.startswith("lora")}
    plain = build_model("transformer_lm", plain_cfg)
    merged = merge_lora(params, alpha=16.0)
    merged_out = np.asarray(
        plain.module.apply({"params": merged}, tokens, train=False), np.float32
    )
    assert not np.allclose(
        with_lora,
        np.asarray(plain.module.apply(
            {"params": merge_lora(params, alpha=0.0)}, tokens, train=False
        ), np.float32),
    ), "lora delta was trivial — test is vacuous"
    np.testing.assert_allclose(merged_out, with_lora, atol=2e-4, rtol=1e-4)
