"""Fleet scheduler: inventory block math, gang reservations, quotas,
admission ordering, priority preemption, and the deterministic simulator.

The acceptance core lives in TestSimulationAcceptance: a seeded workload
replayed through the REAL admission stack with invariants asserted at
EVERY simulation event — quotas never exceeded at any instant, gangs
all-or-nothing, a high-priority arrival evicts the cheapest lower-
priority victim set, and every preempted run resumes from its checkpoint
and reaches SUCCEEDED.
"""

from __future__ import annotations

import json
import multiprocessing
import subprocess
import sys
from pathlib import Path

import pytest

from polyaxon_tpu.schemas.lifecycle import V1Statuses
from polyaxon_tpu.schemas.quota import V1QuotaSpec
from polyaxon_tpu.scheduler.admission import (
    ADMIT,
    REJECT,
    WAIT,
    AdmissionController,
    QuotaManager,
)
from polyaxon_tpu.scheduler.clock import SimClock
from polyaxon_tpu.scheduler.fleet import (
    DeviceInventory,
    Fleet,
    chips_demand,
    topology_request,
)
from polyaxon_tpu.scheduler.queue import RunQueue
from polyaxon_tpu.scheduler.sim import (
    FleetSimulator,
    SimJob,
    synthetic_workload,
)
from polyaxon_tpu.scheduler.topology import (
    choose_block_shape,
    fits_torus,
    grid_blocks,
    parse_topology,
)
from polyaxon_tpu.store.local import RunStore

pytestmark = pytest.mark.scheduler

REPO = Path(__file__).resolve().parent.parent


# ------------------------------------------------------------ topology
def test_parse_topology_forms():
    assert parse_topology("4x8") == (4, 8)
    assert parse_topology("2X2x2") == (2, 2, 2)
    assert parse_topology((4, 4)) == (4, 4)
    assert parse_topology(None) is None
    assert parse_topology("4x") is None
    assert parse_topology("0x4") is None


def test_block_math_shared_with_placement():
    # the tuner's placement module re-exports the same helpers — one
    # implementation of the torus math, not two drifting copies
    from polyaxon_tpu.tuner import placement

    assert placement.choose_block_shape is choose_block_shape
    assert placement.parse_topology is parse_topology
    blocks = grid_blocks((4, 4), (2, 2))
    assert len(blocks) == 4
    assert all(len(b) == 4 for b in blocks)
    assert fits_torus((4, 4), (2, 4))
    assert not fits_torus((4, 4), (3, 2))  # 3 does not divide 4
    assert fits_torus((4, 4), (4,))  # right-padded with 1s


# ----------------------------------------------------------- inventory
def test_inventory_flat_and_torus_placement():
    inv = DeviceInventory(chips=4)
    got = inv.place(3, used=set())
    assert got is not None and len(got) == 3
    assert inv.place(2, used=set(got)) is None  # only 1 free: all-or-nothing
    assert inv.fits(4) and not inv.fits(5)

    torus = DeviceInventory(topology=(4, 4))
    a = torus.place(8, used=set(), block=(2, 4))
    assert a is not None and len(a) == 8
    b = torus.place(8, used=set(a), block=(2, 4))
    assert b is not None and not (set(a) & set(b))
    assert torus.place(8, used=set(a) | set(b), block=(2, 4)) is None
    # a block that cannot tile the torus can never fit
    assert not torus.fits(6, block=(3, 2))
    assert torus.fits(8, block=(2, 4))


def test_reservations_all_or_nothing_and_persistent(tmp_home):
    store = RunStore()
    fleet = Fleet(store)
    fleet.configure(topology="4x4")
    r = fleet.reserve("a", chips=8, block=(2, 4))
    assert r is not None and len(r["coords"]) == 8
    # idempotent: same run re-reserving returns the SAME record
    assert fleet.reserve("a", chips=8, block=(2, 4))["coords"] == r["coords"]
    # a second handle on the same home sees the reservation (persistence)
    assert Fleet(RunStore()).ledger.get("a") is not None
    assert fleet.reserve("b", chips=16) is None  # 8 free < 16: nothing
    assert fleet.reserved_chips() == 8
    fleet.release("a")
    assert fleet.reserved_chips() == 0


def test_store_releases_reservation_on_every_terminal_transition(tmp_home):
    store = RunStore()
    fleet = Fleet(store)
    fleet.configure(chips=4)
    for status in (V1Statuses.SUCCEEDED, V1Statuses.FAILED, V1Statuses.STOPPED):
        uid = f"run-{status}"
        store.create_run(uid, uid, "p", {})
        fleet.reserve(uid, chips=2)
        assert fleet.ledger.get(uid) is not None
        for s in (
            V1Statuses.COMPILED,
            V1Statuses.QUEUED,
            V1Statuses.SCHEDULED,
            V1Statuses.STARTING,
            V1Statuses.RUNNING,
        ):
            store.set_status(uid, s)
        if status == V1Statuses.STOPPED:
            store.set_status(uid, V1Statuses.STOPPING)
        store.set_status(uid, status)
        assert fleet.ledger.get(uid) is None, f"leaked on {status}"


# --------------------------------------------------------------- demand
def test_chips_demand_resolution_order():
    assert chips_demand({}) == 1
    assert chips_demand(
        {"environment": {"resources": {"chips": 4}}}
    ) == 4
    spec = {"environment": {"resources": {"tpu": {"topology": "2x4"}}}}
    assert chips_demand(spec) == 8  # tpu wins
    assert topology_request(spec) == (2, 4)
    multi = {
        "environment": {
            "resources": {"tpu": {"topology": "2x4", "slices": 2}}
        }
    }
    assert chips_demand(multi) == 16
    assert topology_request(multi) is None  # multi-slice: flat grab
    nested = {
        "component": {
            "run": {"environment": {"resources": {"chips": 3}}}
        }
    }
    assert chips_demand(nested) == 3


# --------------------------------------------------------------- quotas
def test_quota_spec_validation():
    q = V1QuotaSpec(scope="queue:bulk", max_chips=8)
    assert q.is_queue_scope and q.scope_name == "bulk"
    with pytest.raises(Exception):
        V1QuotaSpec(scope="p", weight=0)
    with pytest.raises(Exception):
        V1QuotaSpec(scope="", max_chips=1)


def test_quota_check_reject_vs_wait(tmp_home):
    qm = QuotaManager(RunStore())
    qm.set(V1QuotaSpec(scope="p1", max_chips=8, max_runs=2))
    # ceiling: can NEVER fit → reject
    assert qm.check("p1", "default", 16, {})[0] == REJECT
    # over only because of current usage → wait
    assert (
        qm.check("p1", "default", 4, {"p1": {"chips": 6, "runs": 1}})[0]
        == WAIT
    )
    assert (
        qm.check("p1", "default", 4, {"p1": {"chips": 2, "runs": 2}})[0]
        == WAIT  # run-count limit
    )
    assert qm.check("p1", "default", 4, {})[0] == ADMIT
    assert qm.check("other", "default", 99, {})[0] == ADMIT  # no quota
    # queue-scoped quotas gate by routed queue
    qm.set(V1QuotaSpec(scope="queue:bulk", max_runs=1))
    assert (
        qm.check("other", "bulk", 1, {"queue:bulk": {"chips": 1, "runs": 1}})[0]
        == WAIT
    )


def test_admission_decisions(tmp_home):
    store = RunStore()
    fleet = Fleet(store)
    fleet.configure(topology="4x4")
    adm = AdmissionController(store, fleet=fleet)
    assert adm.active

    def entry(uuid, chips, priority=0, block=None, project="p"):
        return {
            "uuid": uuid,
            "priority": priority,
            "seq": 0,
            "chips": chips,
            "block": block,
            "payload": {"project": project},
        }

    d = adm.try_admit(entry("a", 8, block=[2, 4]))
    assert d.outcome == ADMIT and len(d.reservation["coords"]) == 8
    # bigger than the fleet: UNSCHEDULABLE, not queued forever
    assert adm.try_admit(entry("big", 32)).outcome == REJECT
    # un-tileable block: likewise
    assert adm.try_admit(entry("odd", 6, block=[3, 2])).outcome == REJECT
    # fits the fleet but not right now: WAIT
    d = adm.try_admit(entry("b", 16))
    assert d.outcome == WAIT and not d.preempt  # equal priority: no eviction


def test_fair_share_ordering(tmp_home):
    store = RunStore()
    fleet = Fleet(store)
    fleet.configure(chips=16)
    qm = QuotaManager(store)
    qm.set(V1QuotaSpec(scope="heavy", weight=4.0))
    adm = AdmissionController(store, fleet=fleet, quotas=qm)
    # heavy already holds 8 chips but weight 4 → share 2; light holds 4
    # at weight 1 → share 4. heavy goes first at equal priority.
    fleet.reserve("h1", chips=8, project="heavy")
    fleet.reserve("l1", chips=4, project="light")
    entries = [
        {"uuid": "l2", "priority": 0, "seq": 1, "payload": {"project": "light"}},
        {"uuid": "h2", "priority": 0, "seq": 2, "payload": {"project": "heavy"}},
        {"uuid": "hi", "priority": 9, "seq": 3, "payload": {"project": "light"}},
    ]
    ordered = [e["uuid"] for e in adm.order(entries)]
    assert ordered == ["hi", "h2", "l2"]  # priority first, then fair share


def test_cheapest_victim_selection(tmp_home):
    store = RunStore()
    fleet = Fleet(store)
    fleet.configure(chips=8)
    adm = AdmissionController(store, fleet=fleet)
    fleet.reserve("small", chips=2, priority=0)
    fleet.reserve("large", chips=4, priority=0)
    fleet.reserve("important", chips=2, priority=5)
    # need 4 chips at priority 3: evict ONLY `large` (cheapest sufficient
    # set among strictly-lower-priority holders; `important` untouchable)
    victims = adm.pick_victims(4, None, priority=3)
    assert [v["uuid"] for v in victims] == ["large"]
    # nothing below priority 0 → no victims for an equal-priority gang
    assert adm.pick_victims(4, None, priority=0) == []
    # even evicting all lower-priority holders can't make room → []
    assert adm.pick_victims(8, None, priority=3) == []


# ---------------------------------------------------------------- queue
def test_fifo_within_priority_across_push_pop_remove(tmp_home):
    q = RunQueue(RunStore(), name="fifo")
    for i in range(4):
        q.push(f"a{i}", {}, priority=0)
    q.push("hot", {}, priority=5)
    # remove from the middle, re-add: the re-add goes to the BACK of its
    # priority band (fresh seq), everyone else keeps relative order
    assert q.remove("a1")
    q.push("a1", {}, priority=0)
    assert [e["uuid"] for e in q.peek_all()] == [
        "hot", "a0", "a2", "a3", "a1",
    ]
    assert q.pop()["uuid"] == "hot"
    q.push("late-hot", {}, priority=5)
    assert q.pop()["uuid"] == "late-hot"
    assert [q.pop()["uuid"] for _ in range(4)] == ["a0", "a2", "a3", "a1"]


def test_queue_entries_carry_seq_and_enqueued_at(tmp_home):
    q = RunQueue(RunStore(), name="meta")
    e1 = q.push("u1", {}, priority=0)
    e2 = q.push("u2", {}, priority=0, chips=4, enqueued_at=123.0)
    assert e2["seq"] == e1["seq"] + 1
    assert e1["enqueued_at"] > 0
    assert e2["enqueued_at"] == 123.0 and e2["chips"] == 4
    # seq survives drain-to-empty: later pushes never recycle seq numbers
    q.pop(), q.pop()
    e3 = q.push("u3", {}, priority=0)
    assert e3["seq"] == e2["seq"] + 1


def _queue_worker(home: str, worker: int, n: int, out_path: str):
    from polyaxon_tpu.scheduler.queue import RunQueue
    from polyaxon_tpu.store.local import RunStore

    q = RunQueue(RunStore(home), name="mp")
    popped = []
    for i in range(n):
        q.push(f"w{worker}-{i}", {}, priority=i % 3)
        got = q.pop()
        if got is not None:
            popped.append(got["uuid"])
    Path(out_path).write_text(json.dumps(popped))


def test_multiprocess_push_pop_under_fcntl_lock(tmp_home, tmp_path):
    """N processes hammering one queue file: every pushed entry is popped
    exactly once (the fcntl lock serializes read-modify-write cycles)."""
    n_workers, n_each = 4, 25
    ctx = multiprocessing.get_context("spawn")
    outs = [tmp_path / f"out-{w}.json" for w in range(n_workers)]
    procs = [
        ctx.Process(
            target=_queue_worker, args=(str(tmp_home), w, n_each, str(outs[w]))
        )
        for w in range(n_workers)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=60)
        assert p.exitcode == 0
    popped = []
    for o in outs:
        popped.extend(json.loads(o.read_text()))
    q = RunQueue(RunStore(), name="mp")
    remaining = [e["uuid"] for e in q.peek_all()]
    all_seen = popped + remaining
    assert len(all_seen) == n_workers * n_each
    assert len(set(all_seen)) == len(all_seen)  # nothing lost or doubled
    # the survivors are still a well-formed priority queue
    seqs = [(e["priority"], e["seq"]) for e in q.peek_all()]
    assert seqs == sorted(seqs, key=lambda t: (-t[0], t[1]))


# ------------------------------------------------------- agent admission
def _chip_op(name: str, chips: int, queue: str = "default"):
    from polyaxon_tpu.schemas.operation import V1Operation

    return V1Operation.model_validate(
        {
            "name": name,
            "queue": queue,
            "environment": {"resources": {"chips": chips}},
            "component": {
                "name": "c",
                "run": {
                    "kind": "job",
                    "container": {"command": ["true"]},
                },
            },
        }
    )


def test_agent_without_fleet_keeps_old_claiming(tmp_home):
    from polyaxon_tpu.scheduler.agent import Agent

    store = RunStore()
    agent = Agent(store=store)
    assert not agent.admission.active
    uid = agent.submit(_chip_op("plain", chips=999))  # no fleet: no gating
    assert agent.drain() == 1
    assert store.get_status(uid)["status"] == V1Statuses.SUCCEEDED


def test_agent_admission_gates_and_rejects(tmp_home):
    from polyaxon_tpu.scheduler.agent import Agent

    store = RunStore()
    Fleet(store).configure(chips=4)
    agent = Agent(store=store)
    assert agent.admission.active
    ok = agent.submit(_chip_op("fits", chips=2))
    huge = agent.submit(_chip_op("huge", chips=8))
    assert agent.drain() == 1  # only the schedulable one is claimed
    assert store.get_status(ok)["status"] == V1Statuses.SUCCEEDED
    assert store.get_status(huge)["status"] == V1Statuses.UNSCHEDULABLE
    # terminal transition released the chips
    assert Fleet(store).reserved_chips() == 0


def test_agent_quota_throttles_but_backfills(tmp_home):
    from polyaxon_tpu.scheduler.agent import Agent

    store = RunStore()
    Fleet(store).configure(chips=8)
    QuotaManager(store).set(V1QuotaSpec(scope="capped", max_runs=0))
    agent = Agent(store=store)
    blocked = agent.submit(_chip_op("blocked", chips=1), project="capped")
    free = agent.submit(_chip_op("free", chips=1), project="open")
    agent.drain()
    # maxRuns=0 is a hard ceiling → the capped run is UNSCHEDULABLE, the
    # open-project run backfilled past it and succeeded
    assert store.get_status(blocked)["status"] == V1Statuses.UNSCHEDULABLE
    assert store.get_status(free)["status"] == V1Statuses.SUCCEEDED


def test_executor_eviction_checkpoints_requeues_and_resumes(tmp_home):
    """The REAL eviction path end to end: the admission flag is observed
    at a log boundary, the trainer checkpoints at the step boundary and
    raises Preempted, the executor releases chips and requeues at the
    original priority, and the re-claimed run RESUMES from the checkpoint
    (not step 0) to SUCCEEDED."""
    from polyaxon_tpu.schemas.operation import V1Operation
    from polyaxon_tpu.scheduler.agent import Agent

    store = RunStore()
    Fleet(store).configure(chips=2)
    agent = Agent(store=store)
    op = V1Operation.model_validate(
        {
            "name": "victim",
            "component": {
                "name": "c",
                "run": {
                    "kind": "jaxjob",
                    "program": {
                        "model": {
                            "name": "mlp",
                            "config": {
                                "input_dim": 8,
                                "num_classes": 2,
                                "hidden": [4],
                            },
                        },
                        "data": {
                            "name": "synthetic",
                            # divisible by the 8-device virtual slice the
                            # test harness fakes (conftest.py)
                            "batchSize": 8,
                            "config": {"shape": [8], "num_classes": 2},
                        },
                        "optimizer": {"name": "sgd", "learningRate": 0.01},
                        "train": {
                            "steps": 6,
                            "logEvery": 1,
                            "checkpointEvery": 2,
                            "precision": "float32",
                        },
                    },
                },
            },
        }
    )
    uid = agent.submit(op, priority=2)
    # flag the eviction BEFORE the agent claims the run: the very first
    # log boundary observes it and routes through the SIGTERM machinery
    store.set_meta(uid, preempt_requested=True)
    # one drain: claim → run → evict+requeue → re-claim → resume → done
    agent.drain()
    status = store.get_status(uid)
    assert status["status"] == V1Statuses.SUCCEEDED
    meta = status["meta"]
    assert meta["preempt_restarts"] == 1
    assert meta["preempt_requested"] is False
    events = store.read_events(uid)
    evictions = [
        e for e in events if e["kind"] == "preempted" and e.get("scheduler")
    ]
    assert len(evictions) == 1
    assert evictions[0]["step"] is not None  # checkpoint flushed at eviction
    # lifecycle shows the round trip: RETRYING(evicted) → QUEUED → ... →
    # SUCCEEDED, and the re-enqueued entry kept the original priority
    reasons = [c.get("reason") for c in status["conditions"]]
    assert "evicted" in reasons
    # chips released at the end
    assert Fleet(store).reserved_chips() == 0


# ---------------------------------------------------- simulator acceptance
class TestSimulationAcceptance:
    def test_invariants_every_event_and_all_jobs_finish(self):
        jobs = synthetic_workload(seed=11, n_jobs=60, topology="4x4")
        quotas = [
            V1QuotaSpec(scope="alpha", max_chips=12, weight=2.0),
            V1QuotaSpec(scope="beta", max_chips=8),
        ]
        sim = FleetSimulator(
            jobs,
            topology="4x4",
            quotas=quotas,
            invariant_fn=lambda s: s.check_invariants(),
        )
        report = sim.run()
        assert report["succeeded"] + report["unschedulable"] == report["jobs"]
        assert report["events"] > 0
        # re-running the same seed reproduces the schedule exactly
        sim2 = FleetSimulator(
            synthetic_workload(seed=11, n_jobs=60, topology="4x4"),
            topology="4x4",
            quotas=quotas,
        )
        assert sim2.run() == report

    def test_high_priority_preempts_cheapest_victims_and_they_resume(self):
        jobs = [
            SimJob("low-small", duration=100, arrival=0, chips=2, priority=0),
            SimJob("low-large", duration=100, arrival=0, chips=6, priority=0),
            # arrives while the fleet is full; needs the chips low-large
            # holds, and low-large (not low-small + something) is the
            # cheapest sufficient victim set
            SimJob("high", duration=50, arrival=10, chips=6, priority=10),
        ]
        sim = FleetSimulator(
            jobs, chips=8, invariant_fn=lambda s: s.check_invariants()
        )
        report = sim.run()
        by_name = {j.name: j for j in sim.jobs}
        assert by_name["high"].preemptions == 0
        assert by_name["low-large"].preemptions == 1
        assert by_name["low-small"].preemptions == 0  # cheapest set only
        # the victim checkpointed at eviction (t=10), resumed, and did NOT
        # restart from scratch: progress at eviction is preserved work
        victim = by_name["low-large"]
        assert victim.final_status == V1Statuses.SUCCEEDED
        assert victim.finished_at == pytest.approx(10 + 50 + 90)
        # high ran immediately after eviction
        assert by_name["high"].started_at == pytest.approx(10)
        assert report["preemptions"] == 1
        # store agrees: the victim's run carries the preempt counter and
        # ended SUCCEEDED via the normal lifecycle
        status = sim.store.get_status(victim.uuid)
        assert status["status"] == V1Statuses.SUCCEEDED
        assert status["meta"]["preempt_restarts"] == 1

    def test_gang_all_or_nothing_waits_for_whole_slice(self):
        jobs = [
            SimJob("half-a", duration=40, arrival=0, chips=4,
                   block=(2, 2), priority=0),
            SimJob("half-b", duration=60, arrival=0, chips=4,
                   block=(2, 2), priority=0),
            SimJob("whole", duration=10, arrival=5, chips=16,
                   block=(4, 4), priority=0),
        ]
        sim = FleetSimulator(
            jobs, topology="4x4", invariant_fn=lambda s: s.check_invariants()
        )
        sim.run()
        by_name = {j.name: j for j in sim.jobs}
        # `whole` needs every chip: it starts only after BOTH halves end —
        # never a partial grab of the free half of the torus
        assert by_name["whole"].started_at == pytest.approx(60)
        assert by_name["whole"].final_status == V1Statuses.SUCCEEDED

    def test_unschedulable_over_quota_ceiling(self):
        jobs = [SimJob("too-big", duration=10, chips=8, project="tiny")]
        sim = FleetSimulator(
            jobs,
            chips=16,
            quotas=[V1QuotaSpec(scope="tiny", max_chips=4)],
        )
        report = sim.run()
        assert report["unschedulable"] == 1
        assert sim.jobs[0].final_status == V1Statuses.UNSCHEDULABLE


# ------------------------------------------------------------- surfaces
def test_fleetz_endpoint_and_metrics(tmp_home):
    from polyaxon_tpu.streams.server import make_server

    store = RunStore()
    Fleet(store).configure(topology="2x2")
    Fleet(store).reserve("r1", chips=2, project="p")
    server = make_server(store, port=0)
    import threading

    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        import urllib.request

        port = server.server_address[1]
        body = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleetz", timeout=5
            ).read()
        )
        assert body["configured"] is True
        assert body["chips_total"] == 4 and body["chips_reserved"] == 2
        assert body["reservations"][0]["uuid"] == "r1"
        metrics = (
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metricsz", timeout=5
            )
            .read()
            .decode()
        )
        assert "fleet_chips_total" in metrics
        assert "fleet_chips_reserved" in metrics
    finally:
        server.shutdown()


def test_openapi_documents_fleetz():
    from polyaxon_tpu.streams.openapi import spec

    assert "/fleetz" in spec()["paths"]


def test_scheduler_bench_smoke_runs():
    proc = subprocess.run(
        [sys.executable, str(REPO / "benchmarks" / "scheduler_bench.py"),
         "--smoke", "--seed", "1"],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    for key in (
        "makespan_s", "wait_p50_s", "wait_p95_s",
        "utilization", "preemptions", "events",
    ):
        assert key in rec
    assert rec["succeeded"] + rec["unschedulable"] == rec["jobs"]
